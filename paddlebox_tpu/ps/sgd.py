"""Sparse in-table optimizers (Adagrad / Adam) — pure JAX row updates.

Reference: paddle/fluid/framework/fleet/heter_ps/optimizer.cuh.h —
``SparseAdagradOptimizer::dy_mf_update_value`` (:80-133): show/clk/delta_score
counter updates, Adagrad with ``ratio = lr * sqrt(g0 / (g0 + g2sum))`` and
per-show gradient scaling (``scaled_grad = g / g_show``), ±bound clipping,
g2sum += mean(scaled²), and lazy embedx creation when
``nonclk_coeff*(show-clk) + clk_coeff*clk`` crosses ``mf_create_thresholds``
(init: uniform[0,1) * mf_initial_range, :105-122). ``SparseAdamOptimizer``
(:148-330) keeps per-row beta1/beta2 powers. Defaults mirror
optimizer_conf.h:22-45.

TPU-native formulation: the CUDA version mutates one packed float* per row
inside the hashtable kernel; here updates are batched pure functions over
row-major SoA arrays (``[U]``/``[U, mf_dim]``), vectorized on the VPU and
applied by one scatter per state leaf. Lazy mf creation becomes a two-phase
masked select (update stats → init-new-rows with jax PRNG) instead of an
in-kernel curand side effect — same math, no per-row control flow.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SparseSGDConfig:
    """Adagrad config; field names/defaults from optimizer_conf.h:22-45."""

    nonclk_coeff: float = 0.1
    clk_coeff: float = 1.0
    # embed (wide 1-dim) part
    min_bound: float = -10.0
    max_bound: float = 10.0
    learning_rate: float = 0.05
    initial_g2sum: float = 3.0
    initial_range: float = 0.0
    # embedx (mf) part
    mf_create_thresholds: float = 10.0
    mf_learning_rate: float = 0.05
    mf_initial_g2sum: float = 3.0
    mf_initial_range: float = 1e-4
    mf_min_bound: float = -10.0
    mf_max_bound: float = 10.0


@dataclasses.dataclass(frozen=True)
class SparseAdamConfig(SparseSGDConfig):
    beta1_decay_rate: float = 0.9
    beta2_decay_rate: float = 0.999
    ada_epsilon: float = 1e-8


class RowState(NamedTuple):
    """Per-row slice of the table state touched by one update (SoA)."""

    show: jax.Array          # [U]
    clk: jax.Array           # [U]
    delta_score: jax.Array   # [U]
    embed_w: jax.Array       # [U]
    embed_g2sum: jax.Array   # [U]
    embedx_w: jax.Array      # [U, mf_dim]
    embedx_g2sum: jax.Array  # [U]
    mf_size: jax.Array       # [U] 0/1 — embedx materialized flag


def _adagrad_dir(g: jax.Array, g2sum: jax.Array, scale: jax.Array,
                 lr: float, g0: float, lo: float, hi: float,
                 w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """One update_value_work (optimizer.cuh.h:42-72) on [U] or [U, n] grads.
    Returns (new_w, g2sum_increment). ``scale`` broadcasts over the last dim."""
    ratio = lr * jnp.sqrt(g0 / (g0 + g2sum))
    safe = jnp.maximum(scale, 1e-20)  # rows with g_show==0 are masked upstream
    scaled = g / safe[..., None] if g.ndim == 2 else g / safe
    if g.ndim == 2:
        neww = jnp.clip(w + scaled * ratio[:, None], lo, hi)
        inc = jnp.mean(scaled * scaled, axis=-1)
    else:
        neww = jnp.clip(w + scaled * ratio, lo, hi)
        inc = scaled * scaled
    return neww, inc


def adagrad_update(
    rows: RowState,
    g_show: jax.Array,    # [U]
    g_clk: jax.Array,     # [U]
    g_embed: jax.Array,   # [U]
    g_embedx: jax.Array,  # [U, mf_dim]
    touched: jax.Array,   # [U] bool — at least one real key hit this row
    cfg: SparseSGDConfig,
    rng: jax.Array,
) -> RowState:
    """Batched dy_mf_update_value. Untouched (padding) rows pass through."""
    show = rows.show + g_show
    clk = rows.clk + g_clk
    delta = rows.delta_score + cfg.nonclk_coeff * (g_show - g_clk) \
        + cfg.clk_coeff * g_clk

    embed_w, embed_inc = _adagrad_dir(
        g_embed, rows.embed_g2sum, g_show, cfg.learning_rate,
        cfg.initial_g2sum, cfg.min_bound, cfg.max_bound, rows.embed_w)
    embed_g2sum = rows.embed_g2sum + embed_inc

    # existing mf rows: normal adagrad step
    embedx_new, embedx_inc = _adagrad_dir(
        g_embedx, rows.embedx_g2sum, g_show, cfg.mf_learning_rate,
        cfg.mf_initial_g2sum, cfg.mf_min_bound, cfg.mf_max_bound,
        rows.embedx_w)
    has_mf = rows.mf_size > 0
    # lazy creation: threshold on the *post-update* counters (:105-113)
    score = cfg.nonclk_coeff * (show - clk) + cfg.clk_coeff * clk
    create = (~has_mf) & (score >= cfg.mf_create_thresholds)
    init = jax.random.uniform(rng, rows.embedx_w.shape,
                              rows.embedx_w.dtype) * cfg.mf_initial_range
    embedx_w = jnp.where(create[:, None], init,
                         jnp.where(has_mf[:, None], embedx_new,
                                   rows.embedx_w))
    embedx_g2sum = jnp.where(has_mf, rows.embedx_g2sum + embedx_inc,
                             rows.embedx_g2sum)
    mf_size = jnp.where(create, 1.0, rows.mf_size)

    upd = RowState(show, clk, delta, embed_w, embed_g2sum, embedx_w,
                   embedx_g2sum, mf_size)
    t = touched
    return RowState(*[
        jnp.where(t[:, None] if new.ndim == 2 else t, new, old)
        for new, old in zip(upd, rows)
    ])
