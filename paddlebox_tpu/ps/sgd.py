"""Sparse in-table optimizers (Adagrad / Adam) — pure JAX row updates.

Reference: paddle/fluid/framework/fleet/heter_ps/optimizer.cuh.h —
``SparseAdagradOptimizer::dy_mf_update_value`` (:80-133): show/clk/delta_score
counter updates, Adagrad with ``ratio = lr * sqrt(g0 / (g0 + g2sum))`` and
per-show gradient scaling (``scaled_grad = g / g_show``), ±bound clipping,
g2sum += mean(scaled²), and lazy embedx creation when
``nonclk_coeff*(show-clk) + clk_coeff*clk`` crosses ``mf_create_thresholds``
(init: uniform[0,1) * mf_initial_range, :105-122). ``SparseAdamOptimizer``
(:148-330) keeps per-row beta1/beta2 powers. Defaults mirror
optimizer_conf.h:22-45.

TPU-native formulation: the CUDA version mutates one packed float* per row
inside the hashtable kernel; here updates are batched pure functions over
row-major SoA arrays (``[U]``/``[U, mf_dim]``), vectorized on the VPU and
applied by one scatter per state leaf. Lazy mf creation becomes a two-phase
masked select (update stats → init-new-rows with jax PRNG) instead of an
in-kernel curand side effect — same math, no per-row control flow.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SparseSGDConfig:
    """Adagrad config; field names/defaults from optimizer_conf.h:22-45."""

    nonclk_coeff: float = 0.1
    clk_coeff: float = 1.0
    # embed (wide 1-dim) part
    min_bound: float = -10.0
    max_bound: float = 10.0
    learning_rate: float = 0.05
    initial_g2sum: float = 3.0
    initial_range: float = 0.0
    # embedx (mf) part
    mf_create_thresholds: float = 10.0
    mf_learning_rate: float = 0.05
    mf_initial_g2sum: float = 3.0
    mf_initial_range: float = 1e-4
    mf_min_bound: float = -10.0
    mf_max_bound: float = 10.0


@dataclasses.dataclass(frozen=True)
class SparseAdamConfig(SparseSGDConfig):
    """Selects SparseAdamOptimizer (optimizer.cuh.h:148) —
    ``shared=True`` selects SparseAdamSharedOptimizer (:330), whose
    embedx moments are single scalars shared across dims (each dim's
    update starts from the same stored moment; the stored value becomes
    the MEAN of the per-dim new moments)."""

    beta1_decay_rate: float = 0.9
    beta2_decay_rate: float = 0.999
    ada_epsilon: float = 1e-8
    shared: bool = False


def opt_ext_width(cfg: SparseSGDConfig, mf_dim: int) -> int:
    """Width of the per-row optimizer EXTENSION block appended after
    embedx_w in the table row (the optimizer's EmbedDim/EmbedxDim beyond
    what the base layout already stores — optimizer.cuh.h Dim()).

    Layout (documented here, sliced only by RowState/apply_push):
      adagrad      → 0 (embed_g2sum/embedx_g2sum base columns suffice)
      adam         → [embed_gsum, embed_b1p, embed_b2p, emx_b1p,
                      emx_b2p, emx_m1[mf], emx_m2[mf]]  = 5 + 2*mf
      adam shared  → [embed_gsum, embed_b1p, embed_b2p, emx_b1p,
                      emx_b2p, emx_m1, emx_m2]          = 7
    """
    if not isinstance(cfg, SparseAdamConfig):
        return 0
    return 7 if cfg.shared else 5 + 2 * mf_dim


class RowState(NamedTuple):
    """Per-row slice of the table state touched by one update (SoA)."""

    show: jax.Array          # [U]
    clk: jax.Array           # [U]
    delta_score: jax.Array   # [U]
    embed_w: jax.Array       # [U]
    embed_g2sum: jax.Array   # [U]
    embedx_w: jax.Array      # [U, mf_dim]
    embedx_g2sum: jax.Array  # [U]
    mf_size: jax.Array       # [U] 0/1 — embedx materialized flag
    opt_ext: jax.Array       # [U, opt_ext_width] optimizer extension


def _adagrad_dir(g: jax.Array, g2sum: jax.Array, scale: jax.Array,
                 lr: float, g0: float, lo: float, hi: float,
                 w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """One update_value_work (optimizer.cuh.h:42-72) on [U] or [U, n] grads.
    Returns (new_w, g2sum_increment). ``scale`` broadcasts over the last dim."""
    ratio = lr * jnp.sqrt(g0 / (g0 + g2sum))
    safe = jnp.maximum(scale, 1e-20)  # rows with g_show==0 are masked upstream
    scaled = g / safe[..., None] if g.ndim == 2 else g / safe
    if g.ndim == 2:
        neww = jnp.clip(w + scaled * ratio[:, None], lo, hi)
        inc = jnp.mean(scaled * scaled, axis=-1)
    else:
        neww = jnp.clip(w + scaled * ratio, lo, hi)
        inc = scaled * scaled
    return neww, inc


def adagrad_update(
    rows: RowState,
    g_show: jax.Array,    # [U]
    g_clk: jax.Array,     # [U]
    g_embed: jax.Array,   # [U]
    g_embedx: jax.Array,  # [U, mf_dim]
    touched: jax.Array,   # [U] bool — at least one real key hit this row
    cfg: SparseSGDConfig,
    rng: jax.Array,
) -> RowState:
    """Batched dy_mf_update_value. Untouched (padding) rows pass through."""
    show = rows.show + g_show
    clk = rows.clk + g_clk
    delta = rows.delta_score + cfg.nonclk_coeff * (g_show - g_clk) \
        + cfg.clk_coeff * g_clk

    embed_w, embed_inc = _adagrad_dir(
        g_embed, rows.embed_g2sum, g_show, cfg.learning_rate,
        cfg.initial_g2sum, cfg.min_bound, cfg.max_bound, rows.embed_w)
    embed_g2sum = rows.embed_g2sum + embed_inc

    # existing mf rows: normal adagrad step
    embedx_new, embedx_inc = _adagrad_dir(
        g_embedx, rows.embedx_g2sum, g_show, cfg.mf_learning_rate,
        cfg.mf_initial_g2sum, cfg.mf_min_bound, cfg.mf_max_bound,
        rows.embedx_w)
    has_mf = rows.mf_size > 0
    # lazy creation: threshold on the *post-update* counters (:105-113)
    score = cfg.nonclk_coeff * (show - clk) + cfg.clk_coeff * clk
    create = (~has_mf) & (score >= cfg.mf_create_thresholds)
    init = jax.random.uniform(rng, rows.embedx_w.shape,
                              rows.embedx_w.dtype) * cfg.mf_initial_range
    embedx_w = jnp.where(create[:, None], init,
                         jnp.where(has_mf[:, None], embedx_new,
                                   rows.embedx_w))
    embedx_g2sum = jnp.where(has_mf, rows.embedx_g2sum + embedx_inc,
                             rows.embedx_g2sum)
    mf_size = jnp.where(create, 1.0, rows.mf_size)

    upd = RowState(show, clk, delta, embed_w, embed_g2sum, embedx_w,
                   embedx_g2sum, mf_size, rows.opt_ext)
    return _mask_untouched(upd, rows, touched)


def _mask_untouched(upd: RowState, rows: RowState,
                    touched: jax.Array) -> RowState:
    t = touched
    return RowState(*[
        jnp.where(t[:, None] if new.ndim == 2 else t, new, old)
        for new, old in zip(upd, rows)
    ])


def _adam_dir(w, m1, m2, b1p, b2p, g, scale, cfg: SparseAdamConfig):
    """One SparseAdam update_lr/update_mf (optimizer.cuh.h:159-236) over
    [U] or [U, n] grads with per-row (m1, m2 matching g's shape) moments
    and scalar beta powers. Returns (new_w, new_m1, new_m2, new_b1p,
    new_b2p). Both directions use cfg.learning_rate and the mf bounds —
    mirroring the reference exactly (update_lr clips with mf_min/max and
    reads optimizer_config.learning_rate)."""
    b1, b2 = cfg.beta1_decay_rate, cfg.beta2_decay_rate
    ratio = (cfg.learning_rate * jnp.sqrt(1.0 - b2p)
             / (1.0 - b1p))
    safe = jnp.maximum(scale, 1e-20)
    scaled = g / (safe[..., None] if g.ndim == 2 else safe)
    new_m1 = b1 * m1 + (1.0 - b1) * scaled
    new_m2 = b2 * m2 + (1.0 - b2) * scaled * scaled
    step = new_m1 / (jnp.sqrt(new_m2) + cfg.ada_epsilon)
    r = ratio[..., None] if g.ndim == 2 else ratio
    new_w = jnp.clip(w + r * step, cfg.mf_min_bound, cfg.mf_max_bound)
    return new_w, new_m1, new_m2, b1p * b1, b2p * b2


def adam_update(
    rows: RowState,
    g_show: jax.Array,    # [U]
    g_clk: jax.Array,     # [U]
    g_embed: jax.Array,   # [U]
    g_embedx: jax.Array,  # [U, mf_dim]
    touched: jax.Array,   # [U] bool
    cfg: SparseAdamConfig,
    rng: jax.Array,
) -> RowState:
    """Batched SparseAdam[Shared]Optimizer::dy_mf_update_value
    (optimizer.cuh.h:244-273 / :395-446). Per-row beta powers live in
    the opt_ext block (see opt_ext_width); a beta power of 0 with
    show == 0 marks a never-initialized row, whose powers behave as the
    creation value (beta itself) — trained rows whose powers underflow
    to 0 keep show > 0 and are NOT re-initialized (they are exactly the
    fully-bias-corrected regime, as in the reference)."""
    b1, b2 = cfg.beta1_decay_rate, cfg.beta2_decay_rate
    mf = rows.embedx_w.shape[1]
    ext = rows.opt_ext
    e_gsum, e_b1p, e_b2p = ext[:, 0], ext[:, 1], ext[:, 2]
    x_b1p, x_b2p = ext[:, 3], ext[:, 4]
    if cfg.shared:
        x_m1 = ext[:, 5:6]     # scalar moments broadcast over dims
        x_m2 = ext[:, 6:7]
    else:
        x_m1 = ext[:, 5:5 + mf]
        x_m2 = ext[:, 5 + mf:5 + 2 * mf]

    show = rows.show + g_show
    clk = rows.clk + g_clk
    delta = rows.delta_score + cfg.nonclk_coeff * (g_show - g_clk) \
        + cfg.clk_coeff * g_clk

    # embed (lr) direction — n=1 scalars
    fresh = (rows.show == 0) & (e_b1p == 0)
    eb1p = jnp.where(fresh, b1, e_b1p)
    eb2p = jnp.where(fresh, b2, e_b2p)
    # (shared variant: the stored moment is the mean of new moments —
    # n=1 for the embed direction, so mean == value, same code path)
    embed_w, e_gsum_n, e_g2sum_n, eb1p_n, eb2p_n = _adam_dir(
        rows.embed_w, e_gsum, rows.embed_g2sum, eb1p, eb2p,
        g_embed, g_show, cfg)

    # embedx (mf) direction: update existing, lazily create the rest
    if cfg.shared:
        upd_w, m1_full, m2_full, xb1p_n, xb2p_n = _adam_dir(
            rows.embedx_w, x_m1, x_m2, x_b1p, x_b2p,
            g_embedx, g_show, cfg)
        m1_n = jnp.mean(m1_full, axis=1, keepdims=True)
        m2_n = jnp.mean(m2_full, axis=1, keepdims=True)
    else:
        upd_w, m1_n, m2_n, xb1p_n, xb2p_n = _adam_dir(
            rows.embedx_w, x_m1, x_m2, x_b1p, x_b2p,
            g_embedx, g_show, cfg)
    has_mf = rows.mf_size > 0
    score = cfg.nonclk_coeff * (show - clk) + cfg.clk_coeff * clk
    create = (~has_mf) & (score >= cfg.mf_create_thresholds)
    init = jax.random.uniform(rng, rows.embedx_w.shape,
                              rows.embedx_w.dtype) * cfg.mf_initial_range
    embedx_w = jnp.where(create[:, None], init,
                         jnp.where(has_mf[:, None], upd_w, rows.embedx_w))
    # on creation the reference writes the beta powers = decay rates
    # (optimizer.cuh.h:285-289); moments start at 0
    x_m1_out = jnp.where(has_mf[:, None], m1_n, x_m1)
    x_m2_out = jnp.where(has_mf[:, None], m2_n, x_m2)
    xb1p_out = jnp.where(create, b1, jnp.where(has_mf, xb1p_n, x_b1p))
    xb2p_out = jnp.where(create, b2, jnp.where(has_mf, xb2p_n, x_b2p))
    mf_size = jnp.where(create, 1.0, rows.mf_size)

    ext_new = jnp.concatenate(
        [e_gsum_n[:, None], eb1p_n[:, None], eb2p_n[:, None],
         xb1p_out[:, None], xb2p_out[:, None], x_m1_out, x_m2_out], axis=1)
    upd = RowState(show, clk, delta, embed_w, e_g2sum_n, embedx_w,
                   rows.embedx_g2sum, mf_size, ext_new)
    return _mask_untouched(upd, rows, touched)


def sparse_update(rows: RowState, g_show, g_clk, g_embed, g_embedx,
                  touched, cfg: SparseSGDConfig, rng) -> RowState:
    """Dispatch to the configured in-table optimizer (the OptimizerType
    selection of heter_ps — adagrad / adam / adam-shared)."""
    if isinstance(cfg, SparseAdamConfig):
        return adam_update(rows, g_show, g_clk, g_embed, g_embedx,
                           touched, cfg, rng)
    return adagrad_update(rows, g_show, g_clk, g_embed, g_embedx,
                          touched, cfg, rng)
