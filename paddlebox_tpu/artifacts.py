"""Versioned, crash-safe artifact/publishing layer (the "xbox publish"
flow, SURVEY.md §3.4 — day/delta model shipping from training to
serving).

Before this module, the export/restore surface was spread across
``train/checkpoint.py`` (ckpt dirs), ``ps/host_store.py`` /
``ps/tiered.py`` / ``ps/ssd.py`` (spill manifests) and ``serving.py``
(``load_base``/``apply_delta``) with no shared notion of what a
published version IS, who may read it, or when it may be deleted. The
``ArtifactStore`` consolidates that into ONE registry directory where a
published version is one self-describing, checksummed manifest:

    <root>/
        versions/<aid>/
            MANIFEST.json       (see below)
            MANIFEST.sha256     (sidecar digest of the manifest itself)
            <payload files>     (sparse.npz / sparse_delta.npz /
                                 dense.pkl / cursor.json / ...)
        leases/<aid>.<pid>-<token>.lease   (reader lease files)
        .stage-<pid>-<token>/   (in-flight publishes; swept when the
                                 writer is provably dead)

``MANIFEST.json``::

    {"format": 1,
     "artifact": "v0000000007",     # the version's id (aid)
     "epoch": 7,                    # monotone publish counter
     "kind": "base" | "delta",
     "parent": "v0000000006",       # lineage link (delta chains); null
                                    # for a base
     "created_unix": 1754...,
     "writer": {"pid": ..., "host": ...},
     "files": {"sparse_delta.npz": {"sha256": "...", "bytes": N}, ...},
     "refs": {"spill_manifest": {...}, "cursor": {...}},  # references,
                                    # not payloads: SSD spill-manifest
                                    # digest, stream-cursor position
     "meta": {...}}                 # producer extras (step, pass id...)

Robustness contract (docs/RESILIENCE.md §Publishing):

- **Atomic publish**: payloads + manifest land in a stage dir, every
  file AND the dir are fsynced, then ONE ``os.replace`` makes the
  version visible (the ``utils/fsio.atomic_write_json`` discipline at
  directory granularity). A crash mid-publish leaves a stage carcass,
  never a half-readable version; carcasses from provably-dead writers
  are swept on store open.
- **Verify before adopt**: ``open()`` verifies the FULL checksum chain
  (manifest sidecar, every payload, every lineage parent) before any
  consumer touches state, refuses loudly (``ArtifactCorruptError``) on
  the first mismatch, and — when no explicit version was requested —
  degrades to the newest version that DOES verify.
- **Lease-fenced readers**: ``open()`` takes a lease file (pid +
  heartbeat mtime) before verifying, so the retention sweep can never
  delete a version out from under a reader mid-adoption. Retention
  reaps only provably-stale leases (same-host dead pid, or heartbeat
  older than the TTL) — and because wall-clock staleness can reap a
  merely-PAUSED reader (SIGSTOP/debugger), every handle access
  re-checks the lease file and raises ``ArtifactLeaseLostError``
  instead of serving from possibly-swept files; the reader re-opens.
- **Retention**: ``retain(keep)`` keeps the newest ``keep`` versions,
  every leased version, and the transitive parent lineage of everything
  kept (a delta restores through its whole chain), then sweeps the
  rest.

Fault seams (resilience/faults.py): ``artifact.publish`` fires just
before the atomic publish rename (a ``fail`` is a crash-mid-publish; a
transient one retries on the seeded RetryPolicy), ``artifact.read``
fires on every manifest/payload read (``corrupt`` mangles the bytes so
the checksum verify refuses).

Telemetry: ``pbox_artifact_published_total{kind}``,
``pbox_artifact_adopted_total{kind}``,
``pbox_artifact_refused_total{reason}`` + ``artifact_published`` /
``artifact_adopted`` / ``artifact_refused`` events.
"""

from __future__ import annotations

import hashlib
import json
import os
import secrets
import shutil
import socket
import time
from typing import Callable, Dict, List, Optional, Union

from paddlebox_tpu.resilience import faults
from paddlebox_tpu.resilience.retry import RetryPolicy, TransientError
from paddlebox_tpu.utils.fsio import atomic_write_json
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

MANIFEST = "MANIFEST.json"
MANIFEST_SIDECAR = "MANIFEST.sha256"
_FORMAT = 1


class ArtifactCorruptError(RuntimeError):
    """A version's manifest or payload fails its recorded sha256 (or
    the manifest is torn/unreadable) — the version must not be adopted.
    ``ArtifactStore.open()`` degrades to the newest verifiable version
    when no explicit version was requested."""


class ArtifactLineageError(RuntimeError):
    """A delta's lineage does not extend the consumer's current state
    (wrong/unknown parent, or a chain that never reaches a base) —
    applying it would silently merge out-of-order rows."""


class ArtifactLeaseLostError(RuntimeError):
    """The reader's lease file is gone (reaped as stale while the
    reader was paused, or released elsewhere) — the version's files may
    already be swept. Re-open the store instead of serving from them."""


# ---------------------------------------------------------------------------
# small helpers
# ---------------------------------------------------------------------------

def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True  # exists but not ours / can't tell: assume alive
    return True


def _hostname() -> str:
    try:
        return socket.gethostname()
    except OSError:
        return "unknown"


def _io_retry(site: str) -> RetryPolicy:
    return RetryPolicy.from_flags(site=site,
                                  retryable=(OSError, TransientError))


def _read_bytes(path: str, seam: Optional[str] = "artifact.read") -> bytes:
    """Read a registry file through the ``artifact.read`` fault seam
    (transient failures retry; ``corrupt`` mangles the bytes so the
    caller's digest check refuses)."""
    def read() -> bytes:
        with open(path, "rb") as fh:
            blob = fh.read()
        if seam:
            blob = faults.inject(seam, blob, path=path)
        return blob
    return _io_retry("artifact.read").call(read)


def file_digest(path: str, seam: Optional[str] = "artifact.read",
                chunk: int = 1 << 20) -> str:
    """Streaming sha256 of a registry file (payloads can be multi-GB —
    never buffer them whole), read through the fault seam: the seam
    fires once per file on the first chunk, which is where ``corrupt``
    mangles and where a transient ``fail`` raises into the retry."""
    def digest() -> str:
        h = hashlib.sha256()
        with open(path, "rb") as fh:
            buf = fh.read(chunk)
            if seam:
                buf = faults.inject(seam, buf, path=path)
            while buf:
                h.update(buf)
                buf = fh.read(chunk)
        return h.hexdigest()
    return _io_retry("artifact.read").call(digest)


def hardlink_or_copy(src: str, dst: str) -> None:
    """Hardlink a payload into a stage dir (free for same-filesystem
    publishes of already-written checkpoint files — both sides treat
    the bytes as immutable once published) or copy when linking is
    unsupported (cross-device, FUSE)."""
    try:
        os.link(src, dst)
    except OSError:
        shutil.copyfile(src, dst)


def _fsync_file(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass  # FUSE/NFS may refuse; the rename convention still holds
    finally:
        os.close(fd)


def _counter(name: str, help_: str, **labels) -> None:
    try:
        from paddlebox_tpu.obs.hub import get_hub
        get_hub().counter(name, help_).inc(**labels)
    except Exception:
        log.debug("artifact counter failed", exc_info=True)


def _emit(event: str, **fields) -> None:
    try:
        from paddlebox_tpu.obs.hub import get_hub
        hub = get_hub()
        if hub.active:
            hub.emit(event, **fields)
    except Exception:
        log.debug("artifact event emit failed", exc_info=True)


# ---------------------------------------------------------------------------
# leases
# ---------------------------------------------------------------------------

class Lease:
    """One reader's claim on one artifact (or checkpoint step): a file
    whose mtime is the heartbeat. The lease FENCES reads — every access
    through :meth:`check` verifies the file still exists, so a reader
    whose lease was reaped while it was paused (SIGSTOP, debugger, VM
    migration) finds out on its next read instead of serving from files
    the retention sweep may already have deleted."""

    def __init__(self, registry: "LeaseRegistry", name: str,
                 path: str) -> None:
        self.registry = registry
        self.name = name
        self.path = path
        self._released = False

    def alive(self) -> bool:
        return not self._released and os.path.isfile(self.path)

    def check(self) -> None:
        """Raise ``ArtifactLeaseLostError`` unless the lease still
        holds. Called by every handle access — the reader-side half of
        the stale-lease protocol (reaping alone cannot be safe: the
        reaper can only prove staleness, not reader death). A passing
        check also refreshes the heartbeat, so an ACTIVELY reading
        consumer never ages past the TTL — only idle (or same-host
        dead) holders can be reaped."""
        if not self.alive():
            raise ArtifactLeaseLostError(
                f"lease {self.name!r} ({os.path.basename(self.path)}) "
                "is gone — it was reaped as stale (or released); the "
                "leased files may already be swept. Re-open the store "
                "to adopt a live version.")
        try:
            os.utime(self.path, None)
        except OSError:
            pass  # raced with a reap: the next access fences

    def heartbeat(self) -> None:
        """Refresh the lease mtime; raises if the lease was lost (a
        paused reader must re-open, never resurrect a reaped lease —
        the sweep may already be deleting its files)."""
        self.check()
        try:
            os.utime(self.path, None)
        except OSError as e:
            raise ArtifactLeaseLostError(
                f"lease {self.name!r} heartbeat failed: {e!r}") from e

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def __enter__(self) -> "Lease":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class LeaseRegistry:
    """Shared-dir lease files: ``<name>.<pid>-<token>.lease`` holding
    ``{name, pid, host, created_unix}``. Heartbeat = file mtime (the
    heartbeat-store convention from obs/watchdog). A lease is
    **provably stale** when its writer pid is dead on OUR host, or its
    heartbeat mtime is older than ``ttl_sec`` — those are the only
    leases :meth:`reap_stale` removes."""

    SUFFIX = ".lease"

    def __init__(self, root: str, ttl_sec: float = 300.0) -> None:
        self.root = root
        self.ttl_sec = float(ttl_sec)
        os.makedirs(root, exist_ok=True)

    # ---- acquire -------------------------------------------------------
    def acquire(self, name: str) -> Lease:
        token = secrets.token_hex(4)
        fname = f"{name}.{os.getpid()}-{token}{self.SUFFIX}"
        path = os.path.join(self.root, fname)
        atomic_write_json(path, {"name": name, "pid": os.getpid(),
                                 "host": _hostname(),
                                 "created_unix": time.time()})
        return Lease(self, name, path)

    # ---- enumeration ---------------------------------------------------
    def _entries(self) -> List[str]:
        try:
            return [n for n in os.listdir(self.root)
                    if n.endswith(self.SUFFIX)]
        except OSError:
            return []

    def _name_of(self, fname: str) -> str:
        # "<name>.<pid>-<token>.lease" — name may itself contain dots
        return fname[:-len(self.SUFFIX)].rsplit(".", 1)[0]

    def _is_stale(self, fname: str) -> bool:
        """Provably stale: the holder pid is dead on OUR host — or,
        for a lease we cannot test liveness on (another host / torn
        file), a heartbeat older than the TTL. A same-host ALIVE
        holder is never stale, however old its heartbeat: a reader
        blocked in a long chain load is a slow reader, not a dead
        one."""
        path = os.path.join(self.root, fname)
        info = {}
        try:
            with open(path) as fh:
                info = json.load(fh)
        except (OSError, ValueError):
            pass
        try:
            if info.get("host") == _hostname():
                return not _pid_alive(int(info["pid"]))
        except (ValueError, KeyError, TypeError):
            pass
        try:
            age = time.time() - os.stat(path).st_mtime
        except OSError:
            return False  # raced away — someone else handled it
        return self.ttl_sec >= 0 and age > self.ttl_sec

    def holders(self, name: str, include_stale: bool = False) -> List[str]:
        """Lease files currently claiming ``name`` (provably-stale ones
        excluded unless asked for)."""
        out = []
        for fname in self._entries():
            if self._name_of(fname) != name:
                continue
            if include_stale or not self._is_stale(fname):
                out.append(os.path.join(self.root, fname))
        return out

    def held(self, name: str) -> bool:
        return bool(self.holders(name))

    def active_names(self) -> List[str]:
        """Names with at least one live (non-stale) lease."""
        out = set()
        for fname in self._entries():
            if not self._is_stale(fname):
                out.add(self._name_of(fname))
        return sorted(out)

    # ---- reaping -------------------------------------------------------
    def reap_stale(self) -> List[str]:
        """Remove provably-stale leases; returns the reaped names. A
        PAUSED reader past the TTL is reaped too — that is the
        unavoidable half of wall-clock staleness; the reader-side
        ``Lease.check`` fence is what keeps it safe (the resumed reader
        refuses to serve and re-opens)."""
        reaped = []
        for fname in self._entries():
            if self._is_stale(fname):
                try:
                    os.unlink(os.path.join(self.root, fname))
                    reaped.append(self._name_of(fname))
                    log.warning("reaped stale lease %s", fname)
                except OSError:
                    pass
        return reaped


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------

#: a payload source: an existing file (hardlinked/copied in) or a
#: writer callable invoked with the stage-dir destination path (lets
#: producers like ``EmbeddingTable.save_base`` write straight into the
#: stage with no intermediate copy)
PayloadSource = Union[str, Callable[[str], object]]


class ArtifactHandle:
    """A leased, verified view of one published version (plus its
    lineage chain). Every accessor re-checks the lease — see
    :class:`Lease`."""

    def __init__(self, store: "ArtifactStore", chain: List[dict],
                 lease: Lease) -> None:
        self.store = store
        self.chain = chain          # manifests, base → ... → target
        self.lease = lease

    @property
    def aid(self) -> str:
        return self.chain[-1]["artifact"]

    @property
    def manifest(self) -> dict:
        return self.chain[-1]

    def heartbeat(self) -> None:
        self.lease.heartbeat()

    def path(self, name: str, aid: Optional[str] = None) -> str:
        """Absolute path of payload ``name`` in version ``aid``
        (default: the handle's target). Lease-fenced."""
        self.lease.check()
        aid = self.aid if aid is None else aid
        p = os.path.join(self.store.version_dir(aid), name)
        if not os.path.isfile(p):
            raise FileNotFoundError(
                f"artifact {aid} has no payload {name!r}")
        return p

    def read(self, name: str, aid: Optional[str] = None) -> bytes:
        """Payload bytes, lease-fenced AND re-verified against the
        manifest checksum (belt for readers that hold a handle across
        a long pause: even if the files were swept+recreated, a stale
        read can never return silently-wrong bytes)."""
        self.lease.check()
        aid = self.aid if aid is None else aid
        m = next(m for m in self.chain if m["artifact"] == aid)
        blob = _read_bytes(os.path.join(self.store.version_dir(aid),
                                        name))
        want = m["files"][name]["sha256"]
        got = hashlib.sha256(blob).hexdigest()
        if got != want:
            raise ArtifactCorruptError(
                f"artifact {aid}/{name}: sha256 {got[:12]}… != manifest "
                f"{want[:12]}…")
        return blob

    def close(self) -> None:
        self.lease.release()

    def __enter__(self) -> "ArtifactHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class ArtifactStore:
    """The registry. See the module docstring for the layout and the
    robustness contract."""

    def __init__(self, root: str, keep: int = 0,
                 lease_ttl_sec: Optional[float] = None,
                 sweep: bool = True) -> None:
        from paddlebox_tpu.config import FLAGS
        self.root = root
        self.keep = int(keep)   # 0 = retain() keeps everything
        ttl = (FLAGS.artifact_lease_ttl_sec if lease_ttl_sec is None
               else lease_ttl_sec)
        self.versions_dir = os.path.join(root, "versions")
        os.makedirs(self.versions_dir, exist_ok=True)
        self._leases = LeaseRegistry(os.path.join(root, "leases"),
                                     ttl_sec=ttl)
        if sweep:
            self.sweep_carcasses()

    # ---- naming --------------------------------------------------------
    @staticmethod
    def aid_for(epoch: int) -> str:
        return f"v{epoch:010d}"

    @staticmethod
    def epoch_of(aid: str) -> int:
        return int(aid[1:])

    def version_dir(self, aid: str) -> str:
        return os.path.join(self.versions_dir, aid)

    def versions(self) -> List[str]:
        """Published version ids, oldest → newest (only dirs with a
        manifest — a half-swept dir is invisible, like checkpoint
        ``steps()``)."""
        out = []
        try:
            names = os.listdir(self.versions_dir)
        except OSError:
            return []
        for name in names:
            if not name.startswith("v"):
                continue
            try:
                self.epoch_of(name)
            except ValueError:
                continue
            if os.path.isfile(os.path.join(self.versions_dir, name,
                                           MANIFEST)):
                out.append(name)
        return sorted(out, key=self.epoch_of)

    def latest(self) -> Optional[str]:
        vs = self.versions()
        return vs[-1] if vs else None

    def _next_epoch(self) -> int:
        vs = self.versions()
        return (self.epoch_of(vs[-1]) + 1) if vs else 1

    # ---- carcass sweep -------------------------------------------------
    def sweep_carcasses(self) -> List[str]:
        """Remove ``.stage-*`` dirs whose writer is PROVABLY dead: the
        crash-mid-publish leftovers. Proof: the stage marker's (or dir
        name's) pid is dead on OUR host. A same-host pid that is ALIVE
        is never swept — not even past the TTL, a long-running
        multi-GB staging is a live publisher, not a carcass. Only a
        stage provably from another host (marker names a foreign host)
        falls back to the wall-clock TTL rule, where pid liveness
        cannot be tested."""
        swept = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return swept
        for name in names:
            if not name.startswith(".stage-"):
                continue
            path = os.path.join(self.root, name)
            if not os.path.isdir(path):
                continue
            info = {}
            try:
                with open(os.path.join(path, "stage.json")) as fh:
                    info = json.load(fh)
            except (OSError, ValueError):
                pass
            pid = info.get("pid")
            if pid is None:   # marker gone/torn: the dir name has it
                try:
                    pid = int(name.split("-")[1])
                except (IndexError, ValueError):
                    pid = None
            host = info.get("host")
            if host == _hostname():
                dead = pid is not None and not _pid_alive(int(pid))
            elif host is None and pid is not None \
                    and _pid_alive(int(pid)):
                # no host proof but a locally-alive pid: could be OUR
                # live publisher — never sweep on a maybe
                dead = False
            else:
                try:
                    age = time.time() - os.stat(path).st_mtime
                    ttl = self._leases.ttl_sec
                    dead = ttl >= 0 and age > ttl
                except OSError:
                    continue
            if dead:
                shutil.rmtree(path, ignore_errors=True)
                swept.append(name)
                log.warning("swept half-published artifact carcass %s",
                            name)
        return swept

    # ---- publish -------------------------------------------------------
    def publish(self, files: Dict[str, PayloadSource], kind: str = "base",
                parent: Optional[str] = None,
                refs: Optional[dict] = None,
                meta: Optional[dict] = None,
                adoptable: bool = True) -> str:
        """Publish one version; returns its aid. ``files`` maps payload
        name → source path (hardlinked/copied) or writer callable
        (invoked ONCE with the stage destination — retries re-run only
        the commit, so a producer whose writer has side effects, e.g.
        ``save_delta``'s touched-clear, never double-fires). A ``delta``
        must name its ``parent``; lineage is verified at adoption.

        ``adoptable=False`` marks a CHAIN-ONLY link: it participates in
        lineage (and retention's closure) and can be opened explicitly,
        but unpinned ``open(None)`` skips it when picking the newest
        version — the mid-pass backfill links of
        ``CheckpointManager.restore`` use this so a serving reader
        never lands on a half-trained pass state."""
        if kind not in ("base", "delta"):
            raise ValueError(f"unknown artifact kind {kind!r}")
        if kind == "delta" and parent is None:
            raise ArtifactLineageError(
                "a delta artifact must name its parent version — an "
                "unparented delta could never be chain-verified")
        if parent is not None and not os.path.isfile(
                os.path.join(self.version_dir(parent), MANIFEST)):
            raise ArtifactLineageError(
                f"parent artifact {parent!r} is not published in "
                f"{self.root} — publish the base/previous delta first")
        stage = os.path.join(
            self.root, f".stage-{os.getpid()}-{secrets.token_hex(4)}")
        os.makedirs(stage)
        try:
            atomic_write_json(os.path.join(stage, "stage.json"),
                              {"pid": os.getpid(), "host": _hostname(),
                               "created_unix": time.time()})
            checksums: Dict[str, dict] = {}
            for name, src in files.items():
                if name in (MANIFEST, MANIFEST_SIDECAR, "stage.json"):
                    raise ValueError(f"reserved payload name {name!r}")
                dst = os.path.join(stage, name)
                if callable(src):
                    src(dst)
                else:
                    hardlink_or_copy(src, dst)
                # digest WITHOUT the read seam: we just wrote these
                # bytes; the seam models consumer-side reads
                checksums[name] = {
                    "sha256": file_digest(dst, seam=None),
                    "bytes": os.path.getsize(dst)}

            def commit() -> str:
                epoch = self._next_epoch()
                aid = self.aid_for(epoch)
                manifest = {"format": _FORMAT, "artifact": aid,
                            "epoch": epoch, "kind": kind,
                            "parent": parent,
                            "adoptable": bool(adoptable),
                            "created_unix": time.time(),
                            "writer": {"pid": os.getpid(),
                                       "host": _hostname()},
                            "files": checksums, "refs": refs or {},
                            "meta": meta or {}}
                mpath = os.path.join(stage, MANIFEST)
                with open(mpath, "w") as fh:
                    json.dump(manifest, fh, sort_keys=True)
                with open(os.path.join(stage, MANIFEST_SIDECAR),
                          "w") as fh:
                    fh.write(file_digest(mpath, seam=None))
                # the writer-liveness marker protected the stage from
                # the carcass sweep through staging + retries; it must
                # not ride into the published version
                try:
                    os.unlink(os.path.join(stage, "stage.json"))
                except OSError:
                    pass
                # durability: payload bytes AND dir entries hit disk
                # BEFORE the publish rename exposes them
                for name in os.listdir(stage):
                    _fsync_file(os.path.join(stage, name))
                _fsync_file(stage)
                # chaos seam: a "fail" here is the writer dying after
                # staging but before the atomic publish; recovery =
                # carcass sweep + the previous complete version
                faults.inject("artifact.publish", path=stage,
                              artifact=aid)
                # one rename publishes; a concurrent publisher that won
                # this epoch makes the target non-empty → OSError →
                # the retry re-allocates the next epoch
                os.replace(stage, self.version_dir(aid))
                _fsync_file(self.versions_dir)
                return aid

            aid = _io_retry("artifact.publish").call(commit)
        except BaseException as e:
            # a surviving process that failed to publish removes its
            # own stage; an InjectedCrash models the process DYING
            # mid-publish, so the stage stays behind exactly like a
            # real dead writer's — the carcass the sweep handles
            if not isinstance(e, faults.InjectedCrash) \
                    and os.path.isdir(stage):
                shutil.rmtree(stage, ignore_errors=True)
            raise
        _counter("pbox_artifact_published_total",
                 "artifact versions published", kind=kind)
        _emit("artifact_published", artifact=aid, kind=kind,
              parent=parent or "", files=sorted(checksums),
              epoch=self.epoch_of(aid))
        log.info("published artifact %s (%s, parent=%s, %d files)",
                 aid, kind, parent, len(checksums))
        return aid

    # ---- verification --------------------------------------------------
    def read_manifest(self, aid: str, verify: bool = True) -> dict:
        """The version's manifest; ``verify`` checks the sidecar digest
        first (a torn manifest refuses like any corrupt link)."""
        d = self.version_dir(aid)
        mpath = os.path.join(d, MANIFEST)
        try:
            blob = _read_bytes(mpath)
        except (OSError, ValueError) as e:
            raise ArtifactCorruptError(
                f"artifact {aid}: unreadable manifest ({e!r})") from e
        if verify:
            try:
                want = _read_bytes(
                    os.path.join(d, MANIFEST_SIDECAR)).decode().strip()
            except (OSError, ValueError) as e:
                raise ArtifactCorruptError(
                    f"artifact {aid}: unreadable manifest sidecar "
                    f"({e!r})") from e
            got = hashlib.sha256(blob).hexdigest()
            if got != want:
                raise ArtifactCorruptError(
                    f"artifact {aid}: manifest is torn/corrupt (sha256 "
                    f"{got[:12]}… != sidecar {want[:12]}…) — refuse to "
                    "trust this version")
        try:
            m = json.loads(blob)
        except ValueError as e:
            raise ArtifactCorruptError(
                f"artifact {aid}: manifest is not JSON ({e!r})") from e
        if m.get("artifact") != aid:
            raise ArtifactCorruptError(
                f"artifact {aid}: manifest names {m.get('artifact')!r} "
                "— foreign/misplaced version dir")
        return m

    def verify_version(self, aid: str) -> dict:
        """Verify ONE version (manifest + every payload digest);
        returns the manifest. No lineage walk — see verify_chain."""
        m = self.read_manifest(aid)
        d = self.version_dir(aid)
        for name, rec in m.get("files", {}).items():
            p = os.path.join(d, name)
            try:
                got = file_digest(p)
            except OSError as e:
                raise ArtifactCorruptError(
                    f"artifact {aid}/{name}: unreadable ({e!r})") from e
            if got != rec["sha256"]:
                raise ArtifactCorruptError(
                    f"artifact {aid}/{name} is corrupt: sha256 "
                    f"{got[:12]}… != manifest {rec['sha256'][:12]}… — "
                    "refuse to adopt this version")
        return m

    def verify_chain(self, aid: str) -> List[dict]:
        """Verify ``aid`` AND its whole parent lineage down to a base;
        returns the manifests base → … → aid. Every adoption runs this
        BEFORE any consumer state is touched."""
        chain: List[dict] = []
        seen = set()
        cur: Optional[str] = aid
        while cur is not None:
            if cur in seen:
                raise ArtifactCorruptError(
                    f"artifact {aid}: lineage cycle at {cur}")
            seen.add(cur)
            m = self.verify_version(cur)
            chain.append(m)
            parent = m.get("parent")
            if parent is None:
                if m.get("kind") != "base":
                    raise ArtifactLineageError(
                        f"artifact {aid}: chain ends at {cur} which is "
                        f"a {m.get('kind')!r}, not a base — the lineage "
                        "never reaches a full snapshot")
                break
            if not os.path.isdir(self.version_dir(parent)):
                raise ArtifactLineageError(
                    f"artifact {aid}: lineage parent {parent} is gone "
                    "(swept or lost) — the delta chain cannot be "
                    "replayed")
            cur = parent
        chain.reverse()
        return chain

    # ---- adoption ------------------------------------------------------
    def open(self, version: Optional[str] = None) -> ArtifactHandle:
        """Lease + verify + hand out a version. With ``version=None``
        adopts the NEWEST verifiable version, refusing corrupt ones
        loudly along the way (the degrade path); an explicit version
        that fails verification raises instead. The lease is taken
        BEFORE verification so the retention sweep can never race the
        adoption."""
        explicit = version is not None
        candidates = ([version] if explicit
                      else list(reversed(self.versions())))
        if not candidates:
            raise FileNotFoundError(
                f"no published versions in {self.root}")
        last_err: Optional[Exception] = None
        for aid in candidates:
            lease = self._leases.acquire(aid)
            try:
                if not explicit and not self.read_manifest(
                        aid, verify=False).get("adoptable", True):
                    # chain-only link (mid-pass backfill): never the
                    # tip an unpinned reader lands on
                    lease.release()
                    continue
                chain = self.verify_chain(aid)
            except (ArtifactCorruptError, ArtifactLineageError,
                    OSError, ValueError) as e:
                lease.release()
                last_err = e
                reason = ("corrupt"
                          if isinstance(e, ArtifactCorruptError)
                          else "lineage" if isinstance(
                              e, ArtifactLineageError) else "io")
                _counter("pbox_artifact_refused_total",
                         "artifact versions refused at adoption",
                         reason=reason)
                _emit("artifact_refused", artifact=aid, reason=reason,
                      error=repr(e))
                log.error("REFUSING artifact %s: %s", aid, e)
                if explicit:
                    raise
                continue
            _counter("pbox_artifact_adopted_total",
                     "artifact versions adopted by readers",
                     kind=chain[-1].get("kind", "base"))
            _emit("artifact_adopted", artifact=aid,
                  chain=[m["artifact"] for m in chain])
            return ArtifactHandle(self, chain, lease)
        raise last_err if last_err is not None else FileNotFoundError(
            f"no adoptable versions in {self.root}")

    # ---- retention -----------------------------------------------------
    def leased_versions(self) -> List[str]:
        return [n for n in self._leases.active_names()
                if n in set(self.versions())]

    def lease_registry(self) -> LeaseRegistry:
        return self._leases

    def retain(self, keep: Optional[int] = None) -> List[str]:
        """Sweep old versions; returns what was removed. NEVER removes
        a leased version or any lineage parent of a kept one; reaps
        provably-stale leases first. ``keep<=0`` keeps everything (only
        stale leases and carcasses are cleaned)."""
        keep = self.keep if keep is None else keep
        self._leases.reap_stale()
        self.sweep_carcasses()
        vs = self.versions()
        if keep is None or keep <= 0 or len(vs) <= keep:
            return []
        kept = set(vs[-keep:])
        kept.update(self.leased_versions())
        # lineage closure: a kept delta needs its whole parent chain
        frontier = list(kept)
        while frontier:
            aid = frontier.pop()
            try:
                parent = self.read_manifest(aid,
                                            verify=False).get("parent")
            except (ArtifactCorruptError, OSError, ValueError):
                continue  # unreadable: nothing to protect through it
            if parent is not None and parent in set(vs) \
                    and parent not in kept:
                kept.add(parent)
                frontier.append(parent)
        removed = []
        for aid in vs:
            if aid in kept:
                continue
            # narrow the lease-vs-sweep window: a reader may have
            # leased this version AFTER the kept-set snapshot above —
            # re-check right before the delete. (The residual race is
            # closed from the reader side: open() verifies AFTER
            # leasing, so a sweep that slips through surfaces as a
            # loud refusal + degrade/retry, never as silent garbage.)
            if self._leases.held(aid):
                log.info("retention deferring %s (late lease)", aid)
                continue
            shutil.rmtree(self.version_dir(aid), ignore_errors=True)
            removed.append(aid)
            log.info("retention swept artifact %s", aid)
        return removed


# ---------------------------------------------------------------------------
# sidecar helpers (legacy path + manifest coexistence — serving.py)
# ---------------------------------------------------------------------------

def manifest_beside(path: str) -> Optional[dict]:
    """The verified MANIFEST.json sitting next to ``path`` (i.e. the
    payload lives inside a published version dir), or None for a plain
    legacy file. Raises ``ArtifactCorruptError`` on a torn manifest —
    a payload that CLAIMS to be managed never degrades silently."""
    d = os.path.dirname(os.path.abspath(path))
    mpath = os.path.join(d, MANIFEST)
    if not os.path.isfile(mpath):
        return None
    blob = _read_bytes(mpath)
    side = os.path.join(d, MANIFEST_SIDECAR)
    if os.path.isfile(side):
        want = _read_bytes(side).decode().strip()
        got = hashlib.sha256(blob).hexdigest()
        if got != want:
            raise ArtifactCorruptError(
                f"manifest next to {path} is torn/corrupt (sha256 "
                f"{got[:12]}… != sidecar {want[:12]}…)")
    try:
        return json.loads(blob)
    except ValueError as e:
        raise ArtifactCorruptError(
            f"manifest next to {path} is not JSON ({e!r})") from e


def verify_payload(manifest: dict, path: str) -> None:
    """Check one payload file against its manifest record; raises
    ``ArtifactCorruptError`` on mismatch or an unmanifested name."""
    name = os.path.basename(path)
    rec = manifest.get("files", {}).get(name)
    if rec is None:
        raise ArtifactCorruptError(
            f"{name} is not in artifact {manifest.get('artifact')}'s "
            "manifest — refusing an unverifiable payload")
    got = file_digest(path)
    if got != rec["sha256"]:
        raise ArtifactCorruptError(
            f"artifact {manifest.get('artifact')}/{name} is corrupt: "
            f"sha256 {got[:12]}… != manifest {rec['sha256'][:12]}…")
