"""paddlebox_tpu — a TPU-native large-scale sparse CTR training framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of PaddleBox
(Baidu's PaddlePaddle fork for trillion-feature CTR training; reference
layout documented in SURVEY.md):

- ``paddlebox_tpu.data``     — streaming slot dataset / data-feed pipeline
  (reference: paddle/fluid/framework/data_feed.*, data_set.*).
- ``paddlebox_tpu.ps``       — the embedding parameter server: HBM-resident,
  mesh-sharded feature table with sparse optimizers
  (reference: paddle/fluid/framework/fleet/box_wrapper.*, heter_ps/*).
- ``paddlebox_tpu.ops``      — CTR op library: fused_seqpool_cvm family,
  rank_attention, batch_fc, … (reference: paddle/fluid/operators/*).
- ``paddlebox_tpu.models``   — ctr_dnn / DeepFM / Wide&Deep / DCN-v2 /
  AdsRank (PV ads ranking with rank attention) / MMoE (multi-task).
- ``paddlebox_tpu.train``    — trainer runtime: pass lifecycle, jit train
  step, checkpointing (reference: framework/boxps_trainer.cc, boxps_worker.cc).
- ``paddlebox_tpu.parallel`` — mesh construction, collectives, shardings
  (reference: fleet/nccl_wrapper.*, gloo_wrapper.*, collective ops).
- ``paddlebox_tpu.metrics``  — bucketed AUC / WuAUC / metric registry
  (reference: fleet/metrics.{h,cc}).
"""

__version__ = "0.1.0"

from paddlebox_tpu import config as config
from paddlebox_tpu.config import FLAGS as FLAGS

# older jax lines lack jax.shard_map (it lives in jax.experimental);
# publish the translating shim before any subpackage builds a mesh step
from paddlebox_tpu.utils import jax_compat as _jax_compat

_jax_compat.install()
