"""Cross-host global-shuffle transport — the ``PaddleShuffler`` analogue.

Reference: PadBoxSlotDataset::ShuffleData (data_set.cc:2573): each MPI rank
routes every record to ``hash(record) % mpi_size``, serializes batches with
``BinaryArchive`` and sends them through the closed ``boxps::PaddleShuffler``
callbacks; peers collect into ``ReceiveSuffleData`` (:2681).

TPU-native redesign: the MPI plane is replaced by a plain TCP full mesh
over DCN (record exchange is host-side data plane, not accelerator
traffic — XLA collectives stay reserved for tensors inside jit). Records
travel in a compact self-describing binary layout (no pickle on the
wire), one length-framed buffer per (src, dst) pair. The route hash is
deterministic in (uid | ins_id | record content, seed) so every rank
computes the same placement without coordination.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu.data.dataset import Shuffler
from paddlebox_tpu.data.record import SlotRecord
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

_REC_HDR = struct.Struct("<iiii fff qqq ii")  # see serialize_records


def serialize_records(records: Sequence[SlotRecord]) -> bytes:
    """Records → one compact buffer (BinaryArchive role). Layout per
    record: fixed header (counts, scalars, metadata) followed by the
    keys/slot_offsets/dense arrays and the utf-8 ins_id."""
    parts: List[bytes] = [struct.pack("<q", len(records))]
    for r in records:
        keys = np.ascontiguousarray(r.keys, dtype=np.uint64)
        offs = np.ascontiguousarray(r.slot_offsets, dtype=np.int32)
        dense = np.ascontiguousarray(r.dense, dtype=np.float32)
        ins = r.ins_id.encode("utf-8")
        parts.append(_REC_HDR.pack(
            keys.size, offs.size, dense.size, len(ins),
            float(r.label), float(r.show), float(r.clk),
            int(r.search_id), int(r.uid), int(r.timestamp),
            int(r.rank), int(r.cmatch)))
        parts += [keys.tobytes(), offs.tobytes(), dense.tobytes(), ins]
    return b"".join(parts)


def deserialize_records(buf: bytes) -> List[SlotRecord]:
    (n,) = struct.unpack_from("<q", buf, 0)
    pos = 8
    out: List[SlotRecord] = []
    for _ in range(n):
        (nk, ns, nd, ni, label, show, clk, sid, uid, ts, rank,
         cmatch) = _REC_HDR.unpack_from(buf, pos)
        pos += _REC_HDR.size
        keys = np.frombuffer(buf, np.uint64, nk, pos).copy()
        pos += nk * 8
        offs = np.frombuffer(buf, np.int32, ns, pos).copy()
        pos += ns * 4
        dense = np.frombuffer(buf, np.float32, nd, pos).copy()
        pos += nd * 4
        ins = buf[pos:pos + ni].decode("utf-8")
        pos += ni
        out.append(SlotRecord(keys=keys, slot_offsets=offs, dense=dense,
                              label=label, show=show, clk=clk, ins_id=ins,
                              search_id=sid, uid=uid, timestamp=ts,
                              rank=rank, cmatch=cmatch))
    return out


def default_route(rec: SlotRecord, world: int, seed: int) -> int:
    """hash(record) % world — uid first (keeps user timelines on one host
    for the WuAUC/uid-merge paths), then ins_id, then record content."""
    if rec.uid:
        h = zlib.crc32(struct.pack("<qq", rec.uid, seed))
    elif rec.ins_id:
        h = zlib.crc32(rec.ins_id.encode() + struct.pack("<q", seed))
    else:
        h = zlib.crc32(rec.keys.tobytes() + struct.pack("<q", seed))
    return h % world


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = conn.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError("peer closed mid-message")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


class TcpMesh:
    """Full-mesh TCP byte exchange — the host-side data/metrics plane
    shared by the record shuffler (global shuffle) and the host
    collective (cross-worker metric allreduce, metrics.cc:288-304 role).

    ``endpoints`` — "host:port" per rank, index == rank. One
    :meth:`exchange_bytes` per round on every rank (a data barrier, like
    the reference's shuffler wait, data_set.cc:2681). A PERSISTENT
    listener thread drains peers continuously, so inter-round skew is
    bounded only by ``timeout``, never by socket buffers."""

    def __init__(self, rank: int, world: int, endpoints: Sequence[str],
                 timeout: float = 120.0) -> None:
        if len(endpoints) != world:
            raise ValueError("need one endpoint per rank")
        self.rank, self.world = rank, world
        self.endpoints = [(e.rsplit(":", 1)[0], int(e.rsplit(":", 1)[1]))
                          for e in endpoints]
        self.timeout = timeout
        self._round = 0
        # payloads stashed by (round, src). A PERSISTENT listener thread
        # accepts and drains continuously, so a fast peer's sendall never
        # blocks on our socket buffers while we are still training the
        # previous pass — inter-pass skew is bounded only by ``timeout``
        # against a genuinely dead peer, not by buffer sizes.
        self._stash: Dict[Tuple[int, int], bytes] = {}
        self._cv = threading.Condition()
        self._listen_err: Optional[BaseException] = None
        self._closed = False
        host, port = self.endpoints[rank]
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(world)
        self._listener = threading.Thread(target=self._listen_loop,
                                          daemon=True,
                                          name=f"shuffler-r{rank}")
        self._listener.start()

    @property
    def bound_port(self) -> int:
        return self._srv.getsockname()[1]

    def close(self) -> None:
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass

    def _listen_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return  # socket closed
            try:
                with conn:
                    conn.settimeout(self.timeout)
                    src, rnd, nbytes = struct.unpack(
                        "<iiq", _recv_exact(conn, 16))
                    payload = _recv_exact(conn, nbytes)
            except (OSError, ConnectionError, struct.error) as e:
                # stray probes / aborted sends are DROPPED, not fatal:
                # the listener lives for the whole process, and a health
                # check must not kill the next round (a genuinely lost
                # payload surfaces as that round's TimeoutError naming
                # the silent rank)
                log.warning("mesh listener: dropped bad connection (%s)",
                            e)
                continue
            with self._cv:
                if rnd < self._round:
                    self._listen_err = RuntimeError(
                        f"shuffle round mismatch: got stale round {rnd} "
                        f"from rank {src}, at {self._round}")
                else:
                    self._stash[(rnd, src)] = payload
                self._cv.notify_all()

    def _send_to(self, dst: int, payload: bytes,
                 errors: List[BaseException], rnd: int) -> None:
        # ``rnd`` is captured at spawn: exchange_bytes may advance
        # self._round (inbox complete) while a slow sender is still
        # writing — the stamp must stay this round's
        try:
            deadline = time.monotonic() + self.timeout
            delay = 0.05
            while True:
                try:
                    c = socket.create_connection(
                        self.endpoints[dst],
                        timeout=max(0.05, deadline - time.monotonic()))
                    break
                except socket.gaierror:
                    raise  # bad hostname — permanent, fail fast
                except OSError:
                    # peer hasn't bound its shuffler / its host or route
                    # is still coming up (ECONNREFUSED, ENETUNREACH,
                    # EHOSTUNREACH, timeouts) — retry until the deadline
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(delay)
                    delay = min(delay * 2, 1.0)
            with c:
                c.sendall(struct.pack("<iiq", self.rank, rnd,
                                      len(payload)))
                c.sendall(payload)
        except BaseException as e:
            errors.append(e)
            # wake exchange_bytes' inbox wait so a dead peer aborts the
            # round immediately instead of burning the full timeout
            with self._cv:
                self._cv.notify_all()

    def exchange_bytes(self, payloads: Dict[int, bytes]
                       ) -> Dict[int, bytes]:
        """One full-mesh round: send payloads[dst] to each peer, return
        {src: payload} for every other rank. All ranks must call once
        per round."""
        errors: List[BaseException] = []
        senders = []
        for dst in range(self.world):
            if dst == self.rank:
                continue
            t = threading.Thread(
                target=self._send_to,
                args=(dst, payloads[dst], errors, self._round),
                daemon=True)
            t.start()
            senders.append(t)
        # collect this round's payloads from the background listener while
        # the sender threads run; a send failure wakes the wait and aborts
        want = [(self._round, src) for src in range(self.world)
                if src != self.rank]
        deadline = time.monotonic() + self.timeout
        inbox: Dict[int, bytes] = {}
        with self._cv:
            while True:
                if self._listen_err is not None:
                    err, self._listen_err = self._listen_err, None
                    raise err
                if errors:
                    raise errors[0]
                for key in want:
                    if key in self._stash and key[1] not in inbox:
                        inbox[key[1]] = self._stash.pop(key)
                if len(inbox) == len(want):
                    break
                left = deadline - time.monotonic()
                if left <= 0 or not self._cv.wait(timeout=left):
                    missing = [k[1] for k in want if k[1] not in inbox]
                    raise TimeoutError(
                        f"mesh round {self._round}: no payload from "
                        f"ranks {missing} within {self.timeout}s")
            self._round += 1
        for t in senders:
            t.join()
        if errors:
            raise errors[0]
        return inbox


class TcpShuffler(TcpMesh, Shuffler):
    """Record global shuffle over the TCP mesh: rank i sends partition j
    to rank j and returns its own partition plus everything received —
    PadBoxSlotDataset::ShuffleData / ReceiveSuffleData."""

    def __init__(self, rank: int, world: int, endpoints: Sequence[str],
                 seed: int = 0,
                 route_fn: Optional[Callable[[SlotRecord, int, int], int]]
                 = None, timeout: float = 120.0) -> None:
        super().__init__(rank, world, endpoints, timeout=timeout)
        self.seed = seed
        self.route_fn = route_fn or default_route

    def exchange(self, records: List[SlotRecord]) -> List[SlotRecord]:
        parts: List[List[SlotRecord]] = [[] for _ in range(self.world)]
        for r in records:
            parts[self.route_fn(r, self.world, self.seed)].append(r)
        inbox = self.exchange_bytes(
            {dst: serialize_records(parts[dst])
             for dst in range(self.world) if dst != self.rank})
        out = list(parts[self.rank])
        kept = len(out)
        for src in sorted(inbox):
            out.extend(deserialize_records(inbox[src]))
        log.info("shuffle r%d: kept %d, received %d records", self.rank,
                 kept, len(out) - kept)
        return out

    def allgather(self, records: List[SlotRecord]) -> List[SlotRecord]:
        """Every rank returns EVERY rank's records, in rank order (rank
        0's first) with each rank's original order preserved —
        deterministic and identical on all ranks. This is the host data
        plane of multi-controller SPMD training (train/multihost.py):
        each host reads only its own file shard, then allgathers so
        every process builds byte-identical global batches and routing
        plans. O(world) duplication — intended for host-count ≪
        chip-count jobs (one process per host)."""
        blob = serialize_records(records)
        inbox = self.exchange_bytes(
            {dst: blob for dst in range(self.world)
             if dst != self.rank})
        out: List[SlotRecord] = []
        for src in range(self.world):
            if src == self.rank:
                out.extend(records)
            else:
                out.extend(deserialize_records(inbox[src]))
        log.info("allgather r%d: %d local -> %d global records",
                 self.rank, len(records), len(out))
        return out
