"""Network KV backend for the elastic manager — the etcd stand-in.

Reference: fleet elastic uses an etcd cluster for host leases, scale
events and the checkpoint pointer (fleet/elastic/manager.py:131 lease +
watch, :248-250 endpoints). The TPU framework's ElasticManager speaks
the tiny :class:`~paddlebox_tpu.distributed.elastic.KVStore` interface
(put/get/delete/list_prefix/mtime — leases are heartbeat keys + mtime,
watches are polls), so a single-process TCP server covers the whole
contract without a shared filesystem: run :class:`KVServer` anywhere
reachable (e.g. alongside rank 0 or a scheduler), point every host's
:class:`TcpKVStore` at it.

Wire protocol (length-framed, one request per connection round):
  request : op u8 | klen u32 | key | vlen u64 | value
  response: ok u8 | vlen u64 | value
ops: 1=PUT 2=GET 3=DEL 4=LIST(prefix) 5=MTIME 6=TOUCH. LIST value =
repeated [klen u32 | key | vlen u64 | value]; MTIME value = the entry's
AGE in seconds as f64 (server now − write stamp) — ages, not absolute
timestamps, so lease liveness is immune to cross-host clock skew. TOUCH
refreshes the stamp without rewriting the payload (the heartbeat op);
its value is 1 byte: 1=refreshed, 0=key gone (lease was deleted)."""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Dict, Optional, Tuple

from paddlebox_tpu.distributed.elastic import KVStore
from paddlebox_tpu.distributed.shuffle import _recv_exact
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

_PUT, _GET, _DEL, _LIST, _MTIME, _TOUCH = 1, 2, 3, 4, 5, 6
_MAX_KEY = 1 << 16   # sanity caps: elastic keys/payloads are tiny;
_MAX_VAL = 1 << 26   # anything bigger is a stray/garbage connection
_VERY_OLD = 1e12     # age reported for missing keys


class KVServer:
    """Threaded in-memory KV server (one handler thread per connection;
    dict + lock — elastic traffic is heartbeats, not a datastore)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._data: Dict[str, Tuple[bytes, float]] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="kv-server")
        self._thread.start()

    @property
    def endpoint(self) -> str:
        h, p = self._srv.getsockname()
        return f"{h}:{p}"

    def close(self) -> None:
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            with conn:
                # reap half-open dead peers without an idle cap: keepalive
                # probes detect a power-failed/partitioned client, while a
                # quiet-but-alive TcpKVStore connection (poll cadence can
                # exceed any fixed idle timeout) is never dropped
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
                tuned_keepalive = False
                # linux spelling, then the macOS one (TCP_KEEPALIVE is
                # its idle-seconds knob) — tuned keepalive means a dead
                # peer is probed within ~2 min instead of the OS default
                # first probe at ~2h
                if hasattr(socket, "TCP_KEEPIDLE"):
                    conn.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_KEEPIDLE, 60)
                    tuned_keepalive = True
                elif hasattr(socket, "TCP_KEEPALIVE"):
                    conn.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_KEEPALIVE, 60)
                    tuned_keepalive = True
                if tuned_keepalive:
                    if hasattr(socket, "TCP_KEEPINTVL"):
                        conn.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_KEEPINTVL, 15)
                    if hasattr(socket, "TCP_KEEPCNT"):
                        conn.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_KEEPCNT, 4)
                # without ANY idle tuning, cap idle generously so a dead
                # peer can't pin this handler thread for hours; the cost
                # is that a quiet-but-alive client slower than the cap
                # reconnects (logged as idle, not as garbage)
                idle_timeout = None if tuned_keepalive else 900.0
                while True:
                    # idle between requests: tuned keepalive (above) owns
                    # dead-peer reaping with no idle cap — a quiet-but-alive
                    # TcpKVStore connection (poll cadence can exceed any
                    # fixed idle timeout) is never dropped on tuned
                    # platforms
                    conn.settimeout(idle_timeout)
                    try:
                        hdr = conn.recv(1)
                    except socket.timeout:
                        log.info("kv server: closing idle connection "
                                 "(>%.0fs, untuned-keepalive platform)",
                                 idle_timeout)
                        return
                    if not hdr:
                        return
                    # mid-request: a short timeout so a half-written
                    # request can't wedge the handler thread
                    conn.settimeout(30.0)
                    op = hdr[0]
                    (klen,) = struct.unpack("<I", _recv_exact(conn, 4))
                    if not _PUT <= op <= _TOUCH or klen > _MAX_KEY:
                        raise ValueError(f"bad kv request op={op}")
                    key = _recv_exact(conn, klen).decode("utf-8")
                    (vlen,) = struct.unpack("<Q", _recv_exact(conn, 8))
                    if vlen > _MAX_VAL:
                        raise ValueError(f"kv value too large ({vlen})")
                    value = _recv_exact(conn, vlen) if vlen else b""
                    resp = self._apply(op, key, value)
                    conn.sendall(b"\x01" + struct.pack("<Q", len(resp))
                                 + resp)
        except (OSError, ConnectionError, struct.error, ValueError,
                UnicodeDecodeError) as e:
            # garbage connections are dropped, never crash the handler
            log.warning("kv server: dropped bad connection (%s)", e)

    def _apply(self, op: int, key: str, value: bytes) -> bytes:
        with self._lock:
            if op == _PUT:
                self._data[key] = (value, time.time())
                return b""
            if op == _GET:
                ent = self._data.get(key)
                return b"\x00" if ent is None else b"\x01" + ent[0]
            if op == _DEL:
                self._data.pop(key, None)
                return b""
            if op == _LIST:
                parts = []
                for k, (v, _) in self._data.items():
                    if k.startswith(key):
                        kb = k.encode("utf-8")
                        parts.append(struct.pack("<I", len(kb)) + kb
                                     + struct.pack("<Q", len(v)) + v)
                return b"".join(parts)
            if op == _MTIME:
                ent = self._data.get(key)
                age = (time.time() - ent[1]) if ent else _VERY_OLD
                return struct.pack("<d", age)
            if op == _TOUCH:
                ent = self._data.get(key)
                if ent is None:
                    return b"\x00"
                self._data[key] = (ent[0], time.time())
                return b"\x01"
        raise ValueError(f"bad kv op {op}")


class TcpKVStore(KVStore):
    """KVStore client against a :class:`KVServer` endpoint — drop-in for
    FileKVStore, no shared filesystem needed. One persistent connection
    per store (heartbeat cadence), reconnects on failure."""

    def __init__(self, endpoint: str, timeout: float = 10.0) -> None:
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = timeout
        self._conn: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def _close_locked(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    def _request(self, op: int, key: str, value: bytes = b"") -> bytes:
        kb = key.encode("utf-8")
        msg = (bytes([op]) + struct.pack("<I", len(kb)) + kb
               + struct.pack("<Q", len(value)) + value)
        with self._lock:
            for attempt in (0, 1):  # one reconnect on a stale socket
                try:
                    if self._conn is None:
                        self._conn = socket.create_connection(
                            self._addr, timeout=self._timeout)
                    self._conn.sendall(msg)
                    ok = _recv_exact(self._conn, 1)
                    (vlen,) = struct.unpack(
                        "<Q", _recv_exact(self._conn, 8))
                    body = _recv_exact(self._conn, vlen) if vlen else b""
                    if ok != b"\x01":
                        raise ConnectionError("kv server error")
                    return body
                except (OSError, ConnectionError):
                    self._close_locked()
                    if attempt:
                        raise
        raise ConnectionError("unreachable")  # pragma: no cover

    def put(self, key: str, value: bytes) -> None:
        self._request(_PUT, key, value)

    def get(self, key: str) -> Optional[bytes]:
        body = self._request(_GET, key)
        return None if body[:1] == b"\x00" else body[1:]

    def delete(self, key: str) -> None:
        self._request(_DEL, key)

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        body = self._request(_LIST, prefix)
        out: Dict[str, bytes] = {}
        pos = 0
        while pos < len(body):
            (klen,) = struct.unpack_from("<I", body, pos)
            pos += 4
            k = body[pos:pos + klen].decode("utf-8")
            pos += klen
            (vlen,) = struct.unpack_from("<Q", body, pos)
            pos += 8
            out[k] = body[pos:pos + vlen]
            pos += vlen
        return out

    def mtime(self, key: str) -> float:
        """Write time in THIS host's clock: the server reports the
        entry's AGE and we subtract locally, so lease checks
        (now − mtime ≤ ttl) are immune to cross-host clock skew."""
        (age,) = struct.unpack("<d", self._request(_MTIME, key))
        return max(time.time() - age, 0.0)

    def touch(self, key: str) -> bool:
        """Refresh the lease stamp server-side without resending the
        payload — the heartbeat op; False when the lease was deleted."""
        return self._request(_TOUCH, key) == b"\x01"
