"""Multi-process / multi-host launcher.

Reference: python/paddle/distributed/launch.py + fleet/launch.py (spawn
one trainer process per device, export PADDLE_TRAINER_ID /
PADDLE_TRAINER_ENDPOINTS, restart on failure when elastic is on).

TPU-native redesign: on TPU one *process per host* drives all local chips
(JAX SPMD), so the launcher's unit is the host, not the device. It

- exports ``PBOX_*`` env (rank, world size, coordinator address) and, for
  multi-host, hands them to ``jax.distributed.initialize`` via
  ``init_runtime_env()`` called from the worker;
- can spawn N local worker processes to emulate a multi-host job on one
  machine (tests / CPU-mesh dev), each seeing a disjoint rank;
- integrates ElasticManager: on a worker death (or scale event) it stops
  the survivors and restarts everyone from the latest published
  checkpoint pointer.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

from paddlebox_tpu.distributed.elastic import ElasticManager, FileKVStore
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

ENV_RANK = "PBOX_RANK"
ENV_WORLD = "PBOX_WORLD_SIZE"
ENV_COORD = "PBOX_COORDINATOR"
ENV_RESUME = "PBOX_RESUME_CKPT"


@dataclasses.dataclass
class LaunchConfig:
    nproc: int = 1                      # local worker processes
    coordinator: str = "127.0.0.1:8476"
    job_id: str = "default"
    elastic_root: Optional[str] = None  # KV dir; enables elastic restarts
    # network KV (host:port of a KVServer) — elastic restarts with NO
    # shared filesystem (TcpKVStore; overrides elastic_root)
    elastic_endpoint: Optional[str] = None
    max_restarts: int = 3
    stop_grace_sec: float = 5.0


def init_runtime_env() -> Dict[str, int]:
    """Worker-side bootstrap: read the env the launcher exported and, when
    the job is actually multi-process, initialize the JAX distributed
    runtime (coordinator rendezvous over DCN)."""
    rank = int(os.environ.get(ENV_RANK, "0"))
    world = int(os.environ.get(ENV_WORLD, "1"))
    if world > 1 and os.environ.get("PBOX_JAX_DISTRIBUTED", "0") == "1":
        import jax

        jax.distributed.initialize(
            coordinator_address=os.environ[ENV_COORD],
            num_processes=world, process_id=rank)
    return {"rank": rank, "world_size": world}


def _spawn(cmd: Sequence[str], rank: int, world: int, cfg: LaunchConfig,
           resume: Optional[str]) -> subprocess.Popen:
    env = dict(os.environ)
    env[ENV_RANK] = str(rank)
    env[ENV_WORLD] = str(world)
    env[ENV_COORD] = cfg.coordinator
    if resume:
        env[ENV_RESUME] = resume
    return subprocess.Popen(list(cmd), env=env)


def _stop_all(procs: List[subprocess.Popen], grace: float) -> None:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.time() + grace
    for p in procs:
        left = max(0.1, deadline - time.time())
        try:
            p.wait(timeout=left)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()


def launch_local(cmd: Sequence[str], cfg: LaunchConfig) -> int:
    """Run ``cmd`` as cfg.nproc rank-stamped local processes; restart the
    gang (from the latest checkpoint pointer) on failure when elastic is
    enabled. Returns the final exit code (0 = all ranks clean)."""
    manager: Optional[ElasticManager] = None
    if cfg.elastic_endpoint or cfg.elastic_root:
        if cfg.elastic_endpoint:
            from paddlebox_tpu.distributed.kv_server import TcpKVStore
            kv = TcpKVStore(cfg.elastic_endpoint)
        else:
            kv = FileKVStore(cfg.elastic_root)
        manager = ElasticManager(
            kv, cfg.job_id,
            host=f"local-{os.getpid()}", np=1, ttl=10.0)
        manager.register()

    restarts = 0
    try:
        while True:
            resume = None
            if manager is not None:
                ckpt = manager.latest_checkpoint()
                if ckpt:
                    resume = ckpt["path"]
                    log.info("starting gang from checkpoint %s", resume)
            procs = [_spawn(cmd, r, cfg.nproc, cfg, resume)
                     for r in range(cfg.nproc)]
            # poll instead of wait: one crashed rank must not leave hung
            # survivors blocking the restart (peer-loss in a collective)
            failed = False
            while True:
                codes = [p.poll() for p in procs]
                if any(c is not None and c != 0 for c in codes):
                    failed = True
                    break
                if all(c == 0 for c in codes):
                    break
                if manager is not None and manager.scale_event() is not None:
                    log.warning("membership changed; restarting gang")
                    failed = True
                    break
                time.sleep(0.05)
            if not failed:
                return 0
            codes = [p.poll() for p in procs]
            log.warning("gang failed with codes %s", codes)
            _stop_all(procs, cfg.stop_grace_sec)
            codes = [p.returncode for p in procs]
            restarts += 1
            if manager is None or restarts > cfg.max_restarts:
                return max((c for c in codes if c), default=1)
            log.info("elastic restart %d/%d", restarts, cfg.max_restarts)
    finally:
        if manager is not None:
            manager.deregister()


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddlebox_tpu.distributed.launch",
        description="PaddleBox-TPU job launcher")
    ap.add_argument("--nproc", type=int, default=1,
                    help="local worker processes (emulated hosts)")
    ap.add_argument("--coordinator", default="127.0.0.1:8476")
    ap.add_argument("--job-id", default="default")
    ap.add_argument("--elastic-root", default=None,
                    help="shared KV dir; enables elastic restart")
    ap.add_argument("--elastic-endpoint", default=None,
                    help="KVServer host:port (network KV, no shared "
                         "filesystem); enables elastic restart and "
                         "overrides --elastic-root")
    ap.add_argument("--max-restarts", type=int, default=3)
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="worker command (e.g. python train.py ...)")
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("missing worker command")
    cfg = LaunchConfig(nproc=args.nproc, coordinator=args.coordinator,
                       job_id=args.job_id, elastic_root=args.elastic_root,
                       elastic_endpoint=args.elastic_endpoint,
                       max_restarts=args.max_restarts)
    return launch_local(cmd, cfg)


if __name__ == "__main__":
    sys.exit(main())
