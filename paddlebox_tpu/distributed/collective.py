"""Host-side collectives over the TCP mesh — the MPI metric allreduce.

Reference: BasicAucCalculator's cross-worker reduce
(fleet/metrics.cc:288-304): every trainer allreduces its 1e6-bucket
pos/neg tables plus the scalar error sums over MPI before computing one
GLOBAL AUC. XLA collectives cover tensors inside jit on one mesh; this
plane covers the MULTI-PROCESS world (launcher + TcpShuffler ranks),
where metric state lives in host numpy between passes."""

from __future__ import annotations

import struct
from typing import List, Sequence

import numpy as np

from paddlebox_tpu.distributed.shuffle import TcpMesh


class TcpCollective(TcpMesh):
    """allreduce over host float arrays on the full TCP mesh. Small
    worlds (CPU trainer fleets): allgather + local sum, one round."""

    def allreduce_sum(self, arrays: Sequence[np.ndarray]
                      ) -> List[np.ndarray]:
        blob = _pack(arrays)
        inbox = self.exchange_bytes(
            {dst: blob for dst in range(self.world) if dst != self.rank})
        # fold in FIXED rank order (own contribution at its own rank) so
        # the f64 sums — and anything decided from them — are
        # bit-identical on every rank
        mine = [np.asarray(a, np.float64) for a in arrays]
        out = [np.zeros_like(a) for a in mine]
        for src in range(self.world):
            theirs = mine if src == self.rank else _unpack(inbox[src])
            for acc, t in zip(out, theirs):
                if acc.shape != t.shape:
                    raise ValueError(
                        f"allreduce shape mismatch vs rank {src}: "
                        f"{acc.shape} != {t.shape}")
                acc += t
        return out


def _pack(arrays: Sequence[np.ndarray]) -> bytes:
    parts = [struct.pack("<i", len(arrays))]
    for a in arrays:
        # NOT ascontiguousarray: it promotes 0-d scalars to 1-d and the
        # shape must round-trip exactly for the allreduce shape check
        a = np.asarray(a, np.float64, order="C")
        parts.append(struct.pack("<i", a.ndim))
        parts.append(struct.pack(f"<{a.ndim}q", *a.shape))
        parts.append(a.tobytes())
    return b"".join(parts)


def _unpack(buf: bytes) -> List[np.ndarray]:
    (n,) = struct.unpack_from("<i", buf, 0)
    pos = 4
    out = []
    for _ in range(n):
        (ndim,) = struct.unpack_from("<i", buf, pos)
        pos += 4
        shape = struct.unpack_from(f"<{ndim}q", buf, pos)
        pos += 8 * ndim
        size = int(np.prod(shape)) if ndim else 1
        out.append(np.frombuffer(buf, np.float64, size, pos)
                   .reshape(shape).copy())
        pos += 8 * size
    return out
