"""Elastic membership / fault detection.

Reference: python/paddle/distributed/fleet/elastic/manager.py:131
(ElasticManager) — etcd-backed: each node registers under a job prefix
with a TTL lease refreshed by a heartbeat thread; a watch on the node
prefix fires scale events; np (node count) may float in [min_np, max_np]
(ELASTIC level) or must stay fixed (FAULT_TOLERANCE level, restart only).

TPU-native redesign: no etcd dependency — membership rides a pluggable
``KVStore``. The default ``FileKVStore`` uses a shared directory (works
for multi-process single host and for multi-host over NFS/GCS-fuse; the
JAX distributed coordinator handles the device runtime itself, this layer
only decides *when to restart and with how many hosts*). Leases are
mtime-based: a key is alive while its last heartbeat is younger than the
TTL.

Hardening + protocol (docs/RESILIENCE.md §Elastic membership):

* ``put`` publishes through ``utils.fsio.atomic_write_bytes`` — fsync
  before the rename, so a host crash can't leave a torn or
  empty-but-visible lease for survivors to mis-read.
* Heartbeats refresh the lease with ``touch`` (an ``os.utime`` on the
  lease file) instead of the old get-then-put: a concurrent payload
  update can no longer be resurrected with stale bytes, and a *deleted*
  lease (watchdog eviction, explicit deregister) stops the heartbeat
  thread instead of silently re-creating the lease — a rejoin requires
  an explicit ``register()``.
* Key escaping is reversible (percent-encoding): a host name containing
  ``__`` or ``/`` round-trips through ``list_prefix`` intact.
* Dead-rank detection carries ``for_count``-style hysteresis
  (``dead_checks``): a host missing from one ``alive_hosts()`` poll — a
  delayed-but-alive heartbeat, an NFS hiccup — does NOT fire a scale
  event; only ``dead_checks`` consecutive misses (or an explicit
  ``evict_host``) confirm the death. Joins are admitted immediately.
* Every KV op passes the ``elastic.kv`` fault seam and manager-level
  reads retry transient failures on the seeded ``RetryPolicy``
  (site ``elastic.kv``); rendezvous polls pass ``elastic.rendezvous``.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse
from typing import Dict, List, Optional, Set

from paddlebox_tpu.resilience import faults
from paddlebox_tpu.resilience.retry import RetryPolicy, TransientError
from paddlebox_tpu.utils.fsio import atomic_write_bytes
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class ElasticLevel:
    FAULT_TOLERANCE = 1  # fixed np; dead node ⇒ wait for it to come back
    ELASTIC = 2          # np floats in [min_np, max_np]


class KVStore:
    """Minimal KV interface the manager needs (etcd analogue)."""

    def put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        raise NotImplementedError

    def mtime(self, key: str) -> float:
        raise NotImplementedError

    def touch(self, key: str) -> bool:
        """Refresh the key's lease mtime WITHOUT rewriting its payload.
        Returns False when the key no longer exists (deleted lease — the
        holder was evicted or deregistered)."""
        raise NotImplementedError


class FileKVStore(KVStore):
    """Shared-directory KV store; key = relative path, one file per key.

    Keys are flattened to single filenames via percent-encoding
    (``urllib.parse.quote(..., safe="")``), which is reversible — unlike
    the old ``/``→``__`` scheme, a host name that itself contains ``__``
    survives the ``list_prefix`` round trip. Quoting is per-character,
    so logical-prefix matching reduces to filename-prefix matching.
    """

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, self._escape(key))

    @staticmethod
    def _escape(key: str) -> str:
        return urllib.parse.quote(key.strip("/"), safe="")

    @staticmethod
    def _unescape(name: str) -> str:
        return urllib.parse.unquote(name)

    def put(self, key: str, value: bytes) -> None:
        faults.inject("elastic.kv", op="put", key=key)
        # fsync'd atomic publish: a crashed writer can't leave a torn
        # lease, and the payload is durable before it becomes visible
        atomic_write_bytes(self._path(key), value)

    def get(self, key: str) -> Optional[bytes]:
        faults.inject("elastic.kv", op="get", key=key)
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def delete(self, key: str) -> None:
        faults.inject("elastic.kv", op="delete", key=key)
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        faults.inject("elastic.kv", op="list", key=prefix)
        pfx = self._escape(prefix)
        out: Dict[str, bytes] = {}
        for name in os.listdir(self.root):
            if name.startswith(pfx) and ".tmp" not in name:
                try:
                    with open(os.path.join(self.root, name), "rb") as f:
                        out[self._unescape(name)] = f.read()
                except FileNotFoundError:
                    continue
        return out

    def mtime(self, key: str) -> float:
        faults.inject("elastic.kv", op="mtime", key=key)
        try:
            return os.stat(self._path(key)).st_mtime
        except FileNotFoundError:
            return 0.0

    def touch(self, key: str) -> bool:
        faults.inject("elastic.kv", op="touch", key=key)
        try:
            os.utime(self._path(key), None)
            return True
        except FileNotFoundError:
            return False


class ElasticManager:
    """Per-node membership agent.

    Usage: ``register()`` once, keep the heartbeat alive; the launcher
    polls ``scale_event()`` and, on a change, stops workers at the pass
    boundary, waits for ``wait_for_np()``, and restarts them from the
    latest checkpoint (re-sharded to the new world size — see
    ``train.multihost.ElasticStreamRunner``).

    ``dead_checks`` is the detection hysteresis: a host must be missing
    from that many *consecutive* ``scale_event()`` polls before it is
    confirmed dead (``evict_host`` bypasses the grace — an explicit
    eviction is already a confirmed decision). Joins take effect on the
    first poll that sees them.
    """

    def __init__(self, store: KVStore, job_id: str, host: str,
                 np: int, min_np: int = 0, max_np: int = 0,
                 ttl: float = 10.0, heartbeat_period: Optional[float] = None,
                 dead_checks: int = 1) -> None:
        self.store = store
        self.prefix = f"paddlebox/{job_id}"
        self.node_prefix = f"{self.prefix}/nodes"
        self.host = host
        self.np = np
        self.min_np = min_np or np
        self.max_np = max_np or np
        self.ttl = ttl
        self.heartbeat_period = heartbeat_period or ttl / 3.0
        self.dead_checks = max(int(dead_checks), 1)
        self.level = (ElasticLevel.ELASTIC if self.max_np > self.min_np
                      else ElasticLevel.FAULT_TOLERANCE)
        self._hb_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._key = f"{self.node_prefix}/{host}"
        self._members: Optional[List[str]] = None
        self._miss_counts: Dict[str, int] = {}
        self._forced_dead: Set[str] = set()
        self._retry = RetryPolicy.from_flags(site="elastic.kv")
        self.last_scale_event_ts = 0.0
        self.last_event: Optional[dict] = None
        self.reshard_count = 0

    # -- membership ---------------------------------------------------------

    def register(self, payload: Optional[dict] = None) -> None:
        body = dict(payload or {})
        body["host"] = self.host
        self._retry.call(self.store.put, self._key,
                         json.dumps(body).encode())
        self._stop.clear()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name=f"elastic-hb-{self.host}")
        self._hb_thread.start()
        self._register_probe()

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_period):
            try:
                alive = self._retry.call(self.store.touch, self._key)
            except Exception:
                # a failed refresh is survivable while the lease TTL
                # holds; the next beat retries
                log.warning("elastic heartbeat refresh failed (%s)",
                            self.host, exc_info=True)
                continue
            if not alive:
                # lease file gone = we were evicted (or deregistered by
                # another thread): do NOT resurrect it — rejoining the
                # job requires an explicit register()
                log.warning("elastic lease for %s disappeared; stopping "
                            "heartbeat (evicted?)", self.host)
                return

    def deregister(self) -> None:
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2 * self.heartbeat_period)
            self._hb_thread = None
        self.store.delete(self._key)

    def alive_hosts(self) -> List[str]:
        now = time.time()
        hosts = []
        listing = self._retry.call(self.store.list_prefix, self.node_prefix)
        for key in listing:
            if now - self.store.mtime(key) <= self.ttl:
                hosts.append(key.rsplit("/", 1)[-1])
        return sorted(hosts)

    # -- events -------------------------------------------------------------

    def scale_event(self) -> Optional[List[str]]:
        """Returns the new effective-membership list when it changed
        since the last call (the etcd watch-callback analogue), else
        None. A host only *leaves* the effective membership after
        ``dead_checks`` consecutive polls without a fresh lease, or an
        explicit ``evict_host`` — one delayed heartbeat is not a death.
        """
        fresh = set(self.alive_hosts())
        if self._members is None:
            self._members = sorted(fresh)
            return None
        members = set(self._members)
        for host in fresh:
            self._miss_counts.pop(host, None)
            self._forced_dead.discard(host)  # re-registered: clean slate
        confirmed_dead: Set[str] = set()
        for host in members - fresh:
            if host in self._forced_dead:
                confirmed_dead.add(host)
                continue
            misses = self._miss_counts.get(host, 0) + 1
            self._miss_counts[host] = misses
            if misses >= self.dead_checks:
                confirmed_dead.add(host)
        effective = sorted((members - confirmed_dead) | fresh)
        if effective == self._members:
            return None
        lost = sorted(members - set(effective))
        joined = sorted(set(effective) - members)
        log.info("scale event: %s -> %s (lost=%s joined=%s)",
                 self._members, effective, lost, joined)
        self._members = effective
        for host in lost:
            self._miss_counts.pop(host, None)
            self._forced_dead.discard(host)
        self.last_scale_event_ts = time.time()
        self.last_event = {"hosts": effective, "lost": lost,
                           "joined": joined,
                           "ts": self.last_scale_event_ts}
        self._observe_event(effective, lost, joined)
        return effective

    def evict_host(self, host: str, reason: str = "") -> None:
        """Force-remove ``host`` from the membership (the watchdog
        shrink-and-continue rung): delete its lease so its heartbeat
        thread stops at the next beat, and bypass the dead-check grace —
        the next ``scale_event()`` confirms the removal immediately."""
        log.warning("elastic: evicting host %s (%s)", host, reason or "-")
        self._forced_dead.add(host)
        self.store.delete(f"{self.node_prefix}/{host}")

    def world_ok(self) -> bool:
        n = len(self.alive_hosts())
        if self.level == ElasticLevel.FAULT_TOLERANCE:
            return n == self.np
        return self.min_np <= n <= self.max_np

    def wait_for_np(self, timeout: float = 60.0) -> List[str]:
        """Block until the alive set satisfies the level constraints
        (= the rendezvous barrier before a restart). On timeout the
        error names the hosts that were expected but missing."""
        deadline = time.time() + timeout
        attempt = 0
        while time.time() < deadline:
            attempt += 1
            try:
                faults.inject("elastic.rendezvous", attempt=attempt)
                if self.world_ok():
                    hosts = self.alive_hosts()
                    self._members = hosts
                    self._miss_counts.clear()
                    return hosts
            except TransientError:
                # a flaky poll (injected or real) is just a missed
                # observation; the rendezvous window absorbs it
                log.warning("elastic rendezvous poll %d failed; retrying",
                            attempt, exc_info=True)
            time.sleep(self.heartbeat_period)
        alive = []
        try:
            alive = self.alive_hosts()
        except Exception:
            log.warning("elastic rendezvous: final alive poll failed",
                        exc_info=True)
        missing = sorted(set(self._members or []) - set(alive))
        raise TimeoutError(
            f"elastic rendezvous: alive={alive} does not satisfy "
            f"np∈[{self.min_np},{self.max_np}] within {timeout}s"
            + (f"; missing hosts: {missing}" if missing else ""))

    # -- checkpoint pointer (restart resume source) -------------------------

    def publish_checkpoint(self, path: str, pass_id: int) -> None:
        self._retry.call(
            self.store.put, f"{self.prefix}/ckpt",
            json.dumps({"path": path, "pass_id": pass_id}).encode())

    def latest_checkpoint(self) -> Optional[dict]:
        raw = self._retry.call(self.store.get, f"{self.prefix}/ckpt")
        return json.loads(raw) if raw else None

    # -- observability ------------------------------------------------------

    def note_reshard(self, old_np: int, new_np: int, step: int = -1) -> None:
        """Record one completed re-shard (the controller calls this after
        the world is rebuilt at the new size)."""
        self.reshard_count += 1
        try:
            from paddlebox_tpu.obs.hub import get_hub
            hub = get_hub()
            if hub.active:
                hub.counter("pbox_membership_reshards_total",
                            "completed elastic re-shards").inc()
                hub.emit("reshard", old_np=old_np, new_np=new_np,
                         step=step, count=self.reshard_count)
        except Exception:
            log.debug("reshard bookkeeping failed", exc_info=True)

    def membership_status(self) -> dict:
        """The /healthz ``membership`` block (hub membership probe)."""
        members = list(self._members or [])
        return {
            "host": self.host,
            "alive": members,
            "np": len(members) if self._members is not None else self.np,
            "target_np": self.np,
            "min_np": self.min_np,
            "max_np": self.max_np,
            "level": ("ELASTIC" if self.level == ElasticLevel.ELASTIC
                      else "FAULT_TOLERANCE"),
            "last_scale_event_ts": self.last_scale_event_ts,
            "reshard_count": self.reshard_count,
        }

    def _register_probe(self) -> None:
        try:
            from paddlebox_tpu.obs.hub import get_hub
            get_hub().set_membership_probe(self.membership_status)
        except Exception:
            log.debug("membership probe registration failed", exc_info=True)

    def _observe_event(self, hosts: List[str], lost: List[str],
                       joined: List[str]) -> None:
        try:
            from paddlebox_tpu.obs.hub import get_hub
            hub = get_hub()
            if hub.active:
                hub.gauge("pbox_membership_alive",
                          "effective membership size").set(len(hosts))
                hub.gauge("pbox_membership_degraded",
                          "1 while membership below target np").set(
                              1.0 if len(hosts) < self.np else 0.0)
                ctr = hub.counter("pbox_membership_scale_events_total",
                                  "membership scale events")
                if lost:
                    ctr.inc(len(lost), direction="lost")
                if joined:
                    ctr.inc(len(joined), direction="joined")
                hub.emit("membership_change", hosts=list(hosts),
                         lost=list(lost), joined=list(joined),
                         np=len(hosts), target_np=self.np)
        except Exception:
            log.debug("membership event bookkeeping failed", exc_info=True)
        try:
            from paddlebox_tpu.obs import flightrec
            flightrec.trigger(
                "membership_change",
                reason=f"lost={lost} joined={joined}",
                hosts=list(hosts), np=len(hosts), target_np=self.np)
        except Exception:
            log.debug("membership flightrec trigger failed", exc_info=True)
