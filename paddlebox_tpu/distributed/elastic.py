"""Elastic membership / fault detection.

Reference: python/paddle/distributed/fleet/elastic/manager.py:131
(ElasticManager) — etcd-backed: each node registers under a job prefix
with a TTL lease refreshed by a heartbeat thread; a watch on the node
prefix fires scale events; np (node count) may float in [min_np, max_np]
(ELASTIC level) or must stay fixed (FAULT_TOLERANCE level, restart only).

TPU-native redesign: no etcd dependency — membership rides a pluggable
``KVStore``. The default ``FileKVStore`` uses a shared directory (works
for multi-process single host and for multi-host over NFS/GCS-fuse; the
JAX distributed coordinator handles the device runtime itself, this layer
only decides *when to restart and with how many hosts*). Leases are
mtime-based: a key is alive while its last heartbeat is younger than the
TTL.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class ElasticLevel:
    FAULT_TOLERANCE = 1  # fixed np; dead node ⇒ wait for it to come back
    ELASTIC = 2          # np floats in [min_np, max_np]


class KVStore:
    """Minimal KV interface the manager needs (etcd analogue)."""

    def put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        raise NotImplementedError

    def mtime(self, key: str) -> float:
        raise NotImplementedError


class FileKVStore(KVStore):
    """Shared-directory KV store; key = relative path, one file per key."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        safe = key.strip("/").replace("/", "__")
        return os.path.join(self.root, safe)

    def put(self, key: str, value: bytes) -> None:
        path = self._path(key)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(value)
        os.replace(tmp, path)  # atomic publish

    def get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        pfx = prefix.strip("/").replace("/", "__")
        out: Dict[str, bytes] = {}
        for name in os.listdir(self.root):
            if name.startswith(pfx) and not name.endswith(".tmp"):
                try:
                    with open(os.path.join(self.root, name), "rb") as f:
                        out[name.replace("__", "/")] = f.read()
                except FileNotFoundError:
                    continue
        return out

    def mtime(self, key: str) -> float:
        try:
            return os.stat(self._path(key)).st_mtime
        except FileNotFoundError:
            return 0.0


class ElasticManager:
    """Per-node membership agent.

    Usage: ``register()`` once, keep the heartbeat alive; the launcher
    polls ``scale_event()`` and, on a change, stops workers, waits for
    ``wait_for_np()``, and restarts them from the latest checkpoint.
    """

    def __init__(self, store: KVStore, job_id: str, host: str,
                 np: int, min_np: int = 0, max_np: int = 0,
                 ttl: float = 10.0, heartbeat_period: Optional[float] = None
                 ) -> None:
        self.store = store
        self.prefix = f"paddlebox/{job_id}"
        self.node_prefix = f"{self.prefix}/nodes"
        self.host = host
        self.np = np
        self.min_np = min_np or np
        self.max_np = max_np or np
        self.ttl = ttl
        self.heartbeat_period = heartbeat_period or ttl / 3.0
        self.level = (ElasticLevel.ELASTIC if self.max_np > self.min_np
                      else ElasticLevel.FAULT_TOLERANCE)
        self._hb_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._key = f"{self.node_prefix}/{host}"
        self._last_hosts: Optional[List[str]] = None

    # -- membership ---------------------------------------------------------

    def register(self, payload: Optional[dict] = None) -> None:
        body = dict(payload or {})
        body["host"] = self.host
        self.store.put(self._key, json.dumps(body).encode())
        self._stop.clear()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True)
        self._hb_thread.start()

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_period):
            raw = self.store.get(self._key) or b"{}"
            self.store.put(self._key, raw)  # refresh lease mtime

    def deregister(self) -> None:
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2 * self.heartbeat_period)
            self._hb_thread = None
        self.store.delete(self._key)

    def alive_hosts(self) -> List[str]:
        now = time.time()
        hosts = []
        for key in self.store.list_prefix(self.node_prefix):
            if now - self.store.mtime(key) <= self.ttl:
                hosts.append(key.rsplit("/", 1)[-1])
        return sorted(hosts)

    # -- events -------------------------------------------------------------

    def scale_event(self) -> Optional[List[str]]:
        """Returns the new alive-host list when membership changed since the
        last call (the etcd watch-callback analogue), else None."""
        hosts = self.alive_hosts()
        if self._last_hosts is None:
            self._last_hosts = hosts
            return None
        if hosts != self._last_hosts:
            log.info("scale event: %s -> %s", self._last_hosts, hosts)
            self._last_hosts = hosts
            return hosts
        return None

    def world_ok(self) -> bool:
        n = len(self.alive_hosts())
        if self.level == ElasticLevel.FAULT_TOLERANCE:
            return n == self.np
        return self.min_np <= n <= self.max_np

    def wait_for_np(self, timeout: float = 60.0) -> List[str]:
        """Block until the alive set satisfies the level constraints
        (= the rendezvous barrier before a restart)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.world_ok():
                hosts = self.alive_hosts()
                self._last_hosts = hosts
                return hosts
            time.sleep(self.heartbeat_period)
        raise TimeoutError(
            f"elastic rendezvous: alive={self.alive_hosts()} does not "
            f"satisfy np∈[{self.min_np},{self.max_np}] within {timeout}s")

    # -- checkpoint pointer (restart resume source) -------------------------

    def publish_checkpoint(self, path: str, pass_id: int) -> None:
        self.store.put(f"{self.prefix}/ckpt",
                       json.dumps({"path": path, "pass_id": pass_id}).encode())

    def latest_checkpoint(self) -> Optional[dict]:
        raw = self.store.get(f"{self.prefix}/ckpt")
        return json.loads(raw) if raw else None
