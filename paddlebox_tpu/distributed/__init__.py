from paddlebox_tpu.distributed.elastic import (
    ElasticLevel, ElasticManager, FileKVStore, KVStore,
)
from paddlebox_tpu.distributed.kv_server import KVServer, TcpKVStore
from paddlebox_tpu.distributed.launch import (
    LaunchConfig, init_runtime_env, launch_local, main,
)

__all__ = [
    "ElasticLevel", "ElasticManager", "FileKVStore", "KVStore",
    "KVServer", "TcpKVStore",
    "LaunchConfig", "init_runtime_env", "launch_local", "main",
]
