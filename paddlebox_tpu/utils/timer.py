"""Pause/resume wall timers used across pipeline stages.

Reference: paddle/fluid/platform/timer.h:31 (``platform::Timer``) — the same
Start/Pause/Resume/ElapsedSec contract used by every pass stage and by
``DeviceBoxData`` per-device timers (fleet/box_wrapper.h:394-403).
"""

from __future__ import annotations

import time


class Timer:
    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self._elapsed = 0.0
        self._start: float | None = None
        self._count = 0

    def start(self) -> None:
        self._elapsed = 0.0
        self._count = 0
        self._start = time.perf_counter()

    def pause(self) -> None:
        if self._start is not None:
            self._elapsed += time.perf_counter() - self._start
            self._start = None
            self._count += 1

    def resume(self) -> None:
        if self._start is None:
            self._start = time.perf_counter()

    def elapsed_sec(self) -> float:
        live = time.perf_counter() - self._start if self._start is not None else 0.0
        return self._elapsed + live

    def elapsed_ms(self) -> float:
        return self.elapsed_sec() * 1e3

    def elapsed_us(self) -> float:
        return self.elapsed_sec() * 1e6

    def count(self) -> int:
        return self._count

    def __enter__(self) -> "Timer":
        self.resume()
        return self

    def __exit__(self, *exc: object) -> None:
        self.pause()
