"""Crash-safe small-file IO shared by the shared-dir protocols.

One implementation of the write-tmp → flush → fsync → ``os.replace``
publish used by the heartbeat store (obs/watchdog), the restore
consensus (resilience/consensus), and the resume marker
(resilience/preemption): readers never see a torn file, and the payload
is durable before the rename makes it visible.
"""

from __future__ import annotations

import json
import os


def atomic_write_json(path: str, payload: dict,
                      fsync: bool = True) -> str:
    """Atomically publish ``payload`` as JSON at ``path``. The temp
    file carries the writer's pid so concurrent writers (one per
    process in the shared-dir protocols) never collide."""
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
        if fsync:
            fh.flush()
            try:
                os.fsync(fh.fileno())
            except OSError:
                pass  # some FUSE mounts reject fsync; rename still atomic
    os.replace(tmp, path)
    return path


def atomic_write_bytes(path: str, data: bytes,
                       fsync: bool = True) -> str:
    """Raw-bytes sibling of :func:`atomic_write_json` — the same
    write-tmp → flush → fsync → ``os.replace`` publish for stores whose
    payloads are opaque (the elastic membership ``FileKVStore``)."""
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "wb") as fh:
        fh.write(data)
        if fsync:
            fh.flush()
            try:
                os.fsync(fh.fileno())
            except OSError:
                pass  # some FUSE mounts reject fsync; rename still atomic
    os.replace(tmp, path)
    return path


def read_json(path: str):
    """Read a JSON file published by :func:`atomic_write_json`;
    returns None on a missing/torn/foreign file (the caller's next
    poll sees the completed rename)."""
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None
