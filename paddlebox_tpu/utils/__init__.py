from paddlebox_tpu.utils.timer import Timer
from paddlebox_tpu.utils.monitor import StatRegistry, STATS, stat_add
from paddlebox_tpu.utils.channel import Channel, ChannelClosed

__all__ = ["Timer", "StatRegistry", "STATS", "stat_add", "Channel", "ChannelClosed"]
