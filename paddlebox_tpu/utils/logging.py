"""Framework logger (reference uses glog/VLOG throughout)."""

from __future__ import annotations

import logging
import os
import sys

_LOGGER: logging.Logger | None = None


def get_logger(name: str = "paddlebox_tpu") -> logging.Logger:
    global _LOGGER
    if _LOGGER is None:
        logger = logging.getLogger("paddlebox_tpu")
        level = os.environ.get("PADDLEBOX_TPU_LOGLEVEL", "INFO").upper()
        logger.setLevel(level)
        if not logger.handlers:
            h = logging.StreamHandler(sys.stderr)
            h.setFormatter(logging.Formatter(
                "%(asctime)s %(levelname).1s %(name)s] %(message)s", "%H:%M:%S"))
            logger.addHandler(h)
        logger.propagate = False
        _LOGGER = logger
    if name == "paddlebox_tpu":
        return _LOGGER
    return _LOGGER.getChild(name.removeprefix("paddlebox_tpu."))
