"""Per-sample prediction dump + parameter dump.

Reference: BoxPSWorker::DumpField/DumpParam (framework/boxps_worker.cc:
1595-1858) — each worker writes sample-level lines (ins_id + named
field values, used for offline eval/debug) through a channel to sharded
files, uploaded to AFS via BoxFileMgr; param dump writes named parameter
tensors. Trainer wires it via dump_fields/dump_param in TrainerDesc
(boxps_trainer.cc:112-156 dump env).

TPU-native: the trainer enqueues (ins_ids, device pred, host label) per
batch on a bounded channel; a background writer thread does the
device_get and formatting, so the jit stream never blocks on IO. Files
are local paths (the AFS tier is out of scope; any fsspec-style mount
works the same way).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.utils.channel import Channel, ChannelClosed
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class DumpConfig:
    """dump_fields semantics (trainer_desc dump_fields/dump_interval)."""

    def __init__(self, path: str, fields: Sequence[str] = ("pred", "label"),
                 interval: int = 1, rank: int = 0) -> None:
        self.path = path
        self.fields = list(fields)
        self.interval = interval
        self.rank = rank


class DumpWriter:
    """Channel-buffered sharded line writer (DumpField role)."""

    def __init__(self, cfg: DumpConfig) -> None:
        self.cfg = cfg
        os.makedirs(os.path.dirname(cfg.path) or ".", exist_ok=True)
        self._file = open(f"{cfg.path}.part-{cfg.rank:05d}", "w")
        self._ch: Channel = Channel(capacity=64)
        self._exc: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        self.lines = 0

    def add_batch(self, ins_ids: Optional[List[str]],
                  fields: Dict[str, object], num_real: int) -> None:
        """fields: name → array-like [B] (device arrays fine — fetched on
        the writer thread)."""
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise exc
        try:
            self._ch.put((ins_ids, fields, num_real))
        except ChannelClosed:
            # writer thread died and closed the channel; surface its error
            if self._exc is not None:
                exc, self._exc = self._exc, None
                raise exc
            raise

    def _run(self) -> None:
        try:
            while True:
                item = self._ch.get()
                if item is None:
                    break
                ins_ids, fields, n = item
                cols = {k: np.asarray(v) for k, v in fields.items()}
                for i in range(n):
                    ins = ins_ids[i] if ins_ids else str(self.lines)
                    vals = "\t".join(
                        f"{k}:{float(cols[k][i]):.6g}" for k in
                        self.cfg.fields if k in cols)
                    self._file.write(f"{ins}\t{vals}\n")
                    self.lines += 1
        except BaseException as e:
            self._exc = e
            # close the channel so blocked/future producers fail fast
            # instead of deadlocking on a full channel
            self._ch.close()

    def close(self) -> int:
        try:
            self._ch.put(None)
        except ChannelClosed:
            pass
        self._thread.join()
        self._file.close()
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise exc
        log.info("dump: %d lines -> %s", self.lines, self._file.name)
        return self.lines


def dump_param(params, path: str) -> int:
    """Write named parameter tensors (DumpParam, boxps_worker.cc:1633).
    Returns the number of tensors written."""
    import jax
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out = {}
    for keypath, leaf in flat:
        name = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in keypath)
        out[name] = np.asarray(jax.device_get(leaf))
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez_compressed(path, **out)
    return len(out)
