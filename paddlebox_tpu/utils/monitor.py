"""Global named int64 stat registry.

Reference: paddle/fluid/platform/monitor.h:80 (``StatRegistry``; ``STAT_ADD``
macro :133) — e.g. ``STAT_total_feasign_num_in_mem``. Thread-safe counters,
queryable and resettable by name.
"""

from __future__ import annotations

import threading
from typing import Dict


class StatRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stats: Dict[str, int] = {}

    def add(self, name: str, value: int) -> None:
        with self._lock:
            self._stats[name] = self._stats.get(name, 0) + int(value)

    def set(self, name: str, value: int) -> None:
        with self._lock:
            self._stats[name] = int(value)

    def get(self, name: str) -> int:
        with self._lock:
            return self._stats.get(name, 0)

    def reset(self, name: str | None = None) -> None:
        with self._lock:
            if name is None:
                self._stats.clear()
            else:
                self._stats.pop(name, None)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)


STATS = StatRegistry()


def stat_add(name: str, value: int = 1) -> None:
    STATS.add(name, value)


def device_mem_used(device=None) -> Dict[str, int]:
    """HBM usage for one device — the ``GpuMemUsed`` report
    (fleet/box_wrapper.h:420). Returns {bytes_in_use, peak_bytes_in_use,
    bytes_limit} (0s when the backend exposes no allocator stats, e.g.
    virtual CPU devices)."""
    import jax
    if device is None:
        device = jax.local_devices()[0]
    stats = device.memory_stats() or {}
    return {"bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
            "bytes_limit": int(stats.get("bytes_limit", 0))}


def log_device_mem(tag: str = "") -> Dict[str, int]:
    """Record HBM usage into the stat registry and return it."""
    m = device_mem_used()
    prefix = f"hbm_{tag}_" if tag else "hbm_"
    for k, v in m.items():
        STATS.set(prefix + k, v)
    return m
