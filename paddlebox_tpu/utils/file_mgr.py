"""File manager — AFS/HDFS-style storage facade.

Reference: ``BoxFileMgr`` (fleet/box_wrapper.h:1016-1041, bound at
pybind/box_helper_py.cc:167-216) wraps the closed ``boxps::PaddleFileMgr``
with: init, list_dir, makedir, exists, download, upload, remove,
file_size, dus, truncate, touch, rename, list_info, count, finalize.
The reference also shells out to ``hadoop fs`` for dataset IO
(python/paddle/fluid/dataset.py hdfs configs, data_feed pipe commands).

TPU-native redesign: one ``FileMgr`` facade over scheme-registered
backends. ``file://`` (and bare paths) are fully implemented; remote
schemes (afs://, hdfs://, gs://) register either a real backend or a
``CommandBackend`` that shells out to a configured CLI (the way the
reference drives hadoop), so production storage plugs in without code
changes to callers (dump subsystem, checkpoints, dataset file lists).
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import Callable, Dict, List, Optional, Tuple

from paddlebox_tpu.resilience.retry import TransientError
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


def split_scheme(path: str) -> Tuple[str, str]:
    if "://" in path:
        scheme, rest = path.split("://", 1)
        return scheme, rest
    return "file", path


class LocalBackend:
    """POSIX filesystem backend (the file:// scheme and bare paths)."""

    def list_dir(self, path: str) -> List[str]:
        return sorted(os.listdir(path))

    def list_info(self, path: str) -> List[Tuple[str, int]]:
        out = []
        for name in sorted(os.listdir(path)):
            p = os.path.join(path, name)
            out.append((name, os.path.getsize(p) if os.path.isfile(p) else 0))
        return out

    def makedir(self, path: str) -> bool:
        os.makedirs(path, exist_ok=True)
        return True

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def download(self, remote: str, local: str) -> bool:
        if os.path.abspath(remote) != os.path.abspath(local):
            shutil.copy2(remote, local)
        return True

    def upload(self, local: str, remote: str) -> bool:
        if os.path.abspath(remote) != os.path.abspath(local):
            os.makedirs(os.path.dirname(remote) or ".", exist_ok=True)
            shutil.copy2(local, remote)
        return True

    def remove(self, path: str) -> bool:
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)
        return True

    def file_size(self, path: str) -> int:
        return os.path.getsize(path)

    def dus(self, path: str) -> int:
        total = 0
        for root, _, files in os.walk(path):
            for f in files:
                total += os.path.getsize(os.path.join(root, f))
        return total

    def truncate(self, path: str, size: int = 0) -> bool:
        with open(path, "ab") as f:
            f.truncate(size)
        return True

    def touch(self, path: str) -> bool:
        open(path, "ab").close()
        return True

    def rename(self, src: str, dst: str) -> bool:
        os.replace(src, dst)
        return True

    def count(self, path: str) -> int:
        if os.path.isfile(path):
            return 1
        n = 0
        for _, _, files in os.walk(path):
            n += len(files)
        return n


class TransientCommandError(TransientError):
    """A CLI invocation failed transiently (nonzero rc, timeout, or the
    binary itself failed to launch) — retryable by RetryPolicy."""


class CommandBackend:
    """Remote storage driven by a CLI (``hadoop fs`` style), mirroring the
    reference's pipe-command approach to AFS/HDFS. Only the operations the
    pipeline needs are mapped; unmapped ops raise NotImplementedError.

    Receives the FULL URI (scheme included) — hadoop-style CLIs resolve
    scheme-less paths relative to the user's remote home dir.

    Resilience (docs/RESILIENCE.md): every invocation runs under a
    ``RetryPolicy`` (FLAGS.retry_* knobs unless one is passed) with a
    subprocess timeout (``FLAGS.command_timeout_sec``) so a hung CLI is
    killed and retried instead of wedging the pipeline; the
    ``file_mgr.command`` fault-injection seam fires before each spawn."""

    wants_full_uri = True

    def __init__(self, cmd_prefix: List[str], retry=None,
                 timeout: Optional[float] = None) -> None:
        from paddlebox_tpu.config import FLAGS
        from paddlebox_tpu.resilience.retry import RetryPolicy
        self.prefix = list(cmd_prefix)
        self.timeout = (FLAGS.command_timeout_sec if timeout is None
                        else timeout)
        self.retry = retry or RetryPolicy.from_flags(
            site="file_mgr.command")

    def _run_once(self, *args: str) -> Tuple[int, str, str]:
        """One CLI invocation → (rc, stdout, stderr). Spawn failures and
        timeouts surface as TransientCommandError (retryable); the rc is
        returned raw so callers can classify (``-test`` rc=1 means
        "absent", not "broken")."""
        from paddlebox_tpu.resilience.faults import inject
        inject("file_mgr.command", op=args[0] if args else "")
        cmd = self.prefix + list(args)
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True,
                timeout=self.timeout if self.timeout > 0 else None)
        except subprocess.TimeoutExpired as e:
            raise TransientCommandError(
                f"{' '.join(cmd)}: timed out after {self.timeout}s") from e
        except OSError as e:
            raise TransientCommandError(f"{' '.join(cmd)}: {e}") from e
        return proc.returncode, proc.stdout, proc.stderr

    def _run(self, *args: str) -> str:
        """Invoke the CLI under the retry policy; any nonzero rc is
        treated as transient and retried up to the policy's caps."""
        def attempt() -> str:
            rc, out, err = self._run_once(*args)
            if rc != 0:
                raise TransientCommandError(
                    f"{' '.join(self.prefix + list(args))}: rc={rc}: {err}")
            return out
        return self.retry.call(attempt)

    def list_dir(self, path: str) -> List[str]:
        return [line.split()[-1].rsplit("/", 1)[-1]
                for line in self._run("-ls", path).splitlines()
                if line and not line.startswith("Found")]

    def exists(self, path: str) -> bool:
        """``-test -e`` semantics: rc=0 present, rc=1 absent. Any OTHER
        failure (connection refused, CLI crash, timeout) is retried and
        ultimately RAISES — reporting a flaky cluster as "file does not
        exist" silently corrupts checkpoint/dataset decisions."""
        def attempt() -> bool:
            rc, _, err = self._run_once("-test", "-e", path)
            if rc == 0:
                return True
            if rc == 1:
                return False
            raise TransientCommandError(
                f"{' '.join(self.prefix)} -test -e {path}: rc={rc}: {err}")
        return self.retry.call(attempt)

    def download(self, remote: str, local: str) -> bool:
        self._run("-get", remote, local)
        return True

    def upload(self, local: str, remote: str) -> bool:
        """Crash-safe put: write a ``.tmp`` remote name, then rename —
        mirroring the local ``os.replace`` convention checkpoints rely
        on, so a crash mid-upload never leaves a torn final file."""
        tmp = f"{remote}.tmp-{os.getpid()}"
        self._run("-put", local, tmp)
        try:
            self._run("-mv", tmp, remote)
        except BaseException:
            try:  # best-effort: don't litter tmp files on failure
                self._run("-rm", "-r", tmp)
            except Exception:
                log.warning("orphan upload temp left behind: %s", tmp)
            raise
        return True

    def rename(self, src: str, dst: str) -> bool:
        self._run("-mv", src, dst)
        return True

    def remove(self, path: str) -> bool:
        self._run("-rm", "-r", path)
        return True

    def makedir(self, path: str) -> bool:
        self._run("-mkdir", "-p", path)
        return True

    def __getattr__(self, name: str) -> Callable:
        raise NotImplementedError(
            f"CommandBackend has no mapping for '{name}'")


class FileMgr:
    """Scheme-dispatching facade; API mirrors BoxFileMgr's binding."""

    def __init__(self) -> None:
        self._backends: Dict[str, object] = {"file": LocalBackend()}
        self._initialized = False

    def init(self, fs_name: str = "", fs_ugi: str = "",
             conf_path: str = "", scheme: str = "",
             command: Optional[List[str]] = None) -> bool:
        """Configure a remote backend; e.g.
        ``init(scheme="hdfs", command=["hadoop", "fs"])``."""
        if scheme and command:
            self._backends[scheme] = CommandBackend(command)
        self._initialized = True
        return True

    def register_backend(self, scheme: str, backend: object) -> None:
        self._backends[scheme] = backend

    def _resolve(self, path: str) -> Tuple[object, str]:
        scheme, rest = split_scheme(path)
        if scheme not in self._backends:
            raise KeyError(f"no backend for scheme '{scheme}://' "
                           f"(registered: {sorted(self._backends)})")
        backend = self._backends[scheme]
        if getattr(backend, "wants_full_uri", False):
            return backend, path
        return backend, rest

    # -- BoxFileMgr surface -------------------------------------------------

    def list_dir(self, path: str) -> List[str]:
        b, p = self._resolve(path)
        return b.list_dir(p)

    def list_info(self, path: str) -> List[Tuple[str, int]]:
        b, p = self._resolve(path)
        return b.list_info(p)

    def makedir(self, path: str) -> bool:
        b, p = self._resolve(path)
        return b.makedir(p)

    def exists(self, path: str) -> bool:
        b, p = self._resolve(path)
        return b.exists(p)

    def download(self, remote: str, local: str) -> bool:
        b, p = self._resolve(remote)
        return b.download(p, local)

    def upload(self, local: str, remote: str) -> bool:
        b, p = self._resolve(remote)
        return b.upload(local, p)

    def remove(self, path: str) -> bool:
        b, p = self._resolve(path)
        return b.remove(p)

    def file_size(self, path: str) -> int:
        b, p = self._resolve(path)
        return b.file_size(p)

    def dus(self, path: str) -> int:
        b, p = self._resolve(path)
        return b.dus(p)

    def truncate(self, path: str, size: int = 0) -> bool:
        b, p = self._resolve(path)
        return b.truncate(p, size)

    def touch(self, path: str) -> bool:
        b, p = self._resolve(path)
        return b.touch(p)

    def rename(self, src: str, dst: str) -> bool:
        bs, ps = self._resolve(src)
        bd, pd = self._resolve(dst)
        if bs is not bd:
            raise ValueError("rename across schemes is not supported")
        return bs.rename(ps, pd)

    def count(self, path: str) -> int:
        b, p = self._resolve(path)
        return b.count(p)

    def finalize(self) -> None:
        self._backends = {"file": LocalBackend()}
        self._initialized = False
