"""Profiling tiers: stage timers, per-pass sync report, XPlane tracing.

Reference (SURVEY.md §5.1): (a) per-stage ``platform::Timer`` aggregation
printed after each pass (``PrintSyncTimer``, fleet/box_wrapper.cc:1182;
``DeviceBoxData`` timers box_wrapper.h:394-403); (b) worker profile mode
timing every op by name (``TrainFilesWithProfiler``,
boxps_worker.cc:1358-1387); (c) the full chrome-trace profiler
(platform/profiler/ + chrometracing_logger.cc).

TPU-native mapping: (a) → ``StageTimers`` (named pause/resume timers +
one-line pass report); (b) → per-step timing happens at jit-step
granularity (XLA fuses the "ops"; finer slicing comes from tier c);
(c) → ``trace()``: jax.profiler XPlane/TensorBoard traces, which include
per-HLO device timing — the chrome-trace equivalent."""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Dict, Iterator, List, Optional

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.utils.logging import get_logger
from paddlebox_tpu.utils.timer import Timer

log = get_logger(__name__)


class ChromeTraceWriter:
    """Host-side chrome://tracing ("Perfetto") event log — the
    ``chrometracing_logger.cc`` role for OUR runtime stages (pass build,
    upload, train, shuffle, checkpoint...). Device-side HLO timing comes
    from ``trace()`` (XPlane) — this covers the host orchestration the
    XPlane view doesn't label.

    Thread-safe; events carry the recording thread id so overlapped
    preload/train lanes render as separate tracks. The buffer is CAPPED
    (``max_events``, default 1M ≈ 200MB of JSON): stages fire per batch,
    and an uncapped log would grow without bound over a long job —
    events past the cap are counted and reported, not stored."""

    def __init__(self, max_events: int = 1_000_000) -> None:
        self._events: List[dict] = []
        self._max = max_events
        self.dropped = 0
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    def _append(self, ev: dict) -> None:
        with self._lock:
            if len(self._events) >= self._max:
                self.dropped += 1
                return
            self._events.append(ev)

    @contextlib.contextmanager
    def event(self, name: str, **args) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            end = time.perf_counter()
            self._append({
                "name": name, "ph": "X", "pid": 0,
                "tid": threading.get_ident() & 0xFFFF,
                "ts": (start - self._t0) * 1e6,
                "dur": (end - start) * 1e6,
                **({"args": args} if args else {}),
            })

    def complete(self, name: str, start_s: float, dur_s: float,
                 tid: Optional[int] = None, **args) -> None:
        """Record an already-timed span (the obs hub's span-sink entry:
        ``start_s`` is a perf_counter reading from this process).
        ``tid`` overrides the row the span renders on — the lane-trace
        sink (obs/trace.ChromeLaneTraceSink) assigns one stable tid per
        pipeline lane instead of the raw OS thread id."""
        self._append({
            "name": name, "ph": "X", "pid": 0,
            "tid": (threading.get_ident() & 0xFFFF
                    if tid is None else tid),
            "ts": (start_s - self._t0) * 1e6,
            "dur": dur_s * 1e6,
            **({"args": args} if args else {}),
        })

    def thread_meta(self, tid: int, name: str,
                    sort_index: Optional[int] = None) -> None:
        """Label (and optionally order) a tid row — Chrome's
        ``thread_name`` / ``thread_sort_index`` metadata events, so
        lane rows render with their lane names instead of bare ids.
        Metadata bypasses the event cap (a dropped label would mislabel
        every span on the row)."""
        with self._lock:
            self._events.append({
                "name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
                "args": {"name": name}})
            if sort_index is not None:
                self._events.append({
                    "name": "thread_sort_index", "ph": "M", "pid": 0,
                    "tid": tid, "args": {"sort_index": sort_index}})

    def flow(self, flow_id: int, phase: str, ts_s: float, tid: int,
             name: str = "flow", cat: str = "flow") -> None:
        """Flow event: ``phase`` "s" starts an arrow, "f" binds its end
        ("bp":"e" = bind to the ENCLOSING slice's start) — the
        cross-lane causality arrows of the pass trace (a build span on
        ``preload.worker`` flowing into its consume span on ``main``)."""
        ev = {"name": name, "cat": cat, "ph": phase, "id": flow_id,
              "pid": 0, "tid": tid, "ts": (ts_s - self._t0) * 1e6}
        if phase == "f":
            ev["bp"] = "e"
        self._append(ev)

    def instant(self, name: str, **args) -> None:
        self._append({
            "name": name, "ph": "i", "pid": 0, "s": "g",
            "tid": threading.get_ident() & 0xFFFF,
            "ts": (time.perf_counter() - self._t0) * 1e6,
            **({"args": args} if args else {}),
        })

    def save(self, path: str) -> int:
        """Write chrome://tracing JSON; returns the event count."""
        with self._lock:
            evs = list(self._events)
            dropped = self.dropped
        with open(path, "w") as fh:
            json.dump({"traceEvents": evs,
                       "displayTimeUnit": "ms"}, fh)
        if dropped:
            log.warning("chrome trace: %d events past max_events dropped",
                        dropped)
        log.info("chrome trace: %d events -> %s", len(evs), path)
        return len(evs)


_CHROME: Optional[ChromeTraceWriter] = None


def set_chrome_trace(writer: Optional[ChromeTraceWriter]) -> None:
    """Install a process-wide writer; StageTimers.stage() then records
    every stage as a trace event too."""
    global _CHROME
    _CHROME = writer


def chrome_trace() -> Optional[ChromeTraceWriter]:
    return _CHROME


class StageTimers:
    """Named stage timers with a PrintSyncTimer-style report."""

    def __init__(self) -> None:
        self._timers: Dict[str, Timer] = {}

    def __getitem__(self, name: str) -> Timer:
        t = self._timers.get(name)
        if t is None:
            t = self._timers[name] = Timer()
        return t

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[Timer]:
        t = self[name]
        t.resume()
        w = _CHROME  # snapshot: set_chrome_trace may race from other threads
        try:
            if w is not None:
                with w.event(name):
                    yield t
            else:
                yield t
        finally:
            t.pause()

    def report(self, prefix: str = "") -> str:
        """One line per pass: 'stage=1.23s(xN)' (box_wrapper.cc:1182)."""
        parts = [
            f"{k}={t.elapsed_sec():.3f}s(x{t.count()})"
            for k, t in sorted(self._timers.items())
        ]
        line = f"{prefix}timers: " + " ".join(parts) if parts else "timers: -"
        log.info("%s", line)
        return line

    def reset(self) -> None:
        for t in self._timers.values():
            t.reset()

    def as_dict(self) -> Dict[str, float]:
        return {k: t.elapsed_sec() for k, t in self._timers.items()}

    def counts(self) -> Dict[str, int]:
        return {k: t.count() for k, t in self._timers.items()}


@contextlib.contextmanager
def trace(logdir: Optional[str] = None) -> Iterator[None]:
    """XPlane/TensorBoard trace window (tier c). No-op unless
    FLAGS.profile or an explicit logdir is given."""
    import jax
    target = logdir or ("/tmp/paddlebox_tpu_trace" if FLAGS.profile else None)
    if target is None:
        yield
        return
    jax.profiler.start_trace(target)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        log.info("profiler trace written to %s", target)
