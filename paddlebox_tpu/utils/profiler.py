"""Profiling tiers: stage timers, per-pass sync report, XPlane tracing.

Reference (SURVEY.md §5.1): (a) per-stage ``platform::Timer`` aggregation
printed after each pass (``PrintSyncTimer``, fleet/box_wrapper.cc:1182;
``DeviceBoxData`` timers box_wrapper.h:394-403); (b) worker profile mode
timing every op by name (``TrainFilesWithProfiler``,
boxps_worker.cc:1358-1387); (c) the full chrome-trace profiler
(platform/profiler/ + chrometracing_logger.cc).

TPU-native mapping: (a) → ``StageTimers`` (named pause/resume timers +
one-line pass report); (b) → per-step timing happens at jit-step
granularity (XLA fuses the "ops"; finer slicing comes from tier c);
(c) → ``trace()``: jax.profiler XPlane/TensorBoard traces, which include
per-HLO device timing — the chrome-trace equivalent."""

from __future__ import annotations

import contextlib
from typing import Dict, Iterator, Optional

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.utils.logging import get_logger
from paddlebox_tpu.utils.timer import Timer

log = get_logger(__name__)


class StageTimers:
    """Named stage timers with a PrintSyncTimer-style report."""

    def __init__(self) -> None:
        self._timers: Dict[str, Timer] = {}

    def __getitem__(self, name: str) -> Timer:
        t = self._timers.get(name)
        if t is None:
            t = self._timers[name] = Timer()
        return t

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[Timer]:
        t = self[name]
        t.resume()
        try:
            yield t
        finally:
            t.pause()

    def report(self, prefix: str = "") -> str:
        """One line per pass: 'stage=1.23s(xN)' (box_wrapper.cc:1182)."""
        parts = [
            f"{k}={t.elapsed_sec():.3f}s(x{t.count()})"
            for k, t in sorted(self._timers.items())
        ]
        line = f"{prefix}timers: " + " ".join(parts) if parts else "timers: -"
        log.info("%s", line)
        return line

    def reset(self) -> None:
        for t in self._timers.values():
            t.reset()

    def as_dict(self) -> Dict[str, float]:
        return {k: t.elapsed_sec() for k, t in self._timers.items()}


@contextlib.contextmanager
def trace(logdir: Optional[str] = None) -> Iterator[None]:
    """XPlane/TensorBoard trace window (tier c). No-op unless
    FLAGS.profile or an explicit logdir is given."""
    import jax
    target = logdir or ("/tmp/paddlebox_tpu_trace" if FLAGS.profile else None)
    if target is None:
        yield
        return
    jax.profiler.start_trace(target)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        log.info("profiler trace written to %s", target)
