"""Bounded MPMC channel — backbone of every data-pipeline stage.

Reference: paddle/fluid/framework/channel.h:39 (``ChannelObject``): bounded
block/batch read-write with close semantics. We keep the same contract
(capacity, block_size batched reads, ``close()`` drains then raises) on top of
a condition-variable deque; readers get whole batches to amortize locking just
like the reference's ``ReadMove`` batched path.
"""

from __future__ import annotations

import collections
import threading
from typing import Deque, Generic, Iterable, Iterator, List, Optional, TypeVar

T = TypeVar("T")


class ChannelClosed(Exception):
    pass


class Channel(Generic[T]):
    def __init__(self, capacity: int = 65536, block_size: int = 1024) -> None:
        self._capacity = max(1, capacity)
        self._block_size = max(1, block_size)
        self._q: Deque[T] = collections.deque()
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._not_empty = threading.Condition(self._lock)
        self._closed = False

    # -- write side ---------------------------------------------------------
    def put(self, item: T) -> None:
        with self._not_full:
            while len(self._q) >= self._capacity and not self._closed:
                self._not_full.wait()
            if self._closed:
                raise ChannelClosed("put on closed channel")
            self._q.append(item)
            self._not_empty.notify()

    def put_many(self, items: Iterable[T]) -> None:
        for it in items:
            self.put(it)

    # -- read side ----------------------------------------------------------
    def get(self, timeout: Optional[float] = None) -> T:
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._not_empty:
            while not self._q and not self._closed:
                remaining = None if deadline is None \
                    else deadline - _time.monotonic()
                if remaining is not None and remaining <= 0:
                    break
                self._not_empty.wait(timeout=remaining)
            if self._q:
                item = self._q.popleft()
                self._not_full.notify()
                return item
            if self._closed:
                raise ChannelClosed("get on closed empty channel")
            raise TimeoutError("channel get timed out")

    def get_batch(self, max_items: Optional[int] = None) -> List[T]:
        """Blocking batched read; returns [] only when closed and drained."""
        n = self._block_size if max_items is None else max_items
        if n <= 0:
            raise ValueError(f"max_items must be positive, got {n}")
        with self._not_empty:
            while not self._q and not self._closed:
                self._not_empty.wait()
            out: List[T] = []
            while self._q and len(out) < n:
                out.append(self._q.popleft())
            if out:
                self._not_full.notify_all()
            return out

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    def __iter__(self) -> Iterator[T]:
        while True:
            batch = self.get_batch()
            if not batch:
                return
            yield from batch
