"""Bounded MPMC channel — backbone of every data-pipeline stage.

Reference: paddle/fluid/framework/channel.h:39 (``ChannelObject``): bounded
block/batch read-write with close semantics. We keep the same contract
(capacity, block_size batched reads, ``close()`` drains then raises) on top of
a condition-variable deque; readers get whole batches to amortize locking just
like the reference's ``ReadMove`` batched path.

Pipeline gauges: every channel tracks its depth high-watermark and the
wall seconds producers/consumers spent BLOCKED (full on put / empty on
get) — the signal that finally separates "prefetch starved the device"
from "device-bound" (obs/ TelemetryHub reads these). The accounting
rides the existing lock and only touches the clock on the blocking slow
path, so the unblocked hot path pays one integer compare. Channels
constructed with a ``name`` aggregate into a process-wide registry
(``channel_stats_snapshot``): live ones are snapshotted directly (a
closed channel still counts while consumers drain it); a finalizer
folds each channel's totals into the per-name aggregate at GC, so
short-lived per-pass pipelines keep their history."""

from __future__ import annotations

import collections
import threading
import time
import weakref
from typing import (Deque, Dict, Generic, Iterable, Iterator, List,
                    Optional, TypeVar)

T = TypeVar("T")

_LIVE: "weakref.WeakSet[Channel]" = weakref.WeakSet()
_CLOSED: Dict[str, Dict[str, float]] = {}
_REG_LOCK = threading.Lock()


def _fold_stats(name: str, m: Dict[str, float]) -> None:
    """Fold one channel's final counters into the per-name aggregate
    (weakref.finalize callback — ``m`` outlives the channel)."""
    with _REG_LOCK:
        agg = _CLOSED.setdefault(name, {
            "channels": 0, "high_watermark": 0, "puts": 0, "gets": 0,
            "blocked_put_sec": 0.0, "blocked_get_sec": 0.0})
        agg["channels"] += 1
        agg["high_watermark"] = max(agg["high_watermark"],
                                    m["high_watermark"])
        for k in ("puts", "gets", "blocked_put_sec", "blocked_get_sec"):
            agg[k] += m[k]


class ChannelClosed(Exception):
    pass


class Channel(Generic[T]):
    def __init__(self, capacity: int = 65536, block_size: int = 1024,
                 name: Optional[str] = None) -> None:
        self._capacity = max(1, capacity)
        self._block_size = max(1, block_size)
        self._q: Deque[T] = collections.deque()
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._not_empty = threading.Condition(self._lock)
        self._closed = False
        self.name = name
        # gauge counters in a dict that OUTLIVES the channel (the
        # finalizer folds it into the registry at GC)
        self._m: Dict[str, float] = {
            "high_watermark": 0, "puts": 0, "gets": 0,
            "blocked_put_sec": 0.0, "blocked_get_sec": 0.0}
        if name is not None:
            with _REG_LOCK:
                _LIVE.add(self)
            weakref.finalize(self, _fold_stats, name, self._m)

    # -- write side ---------------------------------------------------------
    def put(self, item: T) -> None:
        m = self._m
        with self._not_full:
            if len(self._q) >= self._capacity and not self._closed:
                t0 = time.perf_counter()
                while len(self._q) >= self._capacity and not self._closed:
                    self._not_full.wait()
                m["blocked_put_sec"] += time.perf_counter() - t0
            if self._closed:
                raise ChannelClosed("put on closed channel")
            self._q.append(item)
            m["puts"] += 1
            n = len(self._q)
            if n > m["high_watermark"]:
                m["high_watermark"] = n
            self._not_empty.notify()

    def put_many(self, items: Iterable[T]) -> None:
        for it in items:
            self.put(it)

    # -- read side ----------------------------------------------------------
    def get(self, timeout: Optional[float] = None) -> T:
        deadline = None if timeout is None else time.monotonic() + timeout
        m = self._m
        with self._not_empty:
            if not self._q and not self._closed:
                t0 = time.perf_counter()
                while not self._q and not self._closed:
                    remaining = None if deadline is None \
                        else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        break
                    self._not_empty.wait(timeout=remaining)
                m["blocked_get_sec"] += time.perf_counter() - t0
            if self._q:
                item = self._q.popleft()
                m["gets"] += 1
                self._not_full.notify()
                return item
            if self._closed:
                raise ChannelClosed("get on closed empty channel")
            raise TimeoutError("channel get timed out")

    def get_batch(self, max_items: Optional[int] = None) -> List[T]:
        """Blocking batched read; returns [] only when closed and drained."""
        n = self._block_size if max_items is None else max_items
        if n <= 0:
            raise ValueError(f"max_items must be positive, got {n}")
        m = self._m
        with self._not_empty:
            if not self._q and not self._closed:
                t0 = time.perf_counter()
                while not self._q and not self._closed:
                    self._not_empty.wait()
                m["blocked_get_sec"] += time.perf_counter() - t0
            out: List[T] = []
            while self._q and len(out) < n:
                out.append(self._q.popleft())
            if out:
                m["gets"] += len(out)
                self._not_full.notify_all()
            return out

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def cancel(self) -> None:
        """CONSUMER-side close: mark closed and drop whatever is queued,
        so a producer blocked on a full channel unblocks promptly (its
        pending ``put`` raises ChannelClosed) and nothing is retained
        for a consumer that has walked away. ``close()`` keeps drain
        semantics for the normal producer-side end-of-stream."""
        with self._lock:
            self._closed = True
            self._q.clear()
            self._not_empty.notify_all()
            self._not_full.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def capacity(self) -> int:
        return self._capacity

    def metrics(self) -> Dict[str, float]:
        """Pipeline gauges for this channel (see module docstring)."""
        with self._lock:
            return dict(self._m, depth=len(self._q),
                        capacity=self._capacity)

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    def __iter__(self) -> Iterator[T]:
        while True:
            batch = self.get_batch()
            if not batch:
                return
            yield from batch


def channel_stats_snapshot() -> Dict[str, Dict[str, float]]:
    """Per-name aggregate over collected + live named channels. Counters
    (puts/gets/blocked_*_sec) are CUMULATIVE for the process — per-pass
    views diff consecutive snapshots; ``depth`` is the live depth now."""
    with _REG_LOCK:
        out: Dict[str, Dict[str, float]] = {}
        for name, agg in _CLOSED.items():
            out[name] = dict(agg, depth=0, capacity=0)
        live = list(_LIVE)
    for ch in live:
        if ch.name is None:
            continue
        m = ch.metrics()
        st = out.setdefault(ch.name, {
            "channels": 0, "high_watermark": 0, "puts": 0, "gets": 0,
            "blocked_put_sec": 0.0, "blocked_get_sec": 0.0,
            "depth": 0, "capacity": 0})
        st["channels"] += 1
        st["depth"] += m["depth"]
        st["capacity"] = max(st["capacity"], m["capacity"])
        st["high_watermark"] = max(st["high_watermark"],
                                   m["high_watermark"])
        for k in ("puts", "gets", "blocked_put_sec", "blocked_get_sec"):
            st[k] += m[k]
    for st in out.values():
        st["blocked_put_sec"] = round(st["blocked_put_sec"], 6)
        st["blocked_get_sec"] = round(st["blocked_get_sec"], 6)
    return out


def reset_channel_stats() -> None:
    """Drop the per-name aggregates of collected channels (tests)."""
    with _REG_LOCK:
        _CLOSED.clear()
