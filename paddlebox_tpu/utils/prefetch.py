"""Producer-thread prefetch over a bounded channel — shared by trainers to
overlap host batch prep with device compute."""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Iterator, TypeVar

from paddlebox_tpu.utils.channel import Channel, ChannelClosed

T = TypeVar("T")
U = TypeVar("U")


def prefetch_iter(items: Iterable[T], prepare: Callable[[T], U],
                  capacity: int = 4,
                  name: str | None = None) -> Iterator[U]:
    """Yield prepare(item) for each item, with preparation running in a
    producer thread up to `capacity` items ahead. Producer exceptions
    re-raise at the consumer. ``name`` registers the backing channel's
    pipeline gauges (depth/high-watermark/blocked time) with the
    telemetry registry (utils.channel.channel_stats_snapshot).

    Abandon-safe: if the consumer walks away early (break / exception /
    GeneratorExit), the ``finally`` cancels the channel so a producer
    blocked on ``put`` unblocks promptly (ChannelClosed == normal
    shutdown, not an error), and ``items`` is closed when it is itself a
    generator — so chained prefetch stages unwind transitively instead
    of leaking blocked threads."""
    ch: Channel = Channel(capacity=capacity, name=name)
    err: list = []

    def producer() -> None:
        try:
            for it in items:
                ch.put(prepare(it))
        except ChannelClosed:
            pass  # consumer cancelled the channel — normal abandon path
        except BaseException as e:
            err.append(e)
        finally:
            ch.close()
            close = getattr(items, "close", None)
            if close is not None:
                try:  # unwind an upstream generator (chained stages)
                    close()
                except BaseException as e:
                    if not err:
                        err.append(e)

    th = threading.Thread(target=producer, daemon=True)
    th.start()
    try:
        for out in ch:
            yield out
    finally:
        # consumer-side close: without this, an abandoned generator left
        # the producer blocked on ch.put forever
        ch.cancel()
        th.join()
    if err:
        raise err[0]
