"""Producer-thread prefetch over a bounded channel — shared by trainers to
overlap host batch prep with device compute."""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Iterator, TypeVar

from paddlebox_tpu.utils.channel import Channel

T = TypeVar("T")
U = TypeVar("U")


def prefetch_iter(items: Iterable[T], prepare: Callable[[T], U],
                  capacity: int = 4,
                  name: str | None = None) -> Iterator[U]:
    """Yield prepare(item) for each item, with preparation running in a
    producer thread up to `capacity` items ahead. Producer exceptions
    re-raise at the consumer. ``name`` registers the backing channel's
    pipeline gauges (depth/high-watermark/blocked time) with the
    telemetry registry (utils.channel.channel_stats_snapshot)."""
    ch: Channel = Channel(capacity=capacity, name=name)
    err: list = []

    def producer() -> None:
        try:
            for it in items:
                ch.put(prepare(it))
        except BaseException as e:
            err.append(e)
        finally:
            ch.close()

    th = threading.Thread(target=producer, daemon=True)
    th.start()
    for out in ch:
        yield out
    th.join()
    if err:
        raise err[0]
