"""JAX version-compat shims.

The codebase targets the modern ``jax.shard_map`` API (mesh/in_specs/
out_specs kwargs + ``check_vma``); older jax releases ship the same
machinery as ``jax.experimental.shard_map.shard_map`` with the
``check_vma`` knob named ``check_rep``. ``install()`` publishes a
translating wrapper as ``jax.shard_map`` when the attribute is missing,
so every call site (and the tests) runs unchanged on both lines.
Installed from ``paddlebox_tpu/__init__`` — importing any subpackage is
enough.

Deliberate tradeoff: this mutates the global ``jax`` namespace (only
when the attribute is MISSING — a real ``jax.shard_map`` is never
touched). A repo-local wrapper would avoid that but couldn't cover the
test suite's direct ``jax.shard_map`` calls; the shim mirrors the
modern keyword-only signature and passes unknown kwargs through, so a
third-party caller on legacy jax gets at worst the same TypeError the
legacy API would raise for an unsupported feature.
"""

from __future__ import annotations

import jax


def _needs_shim() -> bool:
    try:
        jax.shard_map  # jax >= 0.6 exports it at top level
        return False
    except AttributeError:
        return True


def install() -> None:
    """Idempotently publish ``jax.shard_map`` on older jax."""
    if not _needs_shim():
        return
    from jax.experimental.shard_map import shard_map as _legacy

    def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=None,
                  **kw):
        # the modern check_vma flag was called check_rep on the legacy
        # API; identical meaning for our uses (disable the replication/
        # varying-mesh-axes check)
        if check_vma is not None and "check_rep" not in kw:
            kw["check_rep"] = check_vma
        if f is None:  # decorator form: jax.shard_map(mesh=...)(f)
            return lambda g: _legacy(g, mesh=mesh, in_specs=in_specs,
                                     out_specs=out_specs, **kw)
        return _legacy(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, **kw)

    jax.shard_map = shard_map
