"""XLA persistent compilation cache enablement.

Reference analogue: the CUDA path compiles nothing at runtime — kernels
ship precompiled in the binary, so a cold worker's first pass boundary
costs milliseconds. Under XLA every program compiles at first trace, and
the tiered begin_pass scatter measured ~20 s of compile on TPU
(docs/BENCH_SHAPES.md round-4 tiered row) — paid by every cold process
and every elastic replacement rank exactly at the boundary the delta
windows just shrank to ~12 ms. The fix is jax's on-disk compilation
cache: compiles serialize once per machine and later processes
deserialize in ~0.1-1 s.

Called by Trainer/ShardedTrainer/launcher init (idempotent). Opt out
with FLAGS_compilation_cache_dir=off; point somewhere specific with
FLAGS_compilation_cache_dir=/path or JAX_COMPILATION_CACHE_DIR.
"""

from __future__ import annotations

import os
import tempfile

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

_enabled = False


def enable_compilation_cache() -> bool:
    """Point jax at a persistent on-disk compilation cache. Returns
    True when the cache is (already) on. Safe to call repeatedly and
    from multiple trainers; first caller wins."""
    global _enabled
    if _enabled:
        return True
    if FLAGS.compilation_cache_dir == "off":
        return False
    import jax

    path = (FLAGS.compilation_cache_dir
            or os.environ.get("JAX_COMPILATION_CACHE_DIR")
            or os.path.join(tempfile.gettempdir(),
                            "paddlebox_tpu_xla_cache"))
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache every compile that took >=1 s (the pass-boundary scatter
        # is ~20 s; trivial elementwise compiles stay out of the cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # unknown config on old jax, read-only fs, …
        log.warning("persistent compilation cache unavailable: %s", e)
        return False
    _enabled = True
    log.info("persistent XLA compilation cache at %s", path)
    return True
