"""Device mesh construction.

Reference communication stacks (SURVEY.md §5.8): NCCL rings
(platform/collective_helper.*), MPI (boxps::MPICluster), Gloo
(fleet/gloo_wrapper.*), brpc PS RPC — all collapse into XLA collectives over
one jax Mesh: the "dp" axis carries both the data-parallel dense allreduce
(NCCL SyncParam role) and the embedding all-to-all (HeterComm P2P role),
riding ICI intra-slice and DCN across slices.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


DATA_AXIS = "dp"


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence[jax.Device]] = None,
              axis_name: str = DATA_AXIS) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def data_axis_size(mesh: Mesh, axis_name: str = DATA_AXIS) -> int:
    return mesh.shape[axis_name]
