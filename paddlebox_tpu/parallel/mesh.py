"""Device mesh construction.

Reference communication stacks (SURVEY.md §5.8): NCCL rings
(platform/collective_helper.*), MPI (boxps::MPICluster), Gloo
(fleet/gloo_wrapper.*), brpc PS RPC — all collapse into XLA collectives over
one jax Mesh: the "dp" axis carries both the data-parallel dense allreduce
(NCCL SyncParam role) and the embedding all-to-all (HeterComm P2P role),
riding ICI intra-slice and DCN across slices.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


DATA_AXIS = "dp"


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence[jax.Device]] = None,
              axis_name: str = DATA_AXIS) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def data_axis_size(mesh: Mesh, axis_name: str = DATA_AXIS) -> int:
    return mesh.shape[axis_name]


ICI_AXIS = "ici"   # chips within a slice (fast interconnect)
DCN_AXIS = "dcn"   # across slices/pods (data-center network)


def make_hierarchical_mesh(n_slices: int,
                           devices_per_slice: Optional[int] = None,
                           devices: Optional[Sequence[jax.Device]] = None
                           ) -> Mesh:
    """2-level [dcn, ici] mesh — the topology the reference manages with
    separate stacks (intra-node NCCL rings + inter-node MPI,
    ps_gpu_wrapper.h:221-265 inner/inter comms; box_wrapper.h:686
    SyncDense). Collectives annotated per axis ride the right fabric.

    On real multi-slice hardware prefer device order from
    ``jax.experimental.mesh_utils.create_hybrid_device_mesh``; this
    reshape form is exact for tests/virtual devices and single-slice
    splits."""
    devs = list(devices) if devices is not None else jax.devices()
    per = devices_per_slice or len(devs) // n_slices
    if n_slices * per > len(devs):
        raise ValueError(f"need {n_slices * per} devices, have {len(devs)}")
    grid = np.array(devs[:n_slices * per]).reshape(n_slices, per)
    return Mesh(grid, (DCN_AXIS, ICI_AXIS))


def hierarchical_allreduce(x: jax.Array, ici_axis: str = ICI_AXIS,
                           dcn_axis: str = DCN_AXIS) -> jax.Array:
    """Bandwidth-optimal 2-level allreduce (inside shard_map over a
    [dcn, ici] mesh): reduce-scatter over ICI → allreduce of the 1/n_ici
    partial over DCN → all-gather over ICI. Exactly the reference's
    dense sync ladder — ncclReduceScatter → ``BoxWrapper::SyncDense``
    (inter-node) → ncclAllGather (boxps_worker.cc:1217-1234) — so the
    slow DCN hop carries only 1/n_ici of the bytes."""
    import jax.numpy as jnp
    n = jax.lax.axis_size(ici_axis)
    flat = x.reshape(-1)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, flat.dtype)])
    part = jax.lax.psum_scatter(flat, ici_axis, scatter_dimension=0,
                                tiled=True)
    part = jax.lax.psum(part, dcn_axis)
    out = jax.lax.all_gather(part, ici_axis, axis=0, tiled=True)
    return out[:x.size].reshape(x.shape)
