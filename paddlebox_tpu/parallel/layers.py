"""Tensor- and pipeline-parallel building blocks.

Reference: python/paddle/distributed/fleet/meta_parallel/parallel_layers/
— ``VocabParallelEmbedding``, ``ColumnParallelLinear``,
``RowParallelLinear`` (mp_layers.py) and the pipeline engine
(``PipelineLayer`` + framework/section_worker.cc scope queues between
program sections).

TPU-native redesign: the layers are plain functions meant to run INSIDE
``shard_map`` over a model axis — each device holds its weight shard and
the reference's explicit c_allreduce/c_concat ops become ``psum``/
``all_gather`` collectives that XLA schedules on ICI. The pipeline is a
GPipe schedule expressed as one ``lax.fori_loop`` with a ``ppermute``
ring between stage devices — no section workers, no scope queues, one
compiled program.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

MODEL_AXIS = "mp"
PIPE_AXIS = "pp"


def vocab_parallel_embedding(ids: jax.Array, weight_shard: jax.Array,
                             axis: str = MODEL_AXIS) -> jax.Array:
    """Vocab-sharded embedding lookup (VocabParallelEmbedding).

    weight_shard: [vocab/P, dim] — this device's contiguous vocab range.
    Out-of-range ids contribute zero locally; a psum assembles the full
    lookup (replaces the reference's c_allreduce after masked lookup)."""
    idx = jax.lax.axis_index(axis)
    per = weight_shard.shape[0]
    local = ids.astype(jnp.int32) - idx * per
    ok = (local >= 0) & (local < per)
    rows = weight_shard[jnp.clip(local, 0, per - 1)]
    rows = rows * ok[..., None].astype(rows.dtype)
    return jax.lax.psum(rows, axis)


def column_parallel_linear(x: jax.Array, weight_shard: jax.Array,
                           bias_shard: Optional[jax.Array] = None,
                           gather_output: bool = True,
                           axis: str = MODEL_AXIS) -> jax.Array:
    """Column-split linear (ColumnParallelLinear): weight [in, out/P];
    each device computes its output columns. gather_output=True
    all-gathers to the full [.., out] (c_concat), else the result stays
    column-sharded for a following row-parallel layer."""
    y = x @ weight_shard
    if bias_shard is not None:
        y = y + bias_shard
    if gather_output:
        y = jax.lax.all_gather(y, axis, axis=-1, tiled=True)
    return y


def row_parallel_linear(x_shard: jax.Array, weight_shard: jax.Array,
                        bias: Optional[jax.Array] = None,
                        axis: str = MODEL_AXIS) -> jax.Array:
    """Row-split linear (RowParallelLinear): weight [in/P, out]; input
    arrives column-sharded (from a gather_output=False column layer);
    partial products reduce with psum (c_allreduce_sum). Bias is full
    [out], added once after the reduce."""
    y = jax.lax.psum(x_shard @ weight_shard, axis)
    if bias is not None:
        y = y + bias
    return y


def pipeline_run(stage_fn: Callable, stage_params, x_micros: jax.Array,
                 axis: str = PIPE_AXIS) -> jax.Array:
    """GPipe schedule inside shard_map over the pipeline axis.

    stage_fn(params, act) -> act: one stage's compute (shape-preserving
    across stages). stage_params: this device's stage weights.
    x_micros: [M, mb, d] microbatched input (meaningful on stage 0).
    Returns [M, mb, d] — the last stage's outputs (zeros elsewhere; a
    caller using out_specs=P(axis) takes shard [-1], or psum-collects).

    Tick t: stage i computes microbatch m = t − i (when 0 ≤ m < M), then
    activations ppermute one hop down the ring — the scope-queue handoff
    of section_worker.cc as a single traced collective.

    DIFFERENTIABLE: the tick loop is a ``lax.scan`` (reverse-mode
    support; fori_loop has none) and every primitive inside — ppermute,
    masked writes — has a transpose rule, so ``jax.grad`` through
    pipeline_run runs the backward pipeline automatically (cotangents
    ppermute the ring in reverse — the 1B1F phase of section_worker
    without hand-scheduling). See pipeline_train_step."""
    s = jax.lax.psum(1, axis)
    i = jax.lax.axis_index(axis)
    m_count = x_micros.shape[0]
    ticks = m_count + s - 1

    def tick(carry, t):
        act, out = carry
        inp = jnp.where(i == 0, x_micros[jnp.clip(t, 0, m_count - 1)], act)
        y = stage_fn(stage_params, inp)
        m = t - (s - 1)
        valid = (i == s - 1) & (m >= 0) & (m < m_count)
        out = jnp.where(valid,
                        out.at[jnp.clip(m, 0, m_count - 1)].set(y), out)
        perm = [(j, (j + 1) % s) for j in range(s)]
        act = jax.lax.ppermute(y, axis, perm)
        return (act, out), None

    # the loop body makes the carry vary over the pipe axis (ppermute /
    # per-stage writes); mark the zero-init carry as varying to match
    pvary = getattr(jax.lax, "pvary", lambda x, names: x)
    act0 = pvary(jnp.zeros_like(x_micros[0]), (axis,))
    out0 = pvary(jnp.zeros_like(x_micros), (axis,))
    (_, out), _ = jax.lax.scan(tick, (act0, out0),
                               jnp.arange(ticks, dtype=jnp.int32))
    # only the last stage holds real outputs; mask so callers can psum
    return out * (i == s - 1).astype(out.dtype)


def pipeline_train_step(stage_fn: Callable, loss_fn: Callable,
                        stage_params, x_micros: jax.Array,
                        y_micros: jax.Array,
                        axis: str = PIPE_AXIS):
    """One TRAINING step through the pipeline: forward GPipe schedule,
    loss on the last stage's microbatch outputs, backward through the
    scanned schedule (grads ppermute the ring in reverse — the
    PipelineTrainer/section_worker training loop, section_worker.cc).

    stage_fn(params, act) -> act; loss_fn(out, y) -> scalar mean loss
    over the microbatch outputs — written as if single-device (e.g.
    ``jnp.mean((out - y) ** 2)``); the last-stage masking happens HERE,
    so off-stage devices contribute exactly zero to the reported loss.
    Returns (loss, stage_grads) where stage_grads matches this device's
    ``stage_params`` — feed any optax optimizer. Mathematically
    identical to sequential training on the concatenated microbatches
    (GPipe has no weight staleness inside a step)."""
    def objective(params):
        out = pipeline_run(stage_fn, params, x_micros, axis)
        last = jax.lax.axis_index(axis) == jax.lax.psum(1, axis) - 1
        # out is zero off the last stage; mask the loss there too so a
        # plain mean-style loss_fn reports the true loss (constant
        # mean(y**2) terms from zero outputs must not psum in)
        loss = jnp.where(last, loss_fn(out, y_micros), 0.0)
        return jax.lax.psum(loss, axis)

    return jax.value_and_grad(objective)(stage_params)
