from paddlebox_tpu.parallel.mesh import make_mesh, data_axis_size

__all__ = ["make_mesh", "data_axis_size"]
