from paddlebox_tpu.parallel.mesh import make_mesh, data_axis_size
from paddlebox_tpu.parallel.layers import (
    vocab_parallel_embedding, column_parallel_linear, row_parallel_linear,
    pipeline_run,
)
from paddlebox_tpu.parallel.moe import (
    moe_forward_local, moe_forward_sharded, naive_gating, top1_gating,
    top2_gating,
)

__all__ = [
    "make_mesh", "data_axis_size", "vocab_parallel_embedding",
    "column_parallel_linear", "row_parallel_linear", "pipeline_run",
    "moe_forward_local", "moe_forward_sharded", "naive_gating",
    "top1_gating", "top2_gating",
]
