from paddlebox_tpu.parallel.mesh import make_mesh, data_axis_size
from paddlebox_tpu.parallel.layers import (
    vocab_parallel_embedding, column_parallel_linear, row_parallel_linear,
    pipeline_run,
    pipeline_train_step,
)
from paddlebox_tpu.parallel.moe import (
    moe_forward_local, moe_forward_sharded, naive_gating, top1_gating,
    top2_gating,
)
from paddlebox_tpu.parallel.ring_attention import (
    make_context_parallel_attention, reference_attention, ring_attention,
    ulysses_attention,
)

__all__ = [
    "make_mesh", "data_axis_size", "vocab_parallel_embedding",
    "column_parallel_linear", "row_parallel_linear", "pipeline_run",
    "pipeline_train_step",
    "moe_forward_local", "moe_forward_sharded", "naive_gating",
    "top1_gating", "top2_gating",
    "make_context_parallel_attention", "reference_attention",
    "ring_attention", "ulysses_attention",
]
