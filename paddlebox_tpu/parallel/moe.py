"""Mixture-of-Experts with expert parallelism.

Reference: python/paddle/incubate/distributed/models/moe/ — ``NaiveGate``
(plain top-k), ``SwitchGate`` (top-1 + capacity), ``GShardGate`` (top-2 +
capacity + load-balance aux loss), and a MoELayer that all-to-alls tokens
to the device owning each expert.

TPU-native redesign: the classic GShard einsum formulation — gating
produces dense one-hot **dispatch** [T, E, C] and weighted **combine**
tensors, expert inputs are one einsum (MXU), and the token exchange is a
single ``jax.lax.all_to_all`` over the ``ep`` mesh axis inside
``shard_map`` (replaces the reference's NCCL Global_Scatter/Gather ops).
Shapes are fully static: capacity drops overflow tokens exactly like the
reference's capacity gates.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

try:
    from jax import shard_map as _shard_map_mod  # jax >= 0.8

    def _shard_map(f, mesh, in_specs, out_specs):
        return _shard_map_mod(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs)
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _sm

    def _shard_map(f, mesh, in_specs, out_specs):
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def _one_hot(idx: jax.Array, n: int) -> jax.Array:
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def top1_gating(logits: jax.Array, capacity: int
                ) -> Tuple[jax.Array, jax.Array, jax.Array, Dict[str, Any]]:
    """Switch-style top-1 routing.

    Returns (dispatch [T,E,C], combine [T,E,C], aux_loss, metrics).
    Tokens beyond an expert's capacity are dropped (zero rows), matching
    the reference SwitchGate's capacity clamp.
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                      # [T]
    gate = jnp.take_along_axis(probs, expert[:, None], 1)[:, 0]

    mask = _one_hot(expert, e)                               # [T, E]
    pos = jnp.cumsum(mask, axis=0) * mask - 1.0              # [T, E]
    pos_in_e = jnp.sum(pos * mask, axis=1)                   # [T]
    keep = pos_in_e < capacity
    gate = gate * keep

    # load-balance aux loss (Switch eq.4): E * Σ_e fraction_e * prob_e
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(mask, axis=0)
    aux = e * jnp.sum(me * ce)

    disp = mask[:, :, None] * _one_hot(
        jnp.clip(pos_in_e, 0, capacity - 1).astype(jnp.int32), capacity
    )[:, None, :] * keep[:, None, None]                      # [T, E, C]
    comb = disp * gate[:, None, None]
    metrics = {"dropped": jnp.sum(1.0 - keep), "load": ce}
    return disp, comb, aux, metrics


def top2_gating(logits: jax.Array, capacity: int
                ) -> Tuple[jax.Array, jax.Array, jax.Array, Dict[str, Any]]:
    """GShard-style top-2 routing with renormalized weights."""
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)

    e1 = jnp.argmax(probs, axis=-1)
    p1 = jnp.take_along_axis(probs, e1[:, None], 1)[:, 0]
    probs2 = probs * (1.0 - _one_hot(e1, e))
    e2 = jnp.argmax(probs2, axis=-1)
    p2 = jnp.take_along_axis(probs2, e2[:, None], 1)[:, 0]

    denom = jnp.maximum(p1 + p2, 1e-9)
    w1, w2 = p1 / denom, p2 / denom

    m1 = _one_hot(e1, e)
    m2 = _one_hot(e2, e)
    pos1 = jnp.sum((jnp.cumsum(m1, 0) - 1.0) * m1, axis=1)
    # second choices queue after every first choice of the same expert
    count1 = jnp.sum(m1, axis=0)                             # [E]
    pos2 = jnp.sum((jnp.cumsum(m2, 0) - 1.0) * m2, axis=1) \
        + jnp.sum(m2 * count1[None, :], axis=1)
    keep1 = pos1 < capacity
    keep2 = pos2 < capacity

    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(m1, axis=0)
    aux = e * jnp.sum(me * ce)

    def build(mask, pos, keep, w):
        d = mask[:, :, None] * _one_hot(
            jnp.clip(pos, 0, capacity - 1).astype(jnp.int32), capacity
        )[:, None, :] * keep[:, None, None]
        return d, d * w[:, None, None]

    d1, c1 = build(m1, pos1, keep1, w1)
    d2, c2 = build(m2, pos2, keep2, w2)
    disp = jnp.maximum(d1, d2)
    comb = c1 + c2
    metrics = {"dropped": jnp.sum(2.0 - keep1.astype(jnp.float32)
                                  - keep2.astype(jnp.float32)),
               "load": ce}
    return disp, comb, aux, metrics


def naive_gating(logits: jax.Array, capacity: Optional[int] = None
                 ) -> Tuple[jax.Array, jax.Array, jax.Array, Dict[str, Any]]:
    """NaiveGate: top-2 without capacity pressure (capacity = T, nothing
    dropped) and no aux loss — the reference's baseline gate."""
    t = logits.shape[0]
    disp, comb, _, metrics = top2_gating(logits, capacity or t)
    return disp, comb, jnp.float32(0.0), metrics


GATES: Dict[str, Callable] = {
    "naive": naive_gating,
    "switch": top1_gating,
    "gshard": top2_gating,
}


def moe_forward_local(x: jax.Array, gate_w: jax.Array,
                      expert_fn: Callable[[jax.Array, Any], jax.Array],
                      expert_params: Any, capacity: int,
                      gate: str = "switch"
                      ) -> Tuple[jax.Array, jax.Array]:
    """Single-device MoE forward (no mesh): all experts local.

    expert_params leaves carry a leading E axis; expert_fn is vmapped.
    Returns (y [T, D], aux_loss).
    """
    logits = x @ gate_w                                      # [T, E]
    disp, comb, aux, _ = GATES[gate](logits, capacity)
    xin = jnp.einsum("tec,td->ecd", disp, x)                 # [E, C, D]
    yout = jax.vmap(expert_fn)(xin, expert_params)           # [E, C, D']
    y = jnp.einsum("tec,ecd->td", comb, yout)
    return y, aux


def moe_forward_sharded(mesh: Any, axis: str,
                        expert_fn: Callable[[jax.Array, Any], jax.Array],
                        capacity: int, gate: str = "switch"):
    """Build an expert-parallel MoE forward over ``mesh[axis]``.

    Tokens are sharded over the axis; expert params carry a leading
    E_local axis per shard. Dispatch einsum happens on the token owner,
    then one all_to_all moves each expert's token slice to the expert
    owner, experts run, and a second all_to_all brings results home.
    """
    from jax.sharding import PartitionSpec as P

    def body(x, gate_w, expert_params):
        logits = x @ gate_w                                   # [t, E_tot]
        disp, comb, aux, _ = GATES[gate](logits, capacity)
        xin = jnp.einsum("tec,td->ecd", disp, x)              # [E_tot, C, D]
        # → [E_loc, n*C, D]: every device contributes its slice of each
        # expert's capacity buffer to the expert's owner
        xin = jax.lax.all_to_all(xin, axis, split_axis=0, concat_axis=1,
                                 tiled=True)
        yout = jax.vmap(expert_fn)(xin, expert_params)        # [E_loc, n*C, D']
        yout = jax.lax.all_to_all(yout, axis, split_axis=1, concat_axis=0,
                                  tiled=True)                 # [E_tot, C, D']
        y = jnp.einsum("tec,ecd->td", comb, yout)
        return y, jax.lax.pmean(aux, axis)

    return _shard_map(
        body, mesh,
        in_specs=(P(axis), P(), P(axis)),
        out_specs=(P(axis), P()),
    )
