"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has NO sequence-parallel mechanism (verified by repo-wide
grep, SURVEY.md §2.6/§5.7 — its only sequence-length device is the
data-level uid-merge split, data_feed.h:624). Long-context support is
nonetheless first-class here, TPU-native by construction:

- ``ring_attention``: blockwise attention with K/V blocks rotating around
  the mesh axis via ``jax.lax.ppermute`` (ICI neighbor exchange), merged
  with the numerically-stable online-softmax accumulation (flash-style
  running max/denominator). Memory per chip is O(T_local²-ish block
  work); the full T_global×T_global score matrix never materializes.
  Compute of ring hop i overlaps the ppermute of hop i+1 (XLA schedules
  the collective-permute concurrently with the einsum).
- ``ulysses_attention``: the all-to-all alternative — resharding
  [B, T/n, H, D] → [B, T, H/n, D] over ICI, local full attention on a
  head subset, and the inverse all-to-all. Cheaper for moderate T with
  many heads; ring wins when T_global is too large for any single chip.

Both run under ``jax.shard_map`` over a mesh axis and are exercised on
the 8-device CPU mesh in tests (tests/test_ring_attention.py) against a
single-device reference attention.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _flash_block(q, k, v, scale, mask, o, m, l):
    """One blockwise attention accumulation step (online softmax).

    q [B,Tq,H,D], k/v [B,Tk,H,D]; o [B,Tq,H,D] running numerator,
    m [B,H,Tq] running max, l [B,H,Tq] running denominator.
    mask [Tq,Tk] True = attend, or None.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked rows (m_new == -inf): exp(-inf - -inf) = nan
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr.transpose(0, 2, 1)[..., None] \
        + jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o_new, m_new, l_new


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    axis_name: str,
    causal: bool = False,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """Context-parallel attention over a mesh axis (call under shard_map).

    q/k/v: [B, T_local, H, D] — the sequence dim sharded over
    ``axis_name`` in contiguous blocks (block i = positions
    [i*T_local, (i+1)*T_local)). Returns [B, T_local, H, D].
    """
    n = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    b, t, h, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(d)
    perm = [(i, (i + 1) % n) for i in range(n)]  # ring: pass K/V rightward

    q_pos = me * t + jnp.arange(t)

    o = jnp.zeros_like(q)
    m = jnp.full((b, h, t), -jnp.inf, q.dtype)
    l = jnp.zeros((b, h, t), q.dtype)

    def hop(i, carry, rotate):
        o, m, l, k_cur, v_cur = carry
        src = (me - i) % n  # whose block we hold at hop i
        if causal:
            k_pos = src * t + jnp.arange(t)
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = None
        o, m, l = _flash_block(q, k_cur, v_cur, scale, mask, o, m, l)
        if rotate:
            # rotate K/V for the next hop (overlaps this hop's compute)
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        return o, m, l, k_cur, v_cur

    # peel the last hop: its rotation would be dead ICI traffic
    carry = jax.lax.fori_loop(
        0, n - 1, lambda i, c: hop(i, c, rotate=True), (o, m, l, k, v))
    o, m, l, _, _ = hop(n - 1, carry, rotate=False)
    l_t = l.transpose(0, 2, 1)[..., None]  # [B,T,H,1]
    return o / jnp.maximum(l_t, 1e-20)


def ulysses_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    axis_name: str,
    causal: bool = False,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism: reshard
    sequence-sharded → head-sharded, full local attention, reshard back.
    Requires H % axis_size == 0. Call under shard_map.

    q/k/v: [B, T_local, H, D] → returns [B, T_local, H, D].
    """
    n = jax.lax.psum(1, axis_name)
    b, t, h, d = q.shape
    if h % n != 0:
        raise ValueError(
            f"ulysses_attention reshards heads over the axis: H={h} must "
            f"be divisible by axis size {n} (use ring_attention otherwise)")

    def to_heads(x):  # [B,T/n,H,D] → [B,T,H/n,D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    def to_seq(x):    # [B,T,H/n,D] → [B,T/n,H,D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) * scale
    if causal:
        tg = t * n
        mask = jnp.arange(tg)[:, None] >= jnp.arange(tg)[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    oh = jnp.einsum("bhqk,bkhd->bqhd", p, vh)
    return to_seq(oh)


def reference_attention(q, k, v, causal=False, sm_scale=None):
    """Single-device full attention — the correctness oracle for both
    parallel formulations (and the T-fits-on-one-chip fallback)."""
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        t, tk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(t)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def make_context_parallel_attention(mesh, axis_name: str,
                                    kind: str = "ring",
                                    causal: bool = False):
    """jit-ready [B, T, H, D] → [B, T, H, D] attention sharded over
    ``axis_name`` (sequence dim). ``kind``: "ring" | "ulysses"."""
    from jax.sharding import PartitionSpec as P

    fn = ring_attention if kind == "ring" else ulysses_attention
    spec = P(None, axis_name, None, None)

    @jax.jit
    def attn(q, k, v):
        return jax.shard_map(
            functools.partial(fn, axis_name=axis_name, causal=causal),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )(q, k, v)

    return attn
