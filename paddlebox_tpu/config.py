"""Global flag/config system.

TPU-native equivalent of the reference's three config layers (SURVEY.md §5.6):
gflags env-settable ``FLAGS_*`` (reference: paddle/fluid/platform/flags.cc,
padbox block :946-975), the ``TrainerDesc``/``DataFeedDesc`` protos, and
per-wrapper config maps. Here: one typed dataclass, every field overridable
from the environment as ``FLAGS_<name>`` at import time or via
``FLAGS.update(...)`` / ``flags_scope(...)`` at runtime.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
from typing import Any, Iterator


def _env_cast(raw: str, ty: type) -> Any:
    if ty is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    if ty is int:
        return int(raw)
    if ty is float:
        return float(raw)
    return raw


@dataclasses.dataclass
class Flags:
    """Process-wide tunables. Defaults mirror the reference's flag defaults
    where a counterpart exists (cited per field)."""

    # --- sparse pull/push (reference: FLAGS_enable_pullpush_dedup_keys,
    # box_wrapper_impl.h:20) ---
    enable_pullpush_dedup_keys: bool = True
    # zero-pad embedding outputs for zero-length slots
    # (reference: FLAGS_enable_pull_box_padding_zero, pull_box_sparse_op.h:25)
    padding_zeros: bool = True

    # --- data pipeline (reference: platform/flags.cc:946-975) ---
    record_pool_max_size: int = 2_000_000
    shuffle_thread_num: int = 8
    read_thread_num: int = 8
    channel_capacity: int = 65536
    # native C++ file→columnar parse fast path (data/parser.py,
    # native/slot_parser.cpp); falls back to per-line python parsing
    native_parse: bool = True

    # --- trainer (reference: boxps_worker.cc) ---
    check_nan_inf: bool = False
    enable_gc: bool = True
    sync_dense_every_steps: int = 1  # K-step dense sync (boxps_worker.cc:1317)
    enable_sharding_stage: int = 0   # FLAGS_padbox_enable_sharding_stage

    # --- embedding store ---
    # Default per-shard row capacity; tables are statically sized for XLA.
    table_capacity_per_shard: int = 1 << 20
    # host-RAM backing store capacity (Phase 5; rows beyond HBM)
    host_store_capacity: int = 1 << 24
    # --- SSD third tier (ps/ssd.SsdTier; docs/STORAGE.md) ---
    # directory for disk-tier segment files; non-empty auto-attaches a
    # tier (unique subdir per HostStore). "" = no tier unless a table
    # passes ssd_dir explicitly or spill_cold lazily creates one.
    ssd_dir: str = ""
    # rows per log-structured segment before it seals (append-only;
    # sealed segments are immutable — the manifest/compaction unit)
    ssd_segment_rows: int = 1 << 15
    # background compaction rewrites a sealed segment when its live-row
    # fraction falls below this (<= 0 disables compaction)
    ssd_compact_live_frac: float = 0.5
    # host-RAM occupancy fraction that triggers background demotion of
    # the coldest rows to the SSD tier (runs on the async-epilogue
    # worker after each end_pass write-back; <= 0 disables — rows then
    # demote only under hard capacity pressure or manual spill_cold)
    host_demote_watermark: float = 0.92
    # demotion drains RAM occupancy down to this fraction
    host_demote_target: float = 0.8
    # embedx (mf) lazy-creation threshold semantics (optimizer.cuh.h:105)
    mf_create_threshold: float = 0.0
    # feature shrink: drop rows whose decayed show falls below this
    shrink_delete_threshold: float = 0.0
    show_click_decay_rate: float = 0.98
    # online-learning daemon (online.py; docs/ONLINE.md): run a shrink
    # cycle every N completed stream windows, counted on the dataset's
    # monotone windows_completed clock so the cadence survives
    # preemption/resume; the boundary checkpoint after a shrink is
    # forced to a BASE save (deltas cannot carry whole-table decay).
    # 0 = lifecycle aging off (keys then accrete without bound — fine
    # for finite jobs, a slow-motion OOM for always-on streams).
    shrink_every_windows: int = 0

    # --- pallas kernels (ops/pallas_kernels.py; interpret-mode off-TPU;
    # docs/PERFORMANCE.md §Device kernels) ---
    # table line-gather via the scalar-prefetch Pallas gather
    # (ps/table.gather_full_rows) instead of XLA's per-element gather
    use_pallas_gather: bool = False
    # route the seqpool family through the fused Pallas embed-pool-CVM
    # kernel: ops/seqpool_cvm.fused_seqpool_cvm{,_with_conv} forward →
    # fused_pool_cvm_forward (MXU one-hot pooling + in-VMEM CVM),
    # backward → segment_gather_mxu (transposed one-hot matmul), and
    # every _pool_core/segment_sum call → segment_sum_mxu. The trivial
    # (segments=None) layout keeps its free reshape path. Off (default)
    # = the XLA composition, byte-for-byte today's program; parity is
    # gated in tier-1 (tests/test_pallas_kernels.py,
    # tests/test_pallas_train_gate.py — forward AND pushed grads,
    # uniform + zipf shapes).
    use_pallas_seqpool: bool = False
    # route the remaining CTR op family through the fused Pallas device
    # kernels (ops/pallas_ctr.py — ISSUE 13, the PR 11 seam pattern
    # applied to rank_attention/batch_fc/cross_norm_hadamard). Each op
    # reads its flag at ONE dispatch seam in its module; a shape that
    # overflows the kernel's VMEM residency budget falls back to the
    # XLA composition. Off (default) = the XLA composition,
    # byte-for-byte today's program; parity matrices are gated in
    # tier-1 (tests/test_pallas_ctr.py, tests/test_pallas_train_gate.py).
    # block-grouped rank attention: ≤ max_rank² VMEM-resident param
    # blocks, keep-mask folded into a one-hot × gathered-X MXU matmul
    # (never materializing the [N, K, D, P] param gather)
    use_pallas_rank_attention: bool = False
    # per-slot blocked batched GEMM with the bias add fused in-VMEM
    # (default, batchcount and transpose_weight modes)
    use_pallas_batch_fc: bool = False
    # one VMEM pass producing the [a, b, a⊙b, a·b] cross blocks with
    # the data_norm mean/scale applied in the same residency (summary
    # update and the sharded sync_stats psum stay outside, unchanged)
    use_pallas_cross_norm: bool = False
    # device-resident key assignment (ops/pallas_index.py — ISSUE 19):
    # route bulk row assignment (EmbeddingTable.bulk_assign_unique, the
    # resident-pass build front) and the sharded plan's per-shard
    # assign/lookup (ps/sharded.prepare_global) through an
    # open-addressing hash index living in device HBM — first-seen
    # dedup of raw 64-bit feature ids (ops/device_unique.
    # dedup_keys_first_seen) + a Pallas linear-probe insert/lookup over
    # a bucket array, with the host kv mirrored only for NEW keys (one
    # O(new) append instead of the O(all keys) per-pass round trip).
    # Row allocation is first-seen sequential, bit-identical to the
    # host index when its free list is empty; any state the device
    # index cannot mirror exactly (free-list holes after shrink,
    # arena-slotted tables, probe/capacity overflow) degrades LOUDLY
    # to the host path (warning + pbox_kernel_dispatch_total booking).
    # Off (default) = the host index path, byte-for-byte today's
    # program; parity + digest gates in tier-1
    # (tests/test_pallas_index.py, tests/test_pallas_train_gate.py).
    use_pallas_index: bool = False

    # --- fused computation-collective sharded step (ISSUE 11;
    # docs/PERFORMANCE.md §Sharded-step overlap) ---
    # number of slot-group chunks the sharded pull exchange decomposes
    # into: chunk k+1's embedding all_to_all is in flight while chunk
    # k's expand_pull → fused_seqpool_cvm pooling runs, and the push
    # grad all_to_all interleaves with the independent dense sync.
    # 1 (default) = the monolithic exchange-then-compute schedule,
    # byte-for-byte today's program. >1 requires slot-qualified keys
    # (each key belongs to one slot — the criteo/CTR schema); a plan
    # build that finds a key spanning slot groups falls back to the
    # monolithic schedule for that batch, loudly. Chunked and
    # monolithic schedules are BIT-IDENTICAL (gated in tier-1:
    # tests/test_sharded.py digest parity, scripts/scaling_check.py).
    a2a_chunks: int = 1

    # --- metrics (reference: metrics.h:46 table_size 1e6+1) ---
    auc_num_buckets: int = 1_000_000
    # False (default) = exact f64 host finalize — BasicAucCalculator::compute
    # semantics (metrics.cc:288-304). True = reduce the AUC bucket tables to
    # scalars ON DEVICE in f32 (~1e-5 AUC drift) and fetch ~8 floats instead
    # of pulling [2, nbins] to host each pass — an optimization for
    # tunneled/remote devices where the bucket pull is dead weight.
    auc_device_reduce: bool = False

    # --- async pass epilogue (ps/epilogue; docs/PERFORMANCE.md) ---
    # end_pass snapshots touched rows, dispatches the D2H gather, and
    # hands the HostStore write-back to a background worker so pass N+1
    # trains while pass N drains; every host-tier read and lifecycle op
    # fences first (bit-for-bit identical to the synchronous path —
    # scripts/pipeline_check.py is the gate). False = write back inline
    # before end_pass returns (the pre-overlap behavior).
    async_end_pass: bool = True
    # --- async capacity eviction (ps/tiered._evict_ahead; ISSUE 9) ---
    # with queued feed-pass stages (the tiered pass pipeline,
    # train/device_pass.PassPipeline), capacity-pressure eviction for
    # the NEXT pass runs on the end_pass epilogue lane right after each
    # write-back lands (clean rows only — release + accounting, no D2H)
    # so steady-state begin_pass pays only for genuinely-new rows; the
    # inline eviction in begin_pass remains as the emergency path
    # (reported as evict_emergency_sec vs evict_async_sec in the bench's
    # begin_stall_breakdown). False = eviction stays fully inline at
    # begin_pass (the pre-pipeline behavior).
    async_capacity_evict: bool = True

    # --- pass-boundary scatter (ps/table.scatter_logical_rows) ---
    # fixed chunk size for the begin_pass delta scatter: one compiled
    # executable per table geometry instead of one per delta size (the
    # per-size compile measured ~20 s on TPU — BENCH_SHAPES tiered row)
    scatter_chunk_rows: int = 1 << 14
    # warm the chunk-scatter executable in a background thread at tiered
    # table construction, so the first pass boundary doesn't pay the
    # compile (utils/compile_cache + ps/tiered)
    warmup_pass_scatter: bool = True

    # --- deep pass preload pipeline (train/device_pass.PassPreloader;
    # docs/PERFORMANCE.md §Deep pass pipeline) ---
    # passes in flight (building or staged) ahead of training; 1 = the
    # old double-buffer. The effective depth self-clamps under the HBM
    # budget below.
    preload_depth: int = 2
    # staged-pass HBM budget: the preloader estimates bytes per staged
    # pass from the first build and clamps its effective depth to
    # max(1, budget // bytes_per_pass) — loudly, instead of OOMing
    # (<= 0 disables the guard)
    preload_hbm_budget_mb: int = 4096
    # index pack/upload chunk (batches): uniq/gidx blocks encode and
    # start their H2D transfer as each chunk completes instead of after
    # the full pack (<= 0 = whole pass, the pre-pipeline behavior)
    preload_pack_chunk_batches: int = 8
    # whole-pass bulk key assignment: one assign round-trip under
    # host_lock per pass instead of one per batch (False = the serial
    # per-batch path, bit-compatible reference)
    bulk_pass_assign: bool = True
    # q8 float wire on NON-columnar re-iterable datasets: True streams
    # per-column min/max batch-by-batch and casts on a second walk —
    # no full-pass f32 staging, but heavy-tailed columns lose
    # quantize_floats' winsorized-range clip and the batches rebuild
    # twice. False restores the staged whole-pass quantization
    # (winsorize + one walk, at the full-pass f32 host cost).
    q8_streaming_front: bool = True

    # --- XLA persistent compilation cache (utils/compile_cache) ---
    # "" = auto (<tmp>/paddlebox_tpu_xla_cache, honoring
    # JAX_COMPILATION_CACHE_DIR); "off" disables. Enabled by
    # Trainer/ShardedTrainer/launcher init so cold processes (elastic
    # replacement ranks included) deserialize compiles instead of
    # re-running XLA at the first pass boundary.
    compilation_cache_dir: str = ""

    # --- telemetry (obs/ TelemetryHub; docs/OBSERVABILITY.md) ---
    # path → attach a JSONL event sink (one structured record per pass)
    telemetry_jsonl: str = ""
    # ≥0 → serve Prometheus text exposition over HTTP (0 = ephemeral
    # port); -1 disables the endpoint
    telemetry_prom_port: int = -1
    # multihost straggler watchdog (obs/watchdog, train/multihost):
    # shared directory for heartbeat files ("" = watchdog not started
    # by make_straggler_watchdog unless a dir/store is passed)
    straggler_heartbeat_dir: str = ""
    straggler_step_lag: int = 1000
    straggler_timeout_sec: float = 120.0
    # >0 → a stall persisting this long arms an abort: the training
    # thread's next heartbeat raises StragglerTimeout
    straggler_abort_sec: float = 0.0
    # JSONL sink rotation (always-on daemon: bound the event log).
    # >0 → when the live segment exceeds this many MiB it rotates to
    # <path>.1 (older segments shift to .2, .3, ...); 0 = one unbounded
    # file (the seed behavior). telemetry_report reads rotated sets in
    # order automatically.
    telemetry_jsonl_max_mb: float = 0.0
    # rotated segments kept per JSONL path (the live file rides on top)
    telemetry_jsonl_keep: int = 3
    # quarantine a telemetry sink after this many CONSECUTIVE
    # emit/span failures (pbox_sink_errors_total books every failure;
    # a broken sink must never take the training hot path down)
    telemetry_sink_errors_max: int = 8

    # --- anomaly flight recorder (obs/flightrec;
    # docs/OBSERVABILITY.md §Flight recorder) ---
    # non-empty → keep a bounded in-memory ring of recent events/spans
    # and publish a self-contained postmortem bundle (ring + instrument
    # snapshot + critical-path blocks + FLAGS + live thread stacks)
    # into this directory whenever a trigger fires (NaN rollback,
    # reload degrade, pipeline hang, watchdog escalation, SLO breach,
    # hub.dump_blackbox). "" = recorder off (zero per-event cost).
    flightrec_dir: str = ""
    # ring capacity (events + spans, newest win)
    flightrec_ring_events: int = 512
    # per-trigger debounce: repeat fires inside this window are
    # suppressed (counted in pbox_flightrec_suppressed_total) — an
    # anomaly storm yields ONE bundle per trigger per window
    flightrec_debounce_sec: float = 60.0
    # newest bundles kept on disk per recorder dir (retention cap)
    flightrec_keep: int = 16

    # --- model-quality drift monitor (obs/quality;
    # docs/OBSERVABILITY.md §Model quality) ---
    # >0 → windowed per-pass quality stats ride every train/stream
    # pass event: key coverage/churn, embedding-norm drift vs the
    # trailing baseline, predicted-vs-observed CTR calibration buckets
    # and a windowed AUC trend with a degradation verdict
    # (pbox_quality_* instruments + quality_window events). 0 = off.
    quality_window_passes: int = 0
    # windowed-AUC degradation verdict: trailing-half mean AUC below
    # leading-half mean by more than this → pbox_quality_degraded=1
    quality_auc_drop: float = 0.01
    # coarse calibration buckets the 1e6-bin AUC tables collapse into
    quality_calibration_buckets: int = 10

    # --- SLO alert engine (obs/alerts; docs/OBSERVABILITY.md §Alerts) ---
    # >0 → evaluate the default alert rules on a cadence thread this
    # often (serving staleness / p99 / stream lag / hang / NaN-rollback
    # rate / AUC degradation → pbox_alerts_active{rule,severity},
    # alert_fired/alert_cleared events, /alertz). 0 = engine not
    # started (construct AlertEngine explicitly for manual evaluation).
    alerts_eval_interval_sec: float = 0.0
    # default-rule thresholds (staleness reuses
    # serving_staleness_max_sec; hang / NaN-rollback fire on any
    # counter increase between evaluations)
    alerts_serving_p99_ms: float = 250.0
    alerts_stream_lag_files: int = 100
    # online daemon lifecycle rules (docs/ONLINE.md): shrink_overdue
    # fires when pbox_online_windows_since_shrink exceeds this; 0 =
    # auto (2 × shrink_every_windows, rule absent when aging is off).
    # backlog_growth fires on a rising pbox_stream_lag_files trend.
    alerts_shrink_overdue_windows: int = 0

    # --- resilience (resilience/; docs/RESILIENCE.md) ---
    # RetryPolicy.from_flags defaults, applied at the IO seams
    # (CommandBackend CLI calls, checkpoint file IO, dataset file opens)
    retry_max_attempts: int = 4
    retry_base_delay_sec: float = 0.05
    retry_max_delay_sec: float = 2.0
    # wall-clock cap for one retried operation (<=0 = no deadline)
    retry_deadline_sec: float = 30.0
    # backoff jitter fraction in [0,1]; seeded from FLAGS.seed + site,
    # so delay sequences are deterministic per run seed
    retry_jitter: float = 0.25
    # kill a hung CommandBackend CLI after this many seconds (<=0 = none)
    command_timeout_sec: float = 300.0
    # max dataset files quarantined per load before the load fails
    # (0 = quarantine disabled: first bad file aborts, the seed behavior)
    poison_budget_files: int = 0
    # max dropped/corrupt records tolerated per FILE before the file is
    # declared poisoned and quarantined (-1 = unlimited silent drops,
    # the seed behavior)
    poison_budget_records: int = -1
    # bounded retry-from-last-checkpoint attempts in Trainer.run_pass
    # (0 = a failed pass raises immediately)
    pass_retry_limit: int = 0
    # deterministic fault-injection plan spec (resilience/faults.py
    # grammar, e.g. "file_mgr.command:fail:nth=1"); "" = no injection
    fault_plan: str = ""

    # --- preemption & mid-pass resume (resilience/preemption,
    # resilience/consensus; docs/RESILIENCE.md) ---
    # install SIGTERM/SIGINT -> graceful-stop handlers at Trainer init;
    # the loop then halts at a batch boundary with an emergency
    # checkpoint + resume cursor instead of dying mid-step
    graceful_shutdown: bool = False
    # >0: periodic in-pass checkpoint (delta + cursor.json) every N
    # batches, so a preempted pass replays seconds, not hours; needs
    # run_pass(checkpoint=...) and an in-memory dataset
    ckpt_every_batches: int = 0
    # shared dir (NFS/FUSE) for multihost-consistent recovery: restore-
    # step agreement + shared quarantine ("" = consensus helpers must be
    # constructed explicitly)
    restore_consensus_dir: str = ""
    # how long a consensus gather waits for the full mesh to publish
    consensus_timeout_sec: float = 60.0
    # elastic membership (distributed/elastic, train/multihost): shared
    # directory backing the FileKVStore lease/rendezvous protocol
    # ("" = make_elastic_manager requires an explicit store)
    elastic_dir: str = ""
    # lease TTL: a host whose heartbeat mtime is older than this is a
    # candidate death (confirmed after elastic_dead_checks polls)
    elastic_ttl_sec: float = 10.0
    # dead-rank hysteresis: consecutive boundary polls a host must miss
    # before a scale event fires (1 = legacy immediate detection; the
    # default 2 absorbs one delayed-but-alive heartbeat)
    elastic_dead_checks: int = 2

    # --- streaming ingest (data/dataset.QueueDataset windowed mode +
    # Trainer.train_stream; docs/RESILIENCE.md §Streaming) ---
    # >0: QueueDataset consumes its filelist in bounded WINDOWS of N
    # files — no record crosses a window boundary, completed windows are
    # tracked per file, and the v2 stream cursor (cursor.json) records
    # fully-consumed files + the open window so a preempted streaming
    # job resumes by skipping completed files and replaying the open
    # window AT-LEAST-ONCE. 0 = legacy unwindowed streaming (no cursor
    # resume; start_batch != 0 keeps refusing).
    stream_window_files: int = 0
    # Trainer.train_stream publishes a stream-boundary checkpoint every
    # N completed windows (bounds replay after a hard kill)
    stream_ckpt_every_windows: int = 1

    # --- artifact/publishing layer (artifacts.py; docs/RESILIENCE.md
    # §Publishing) ---
    # registry dir for versioned model artifacts; non-empty →
    # CheckpointManager auto-attaches an ArtifactStore and publishes
    # every BOUNDARY checkpoint (incl. train_stream stream-boundary
    # saves) as a lineage-linked version. "" = publishing off.
    artifact_root: str = ""
    # reader-lease staleness TTL: a lease whose heartbeat mtime is
    # older than this (or whose same-host writer pid is dead) is
    # provably stale and may be reaped by the retention sweep; readers
    # fence every access against lease loss (ArtifactLeaseLostError)
    artifact_lease_ttl_sec: float = 300.0
    # versions kept by ArtifactStore.retain (plus leased versions and
    # lineage parents, which are NEVER swept); <=0 = keep everything
    artifact_keep: int = 0

    # --- concurrent serving (serving.py; docs/SERVING.md) ---
    # background hot-reload cadence: serving.ReloadLoop polls the
    # ArtifactStore tip this often while healthy (failed polls back off
    # on the seeded RetryPolicy schedule instead — site serving.reload)
    serving_reload_poll_sec: float = 2.0
    # snapshot-staleness SLO: when a newer adoptable version has been
    # published for longer than this without the serving snapshot
    # advancing, the reload loop marks the serving block stale
    # (healthz "serving".stale, pbox_serving_staleness_sec) and logs
    # loudly — the degrade state is visible, never silent
    serving_staleness_max_sec: float = 60.0
    # predict_many micro-batch cap (instances per forward); <=0 = the
    # model desc's batch_size (one compiled bucket). Smaller caps trade
    # throughput for per-query latency under mixed traffic.
    serving_batch_max: int = 0

    # --- pipeline hang deadline (ps/epilogue.PassEpilogue.fence,
    # train/device_pass.PassPreloader.wait) ---
    # >0: a pipeline wait that sees no job/build COMPLETE for this long
    # raises PipelineHangError naming the stuck stage (with queue-depth
    # telemetry) instead of blocking forever on a wedged worker — set
    # above the worst-case single job duration (progress is observed at
    # whole-job granularity); 0 = wait indefinitely (the pre-deadline
    # behavior)
    pipeline_wait_timeout_sec: float = 0.0

    # --- runtime ---
    profile: bool = False
    log_period_steps: int = 100
    seed: int = 0

    def update(self, **kwargs: Any) -> None:
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise AttributeError(f"unknown flag: {k}")
            setattr(self, k, v)

    @classmethod
    def from_env(cls) -> "Flags":
        self = cls()
        for f in dataclasses.fields(self):
            raw = os.environ.get(f"FLAGS_{f.name}")
            if raw is not None:
                ty = type(getattr(self, f.name))
                try:
                    setattr(self, f.name, _env_cast(raw, ty))
                except ValueError as e:
                    raise ValueError(f"bad value for env flag FLAGS_{f.name}={raw!r}: {e}") from None
        return self


FLAGS = Flags.from_env()


@contextlib.contextmanager
def flags_scope(**kwargs: Any) -> Iterator[Flags]:
    """Temporarily override flags (tests use this heavily)."""
    old = {k: getattr(FLAGS, k) for k in kwargs}
    FLAGS.update(**kwargs)
    try:
        yield FLAGS
    finally:
        FLAGS.update(**old)
