"""Dense-parameter handling modes beyond per-step in-jit sync.

Reference (boxps_worker.cc):

- **sync mode** ``SyncParam`` (:1191): workers train on local replicas and
  every K steps allreduce the flattened param buffer, scaling by
  1/(ndev*nnode) — i.e. periodic parameter *averaging*, not per-step grad
  allreduce.
- **async mode** ``BoxPSAsynDenseTable`` (:61-370): a host-side flattened
  param table with Adam state; worker threads PullDense (copy latest
  params) and PushDense (enqueue grads) through a buffer queue while a
  background thread drains the queue and applies Adam on CPU. DataNorm
  "summary" params (batch_size/batch_sum/batch_square_sum) are
  accumulated directly instead of Adam-updated (:93-98).

TPU-native redesign: the per-step psum inside the jit step
(train/sharded.py) is the default; these modes exist for parity and for
host-offloaded experimentation. K-step averaging runs as one tiny jitted
pmean over the mesh (or a stacked-axis mean in the single-process
emulation); the async table is numpy + a Channel, with pull/push crossing
host↔device only at pass boundaries the caller chooses.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.utils.channel import Channel
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


# ---------------------------------------------------------------------------
# Per-param dense learning rates (lr_map)
# ---------------------------------------------------------------------------
# Reference: InitializeGPUAndLoadModel carries a param-name→lr map applied
# to .w_0/.b_0 names (box_wrapper.cc:1303-1335), consumed per parameter by
# the async dense table (boxps_worker.cc:199-204). TPU-native form: a
# per-leaf UPDATE multiplier (lr_name / base_lr) — applied after the
# optimizer's update (scaling the grad instead would be normalized away
# by Adam), so it composes with any optax tx, the in-jit psum mode,
# ZeRO-1 flat chunks, and the host async table.

def lr_pattern_matches(pat: str, keystr: str) -> bool:
    """Segment-boundary substring match — THE lr_map matching rule
    (build_lr_scales AND AsyncDenseTable use it): ``pat`` must occur in
    ``keystr`` with non-identifier characters (or string ends) on both
    sides, so ``"Dense_1"`` matches ``['Dense_1']['kernel']`` but NOT
    ``['Dense_10']`` (the reference's lr_map keys are exact param
    names; a bare substring test silently over-matched)."""
    import re
    for m in re.finditer(re.escape(pat), keystr):
        a = keystr[m.start() - 1] if m.start() else ""
        b = keystr[m.end()] if m.end() < len(keystr) else ""
        if not (a.isalnum() or a == "_") and not (b.isalnum() or b == "_"):
            return True
    return False


def build_lr_scales(params: Any, lr_map: dict, base_lr: float) -> Any:
    """Pytree of per-leaf multipliers matching ``params``: a leaf whose
    path (jax keystr, e.g. ``"['params']['Dense_0']['kernel']"``)
    matches a key of ``lr_map`` (segment-boundary rule,
    lr_pattern_matches) gets ``lr_map[key] / base_lr``; first match
    wins; unmatched leaves get 1.0 (the global lr)."""
    def scale_of(path, _leaf):
        ks = jax.tree_util.keystr(path)
        for pat, lr in lr_map.items():
            if lr_pattern_matches(pat, ks):
                return float(lr) / float(base_lr)
        return 1.0
    return jax.tree_util.tree_map_with_path(scale_of, params)


def lr_map_transform(scales: Any):
    """optax transform scaling each leaf's update by its multiplier —
    chain AFTER the optimizer: ``optax.chain(optax.adam(base_lr),
    lr_map_transform(build_lr_scales(params, lr_map, base_lr)))``."""
    import optax

    def init(params):
        del params
        return optax.EmptyState()

    def update(updates, state, params=None):
        del params
        return jax.tree.map(lambda u, s: u * s, updates, scales), state

    return optax.GradientTransformation(init, update)


# ---------------------------------------------------------------------------
# K-step periodic parameter averaging (SyncParam analogue)
# ---------------------------------------------------------------------------

class KStepParamSync:
    """Average param replicas every ``k`` steps.

    Replicas are a pytree whose leaves carry a leading replica axis
    (the single-process stand-in for one param copy per device/host; under
    a mesh the same pytree is sharded over ``axis`` and the mean lowers to
    one psum over ICI).
    """

    def __init__(self, k: int, mesh: Optional[Any] = None,
                 axis: str = "dp") -> None:
        if k <= 0:
            raise ValueError("k must be positive")
        self.k = k
        self._step = 0

        if mesh is None:
            def _avg(params):
                return jax.tree.map(
                    lambda x: jnp.broadcast_to(
                        jnp.mean(x, axis=0, keepdims=True), x.shape),
                    params)
            self._avg = jax.jit(_avg)
        else:
            from jax.sharding import PartitionSpec as P

            try:
                from jax import shard_map as _shard_map
                shard_map = _shard_map
            except ImportError:  # older jax
                from jax.experimental.shard_map import shard_map

            def _avg(params):
                def body(p):
                    return jax.tree.map(
                        lambda x: jax.lax.pmean(x, axis), p)
                spec = jax.tree.map(lambda _: P(axis), params)
                return shard_map(body, mesh=mesh, in_specs=(spec,),
                                 out_specs=spec)(params)
            self._avg = jax.jit(_avg)

    def maybe_sync(self, params: Any) -> Tuple[Any, bool]:
        """Call once per train step; returns (params, did_sync)."""
        self._step += 1
        if self._step % self.k != 0:
            return params, False
        return self._avg(params), True


# ---------------------------------------------------------------------------
# Async host-side dense table (BoxPSAsynDenseTable analogue)
# ---------------------------------------------------------------------------

class _HostAdam:
    def __init__(self, n: int, lr, beta1: float, beta2: float,
                 eps: float) -> None:
        """``lr`` is a scalar or a per-element [n] vector (lr_map,
        boxps_worker.cc:199-204)."""
        self.m = np.zeros(n, np.float32)
        self.v = np.zeros(n, np.float32)
        self.t = 0
        self.lr, self.b1, self.b2, self.eps = lr, beta1, beta2, eps

    def update(self, p: np.ndarray, g: np.ndarray) -> None:
        self.t += 1
        self.m = self.b1 * self.m + (1 - self.b1) * g
        self.v = self.b2 * self.v + (1 - self.b2) * g * g
        mhat = self.m / (1 - self.b1 ** self.t)
        vhat = self.v / (1 - self.b2 ** self.t)
        p -= self.lr * mhat / (np.sqrt(vhat) + self.eps)

    def _lr_sel(self, sel: np.ndarray):
        return self.lr[sel] if isinstance(self.lr, np.ndarray) else self.lr


class AsyncDenseTable:
    """Host-resident dense params updated by a background Adam thread.

    ``pull()`` returns the latest params as a pytree (device transfer is
    the caller's jnp.asarray); ``push(grads)`` enqueues a gradient pytree
    and returns immediately. Leaves whose path matches ``is_summary``
    (DataNorm batch_size/batch_sum/batch_square_sum) are accumulated
    (ps += grad) instead of Adam-updated, mirroring boxps_worker.cc:93-98.
    """

    def __init__(self, params: Any, lr: float = 1e-3, beta1: float = 0.9,
                 beta2: float = 0.999, eps: float = 1e-8,
                 queue_capacity: int = 64,
                 is_summary: Optional[Callable[[str], bool]] = None,
                 lr_map: Optional[dict] = None) -> None:
        """``lr_map`` — param-name→lr overrides (path-substring match as
        in build_lr_scales); unmatched params use the global ``lr``
        (InitializeGPUAndLoadModel's per-param dense lr map,
        box_wrapper.cc:1303-1335, consumed boxps_worker.cc:199-204)."""
        from jax.flatten_util import ravel_pytree

        host = jax.tree.map(lambda x: np.asarray(x, np.float32), params)
        flat, self._unravel = ravel_pytree(host)
        self._ps = np.array(flat, np.float32)

        # summary mask over the flat vector
        leaves_with_path = jax.tree_util.tree_leaves_with_path(host)
        mask = np.zeros(self._ps.size, bool)
        off = 0
        pred = is_summary or (lambda name: "summary" in name.lower())
        for path, leaf in leaves_with_path:
            n = int(np.size(leaf))
            if pred(jax.tree_util.keystr(path)):
                mask[off:off + n] = True
            off += n
        self._summary_mask = mask

        # per-element lr through THE shared matcher (build_lr_scales):
        # ratios vs the global lr ravel exactly as params do
        lr_vec = None
        if lr_map:
            scales = build_lr_scales(host, lr_map, base_lr=lr)
            sflat, _ = ravel_pytree(jax.tree.map(
                lambda x, s: np.full(np.shape(x), s, np.float32),
                host, scales))
            lr_vec = (lr * np.asarray(sflat)).astype(np.float32)
        self._adam = _HostAdam(self._ps.size,
                               lr if lr_vec is None else lr_vec,
                               beta1, beta2, eps)
        self._q: Channel = Channel(capacity=queue_capacity)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._applied = 0
        self._pushed = 0

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._q.close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _loop(self) -> None:
        while True:
            batch = self._q.get_batch(max_items=1)
            if not batch:  # channel closed and drained
                return
            g = batch[0]
            with self._lock:
                s = self._summary_mask
                if s.any():
                    self._ps[s] += g[s]
                    self._adam_masked(~s, g)
                else:
                    self._adam.update(self._ps, g)
                self._applied += 1

    def _adam_masked(self, sel: np.ndarray, g: np.ndarray) -> None:
        a = self._adam
        a.t += 1
        a.m[sel] = a.b1 * a.m[sel] + (1 - a.b1) * g[sel]
        a.v[sel] = a.b2 * a.v[sel] + (1 - a.b2) * g[sel] ** 2
        mhat = a.m[sel] / (1 - a.b1 ** a.t)
        vhat = a.v[sel] / (1 - a.b2 ** a.t)
        self._ps[sel] -= a._lr_sel(sel) * mhat / (np.sqrt(vhat) + a.eps)

    # -- worker API ---------------------------------------------------------

    def pull(self) -> Any:
        with self._lock:
            snap = self._ps.copy()
        return self._unravel(snap)

    def push(self, grads: Any) -> None:
        from jax.flatten_util import ravel_pytree

        host = jax.tree.map(lambda x: np.asarray(x, np.float32), grads)
        flat, _ = ravel_pytree(host)
        with self._lock:
            self._pushed += 1
        self._q.put(np.asarray(flat, np.float32))

    def drain(self) -> int:
        """Block until every pushed grad has been applied (pass barrier);
        returns how many updates have been applied in total. Compares
        applied vs pushed counters — queue emptiness alone would race with
        the in-flight grad the worker has popped but not yet applied."""
        import time

        while True:
            with self._lock:
                if self._applied >= self._pushed:
                    return self._applied
            time.sleep(0.001)
