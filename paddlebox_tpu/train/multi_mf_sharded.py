"""Mesh train step + trainer for multi-mf (per-slot embedding dims).

The sharded analogue of train/multi_mf_step.py: C dim classes, each a
ShardedEmbeddingTable over the same mesh. One jit shard_map program per
global batch runs C pull all_to_alls → per-class fused_seqpool_cvm →
canonical slot-order concat → dense net → backward → C push all_to_alls
→ per-class in-table optimizer + dense psum. Reference:
feature_value.h:42-185 (the dy-mf accessor IS the sharded PS layout),
ps_gpu_wrapper.cc multi-mf BuildGPUTask. The per-class
``fused_seqpool_cvm`` calls ride the ``FLAGS.use_pallas_seqpool`` seam
onto the fused Pallas MXU kernel (docs/PERFORMANCE.md §Device kernels).
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from paddlebox_tpu.data.batch import SlotBatch
from paddlebox_tpu.metrics import AucState, auc_add_batch, auc_compute
from paddlebox_tpu.ops import fused_seqpool_cvm
from paddlebox_tpu.parallel.mesh import DATA_AXIS
from paddlebox_tpu.ps.multi_mf_sharded import MultiMfShardedTable
from paddlebox_tpu.ps.sharded import ShardedPullIndex
from paddlebox_tpu.ps.table import (TableState, apply_push,
                                    gather_full_rows, pull_values)
from paddlebox_tpu.train.sharded import init_sharded_auc
from paddlebox_tpu.utils.logging import get_logger
from paddlebox_tpu.utils.timer import Timer

log = get_logger(__name__)


class ClassPlan(NamedTuple):
    """One dim class's routing plan for a global batch (leading dim =
    device, sharded over the mesh axis)."""

    resp_idx: jax.Array     # int32 [N, N, A_c]
    serve_rows: jax.Array   # int32 [N, A2_c]
    serve_valid: jax.Array  # f32   [N, A2_c]
    serve_slot: jax.Array   # f32   [N, A2_c] (GLOBAL slot ids)
    gather_idx: jax.Array   # int32 [N, K_c]
    segments: jax.Array     # int32 [N, K_c] (class-local renumbering)


class MmfGlobalBatch(NamedTuple):
    plans: Tuple[ClassPlan, ...]
    dense: jax.Array        # f32 [N, B, Dd]
    label: jax.Array        # f32 [N, B]
    show: jax.Array         # f32 [N, B]
    clk: jax.Array          # f32 [N, B]


class MmfShardedState(NamedTuple):
    tables: Tuple[TableState, ...]   # per class, leaves [N, L, 128]
    params: Any
    opt_state: Any
    auc: AucState                    # leaves [N, ...]
    step: jax.Array


class MultiMfShardedTrainStep:
    """Jitted multi-class mesh step over a MultiMfShardedTable."""

    def __init__(self, model, tx: optax.GradientTransformation,
                 table: MultiMfShardedTable, mesh: Mesh,
                 batch_size_per_device: int, use_cvm: bool = True,
                 cvm_offset: int = 2) -> None:
        from paddlebox_tpu.config import FLAGS
        self.model = model
        self.tx = tx
        self.table = table
        self.mesh = mesh
        self.n = mesh.shape[DATA_AXIS]
        self.batch_size = batch_size_per_device
        self.use_cvm = use_cvm
        self.cvm_offset = cvm_offset
        # fused computation-collective schedule (ISSUE 11): the multi-mf
        # pull is ALREADY class-chunked (one all_to_all per dim class,
        # each pool independent of the others) — the flag here moves the
        # push side to the overlapped order: issue every class's grad
        # all_to_all, run the independent dense psum/update, THEN merge
        # and apply per class. Bit-identical either way (pure op-order
        # motion); default 1 keeps the sequential pre-ISSUE-11 program.
        self.a2a_overlap = max(1, int(FLAGS.a2a_chunks)) > 1
        self.dims = table.dims
        self.class_slots = [len(s) for s in table.class_slots]
        self.route = table.slot_route()

        shard0 = P(DATA_AXIS)
        rep = P()
        # tree-prefix specs: shard0 broadcasts over the tables tuple and
        # every plan leaf (all carry a leading device dim)
        state_spec = MmfShardedState(
            tables=shard0, params=rep, opt_state=rep,
            auc=AucState(*([shard0] * len(AucState._fields))),
            step=rep)
        self._state_spec = state_spec
        batch_spec = MmfGlobalBatch(
            plans=shard0, dense=shard0, label=shard0, show=shard0,
            clk=shard0)
        self._sharded = jax.jit(
            jax.shard_map(self._device_step, mesh=mesh,
                          in_specs=(state_spec, batch_spec, rep),
                          out_specs=(state_spec, rep),
                          check_vma=False),
            donate_argnums=(0,))

    def init_params(self, dense_dim: int) -> Any:
        width = self.table.pooled_width(self.cvm_offset, self.use_cvm)
        flat = jnp.zeros((self.batch_size, width))
        dense = jnp.zeros((self.batch_size, dense_dim))
        return self.model.init(jax.random.PRNGKey(0), flat, dense)

    def init_state(self, params: Any) -> MmfShardedState:
        return MmfShardedState(
            tables=tuple(t.state for t in self.table.tables),
            params=params, opt_state=self.tx.init(params),
            auc=init_sharded_auc(self.n), step=jnp.zeros((), jnp.int32))

    # ---- per-device block program (runs under shard_map) ----
    def _device_step(self, state: MmfShardedState, batch: MmfGlobalBatch,
                     rng: jax.Array):
        n, b = self.n, self.batch_size
        me = jax.lax.axis_index(DATA_AXIS)
        tables = [st.with_packed(st.packed[0]) for st in state.tables]
        auc = AucState(*[l[0] for l in state.auc])
        dense = batch.dense[0]
        label = batch.label[0]
        show = batch.show[0]
        clk = batch.clk[0]
        ins_w = (show > 0).astype(jnp.float32)
        wsum_global = jax.lax.psum(jnp.sum(ins_w), DATA_AXIS)
        show_clk = jnp.stack([show, clk], axis=1)

        # ---- per-class pull: serve, exchange, flatten ----
        rows_fulls, vals_flats, plan_views = [], [], []
        for c, tbl in enumerate(tables):
            p = batch.plans[c]
            resp_idx = p.resp_idx[0]
            serve_rows = p.serve_rows[0]
            a = resp_idx.shape[1]
            d = 3 + tbl.mf_dim
            rows_full = gather_full_rows(tbl, serve_rows)
            serve_vals = pull_values(rows_full, tbl.mf_dim)
            resp = serve_vals[resp_idx]
            recv = jax.lax.all_to_all(resp, DATA_AXIS, 0, 0, tiled=True)
            rows_fulls.append(rows_full)
            vals_flats.append(recv.reshape(n * a, d))
            plan_views.append((resp_idx, serve_rows, p.serve_valid[0],
                               p.serve_slot[0], p.gather_idx[0],
                               p.segments[0]))

        def loss_fn(params, vals_flats):
            parts = []
            for c in range(len(tables)):
                _, _, _, _, gather_idx, segments = plan_views[c]
                values_k = vals_flats[c][gather_idx]
                parts.append(fused_seqpool_cvm(
                    values_k, segments, show_clk, b, self.class_slots[c],
                    self.use_cvm, self.cvm_offset))
            flat = jnp.concatenate(
                [parts[c][:, r, :] for c, r in self.route], axis=1)
            logits = self.model.apply(params, flat, dense)
            ls = optax.sigmoid_binary_cross_entropy(logits, label)
            loss_local = jnp.sum(ls * ins_w) / jnp.maximum(wsum_global, 1.0)
            return loss_local, logits

        (loss_local, logits), (g_params, g_vals) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True)(
                state.params, tuple(vals_flats))

        # ---- per-class push: route back, merge, in-table optimizer ----
        def dense_update():
            gp = jax.lax.psum(g_params, DATA_AXIS)
            updates, opt_state = self.tx.update(gp, state.opt_state,
                                                state.params)
            return optax.apply_updates(state.params, updates), opt_state

        def push_class(c, tbl, g_back):
            resp_idx, serve_rows, serve_valid, serve_slot, _, _ = \
                plan_views[c]
            a = resp_idx.shape[1]
            a2 = serve_rows.shape[0]
            d = 3 + tbl.mf_dim
            g_serve = jax.ops.segment_sum(
                g_back.reshape(n * a, d), resp_idx.reshape(n * a),
                num_segments=a2)
            gb = jnp.concatenate(
                [g_serve[:, :2], g_serve[:, 2:] * (-1.0 * b * n)], axis=1)
            tbl = apply_push(tbl, serve_rows, gb, self.table.cfg,
                             jax.random.fold_in(rng, me * 131 + c),
                             rows_full=rows_fulls[c],
                             touched=serve_valid > 0,
                             slot_val=serve_slot)
            return tbl.with_packed(tbl.packed[None])

        def back(c):
            a = plan_views[c][0].shape[1]
            d = 3 + tables[c].mf_dim
            return jax.lax.all_to_all(
                g_vals[c].reshape(n, a, d), DATA_AXIS, 0, 0, tiled=True)

        new_tables = []
        if self.a2a_overlap:
            # overlapped order (FLAGS.a2a_chunks > 1): every class's grad
            # all_to_all first, the independent dense psum/update next,
            # merges/apply last — the exchanges fly while the dense sync
            # computes. Same ops, same math, different schedule.
            g_backs = [back(c) for c in range(len(tables))]
            params, opt_state = dense_update()
            for c, tbl in enumerate(tables):
                new_tables.append(push_class(c, tbl, g_backs[c]))
        else:
            for c, tbl in enumerate(tables):
                new_tables.append(push_class(c, tbl, back(c)))
            params, opt_state = dense_update()

        pred = jax.nn.sigmoid(logits)
        auc = auc_add_batch(auc, pred, label, ins_w)
        loss = jax.lax.psum(loss_local, DATA_AXIS)
        new_state = MmfShardedState(
            tables=tuple(new_tables), params=params, opt_state=opt_state,
            auc=AucState(*[l[None] for l in auc]), step=state.step + 1)
        return new_state, {"loss": loss}

    def __call__(self, state, batch, rng):
        return self._sharded(state, batch, rng)


class MultiMfShardedTrainer:
    """Streaming mesh trainer over a MultiMfShardedTable (the
    PSGPUTrainer role for mixed-dim tables at pod scale)."""

    def __init__(self, model, table: MultiMfShardedTable, desc, mesh: Mesh,
                 tx: Optional[optax.GradientTransformation] = None,
                 use_cvm: bool = True, prefetch: int = 4,
                 seed: int = 0) -> None:
        self.model = model
        self.table = table
        self.desc = desc
        self.mesh = mesh
        self.n = mesh.shape[DATA_AXIS]
        self.tx = tx or optax.adam(1e-3)
        self.step_fn = MultiMfShardedTrainStep(
            model, self.tx, table, mesh, desc.batch_size, use_cvm=use_cvm)
        self.state = self.step_fn.init_state(
            self.step_fn.init_params(desc.dense_dim))
        self._rng = jax.random.PRNGKey(seed + 1)
        self.global_step = 0
        self.prefetch = prefetch

    def _group_iter(self, batches):
        from paddlebox_tpu.train.sharded import group_batches
        return group_batches(batches, self.n)

    def _prep(self, group):
        # one split serves both the routing plans and the segments —
        # prepare_global_from_subs avoids re-running the key-class
        # routing on the prefetch critical path
        subs = [self.table.split_batch(b)[0] for b in group]
        plans = self.table.prepare_global_from_subs(subs)
        cps = []
        for c, p in enumerate(plans):
            k_c = p.gather_idx.shape[1]
            segs = []
            for d in range(len(group)):
                sb = subs[d][c]
                s = np.full(k_c, sb.pad_segment, np.int32)
                m = min(sb.segments.shape[0], k_c)
                s[:m] = sb.segments[:m]
                segs.append(s)
            cps.append(ClassPlan(
                resp_idx=jnp.asarray(p.resp_idx),
                serve_rows=jnp.asarray(p.serve_rows),
                serve_valid=jnp.asarray(p.serve_valid),
                serve_slot=jnp.asarray(p.serve_slot),
                gather_idx=jnp.asarray(p.gather_idx),
                segments=jnp.asarray(np.stack(segs))))
        return MmfGlobalBatch(
            plans=tuple(cps),
            dense=jnp.asarray(np.stack([b.dense for b in group])),
            label=jnp.asarray(np.stack([b.label for b in group])),
            show=jnp.asarray(np.stack([b.show for b in group])),
            clk=jnp.asarray(np.stack([b.clk for b in group])))

    def train_pass(self, dataset, log_prefix: str = "") -> Dict[str, float]:
        from paddlebox_tpu.utils.prefetch import prefetch_iter
        timer = Timer()
        timer.start()
        nb = 0
        stats = None
        for gb in prefetch_iter(self._group_iter(dataset.batches()),
                                self._prep, capacity=self.prefetch):
            self.global_step += 1
            rng = jax.random.fold_in(self._rng, self.global_step)
            self.state, stats = self.step_fn(self.state, gb, rng)
            nb += 1
        timer.pause()
        self.sync_table()
        auc_host = AucState(*[jnp.sum(l, axis=0) for l in self.state.auc])
        res = auc_compute(auc_host)
        out = res.as_dict()
        out.update(
            batches=nb, elapsed_sec=timer.elapsed_sec(),
            examples_per_sec=res.ins_num / max(timer.elapsed_sec(), 1e-9),
            last_loss=float(stats["loss"]) if stats is not None
            else float("nan"))
        log.info("%smulti-mf sharded pass: %d global batches, %.0f ex/s, "
                 "auc=%.4f", log_prefix, nb, out["examples_per_sec"],
                 res.auc)
        return out

    def reset_metrics(self) -> None:
        self.state = self.state._replace(auc=init_sharded_auc(self.n))

    def sync_table(self) -> None:
        for t, st in zip(self.table.tables, self.state.tables):
            t.state = st

    def adopt_table(self) -> None:
        """Point the jit state at the class tables' (re)built device
        states — after a tiered begin_pass promotes new pass windows."""
        self.state = self.state._replace(
            tables=tuple(t.state for t in self.table.tables))
