"""Trainer runtime: per-pass training loop with host/device pipelining.

Reference: framework/boxps_trainer.cc (BoxPSTrainer::Run :282 — worker per
device) + boxps_worker.cc (TrainFiles :1278 hot loop, NaN guard :1326,
AddAucMonitor :1267) + the Python surface ``exe.train_from_dataset``
(python/paddle/fluid/executor.py:2412).

TPU-native redesign: instead of one CPU thread per GPU running an op
interpreter, ONE jit step consumes the whole device mesh (data parallelism
lives inside the step as shardings, §parallel); the host side is a prefetch
thread doing what the reference's DataFeed+dedup CUDA kernels did — batch
build + key dedup + row assignment — overlapped with device compute through
a bounded channel.
"""

from __future__ import annotations

import math
import threading
import time
from typing import (Any, Callable, Dict, Iterable, Iterator, List,
                    Optional, Sequence, Tuple)

import jax
import jax.numpy as jnp
import numpy as np
import optax

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.data.batch import SlotBatch
from paddlebox_tpu.data.dataset import Dataset, InMemoryDataset
from paddlebox_tpu.metrics import (AucResult, MetricRegistry, auc_compute,
                                   init_auc_state)
from paddlebox_tpu.ps.table import EmbeddingTable, PullIndex
from paddlebox_tpu.train.step import (DeviceBatch, StepState, TrainStep,
                                      make_device_batch)
from paddlebox_tpu.utils import Channel, Timer
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class NanInfError(RuntimeError):
    pass


class Trainer:
    """Single-replica trainer (multi-chip variant in parallel/)."""

    def __init__(
        self,
        model,
        table: EmbeddingTable,
        desc,                       # DataFeedDesc
        tx: Optional[optax.GradientTransformation] = None,
        use_cvm: bool = True,
        prefetch: int = 4,
        seed: int = 0,
        lr_map: Optional[dict] = None,
        lr_map_base: float = 1.0,
    ) -> None:
        """``lr_map`` — per-param dense lr overrides, name
        (path-substring) → lr against ``lr_map_base``; implemented by
        chaining a per-leaf update scaler after ``tx``
        (box_wrapper.cc:1303-1335, boxps_worker.cc:199-204)."""
        from paddlebox_tpu.utils.compile_cache import \
            enable_compilation_cache
        enable_compilation_cache()
        self.model = model
        self.table = table
        self.desc = desc
        self.tx = tx or optax.adam(1e-3)
        params = None
        if lr_map:
            from paddlebox_tpu.train.dense_modes import (build_lr_scales,
                                                         lr_map_transform)
            params = TrainStep.init_params_for(
                model, desc.batch_size, len(desc.sparse_slots),
                table.mf_dim, desc.dense_dim, use_cvm=use_cvm)
            scales = build_lr_scales(params, lr_map, lr_map_base)
            self.tx = optax.chain(self.tx, lr_map_transform(scales))
        self.step_fn = TrainStep(
            model, self.tx, table.cfg, desc.batch_size,
            len(desc.sparse_slots), use_cvm=use_cvm, rng_seed=seed)
        if params is None:
            params = self.step_fn.init_params(table.mf_dim, desc.dense_dim)
        self.state = self.step_fn.init_state(table.state, params,
                                             init_auc_state())
        # table.state now lives inside self.state; keep table's handle in
        # sync lazily (sync_table()) for save/shrink.
        self.metrics = MetricRegistry()
        self.prefetch = prefetch
        self._rng = jax.random.PRNGKey(seed + 1)
        self.global_step = 0
        self._dump_cfg = None
        self._resident_runners: Dict[Any, Any] = {}
        # per-pass stage timers (PrintSyncTimer role, box_wrapper.cc:1182)
        from paddlebox_tpu.utils.profiler import StageTimers
        self.stage_timers = StageTimers()
        # attach flag-selected telemetry sinks (obs/hub; no-op when the
        # telemetry flags are off)
        from paddlebox_tpu.obs.hub import configure_from_flags
        configure_from_flags()
        # install the env-selected fault plan (no-op without
        # FLAGS.fault_plan; chaos runs need no code changes)
        from paddlebox_tpu.resilience.faults import install_from_flags
        install_from_flags()
        # graceful preemption: SIGTERM/SIGINT become a stop flag the
        # pass loop honors at batch boundaries (resilience/preemption)
        if FLAGS.graceful_shutdown:
            from paddlebox_tpu.resilience import preemption
            preemption.install_signal_handlers()
        self._pass_seq = 0
        # optional per-batch hook, called AFTER the step's state update
        # with the host SlotBatch — streaming record accounting and the
        # at-least-once gates (scripts/stream_check.py) key off it
        self.on_batch_trained: Optional[Callable[[SlotBatch], None]] = None
        # per-window hook for the online daemon (online.OnlineLearner):
        # called from _stream_loop AFTER a window's accounting/telemetry
        # and BEFORE the boundary-save decision, with the completed
        # window index and the dataset — the shrink scheduler and
        # /healthz bookkeeping run here, never mid-pass
        self.on_window_complete: Optional[Callable[[int, object],
                                                   None]] = None
        # set (by the hook) to publish a boundary checkpoint at THIS
        # window boundary regardless of the stream_ckpt_every_windows
        # cadence — a shrink cycle must persist before training resumes
        self.stream_save_now = False
        # set to force the next stream-boundary save to a BASE: shrink
        # decays EVERY row without marking it touched, so a delta save
        # would silently miss the decay on untouched rows and a restore
        # would diverge from the live table. Cleared only after a save
        # actually lands (the no-op dedup path keeps it pending).
        self.stream_force_base = False
        # lifecycle bookkeeping published into every checkpoint cursor
        # (and the boundary artifact manifest): shrink cycle count,
        # last shrink window/rows, live rows — a restore replays to the
        # same live-key set and the daemon resumes its cadence from it
        self.lifecycle: Optional[Dict[str, float]] = None
        # elastic membership poll (train/multihost.ElasticController
        # .poll or equivalent): called at every completed window
        # boundary, AFTER on_window_complete and BEFORE the save
        # decision. A truthy decision is a scale event: the loop
        # publishes a boundary checkpoint and returns (coordinated
        # stop) so the launcher can rebuild the world at the new size
        # and resume from the stream cursor — membership is only ever
        # acted on at completed boundaries, never mid-pass
        self.stream_membership: Optional[Callable[[], object]] = None

    # ---- host-side prefetch: batch build + dedup + row assign + H2D ----
    def _prefetch_iter(
        self, batches: Iterable[SlotBatch], prepare=None,
    ) -> Iterator[Tuple[SlotBatch, DeviceBatch]]:
        """Two chained producer threads — stage 1 does dedup + row assign
        (mutates the host index, so single-threaded), stage 2 does the
        device transfer — so the main thread only dispatches jit steps.
        This is the role split of the reference's DataFeed read thread +
        MiniBatchGpuPack H2D stage, with both overlapped against device
        compute through bounded channels."""
        from paddlebox_tpu.utils.prefetch import prefetch_iter
        prep = prepare or self.table.prepare
        st = self.stage_timers

        def do_prep(b):
            with st.stage("prepare"):
                return b, prep(b)

        def do_h2d(t):
            with st.stage("h2d"):
                return t[0], make_device_batch(t[0], t[1])

        prepared = prefetch_iter(batches, do_prep, capacity=self.prefetch,
                                 name="trainer.prepare")
        return prefetch_iter(prepared, do_h2d, capacity=self.prefetch,
                             name="trainer.h2d")

    def set_dump(self, cfg) -> None:
        """Enable per-sample prediction dump for subsequent passes
        (dump_fields, boxps_worker.cc:1595; pass None to disable)."""
        self._dump_cfg = cfg

    def dump_param(self, path: str) -> int:
        """Named dense-parameter dump (DumpParam, boxps_worker.cc:1633)."""
        from paddlebox_tpu.utils.dump import dump_param
        return dump_param(self.state.params, path)

    def train_pass(self, dataset: Dataset, log_prefix: str = "",
                   checkpoint=None,
                   start_cursor: Optional[dict] = None
                   ) -> Dict[str, float]:
        """One pass over the dataset — train_from_dataset analogue.

        Preemption-safe (docs/RESILIENCE.md §Preemption & mid-pass
        resume): the loop polls the graceful-stop flag at every batch
        boundary; a stop finishes the in-flight step, writes an
        emergency checkpoint with a resume cursor (when ``checkpoint``
        is a CheckpointManager and the dataset's batch order is
        deterministic) and raises ``PreemptedError``. With
        ``FLAGS.ckpt_every_batches > 0`` the same cursor checkpoint is
        also written periodically, bounding replay after a HARD kill.
        ``start_cursor`` (from ``CheckpointManager.load_cursor``)
        resumes a preempted pass: the already-trained batch prefix is
        skipped instead of replayed."""
        from paddlebox_tpu.resilience import preemption
        timer = Timer()
        timer.start()
        self.stage_timers.reset()  # this pass's stages only (report below)
        nb = 0
        stats = None
        dump_writer = None
        if self._dump_cfg is not None:
            from paddlebox_tpu.utils.dump import DumpWriter
            dump_writer = DumpWriter(self._dump_cfg)
        n_ex = 0
        st = self.stage_timers
        skip = 0
        if start_cursor is not None:
            skip = int(start_cursor.get("batch_index", 0))
            log.info("%sresuming pass from cursor: skipping %d "
                     "already-trained batches (step %d)", log_prefix,
                     skip, self.global_step)
        cursor_ok = (checkpoint is not None
                     and getattr(dataset, "supports_cursor_resume",
                                 False))
        # consumption feedback for windowed streams: fold a window into
        # the completed set only once its last batch has TRAINED (the
        # reader group runs ahead of training; docs/RESILIENCE.md
        # §Streaming)
        note_consumed = getattr(dataset, "note_batches_consumed", None)
        every = FLAGS.ckpt_every_batches if cursor_ok else 0
        last_save = (-1, None)  # (batch_index, path) of the newest save
        for batch, dev in self._prefetch_iter(
                dataset.batches(start_batch=skip) if skip
                else dataset.batches()):
            n_ex += int((batch.show > 0).sum())
            self.global_step += 1
            rng = jax.random.fold_in(self._rng, self.global_step)
            # "step" times the jit DISPATCH (host cost of launching the
            # fused step; device completion is async) — with prepare/h2d
            # on the prefetch threads, a slow pass now attributes to
            # host dispatch vs starved prefetch vs device-bound
            with st.stage("step"):
                self.state, stats = self.step_fn(self.state, dev, rng)
            nb += 1
            if note_consumed is not None:
                note_consumed(nb)
            if self.on_batch_trained is not None:
                self.on_batch_trained(batch)
            if len(self.metrics):
                # AddAucMonitor hook: feed registered metric variants.
                # Side channels stay HOST numpy — device metrics convert
                # on device, host metrics (wuauc) avoid a round trip;
                # pred stays the device array (host metrics sync on it).
                ins_w = (batch.show > 0).astype(np.float32)
                with st.stage("metrics"):
                    self.metrics.add_batch(
                        stats["pred"], batch.label, ins_w,
                        uid=batch.uid, rank=batch.rank, cmatch=batch.cmatch)
            if dump_writer is not None and nb % self._dump_cfg.interval == 0:
                dump_writer.add_batch(
                    batch.ins_ids,
                    {"pred": stats["pred"], "label": batch.label,
                     "show": batch.show, "clk": batch.clk},
                    int((batch.show > 0).sum()))
            # loss fetch forces a device sync — only on guard/log steps
            if FLAGS.check_nan_inf or nb % FLAGS.log_period_steps == 0:
                loss = float(stats["loss"])
                if math.isnan(loss) or math.isinf(loss):
                    # reference aborts and dumps scope (boxps_worker.cc:1326)
                    raise NanInfError(
                        f"nan/inf loss at step {self.global_step}")
                if nb % FLAGS.log_period_steps == 0:
                    log.info("%spass step %d loss=%.5f", log_prefix,
                             self.global_step, loss)
            # ---- batch boundary: periodic cursor checkpoint + stop poll
            if every > 0 and nb % every == 0:
                last_save = (skip + nb,
                             self._save_inpass(checkpoint, dataset,
                                               skip + nb,
                                               reason="periodic"))
            if preemption.stop_requested():
                # the dispatched step is already folded into self.state;
                # snapshot it, mark the restart, and exit the pass
                if dump_writer is not None:
                    dump_writer.close()  # flush buffered dump records
                path = None
                if cursor_ok:
                    if last_save[0] == skip + nb:
                        # the periodic save already snapshotted THIS
                        # boundary — a second save at the same step
                        # would only churn (or demote a base to delta)
                        path = last_save[1]
                        from paddlebox_tpu.obs.hub import get_hub
                        if get_hub().active:
                            get_hub().emit(
                                "emergency_checkpoint",
                                reason="preempt", reused=True,
                                batch_index=int(skip + nb),
                                global_step=int(self.global_step),
                                path=path)
                    else:
                        path = self._save_inpass(checkpoint, dataset,
                                                 skip + nb,
                                                 reason="preempt")
                    preemption.write_resume_marker(
                        checkpoint.root, step=int(self.global_step),
                        batch_index=skip + nb,
                        reason=preemption.stop_reason())
                else:
                    log.warning(
                        "%sstop requested but no checkpoint manager / "
                        "deterministic dataset — exiting WITHOUT an "
                        "emergency checkpoint (pass will replay)",
                        log_prefix)
                raise preemption.PreemptedError(
                    f"preempted ({preemption.stop_reason()}) at batch "
                    f"{skip + nb}, step {self.global_step}"
                    + ("" if path is None else f"; emergency checkpoint "
                       f"{path}"),
                    step=int(self.global_step), batch_index=skip + nb,
                    checkpoint_path=path)
        last_loss = float(stats["loss"]) if stats is not None else float("nan")
        if dump_writer is not None:
            dump_writer.close()
        timer.pause()
        self.sync_table()
        if note_consumed is not None:
            # the loop has fully drained the generator, so every window
            # mark is set by now — fold the tail window the in-loop
            # note may have raced (its mark lands when the producer
            # thread resumes past the final yield)
            note_consumed(nb)
        streaming = (getattr(dataset, "stream_cursor_state", None)
                     is not None and getattr(dataset, "windowed", False))
        if cursor_ok and (last_save[0] >= 0 or skip > 0
                          or (streaming and start_cursor is not None)):
            # the pass completed after writing (or resuming from) a
            # mid-pass cursor checkpoint: publish a pass-boundary
            # checkpoint so the newest restorable state does not resume
            # into a pass that already finished. For a windowed stream
            # the boundary checkpoint still carries the STREAM cursor
            # (completed files, empty open window) — losing the
            # completed-file set here would retrain the whole stream on
            # the next restart.
            kw = {}
            if streaming:
                kw = dict(cursor=self._boundary_cursor(dataset),
                          clear_touched=True,
                          metrics=(self.metrics if len(self.metrics)
                                   else None))
            try:
                checkpoint.save(self, delta=checkpoint.has_base(), **kw)
            except ValueError:
                # the cadence hit the pass length exactly and the save
                # at this step is the first BASE — a delta re-save over
                # it is refused, so supersede it with a fresh base
                checkpoint.save(self, delta=False, **kw)
        res = auc_compute(self.state.auc)
        out = res.as_dict()
        # ex/s counts THIS pass's instances (res.ins_num is cumulative
        # across passes until reset_metrics, like the reference registry)
        out.update(batches=nb, examples=n_ex,
                   elapsed_sec=timer.elapsed_sec(),
                   examples_per_sec=n_ex / max(timer.elapsed_sec(), 1e-9),
                   last_loss=last_loss)
        log.info("%spass done: %d batches, %.0f ex/s, auc=%.4f",
                 log_prefix, nb, out["examples_per_sec"], res.auc)
        if FLAGS.profile:
            self.stage_timers.report(log_prefix)  # PrintSyncTimer role
        self._emit_pass("train_pass", out, n_ex, stage_timers=True)
        return out

    # ---- mid-pass resume cursor glue (docs/RESILIENCE.md) ----
    def _pass_cursor(self, dataset, batch_index: int) -> dict:
        """The resume cursor stored with an in-pass checkpoint: enough
        to restart THIS pass at ``batch_index`` — the file-list identity
        + quarantine decisions pin the data, global_step pins both the
        trainer position and the per-step rng fold
        (``fold_in(rng, global_step)``), and the AUC/metric accumulators
        ride the checkpoint itself (dense.pkl / metrics.pkl).

        Schema v2 (backward-compatible: v1 cursors — no ``version`` —
        keep their batch-index semantics): windowed streaming datasets
        add a ``stream`` block (completed files + open window,
        ``QueueDataset.stream_cursor_state``) — resume then skips
        completed files and replays the open window at-least-once
        instead of splicing by batch index."""
        cur = {
            "version": 2,
            "pass_seq": int(self._pass_seq) + 1,
            "fingerprint": dataset.filelist_fingerprint(),
            "files_consumed": len(getattr(dataset, "filelist", [])),
            "batch_index": int(batch_index),
            "global_step": int(self.global_step),
            "rng_fold": int(self.global_step),
            "quarantined_files": sorted(
                p for p, _ in getattr(dataset, "quarantined_files", [])),
        }
        state_fn = getattr(dataset, "stream_cursor_state", None)
        if state_fn is not None:
            s = state_fn(int(batch_index))
            if s is not None:
                cur["stream"] = s
        if self.lifecycle is not None:
            # shrink/aging decisions ride EVERY cursor (boundary and
            # emergency alike) so a restore replays to the same
            # live-key set and the daemon's cadence survives resume
            cur["lifecycle"] = dict(self.lifecycle)
        return cur

    def _boundary_cursor(self, dataset) -> Optional[dict]:
        """The cursor a BETWEEN-PASS checkpoint of a windowed streaming
        dataset must carry (completed files, empty open window) so a
        restart skips every consumed file; None for non-streaming
        datasets (their boundary checkpoints stay cursor-free)."""
        state_fn = getattr(dataset, "stream_cursor_state", None)
        if state_fn is None or not getattr(dataset, "windowed", False):
            return None
        return self._pass_cursor(dataset, 0)

    def _save_inpass(self, checkpoint, dataset, batch_index: int,
                     reason: str) -> str:
        """Write a mid-pass checkpoint (delta once a base exists) with
        the resume cursor + metric snapshot."""
        path = checkpoint.save(
            self, delta=checkpoint.has_base(),
            cursor=self._pass_cursor(dataset, batch_index),
            metrics=self.metrics if len(self.metrics) else None)
        from paddlebox_tpu.obs.hub import get_hub
        hub = get_hub()
        event = ("emergency_checkpoint" if reason == "preempt"
                 else "inpass_checkpoint")
        hub.counter("pbox_inpass_checkpoints_total",
                    "mid-pass cursor checkpoints written").inc(
                        reason=reason)
        if hub.active:
            hub.emit(event, reason=reason, batch_index=int(batch_index),
                     global_step=int(self.global_step), path=path)
        return path

    def _adopt_cursor(self, checkpoint, dataset,
                      step: Optional[int] = None) -> Optional[dict]:
        """Cursor for the trainer's CURRENT position, validated against
        this dataset. Returns the cursor to resume from, or None for a
        full pass. A cursor at our step whose data identity mismatches
        (different file list / different quarantine outcome) is
        dangerous — resuming would splice two different batch streams —
        so the trainer rolls BACK to the latest pass-boundary
        checkpoint instead. The same applies when the dataset cannot
        resume at all (non-deterministic batch order): the trainer
        sits on MID-PASS state, and training a "full" pass from it
        would double-train the consumed prefix."""
        cur = checkpoint.load_cursor(step)
        if cur is None:
            return None
        if int(cur.get("global_step", -1)) != int(self.global_step):
            return None  # cursor belongs to a different position
        reason = None
        stream = cur.get("stream")
        stream = stream if isinstance(stream, dict) else None
        if stream is not None:
            # v2 STREAM cursor: resume is by file window, not batch
            # index — validate that the current filelist still extends
            # the cursor's consumption order (completed files then the
            # open window, quarantined files excluded on both sides)
            if (getattr(dataset, "adopt_stream_cursor", None) is None
                    or not getattr(dataset, "windowed", False)):
                reason = ("cursor belongs to a windowed stream but this "
                          "dataset is not a windowed QueueDataset "
                          "(FLAGS.stream_window_files)")
            else:
                from paddlebox_tpu.data.dataset import chain_digest
                quar = set(cur.get("quarantined_files", []))
                fold = stream.get("files_folded") or {}
                nfold = int(fold.get("count", 0) or 0)
                expect = [str(f) for f in
                          list(stream.get("files_completed", []))
                          + list(stream.get("window_files", []))
                          if str(f) not in quar]
                avail = [f for f in dataset.filelist if f not in quar]
                if nfold and (len(avail) < nfold or chain_digest(
                        "", avail[:nfold]) != fold.get("sha256")):
                    reason = ("stream folded-history fingerprint "
                              "mismatch — the filelist's leading files "
                              "no longer reproduce the cursor's "
                              "compacted consumption prefix")
                elif avail[nfold:nfold + len(expect)] != expect:
                    reason = ("stream file prefix changed — the "
                              "filelist no longer extends the cursor's "
                              "consumption order")
        elif not getattr(dataset, "supports_cursor_resume", False):
            reason = ("dataset batch order is not deterministic "
                      "(supports_cursor_resume is False)")
        else:
            fp = dataset.filelist_fingerprint()
            quar = sorted(p for p, _ in dataset.quarantined_files)
            if (cur.get("fingerprint") != fp
                    or sorted(cur.get("quarantined_files", [])) != quar):
                reason = "fingerprint/quarantine changed"
        if reason is not None:
            boundary = checkpoint.latest_boundary_step()
            if boundary is None:
                # no pass-boundary state exists: replaying a "full"
                # pass from mid-pass state would double-train the
                # consumed prefix — unrecoverable automatically
                raise RuntimeError(
                    f"mid-pass cursor cannot be resumed ({reason}) and "
                    "no pass-boundary checkpoint exists to roll back "
                    "to — restart from scratch or restore the original "
                    "file list / deterministic load settings")
            log.warning(
                "mid-pass cursor at step %s cannot be resumed (%s) — "
                "rolling back to pass-boundary step %s",
                self.global_step, reason, boundary)
            checkpoint.restore(self, step=boundary)
            return None
        if stream is not None:
            fold = stream.get("files_folded") or {}
            nfold = int(fold.get("count", 0) or 0)
            completed = [str(f) for f in stream.get("files_completed",
                                                    [])]
            dsc = getattr(dataset, "files_completed", None)
            # with a folded history the cursor names only the tail —
            # the folded prefix was fingerprint-checked above, so the
            # dataset sits at the cursor iff lengths line up and the
            # named tail matches
            if (not stream.get("window_files") and dsc is not None
                    and len(dsc) == nfold + len(completed)
                    and dsc[nfold:] == completed):
                # in-process continuation at a stream BOUNDARY: the
                # dataset already sits exactly where the cursor points
                # (the previous window's boundary save) — nothing to
                # adopt, and counting it as a "resume" would bury the
                # real replay events in per-window noise. Still consume
                # a leftover resume marker (a restart whose kill landed
                # before anything trained matches this branch too).
                from paddlebox_tpu.resilience import preemption
                preemption.clear_resume_marker(checkpoint.root)
                return None
            # skip completed files, replay the open window from its
            # start (at-least-once), and carry the quarantine decisions
            # forward; batch_index is forced to 0 — there is no batch
            # splice in a thread-interleaved stream
            dataset.adopt_stream_cursor(
                stream, quarantined=cur.get("quarantined_files", []))
            cur = dict(cur, batch_index=0)
        mr = checkpoint.load_metrics(step)
        if mr is not None:
            self.metrics = mr
        from paddlebox_tpu.resilience import preemption
        preemption.clear_resume_marker(checkpoint.root)
        from paddlebox_tpu.obs.hub import get_hub
        hub = get_hub()
        hub.counter("pbox_cursor_resumes_total",
                    "passes resumed mid-pass from a cursor").inc()
        if hub.active:
            fields = {}
            if stream is not None:
                fields = dict(
                    stream=True,
                    files_completed=nfold + len(
                        stream.get("files_completed", [])),
                    folded_files=nfold,
                    replay_files=len(stream.get("window_files", [])))
            hub.emit("cursor_resume",
                     global_step=int(self.global_step),
                     batch_index=int(cur.get("batch_index", 0)),
                     pass_seq=cur.get("pass_seq"), **fields)
        return cur

    def _reject_cursor_state(self, checkpoint) -> None:
        """Resident-mode guard: a trainer sitting on a MID-PASS cursor
        checkpoint cannot hand the pass to ``train_pass_resident`` (one
        device program — no mid-pass entry point); training a "full"
        pass from mid-pass state would double-train the consumed
        prefix. Roll back to the pass boundary, or refuse."""
        cur = checkpoint.load_cursor()
        if cur is None or int(cur.get("global_step", -1)) \
                != int(self.global_step):
            return
        boundary = checkpoint.latest_boundary_step()
        if boundary is None:
            raise RuntimeError(
                "trainer state is mid-pass (cursor checkpoint) but "
                "resident passes cannot resume mid-pass, and no "
                "pass-boundary checkpoint exists to roll back to — "
                "finish the pass in streaming mode first")
        log.warning(
            "mid-pass cursor at step %s cannot feed a resident pass — "
            "rolling back to pass-boundary step %s", self.global_step,
            boundary)
        checkpoint.restore(self, step=boundary)

    def run_pass(self, dataset: Dataset, checkpoint=None,
                 log_prefix: str = "", resident: bool = False,
                 max_retries: Optional[int] = None) -> Dict[str, float]:
        """``train_pass`` with bounded retry-from-last-checkpoint and
        cursor-aware recovery (docs/RESILIENCE.md §pass-level recovery,
        §Preemption & mid-pass resume).

        A pass that dies on a *recoverable* error (transient IO /
        injected fault) is retried up to ``FLAGS.pass_retry_limit``
        (override with ``max_retries``) times. With a ``checkpoint``
        (CheckpointManager), each retry first rolls the trainer back to
        the last consistent step — and when that step carries a mid-pass
        cursor matching this dataset, the retry REPLAYS ONLY the batches
        after it instead of the whole pass. The same applies on entry:
        a freshly-restored trainer sitting on a cursor checkpoint
        resumes the interrupted pass seamlessly. A ``NanInfError`` is
        only recoverable when a checkpoint can roll the poisoned state
        back — without one, retrying from live NaN state would just
        re-diverge, so it raises immediately. ``PreemptedError`` (a
        deliberate graceful shutdown) is never retried.

        Resident passes run as ONE device program and cannot stop at a
        batch boundary; the stop flag is honored at PASS granularity
        instead — checked before every attempt, so a preempted
        resident job exits (with an inter-pass checkpoint) before
        dispatching the next pass."""
        from paddlebox_tpu.resilience import faults, preemption
        from paddlebox_tpu.resilience.preemption import PreemptedError
        from paddlebox_tpu.resilience.retry import is_retryable
        limit = (FLAGS.pass_retry_limit if max_retries is None
                 else max_retries)
        attempt = 0
        start_cursor = None
        if checkpoint is not None:
            if resident:
                self._reject_cursor_state(checkpoint)
            else:
                # restart path: a launcher that restored to a mid-pass
                # checkpoint resumes the interrupted pass here
                start_cursor = self._adopt_cursor(checkpoint, dataset)
        while True:
            try:
                if preemption.stop_pending():
                    # graceful stop BETWEEN passes/attempts (the only
                    # stop point a resident pass has). Without an
                    # adopted cursor the state sits at a pass boundary
                    # — snapshot it; with one, the mid-pass checkpoint
                    # already on disk covers the state.
                    path = None
                    if checkpoint is not None:
                        if start_cursor is None:
                            # publish the boundary state (windowed
                            # streams carry their boundary cursor so
                            # the restart skips every consumed file;
                            # a step already on disk is reused)
                            path = self._stream_boundary_save(
                                dataset, checkpoint)
                        preemption.write_resume_marker(
                            checkpoint.root, step=int(self.global_step),
                            reason=preemption.stop_reason())
                    raise PreemptedError(
                        f"preempted ({preemption.stop_reason()}) "
                        f"before pass dispatch at step "
                        f"{self.global_step}",
                        step=int(self.global_step),
                        checkpoint_path=path)
                faults.inject("trainer.pass", attempt=attempt)
                if resident:
                    return self.train_pass_resident(dataset, log_prefix)
                return self.train_pass(dataset, log_prefix,
                                       checkpoint=checkpoint,
                                       start_cursor=start_cursor)
            except PreemptedError:
                raise  # deliberate shutdown — the launcher handles it
            except Exception as e:
                # NaN needs a real rollback TARGET, not just a manager:
                # with nothing saved yet, restore() is a no-op and every
                # retry would replay from the poisoned live state. And
                # the target must be a PASS BOUNDARY — a mid-pass cursor
                # checkpoint may itself hold the poison (params go NaN
                # one batch before the loss guard can see it)
                recoverable = (is_retryable(e)
                               or (isinstance(e, NanInfError)
                                   and checkpoint is not None
                                   and checkpoint.latest_boundary_step()
                                   is not None))
                if attempt >= limit or not recoverable:
                    raise
                attempt += 1
                from paddlebox_tpu.obs.hub import get_hub
                hub = get_hub()
                hub.counter("pbox_pass_retries_total",
                            "pass-level recovery retries").inc()
                if hub.active:
                    hub.emit("pass_retry", attempt=attempt, limit=limit,
                             error=repr(e),
                             global_step=self.global_step)
                if checkpoint is not None:
                    if isinstance(e, NanInfError):
                        # the black-box seam (obs/flightrec): a NaN
                        # rollback is a postmortem-worthy anomaly —
                        # dump the recent-event ring + instrument
                        # snapshot BEFORE the restore overwrites the
                        # poisoned state, and book the counter the
                        # nan_rollback alert rule watches
                        from paddlebox_tpu.obs import flightrec
                        hub.counter(
                            "pbox_nan_rollbacks_total",
                            "NaN/Inf passes rolled back to a clean "
                            "boundary").inc()
                        flightrec.trigger(
                            "nan_rollback", reason=repr(e),
                            global_step=self.global_step,
                            attempt=attempt, limit=limit)
                        # mid-pass snapshots are suspect (see above):
                        # roll all the way back to the clean boundary.
                        # A STREAM boundary still carries its stream
                        # cursor — adopt it so the dataset's
                        # completed-file view matches the restored
                        # state (for batch cursors this is a no-op:
                        # boundary checkpoints have no cursor)
                        restored = checkpoint.restore(
                            self, step=checkpoint.latest_boundary_step())
                        start_cursor = self._adopt_cursor(
                            checkpoint, dataset, restored)
                    elif resident:
                        restored = checkpoint.restore(self)
                        self._reject_cursor_state(checkpoint)
                        start_cursor = None
                    else:
                        restored = checkpoint.restore(self)
                        start_cursor = self._adopt_cursor(checkpoint,
                                                          dataset,
                                                          restored)
                    log.warning(
                        "%spass failed (%r) — rolled back to step %s%s, "
                        "retry %d/%d", log_prefix, e, restored,
                        ("" if start_cursor is None else
                         f" (cursor: batch "
                         f"{start_cursor.get('batch_index')})"),
                        attempt, limit)
                else:
                    log.warning(
                        "%spass failed (%r) — no checkpoint manager, "
                        "retrying from current state (%d/%d)",
                        log_prefix, e, attempt, limit)

    # ---- continuous streaming ingest (docs/RESILIENCE.md §Streaming) ----
    def train_stream(self, dataset, checkpoint=None, *,
                     filelist_fn: Optional[Callable[[], Sequence]] = None,
                     max_windows: Optional[int] = None,
                     max_idle_polls: Optional[int] = None,
                     log_prefix: str = "") -> Dict[str, float]:
        """Always-on streaming loop: train arriving files through a
        windowed ``QueueDataset`` (``FLAGS.stream_window_files``), one
        window per pass, forever (or until the source dries up / a
        bound is hit).

        - **Arrivals**: ``filelist_fn()`` is polled for the current file
          list each iteration (new files append in poll order); with no
          ``filelist_fn`` the dataset's static filelist is drained and
          the loop ends. Empty polls emit ``stream_idle`` events and
          back off on the seeded ``RetryPolicy`` schedule
          (site ``stream.poll`` — deterministic per FLAGS.seed);
          arrivals reset the backoff. ``max_idle_polls`` bounds
          consecutive empty polls (None = poll forever).
        - **Checkpoints**: a stream-boundary checkpoint (v2 cursor:
          completed files, empty open window) publishes every
          ``FLAGS.stream_ckpt_every_windows`` completed windows, so a
          hard kill replays at most that many windows.
        - **Preemption** honors the full run_pass contract: SIGTERM
          mid-window raises ``PreemptedError`` after an emergency
          checkpoint whose stream cursor marks the open window; a
          restarted process (``CheckpointManager.restore`` then
          ``train_stream`` again) skips completed files and replays the
          open window AT-LEAST-ONCE — byte-identical to the
          uninterrupted run at the last common window boundary, modulo
          the documented replay window. Stops during the idle loop
          snapshot a boundary cursor the same way.
        - **Telemetry**: ``pbox_stream_{windows,files,replayed_files,
          idle_polls}_total`` counters, the ``pbox_stream_lag_files``
          backlog gauge (pending files not yet dispatched — the
          straggler watchdog's stalled-stream escalation signal), and
          ``stream_window``/``stream_idle`` events.
        """
        from paddlebox_tpu.obs.hub import get_hub
        if not getattr(dataset, "windowed", False):
            raise ValueError(
                "train_stream needs a windowed QueueDataset — set "
                "FLAGS.stream_window_files > 0 (the unbounded "
                "unwindowed stream cannot checkpoint/resume)")
        known: List[str] = [str(f) for f in dataset.filelist]
        # resume: seed the dataset's stream position and the known-file
        # order from the newest stream cursor, so the first window pass
        # reconstructs the cursor's consumption order exactly
        if checkpoint is not None and not dataset.files_completed:
            cur = checkpoint.load_cursor()
            stream = (cur or {}).get("stream")
            if isinstance(stream, dict):
                if int(cur.get("global_step", -1)) \
                        != int(self.global_step):
                    raise RuntimeError(
                        f"stream cursor at step "
                        f"{cur.get('global_step')} does not match "
                        f"trainer step {self.global_step} — restore "
                        "the checkpoint first "
                        "(CheckpointManager.restore) or point at a "
                        "fresh checkpoint root")
                dataset.adopt_stream_cursor(
                    stream,
                    quarantined=cur.get("quarantined_files", []))
                # the dataset expanded any folded (compacted) history
                # back to names from its filelist — read the prefix
                # from it, not from the cursor's (tail-only) block
                prefix = (list(dataset.files_completed)
                          + [str(f) for f in
                             stream.get("window_files", [])])
                seen = set(prefix)
                known = prefix + [f for f in known if f not in seen]
                if not stream.get("window_files"):
                    # a fresh process resuming at a window BOUNDARY
                    # (e.g. after SIGKILL): _adopt_cursor will treat
                    # the now-positioned dataset as an in-process
                    # continuation and stay silent, so this seam is
                    # the only place the restart-resume is visible —
                    # record it (mid-window cursors keep their single
                    # replay event from _adopt_cursor)
                    hub = get_hub()
                    hub.counter(
                        "pbox_cursor_resumes_total",
                        "passes resumed mid-pass from a cursor").inc()
                    if hub.active:
                        hub.emit(
                            "cursor_resume", stream=True,
                            global_step=int(self.global_step),
                            batch_index=0, replay_files=0,
                            files_completed=len(
                                dataset.files_completed))
        hub = get_hub()
        totals = {"windows": 0, "files": 0, "batches": 0,
                  "examples": 0, "replayed_files": 0, "idle_polls": 0}
        try:
            self._stream_loop(dataset, checkpoint, filelist_fn,
                              max_windows, max_idle_polls, log_prefix,
                              known, totals, hub)
        finally:
            # each window pass narrowed the filelist to its consumption
            # order — restore the full known list on EVERY exit
            # (preemption included) so a later train_stream call or
            # pending_files() probe still sees the whole stream
            dataset.set_filelist(known)
        log.info("%sstream done: %d windows, %d files (%d replayed), "
                 "%d batches", log_prefix, totals["windows"],
                 totals["files"], totals["replayed_files"],
                 totals["batches"])
        return totals

    def _stream_loop(self, dataset, checkpoint, filelist_fn,
                     max_windows, max_idle_polls, log_prefix,
                     known: List[str], totals: Dict[str, float],
                     hub) -> None:
        from paddlebox_tpu.resilience import preemption
        from paddlebox_tpu.resilience.retry import RetryPolicy
        wsize = FLAGS.stream_window_files
        since_ckpt = 0
        idle_run = 0
        backoff = iter(())  # armed lazily; reset on every arrival
        while True:
            if max_windows is not None \
                    and totals["windows"] >= max_windows:
                break
            if preemption.stop_pending():
                # idle/between-window stop: run_pass would catch it too,
                # but the poll loop must honor it without pending work —
                # and the snapshot must carry the stream boundary cursor
                self._stream_stop(dataset, checkpoint)
            if filelist_fn is not None:
                have = set(known)
                known.extend(str(f) for f in filelist_fn()
                             if str(f) not in have)
            dataset.set_filelist(known)
            pending = dataset.pending_files()
            hub.gauge("pbox_stream_lag_files",
                      "stream backlog: pending files not yet "
                      "dispatched into a window").set(
                          max(0, len(pending) - wsize))
            if not pending:
                if filelist_fn is None:
                    break
                idle_run += 1
                totals["idle_polls"] += 1
                if max_idle_polls is not None \
                        and idle_run > max_idle_polls:
                    break
                delay = next(backoff, None)
                if delay is None:
                    # (re)arm the seeded schedule; cap attempts high —
                    # the schedule plateaus at retry_max_delay_sec
                    backoff = RetryPolicy.from_flags(
                        site="stream.poll",
                        max_attempts=1 << 20).delays()
                    delay = next(backoff)
                hub.counter("pbox_stream_idle_polls_total",
                            "filelist polls that found no new files"
                            ).inc()
                if hub.active:
                    hub.emit("stream_idle", idle_polls=idle_run,
                             backoff_sec=round(delay, 4),
                             known_files=len(known))
                self._stream_sleep(delay)
                continue
            idle_run = 0
            backoff = iter(())
            window = pending[:wsize]
            # the pass's filelist is exactly the consumption order the
            # cursor records: completed files then this window (files
            # quarantined earlier are excluded from both)
            dataset.set_filelist(dataset.files_completed + window)
            widx = totals["windows"]
            rep0 = int(getattr(dataset, "files_replayed", 0))
            out = self.run_pass(dataset, checkpoint=checkpoint,
                                log_prefix=f"{log_prefix}stream "
                                           f"w{widx}: ")
            # files_replayed is cumulative on the dataset — book the
            # per-window delta so a resumed dataset's history doesn't
            # bleed into this call's totals/events
            replayed = int(getattr(dataset, "files_replayed", 0)) - rep0
            # files CONSUMED, not dispatched: a window file quarantined
            # during this pass never trained, so it must not inflate
            # the throughput totals (bench stream mode divides by them)
            # or desync pbox_stream_files_total from files_completed
            quarantined = {p for p, _ in
                           getattr(dataset, "quarantined_files", [])}
            consumed = [f for f in window if f not in quarantined]
            totals["windows"] += 1
            totals["files"] += len(consumed)
            totals["batches"] += int(out.get("batches", 0))
            totals["examples"] += int(out.get("examples", 0))
            totals["replayed_files"] += replayed
            totals.update({k: out[k] for k in ("auc", "last_loss")
                           if k in out})
            since_ckpt += 1
            hub.counter("pbox_stream_windows_total",
                        "stream windows fully trained").inc()
            hub.counter("pbox_stream_files_total",
                        "files consumed by stream windows").inc(
                            len(consumed))
            if hub.active:
                hub.emit("stream_window", window=widx,
                         files=len(consumed),
                         batches=int(out.get("batches", 0)),
                         lag_files=max(0, len(pending) - len(window)),
                         replayed_files=replayed,
                         global_step=int(self.global_step))
            if self.on_window_complete is not None:
                # the online daemon's boundary work (shrink scheduling,
                # /healthz bookkeeping) — between passes by
                # construction, and BEFORE the save decision so a
                # shrink cycle's stream_save_now/stream_force_base
                # requests take effect at THIS boundary (no training
                # lands between the shrink and its base save)
                self.on_window_complete(int(widx), dataset)
            if self.stream_membership is not None:
                decision = self.stream_membership()
                if decision:
                    # scale event at a COMPLETED boundary: persist the
                    # boundary (checkpoint + stream cursor) and hand
                    # control back — the launcher re-shards to the new
                    # world and resumes from this cursor. No data
                    # rollback: only completed-window state is saved.
                    if checkpoint is not None:
                        self._stream_boundary_save(dataset, checkpoint)
                    totals["membership"] = decision
                    log.warning("stream stop at window %d boundary for "
                                "membership change: %s", widx, decision)
                    return
            if checkpoint is not None and (
                    since_ckpt >= max(1, FLAGS.stream_ckpt_every_windows)
                    or self.stream_save_now):
                self._stream_boundary_save(dataset, checkpoint)
                since_ckpt = 0
                self.stream_save_now = False

    def _stream_boundary_save(self, dataset, checkpoint) -> str:
        """Publish a boundary checkpoint: for a windowed stream it
        carries the stream cursor (completed files, empty open window);
        for any other dataset ``_boundary_cursor`` is None and this is
        a plain cursor-free boundary save. A no-op when this step is
        already on disk (e.g. the window pass published a boundary
        after a mid-pass save or a cursor resume — a re-save would
        refuse as a delta over a base)."""
        if checkpoint.latest_step() == int(self.global_step):
            # NOTE: a pending stream_force_base stays pending through
            # this dedup — the post-shrink state is then captured by
            # the next boundary that actually saves (deterministic
            # either way: a restore replays the shrink at the same
            # windows_completed index)
            return checkpoint._dir(int(self.global_step))
        cursor = self._boundary_cursor(dataset)
        # clear_touched=True only with a stream cursor: a cursor-free
        # boundary save must stay kwarg-free so duck-typed tables whose
        # save surface predates the kwarg (sharded/tiered/multi_mf)
        # keep working on the generic graceful-stop path
        path = checkpoint.save(
            self,
            delta=checkpoint.has_base() and not self.stream_force_base,
            cursor=cursor,
            clear_touched=True if cursor is not None else None,
            metrics=self.metrics if len(self.metrics) else None)
        self.stream_force_base = False
        if cursor is not None:
            # this boundary checkpoint now records every completed file
            # BY NAME — fold them into the compact count+fingerprint
            # form so later cursors stay O(files since this boundary)
            fold = getattr(dataset, "fold_completed_history", None)
            if fold is not None:
                fold()
        return path

    def _stream_stop(self, dataset, checkpoint) -> None:
        """Graceful stop from the stream loop (idle poll / between
        windows): snapshot a stream-boundary checkpoint, write the
        resume marker, raise — the run_pass preemption contract."""
        from paddlebox_tpu.resilience import preemption
        path = None
        if checkpoint is not None:
            path = self._stream_boundary_save(dataset, checkpoint)
            preemption.write_resume_marker(
                checkpoint.root, step=int(self.global_step),
                reason=preemption.stop_reason())
        raise preemption.PreemptedError(
            f"preempted ({preemption.stop_reason()}) in the stream "
            f"loop at step {self.global_step}",
            step=int(self.global_step), checkpoint_path=path)

    @staticmethod
    def _stream_sleep(sec: float) -> None:
        """Stop-aware sleep: wakes early when a graceful stop arrives so
        the grace window is not burned idling."""
        from paddlebox_tpu.resilience import preemption
        deadline = time.monotonic() + sec
        while True:
            if preemption.stop_pending():
                return
            left = deadline - time.monotonic()
            if left <= 0:
                return
            time.sleep(min(0.05, left))

    def _emit_pass(self, kind: str, out: Dict[str, float], examples: int,
                   stage_timers: bool = False) -> None:
        """Per-pass telemetry record (obs/hub.emit_pass_event); returns
        immediately when no sink is attached."""
        from paddlebox_tpu.obs.hub import emit_pass_event, get_hub
        if not get_hub().active:
            return
        self._pass_seq += 1
        emit_pass_event(
            kind, dict(out, global_step=self.global_step,
                       pass_seq=self._pass_seq),
            stage_timers=self.stage_timers if stage_timers else None,
            table=self.table, examples=examples,
            # the quality monitor (obs/quality) diffs the AUC bucket
            # tables per pass for its calibration windows; a bare
            # reference costs nothing when quality is off
            auc_state=getattr(self.state, "auc", None))

    def _feed_registry_resident(self, rp, preds) -> None:
        """Post-pass metric registry feed (the per-batch AddAucMonitor
        hook, replayed from resident predictions + the dataset's
        columnar side channels)."""
        sd = rp.side
        bs = sd["batch_size"]
        r = sd["num_records"]
        preds_h = np.asarray(preds)               # ONE D2H fetch
        for i in range(rp.num_batches):
            a, b = i * bs, min((i + 1) * bs, r)
            m = b - a  # ≥ 1: nb is ceil(r/bs) by construction
            ins_w = (sd["show"][a:b] > 0).astype(np.float32)
            self.metrics.add_batch(
                preds_h[i, :m], sd["label"][a:b], ins_w,
                uid=None if sd["uid"] is None else sd["uid"][a:b],
                rank=None if sd["rank"] is None else sd["rank"][a:b],
                cmatch=(None if sd["cmatch"] is None
                        else sd["cmatch"][a:b]))

    def train_pass_resident(self, pass_or_dataset,
                            log_prefix: str = "") -> Dict[str, float]:
        """One pass in device-resident mode (train/device_pass.py): the
        pass's batches are staged to HBM in bulk and the whole loop runs
        on device via lax.fori_loop — zero per-batch host→device hops.
        Accepts a Dataset (built+uploaded inline) or a prebuilt
        ResidentPass (e.g. from PassPreloader double-buffering).

        Per-sample dumps need host visibility of every batch, which this
        mode gives up by design — with a dump configured, fall back to
        the streaming pass (for a prebuilt ResidentPass that is
        impossible, so raise instead of silently writing no dump)."""
        from paddlebox_tpu.train.device_pass import (ResidentPass,
                                                     ResidentPassRunner)
        if self._dump_cfg is not None:
            if isinstance(pass_or_dataset, ResidentPass):
                raise ValueError(
                    "dump is configured (set_dump) but a prebuilt "
                    "ResidentPass has no host-side batches to dump — "
                    "pass the Dataset, or set_dump(None)")
            log.warning("dump configured: falling back to streaming "
                        "train_pass for this pass")
            return self.train_pass(pass_or_dataset, log_prefix)
        want_metrics = len(self.metrics) > 0
        timer = Timer()
        timer.start()
        self.stage_timers.reset()
        st = self.stage_timers
        if isinstance(pass_or_dataset, ResidentPass):
            rp = pass_or_dataset
        else:
            with st.stage("build"):
                rp = ResidentPass.build(pass_or_dataset, self.table)
        trivial = rp.segs is None
        wire = getattr(rp, "wire", "dedup")
        key = (rp.key_capacity, trivial, wire, rp.chunk_bits)
        runner = self._resident_runners.get(key)
        if runner is None:
            runner = ResidentPassRunner(
                self.step_fn, self.table.capacity, trivial, wire=wire,
                num_slots=self.step_fn.num_slots,
                chunk_bits=getattr(rp, "chunk_bits", None))
            self._resident_runners[key] = runner
        # "step" covers dispatch + device completion here (the resident
        # loop is one XLA program; the block is the honest device time).
        # The consume span links back to the pass's build span on the
        # preloader lane (obs/trace — the cross-thread flow arrow)
        from paddlebox_tpu.obs import trace
        with trace.span("pass.consume",
                        link_from=getattr(rp, "_trace_span_id", 0)), \
                st.stage("step"):
            self.state, preds = runner.run_pass(
                self.state, rp, self._rng,
                collect_preds=want_metrics and rp.side is not None)
            jax.block_until_ready(self.state.step)
        rp.mark_trained_rows(self.table)
        if want_metrics:
            if rp.side is None:
                log.warning(
                    "registry metrics need columnar side channels — "
                    "this pass was built from a non-columnar dataset; "
                    "use train_pass for metric variants here")
            else:
                with st.stage("metrics"):
                    self._feed_registry_resident(rp, preds)
        self.global_step += rp.num_batches
        timer.pause()
        self.sync_table()
        res = auc_compute(self.state.auc)
        out = res.as_dict()
        out.update(batches=rp.num_batches, elapsed_sec=timer.elapsed_sec(),
                   examples_per_sec=rp.num_records /
                   max(timer.elapsed_sec(), 1e-9))
        if FLAGS.check_nan_inf and math.isnan(out.get("auc", 0.0)):
            raise NanInfError(f"nan metrics after resident pass "
                              f"at step {self.global_step}")
        log.info("%sresident pass done: %d batches, %.0f ex/s, auc=%.4f",
                 log_prefix, rp.num_batches, out["examples_per_sec"],
                 res.auc)
        self._emit_pass("train_pass_resident", out, rp.num_records,
                        stage_timers=True)
        return out

    def train_passes_resident(self, datasets: Iterable[Dataset],
                              depth: Optional[int] = None,
                              floats_dtype=np.float32,
                              checkpoint=None,
                              log_prefix: str = "") -> list:
        """Drive device-resident passes through the depth-N preload
        pipeline (train/device_pass.PassPreloader,
        FLAGS.preload_depth): builds for passes k+1..k+depth run on the
        pipeline worker while pass k trains, so the prologue build
        leaves the pass critical path (docs/PERFORMANCE.md §Deep pass
        pipeline). Returns the per-pass result dicts.

        Preemption-safe at PASS granularity: the stop flag is checked
        before every dispatch; on a stop the preloader DRAINS first (no
        orphan preload H2D contending with the checkpoint's D2H), a
        boundary checkpoint is written when a manager is given, and
        ``PreemptedError`` raises — the run_pass contract."""
        from paddlebox_tpu.resilience import preemption
        from paddlebox_tpu.resilience.preemption import PreemptedError
        from paddlebox_tpu.train.device_pass import PassPreloader
        pre = PassPreloader(iter(datasets), self.table,
                            floats_dtype=floats_dtype, depth=depth)
        pre.start_next()
        results = []
        try:
            while True:
                rp = pre.wait()
                # a stop with an empty queue also lands here (the
                # worker aborts its build and wait() returns None) —
                # it must still raise, not return as if complete
                if rp is None and not preemption.stop_pending():
                    break
                if preemption.stop_pending():
                    pre.drain()
                    if rp is not None and getattr(rp, "dev", None) \
                            is not None:
                        # the popped pass left the queue before drain()
                        # could settle it — wait its wire out too
                        jax.block_until_ready(
                            list(jax.tree.leaves(rp.dev)))
                    path = None
                    if checkpoint is not None:
                        path = checkpoint.save(
                            self, delta=checkpoint.has_base())
                        preemption.write_resume_marker(
                            checkpoint.root, step=int(self.global_step),
                            reason=preemption.stop_reason())
                    raise PreemptedError(
                        f"preempted ({preemption.stop_reason()}) before "
                        f"resident pass dispatch at step "
                        f"{self.global_step}",
                        step=int(self.global_step), checkpoint_path=path)
                pre.start_next()
                results.append(
                    self.train_pass_resident(rp, log_prefix=log_prefix))
        finally:
            pre.drain()
        return results

    def eval_pass(self, dataset: Dataset,
                  log_prefix: str = "") -> Dict[str, float]:
        """Forward-only pass: AUC on frozen params/table, no updates, no
        index growth (reference test-phase / infer semantics)."""
        auc = init_auc_state()
        nb = 0
        timer = Timer()
        timer.start()
        self.stage_timers.reset()
        it = self._prefetch_iter(dataset.batches(),
                                 prepare=self.table.prepare_eval)
        st = self.stage_timers
        for batch, dev in it:
            with st.stage("step"):
                auc, pred = self.step_fn.eval(self.state.table,
                                              self.state.params, auc, dev)
            if len(self.metrics):
                # test-phase metric feed (same hook as train_pass)
                with st.stage("metrics"):
                    self.metrics.add_batch(
                        pred, batch.label,
                        (batch.show > 0).astype(np.float32),
                        uid=batch.uid, rank=batch.rank, cmatch=batch.cmatch)
            nb += 1
        timer.pause()
        res = auc_compute(auc)
        out = res.as_dict()
        out.update(batches=nb, elapsed_sec=timer.elapsed_sec(),
                   examples_per_sec=res.ins_num / max(timer.elapsed_sec(),
                                                      1e-9))
        log.info("%seval pass: %d batches, auc=%.4f", log_prefix, nb,
                 res.auc)
        self._emit_pass("eval_pass", out, int(res.ins_num),
                        stage_timers=True)
        return out

    def sync_table(self) -> None:
        """Write the jit-updated table state back to the EmbeddingTable
        facade (for save/shrink/load host ops)."""
        self.table.state = self.state.table

    def fence_table(self) -> None:
        """Drain the table's async end_pass epilogue (ps/epilogue) and
        surface the first write-back failure; no-op for tables without
        one. NOT called at pass boundaries — that would re-serialize
        the overlap; checkpoint capture and host-tier reads fence
        themselves."""
        fence = getattr(self.table, "fence", None)
        if fence is not None:
            fence()

    def restore_state(self, params, opt_state, auc, step: int) -> None:
        """Rebind dense + metric state after a checkpoint restore (the
        table was already loaded); CheckpointManager's trainer hook."""
        self.state = StepState(table=self.table.state, params=params,
                               opt_state=opt_state, auc=auc,
                               step=jnp.asarray(step, jnp.int32))
        self.global_step = step

    def adopt_table(self) -> None:
        """Point the jit state at the table facade's (re)built state —
        used by the pass lifecycle after begin_pass swaps the working set."""
        self.state = self.state._replace(table=self.table.state)

    def reset_metrics(self) -> None:
        self.state = self.state._replace(auc=init_auc_state())

    # ---- checkpoint glue (dense + sparse) ----
    def save(self, prefix: str) -> None:
        import pickle
        self.sync_table()
        # pass-window tables: drain the async end_pass epilogue so the
        # dump never races an in-flight write-back (CheckpointManager
        # fences the same way)
        self.fence_table()
        self.table.save_base(prefix + ".sparse.npz")
        with open(prefix + ".dense.pkl", "wb") as fh:
            pickle.dump(jax.device_get((self.state.params,
                                        self.state.opt_state)), fh)

    def load(self, prefix: str) -> None:
        import pickle
        self.table.load(prefix + ".sparse.npz")
        with open(prefix + ".dense.pkl", "rb") as fh:
            params, opt_state = pickle.load(fh)
        self.state = StepState(
            table=self.table.state,
            params=jax.device_put(params),
            opt_state=jax.device_put(opt_state),
            auc=self.state.auc, step=self.state.step)
