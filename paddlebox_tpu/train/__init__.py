from paddlebox_tpu.train.step import TrainStep, DeviceBatch, make_device_batch
from paddlebox_tpu.train.trainer import Trainer
from paddlebox_tpu.train.dense_modes import (AsyncDenseTable, KStepParamSync,
                                             build_lr_scales,
                                             lr_map_transform)
from paddlebox_tpu.train.device_pass import (PassPipeline,
                                             PassPreloader,
                                             PreloadBuildAborted,
                                             ResidentPass,
                                             ResidentPassRunner)
from paddlebox_tpu.train.checkpoint import CheckpointManager
from paddlebox_tpu.train.multi_mf_step import (MultiMfTrainStep,
                                               MultiMfTrainer)
from paddlebox_tpu.train.sharded import ShardedTrainer
from paddlebox_tpu.train.multi_mf_sharded import MultiMfShardedTrainer

__all__ = ["TrainStep", "DeviceBatch", "make_device_batch", "Trainer",
           "AsyncDenseTable", "KStepParamSync", "build_lr_scales",
           "lr_map_transform",
           "PassPipeline", "PassPreloader", "PreloadBuildAborted",
           "ResidentPass",
           "ResidentPassRunner",
           "CheckpointManager", "MultiMfTrainStep", "MultiMfTrainer",
           "ShardedTrainer", "MultiMfShardedTrainer"]
