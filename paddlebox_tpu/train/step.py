"""The fused train step — pull → fwd → bwd → push → dense update → metrics,
one jit-compiled XLA program.

Reference hot loop: BoxPSWorker::TrainFiles (framework/boxps_worker.cc:1278)
runs the ProgramDesc op list per batch: pull_box_sparse →
fused_seqpool_cvm → dense net fwd/bwd → push_box_sparse, then metric add.
Here the entire loop body is ONE traced function: XLA fuses the gather,
segment ops, MXU matmuls, scatter update and AUC histogram into a single
device program with zero host round-trips; buffer donation makes the table
and optimizer states update in place.

The pooling+CVM inside is itself a dispatch seam: under
``FLAGS.use_pallas_seqpool`` the ``fused_seqpool_cvm`` call (and its
backward feeding the push) routes to the fused Pallas MXU kernel
(ops/pallas_kernels.fused_pool_cvm_forward / segment_gather_mxu —
docs/PERFORMANCE.md §Device kernels); the trivial-layout fast path
(``pool_segments is None``) keeps its free reshape either way.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from paddlebox_tpu.data.batch import SlotBatch
from paddlebox_tpu.metrics import AucState, auc_add_batch
from paddlebox_tpu.ops import fused_seqpool_cvm
from paddlebox_tpu.ps.sgd import SparseSGDConfig
from paddlebox_tpu.ps.table import (PullIndex, TableState, apply_push,
                                    expand_pull, gather_full_rows,
                                    pull_values)


def pack_floats(dense: np.ndarray, label: np.ndarray, show: np.ndarray,
                clk: np.ndarray, dtype=np.float32) -> np.ndarray:
    """THE float-block wire layout, [B, Dd+3] = [dense | label, show, clk].
    Single definition shared by the streaming path (make_device_batch) and
    the resident-pass packer; unpacked only by ``unpack_floats``."""
    return np.concatenate(
        [dense.astype(np.float32, copy=False),
         np.stack([label, show, clk], axis=1)],
        axis=1).astype(dtype, copy=False)


def unpack_floats(floats: jax.Array):
    """(dense, label, show, clk) views of a pack_floats block (traced)."""
    floats = floats.astype(jnp.float32)  # no-op for f32, upcast bf16 wire
    return floats[:, :-3], floats[:, -3], floats[:, -2], floats[:, -1]


def quantize_floats(dense: np.ndarray, label: np.ndarray, show: np.ndarray,
                    clk: np.ndarray, valid: Optional[np.ndarray] = None):
    """Optional q8 float wire: dense features as per-column affine uint8
    (q = round((x - zp) / scale)), label/show/clk as raw uint8 — CTR dense
    features are counts/logs where 8-bit affine precision is ample, and
    the reference itself runs int8 dense paths (scaled_int8fc,
    fused_scale_int8_op.cu). ``valid`` (bool [B]) restricts the range
    stats to real rows — batch-padding rows (show == 0, zero-filled)
    must not widen the range and dilute real-feature precision; their
    encodings clip, which is fine because ins_w masks them everywhere.
    Returns (block u8 [B, D+3], qmeta f32 [2, D] = [scale; zp]) or None
    when the data doesn't fit the wire (non-finite dense, or
    label/show/clk outside exact-u8 range) — callers fall back to the
    bf16 wire."""
    d = dense.astype(np.float32, copy=False)
    lsc = np.stack([label, show, clk], axis=1)
    if not np.isfinite(d).all():
        return None
    if (lsc < 0).any() or (lsc > 255).any() or (lsc != np.rint(lsc)).any():
        return None
    stat = d if valid is None else d[valid]
    if stat.size == 0:
        stat = d[:1]
    # winsorized range: heavy-tailed count features are the norm in CTR
    # logs and a single extreme value must not collapse a whole column's
    # precision to one bucket for the pass — clip the range to the
    # [0.1, 99.9] percentiles when the tails are outlier-dominated
    # (values beyond the range saturate; bounded error instead of
    # unbounded precision loss)
    lo = stat.min(axis=0)
    hi = stat.max(axis=0)
    if stat.shape[0] >= 1000:
        p_lo, p_hi = np.percentile(stat, [0.1, 99.9], axis=0)
        wild = (hi - lo) > 4.0 * np.maximum(p_hi - p_lo, 1e-30)
        lo = np.where(wild, p_lo, lo)
        hi = np.where(wild, p_hi, hi)
    scale = (hi - lo) / 255.0
    scale = np.where(scale > 0, scale, 1.0).astype(np.float32)
    q = np.clip(np.rint((d - lo[None, :]) / scale[None, :]), 0, 255)
    block = np.concatenate([q, lsc], axis=1).astype(np.uint8)
    qmeta = np.stack([scale, lo.astype(np.float32)])
    return block, qmeta


def dequantize_floats(block: jax.Array, qmeta: jax.Array):
    """(dense, label, show, clk) from a quantize_floats block (traced)."""
    f = block.astype(jnp.float32)
    dense = f[:, :-3] * qmeta[0][None, :] + qmeta[1][None, :]
    return dense, f[:, -3], f[:, -2], f[:, -1]


class DeviceBatch(NamedTuple):
    """Everything the device step consumes for one batch, packed into THREE
    host→device transfers (the tunnel/PCIe round-trip is the real cost, not
    bytes — the reference packs per-slot tensors into single copies for the
    same reason, MiniBatchGpuPack data_feed.cu:1210). ``key_valid`` is not
    shipped at all: it's derived on device from the real-key count carried
    in ``ints_u``'s last element. Accessors below unpack inside the traced
    step, where slices are free."""

    ints_u: jax.Array   # int32 [U_pad + 2] = unique_rows ++ [num_keys, pad_segment]
    ints_k: jax.Array   # int32 [2, K_pad] = [gather_idx; segments], or
                        #       [1, K_pad] when segments are derivable
    floats: jax.Array   # f32 [B, Dd + 3] = [dense | label | show | clk]

    @property
    def unique_rows(self) -> jax.Array:
        return self.ints_u[:-2]

    @property
    def num_keys(self) -> jax.Array:
        return self.ints_u[-2]

    @property
    def gather_idx(self) -> jax.Array:
        return self.ints_k[0]

    @property
    def segments(self) -> jax.Array:
        if self.ints_k.shape[0] == 2:
            return self.ints_k[1]
        # trivial layout (one key per slot per record): segment i == i for
        # real keys, pad bin for the tail
        k_pad = self.ints_k.shape[1]
        i = jnp.arange(k_pad, dtype=jnp.int32)
        return jnp.where(i < self.num_keys, i, self.ints_u[-1])

    @property
    def key_valid(self) -> jax.Array:
        k_pad = self.ints_k.shape[1]
        return (jnp.arange(k_pad, dtype=jnp.int32)
                < self.num_keys).astype(jnp.float32)

    @property
    def segments_trivial(self) -> bool:
        return self.ints_k.shape[0] == 1

    @property
    def pool_segments(self):
        """Segments for fused_seqpool_cvm — None declares the trivial
        layout (pool becomes a reshape; no TPU scatter)."""
        return None if self.segments_trivial else self.segments

    @property
    def dense(self) -> jax.Array:
        return unpack_floats(self.floats)[0]

    @property
    def label(self) -> jax.Array:
        return unpack_floats(self.floats)[1]

    @property
    def show(self) -> jax.Array:
        return unpack_floats(self.floats)[2]

    @property
    def clk(self) -> jax.Array:
        return unpack_floats(self.floats)[3]


def make_device_batch(batch: SlotBatch, idx: PullIndex,
                      floats: Optional[jax.Array] = None) -> DeviceBatch:
    """``floats`` reuses an already-staged float block (multi-mf class
    sub-batches share one — the step reads only class 0's copy, so the
    others must not re-pack and re-ship it)."""
    u_pad = idx.unique_rows.shape[0]
    ints_u = np.empty(u_pad + 2, np.int32)
    ints_u[:u_pad] = idx.unique_rows
    ints_u[u_pad] = batch.num_keys
    ints_u[u_pad + 1] = batch.pad_segment
    if getattr(batch, "segments_trivial", False):
        ints_k = np.ascontiguousarray(idx.gather_idx[None, :])
    else:
        ints_k = np.stack([idx.gather_idx, batch.segments.astype(np.int32)])
    if floats is None:
        floats = jnp.asarray(pack_floats(batch.dense, batch.label,
                                         batch.show, batch.clk))
    return DeviceBatch(ints_u=jnp.asarray(ints_u),
                       ints_k=jnp.asarray(ints_k),
                       floats=floats)


def ctr_forward(table: TableState, params: Any, model, batch,
                batch_size: int, num_slots: int, use_cvm: bool = True,
                cvm_offset: int = 2, need_filter: bool = False,
                quant_ratio: int = 0) -> Tuple[jax.Array, jax.Array]:
    """THE CTR inference path (pull → fused_seqpool_cvm → model →
    sigmoid), shared by the train step's eval and the serving loader so
    the seqpool constants live in exactly one place. Returns
    (pred [B], ins_w [B]) — ins_w masks batch-padding instances."""
    batch_show_clk = jnp.stack([batch.show, batch.clk], axis=1)
    vals_u = pull_values(gather_full_rows(table, batch.unique_rows),
                         table.mf_dim)
    values_k = expand_pull(vals_u, batch.gather_idx)
    segs = getattr(batch, "pool_segments", batch.segments)
    pooled = fused_seqpool_cvm(
        values_k, segs, batch_show_clk, batch_size, num_slots,
        use_cvm, cvm_offset, 0.0, need_filter, 0.2, 1.0, 0.96, quant_ratio,
        key_valid=batch.key_valid)
    logits = model.apply(params, pooled, batch.dense)
    ins_w = (batch.show > 0).astype(jnp.float32)
    return jax.nn.sigmoid(logits), ins_w


class StepState(NamedTuple):
    table: TableState
    params: Any
    opt_state: Any
    auc: AucState
    step: jax.Array  # int32 scalar


class TrainStep:
    """Builds and caches the jitted step for a (model, table cfg) pair.
    One compilation per (K_pad, U_pad) bucket combo."""

    def __init__(
        self,
        model,               # flax Module: (pooled, dense) -> logits [B]
        tx: optax.GradientTransformation,
        sgd_cfg: SparseSGDConfig,
        batch_size: int,
        num_slots: int,
        use_cvm: bool = True,
        cvm_offset: int = 2,
        need_filter: bool = False,
        quant_ratio: int = 0,
        rng_seed: int = 0,
    ) -> None:
        self.model = model
        self.tx = tx
        self.sgd_cfg = sgd_cfg
        self.batch_size = batch_size
        self.num_slots = num_slots
        self.use_cvm = use_cvm
        self.cvm_offset = cvm_offset
        self.need_filter = need_filter
        self.quant_ratio = quant_ratio
        self.rng = jax.random.PRNGKey(rng_seed)
        self._jit = jax.jit(self._step, donate_argnums=(0,))
        self._jit_eval = jax.jit(self._eval_step, donate_argnums=(2,))

    @staticmethod
    def init_params_for(model, batch_size: int, num_slots: int,
                        mf_dim: int, dense_dim: int, use_cvm: bool = True,
                        cvm_offset: int = 2) -> Any:
        """Deterministic dense-param init without a TrainStep (lr_map
        scale building needs the param pytree before the tx is final)."""
        d = cvm_offset + 1 + mf_dim if use_cvm else 1 + mf_dim
        pooled = jnp.zeros((batch_size, num_slots, d))
        dense = jnp.zeros((batch_size, dense_dim))
        return model.init(jax.random.PRNGKey(0), pooled, dense)

    def init_params(self, mf_dim: int, dense_dim: int) -> Any:
        return self.init_params_for(self.model, self.batch_size,
                                    self.num_slots, mf_dim, dense_dim,
                                    self.use_cvm, self.cvm_offset)

    def init_state(self, table_state: TableState, params: Any,
                   auc: AucState) -> StepState:
        return StepState(table=table_state, params=params,
                         opt_state=self.tx.init(params), auc=auc,
                         step=jnp.zeros((), jnp.int32))

    # ---- the traced step ----
    def _step(self, state: StepState, batch: DeviceBatch,
              rng: jax.Array) -> Tuple[StepState, Dict[str, jax.Array]]:
        b, s = self.batch_size, self.num_slots
        batch_show_clk = jnp.stack([batch.show, batch.clk], axis=1)
        ins_w = (batch.show > 0).astype(jnp.float32)  # mask tail padding

        # ONE gather serves both the pull values and the push optimizer
        # state (AoS rows — see TableState)
        rows_full = gather_full_rows(state.table, batch.unique_rows)
        vals_u = pull_values(rows_full, state.table.mf_dim)

        pool_segs = getattr(batch, "pool_segments", batch.segments)

        def loss_fn(params, vals_u):
            values_k = expand_pull(vals_u, batch.gather_idx)
            pooled = fused_seqpool_cvm(
                values_k, pool_segs, batch_show_clk, b, s,
                self.use_cvm, self.cvm_offset, 0.0, self.need_filter,
                0.2, 1.0, 0.96, self.quant_ratio,
                key_valid=batch.key_valid)
            logits = self.model.apply(params, pooled, batch.dense)
            ls = optax.sigmoid_binary_cross_entropy(logits, batch.label)
            loss = jnp.sum(ls * ins_w) / jnp.maximum(jnp.sum(ins_w), 1.0)
            return loss, logits

        (loss, logits), (g_params, g_vals_u) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True)(state.params, vals_u)

        # sparse push: autodiff through expand_pull (a gather) already
        # occurrence-merged the per-key grads into per-unique-row grads —
        # g_vals_u[:, 0] is Σ show over occurrences, etc. (the
        # PushMergeCopy/DedupKeys contract for free). Embed grads are scaled
        # by -batch_size as in PushCopy (box_wrapper.cu:368-372: the in-table
        # adagrad ADDS ratio*g/g_show, so push carries the negated sum-grad).
        g_vals_u = jnp.concatenate(
            [g_vals_u[:, :2], g_vals_u[:, 2:] * (-1.0 * b)], axis=1)
        # touched derives from the dup-free unique_rows contract inside
        # apply_push; slot is host metadata (EmbeddingTable.slot_host) —
        # no segment op spent on either
        table = apply_push(state.table, batch.unique_rows, g_vals_u,
                           self.sgd_cfg, rng, rows_full=rows_full)

        updates, opt_state = self.tx.update(g_params, state.opt_state,
                                            state.params)
        params = optax.apply_updates(state.params, updates)

        pred = jax.nn.sigmoid(logits)
        auc = auc_add_batch(state.auc, pred, batch.label, ins_w)

        new_state = StepState(table=table, params=params,
                              opt_state=opt_state, auc=auc,
                              step=state.step + 1)
        stats = {"loss": loss,
                 "pred_mean": jnp.sum(pred * ins_w) /
                 jnp.maximum(jnp.sum(ins_w), 1.0),
                 # per-instance preds for the dump subsystem; stays on
                 # device unless a DumpWriter fetches it
                 "pred": pred}
        return new_state, stats

    def _forward(self, table: TableState, params: Any,
                 batch: DeviceBatch) -> Tuple[jax.Array, jax.Array]:
        """Shared inference path: pull → seqpool_cvm → model → pred."""
        return ctr_forward(table, params, self.model, batch,
                           self.batch_size, self.num_slots, self.use_cvm,
                           self.cvm_offset, self.need_filter,
                           self.quant_ratio)

    def _eval_step(self, table: TableState, params: Any, auc: AucState,
                   batch: DeviceBatch) -> Tuple[AucState, jax.Array]:
        """Forward-only pass: metrics accumulate, nothing trains
        (test_program / infer phase of the reference workers). Returns
        (auc, pred) — pred feeds the metric registry."""
        pred, ins_w = self._forward(table, params, batch)
        return auc_add_batch(auc, pred, batch.label, ins_w), pred

    def eval(self, table: TableState, params: Any, auc: AucState,
             batch: DeviceBatch) -> Tuple[AucState, jax.Array]:
        return self._jit_eval(table, params, auc, batch)

    def __call__(self, state: StepState, batch: DeviceBatch,
                 rng: jax.Array) -> Tuple[StepState, Dict[str, jax.Array]]:
        return self._jit(state, batch, rng)
