"""Multi-controller execution of the mesh trainers — the TPU pod model.

On a real pod, ONE process runs per host and every process executes the
SAME program (JAX multi-controller SPMD): ``jax.distributed.initialize``
(via ``distributed.launch.init_runtime_env``) forms a global device set,
a ``Mesh`` spans every host's chips, and jitted shard_map programs run
collectives over ICI+DCN transparently. This replaces the reference's
per-node NCCL + inter-node MPI hierarchy (SyncDense,
boxps_worker.cc:1191-1258) with one mesh.

The host side follows the SPMD contract: every process builds IDENTICAL
global batches and routing plans (deterministic duplicated prep over a
shared file list — the standard recipe for host-count ≪ chip-count CTR
jobs), then each process contributes only its ADDRESSABLE rows of every
global array (`jax.make_array_from_process_local_data`). The staging
helpers here do that slicing; `tests/test_multihost_jax.py` proves a
2-process global-mesh ShardedTrainStep matches the single-process run
bit-for-bit.
"""

from __future__ import annotations

from typing import Dict

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddlebox_tpu.metrics import AucState
from paddlebox_tpu.parallel.mesh import DATA_AXIS
from paddlebox_tpu.train.sharded import GlobalBatch, ShardedStepState


def global_mesh() -> Mesh:
    """One mesh over EVERY process's devices (call after
    init_runtime_env has initialized the distributed runtime)."""
    return Mesh(np.array(jax.devices()), (DATA_AXIS,))


def stage_global(mesh: Mesh, arr: np.ndarray,
                 shard_dim0: bool = True) -> jax.Array:
    """Stage one globally-identical host array onto the global mesh:
    this process contributes its addressable slice of dim 0 (sharded)
    or the whole array (replicated). ``arr`` must be byte-identical on
    every process (the SPMD host contract)."""
    a = np.asarray(arr)
    if a.ndim == 0 or not shard_dim0:
        sh = NamedSharding(mesh, P())
        return jax.make_array_from_process_local_data(
            sh, a, global_shape=a.shape)
    pi = jax.process_index()
    nl = jax.local_device_count()
    sh = NamedSharding(mesh, P(*([DATA_AXIS] + [None] * (a.ndim - 1))))
    return jax.make_array_from_process_local_data(
        sh, a[pi * nl:(pi + 1) * nl], global_shape=a.shape)


def stage_global_batch(mesh: Mesh,
                       host: Dict[str, np.ndarray]) -> GlobalBatch:
    """make_global_arrays output → GlobalBatch on the global mesh."""
    return GlobalBatch(**{f: stage_global(mesh, host[f])
                          for f in GlobalBatch._fields})


def globalize_state(mesh: Mesh, state: ShardedStepState,
                    zero1: bool = False) -> ShardedStepState:
    """Re-stage a process-locally-initialized ShardedStepState onto the
    global mesh, following the step's sharding spec: table + AUC sharded
    on the device axis, params replicated, opt_state sharded iff zero1,
    step replicated. Init is deterministic (fixed PRNG seeds), so every
    process holds identical host values to slice from."""
    table = state.table.with_packed(
        stage_global(mesh, np.asarray(jax.device_get(state.table.packed))))
    params = jax.tree.map(
        lambda l: stage_global(mesh, np.asarray(jax.device_get(l)),
                               shard_dim0=False), state.params)
    opt_state = jax.tree.map(
        lambda l: stage_global(mesh, np.asarray(jax.device_get(l)),
                               shard_dim0=zero1), state.opt_state)
    auc = AucState(*[stage_global(mesh, np.asarray(jax.device_get(l)))
                     for l in state.auc])
    step = stage_global(mesh, np.asarray(jax.device_get(state.step)),
                        shard_dim0=False)
    return ShardedStepState(table=table, params=params,
                            opt_state=opt_state, auc=auc, step=step)
