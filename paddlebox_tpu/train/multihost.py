"""Multi-controller execution of the mesh trainers — the TPU pod model.

On a real pod, ONE process runs per host and every process executes the
SAME program (JAX multi-controller SPMD): ``jax.distributed.initialize``
(via ``distributed.launch.init_runtime_env``) forms a global device set,
a ``Mesh`` spans every host's chips, and jitted shard_map programs run
collectives over ICI+DCN transparently. This replaces the reference's
per-node NCCL + inter-node MPI hierarchy (SyncDense,
boxps_worker.cc:1191-1258) with one mesh.

The host side follows the SPMD contract: every process builds IDENTICAL
global batches and routing plans (deterministic duplicated prep over a
shared file list — the standard recipe for host-count ≪ chip-count CTR
jobs), then each process contributes only its ADDRESSABLE rows of every
global array (`jax.make_array_from_process_local_data`). The staging
helpers here do that slicing; `tests/test_multihost_jax.py` proves a
2-process global-mesh ShardedTrainStep matches the single-process run
bit-for-bit.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddlebox_tpu.parallel.mesh import DATA_AXIS
from paddlebox_tpu.train.sharded import GlobalBatch, ShardedStepState


def global_mesh() -> Mesh:
    """One mesh over EVERY process's devices (call after
    init_runtime_env has initialized the distributed runtime)."""
    return Mesh(np.array(jax.devices()), (DATA_AXIS,))


def stage_global(mesh: Mesh, arr: np.ndarray,
                 shard_dim0: bool = True) -> jax.Array:
    """Stage one globally-identical host array onto the global mesh.
    ``arr`` must be byte-identical on every process (the SPMD host
    contract); with ``global_shape == local_data.shape``,
    ``make_array_from_process_local_data`` maps every addressable device
    to ITS OWN slice of the global value — correct for any device/mesh
    order, no process-contiguity assumption."""
    a = np.asarray(arr)
    spec = (P(*([DATA_AXIS] + [None] * (a.ndim - 1)))
            if shard_dim0 and a.ndim > 0 else P())
    sh = NamedSharding(mesh, spec)
    return jax.make_array_from_process_local_data(
        sh, a, global_shape=a.shape)


def stage_global_batch(mesh: Mesh,
                       host: Dict[str, np.ndarray]) -> GlobalBatch:
    """make_global_arrays output → GlobalBatch on the global mesh."""
    return GlobalBatch(**{f: stage_global(mesh, host[f])
                          for f in GlobalBatch._fields})


def globalize_state(mesh: Mesh, state, state_spec) -> ShardedStepState:
    """Re-stage a process-locally-initialized step state onto the global
    mesh, following the STEP'S OWN sharding spec (pass
    ``trainer.step_fn.state_spec`` — a pytree prefix of PartitionSpecs,
    the same object the jitted shard_map consumes, so this can never
    drift from the program). Init is deterministic (fixed PRNG seeds),
    so every process holds identical host values.

    IDEMPOTENT on already-global leaves: a leaf that is not fully
    addressable (a multihost table's state, a previously staged array)
    is kept as-is — it cannot be device_get and is already placed."""
    import jax.tree_util as jtu
    is_spec = lambda x: isinstance(x, P)  # noqa: E731

    def stage_leaf(l, sp):
        if isinstance(l, jax.Array) and not l.is_fully_addressable:
            return l
        return stage_global(mesh, np.asarray(jax.device_get(l)),
                            shard_dim0=(len(sp) > 0 and sp[0] == DATA_AXIS))

    spec_def = jtu.tree_structure(state_spec, is_leaf=is_spec)
    subtrees = spec_def.flatten_up_to(state)
    spec_leaves = jtu.tree_leaves(state_spec, is_leaf=is_spec)
    staged = [
        jtu.tree_map(lambda l, sp=sp: stage_leaf(l, sp), sub)
        for sub, sp in zip(subtrees, spec_leaves)
    ]
    return jtu.tree_unflatten(spec_def, staged)


# ---- heartbeat + straggler watchdog (obs/watchdog) ---------------------
def make_straggler_watchdog(heartbeat_dir: Optional[str] = None,
                            start: bool = True, **kwargs):
    """Build the pod's straggler watchdog for THIS process.

    Every process calls this once after ``jax.distributed.initialize``
    and then calls ``wd.beat(step)`` once per pass (or step window): the
    monitor thread flags any process whose step counter falls behind
    the mesh front-runner by ``FLAGS.straggler_step_lag`` or whose
    heartbeat goes stale past ``FLAGS.straggler_timeout_sec`` — finally
    answering "WHICH host is stalling" when a collective hangs. With
    ``FLAGS.straggler_abort_sec > 0`` a persistent stall makes the next
    ``beat()`` raise ``StragglerTimeout`` so the launcher (elastic
    runtime) can replace the rank instead of hanging forever.

    ``heartbeat_dir`` must be shared across hosts (NFS/FUSE); defaults
    to ``FLAGS.straggler_heartbeat_dir``. Single-process meshes get a
    process-local store (still useful: stale-heartbeat detection fires
    when the training thread wedges). ``kwargs`` override any
    ``StragglerWatchdog`` parameter (tests inject ``clock``; pass
    ``escalations=[(after_sec, action), ...]`` for the staged
    emit→requeue→abort-with-checkpoint ladder — obs/watchdog has the
    built-in action factories)."""
    from paddlebox_tpu.config import FLAGS
    from paddlebox_tpu.obs.watchdog import (DirHeartbeatStore,
                                            LocalHeartbeatStore,
                                            StragglerWatchdog)
    hb_dir = heartbeat_dir or FLAGS.straggler_heartbeat_dir
    if hb_dir:
        store = DirHeartbeatStore(hb_dir)
    elif jax.process_count() == 1:
        store = LocalHeartbeatStore()
    else:
        raise ValueError(
            "multihost watchdog needs a SHARED heartbeat dir: pass "
            "heartbeat_dir= or set FLAGS.straggler_heartbeat_dir")
    kw = dict(
        step_lag=FLAGS.straggler_step_lag,
        heartbeat_timeout=FLAGS.straggler_timeout_sec,
        abort_after=(FLAGS.straggler_abort_sec
                     if FLAGS.straggler_abort_sec > 0 else None))
    kw.update(kwargs)
    wd = StragglerWatchdog(store, jax.process_index(),
                           jax.process_count(), **kw)
    return wd.start() if start else wd


# ---- elastic membership (distributed/elastic) --------------------------
def make_elastic_manager(job_id: str, host: Optional[str] = None,
                         np: Optional[int] = None,
                         elastic_dir: Optional[str] = None,
                         store=None, **kwargs):
    """Build THIS process's elastic membership agent (FLAGS wiring —
    docs/RESILIENCE.md §Elastic membership). ``elastic_dir`` (default
    ``FLAGS.elastic_dir``) must be shared across hosts (NFS/FUSE); pass
    ``store=`` (e.g. a ``TcpKVStore``) to skip the filesystem entirely.
    ``host`` defaults to ``host<process_index>``, ``np`` to
    ``jax.process_count()``; ``kwargs`` override any ``ElasticManager``
    parameter (``min_np``/``max_np`` pick the FAULT_TOLERANCE vs ELASTIC
    level, tests inject ``heartbeat_period``)."""
    from paddlebox_tpu.config import FLAGS
    from paddlebox_tpu.distributed.elastic import ElasticManager, FileKVStore
    if store is None:
        d = elastic_dir or FLAGS.elastic_dir
        if not d:
            raise ValueError(
                "elastic membership needs a SHARED dir: pass "
                "elastic_dir=/store= or set FLAGS.elastic_dir")
        store = FileKVStore(d)
    kw = dict(ttl=FLAGS.elastic_ttl_sec,
              dead_checks=FLAGS.elastic_dead_checks)
    kw.update(kwargs)
    return ElasticManager(
        store, job_id,
        host if host is not None else f"host{jax.process_index()}",
        np if np is not None else jax.process_count(), **kw)


class ElasticController:
    """Boundary membership decisions for an elastic stream job: wraps an
    ``ElasticManager`` (+ optional ``RestoreConsensus``) behind the tiny
    protocol the training loops poll at every completed pass/window
    boundary — ``poll`` (did the world change?), ``agree_boundary``
    (which step do the survivors resume from?), ``evict`` (the
    watchdog's shrink-and-continue rung), ``publish``/``note_reshard``
    (restart pointer + bookkeeping). The re-shard itself — rebuild the
    world at the new size and re-import the boundary checkpoint — is the
    caller's move (``ElasticStreamRunner.run`` is the reference driver).
    """

    def __init__(self, manager, consensus=None) -> None:
        self.manager = manager
        self.consensus = consensus

    def poll(self) -> Optional[Dict]:
        """One boundary membership check. None = steady world; else a
        decision dict ``{hosts, np, lost, joined, ts}`` (hysteresis and
        forced evictions already applied by the manager)."""
        hosts = self.manager.scale_event()
        if hosts is None:
            return None
        ev = dict(self.manager.last_event or {})
        ev.setdefault("hosts", hosts)
        ev["np"] = len(hosts)
        return ev

    def evict(self, host: str, reason: str = "") -> None:
        self.manager.evict_host(host, reason)

    def agree_boundary(self, local_step,
                       survivors: Optional[list] = None):
        """Consensus over the surviving world on the boundary step to
        resume from (``RestoreConsensus.agree_restore_step`` — the mesh
        min, so a rank whose boundary save lagged drags everyone to the
        newest step ALL survivors hold). ``survivors`` narrows the
        participant set first; with no consensus wired (single
        controller), the local step IS the agreement."""
        if self.consensus is None:
            return local_step
        if survivors is not None:
            self.consensus.set_participants(survivors)
        return self.consensus.agree_restore_step(local_step)

    def publish(self, path: str, pass_id: int) -> None:
        self.manager.publish_checkpoint(path, pass_id)

    def note_reshard(self, old_np: int, new_np: int,
                     step: int = -1) -> None:
        self.manager.note_reshard(old_np, new_np, step=step)


class ElasticStreamRunner:
    """Windowed stream driver with pass-boundary membership churn — the
    re-shard state machine (docs/RESILIENCE.md §Elastic membership):

    per window: train → boundary save → publish restart pointer →
    ``controller.poll()``; on a scale event: coordinated stop (the
    boundary IS the stop point — completed-window state only, no data
    rollback) → ``agree_boundary`` over the survivors → rebuild the
    world at the new size (``make_world(np)`` — fresh mesh + trainer +
    table with ``num_shards`` matching) → re-import the agreed boundary
    checkpoint (``key % num_shards`` makes the re-shard a deterministic
    re-import; ``CheckpointManager.restore`` replays it) → continue the
    stream at the next window.

    ``make_world(np) -> (trainer, checkpoint_manager)`` owns the
    host-count → mesh mapping; every checkpoint manager must share one
    root so the re-shard import sees the boundary save. ``controller``
    is duck-typed (``ElasticController``, or a scripted schedule in
    gates/oracles — same driver, so digest parity between a churned run
    and its scheduled twin proves the detection machinery is a
    training-math no-op). ``on_boundary(widx, trainer)`` runs after the
    save and before the poll (gates age leases / wedge ranks there).

    Returns one record per window: ``{window, np, step, digest,
    train_sec, reshard?}`` — ``reshard`` carries {old_np, new_np,
    agreed_step, digest_after, stall_sec} and ``digest_after`` must
    equal the boundary ``digest`` (the lossless re-import proof the
    elastic gate asserts)."""

    def __init__(self, make_world, make_dataset, num_windows: int,
                 controller=None, on_boundary=None,
                 digest_fn=None, clock=None) -> None:
        import time
        from paddlebox_tpu.train.checkpoint import elastic_state_digest
        self.make_world = make_world
        self.make_dataset = make_dataset
        self.num_windows = int(num_windows)
        self.controller = controller
        self.on_boundary = on_boundary
        self.digest_fn = digest_fn or elastic_state_digest
        self.clock = clock or time.monotonic

    def run(self, start_np: int) -> list:
        trainer, cm = self.make_world(start_np)
        np_cur = int(start_np)
        records = []
        for widx in range(self.num_windows):
            ds = self.make_dataset(widx)
            t0 = self.clock()
            trainer.train_pass(ds)
            train_sec = self.clock() - t0
            step = int(trainer.global_step)
            cm.save(trainer)  # boundary base: re-shard import source
            rec = {"window": widx, "np": np_cur, "step": step,
                   "digest": self.digest_fn(trainer),
                   "train_sec": train_sec}
            if self.controller is not None:
                self.controller.publish(cm.root, widx)
                if self.on_boundary is not None:
                    self.on_boundary(widx, trainer)
                decision = self.controller.poll()
                if decision is not None and decision["np"] != np_cur:
                    rec["reshard"] = self._reshard(decision, step, np_cur)
                    np_cur = int(decision["np"])
                    trainer, cm = self._world
            records.append(rec)
        return records

    def _reshard(self, decision: Dict, step: int, old_np: int) -> Dict:
        t0 = self.clock()
        agreed = self.controller.agree_boundary(
            step, survivors=decision.get("survivor_ranks"))
        new_np = int(decision["np"])
        trainer, cm = self.make_world(new_np)
        restored = cm.restore(trainer, step=agreed)
        if restored != agreed:
            raise RuntimeError(
                f"elastic re-shard: agreed boundary step {agreed} did "
                f"not restore (got {restored}) — the boundary save is "
                "missing from the shared checkpoint root")
        self.controller.note_reshard(old_np, new_np, step=agreed)
        self._world = (trainer, cm)
        return {"old_np": old_np, "new_np": new_np,
                "agreed_step": int(agreed),
                "lost": decision.get("lost", []),
                "joined": decision.get("joined", []),
                "digest_after": self.digest_fn(trainer),
                "stall_sec": self.clock() - t0}


# ---- consistent recovery (resilience/consensus) ------------------------
def make_restore_consensus(consensus_dir: Optional[str] = None, **kwargs):
    """Build this process's restore-consensus client (same shared-dir
    pattern as the heartbeat store). Every process constructs one after
    ``jax.distributed.initialize`` and recovers through it:

        consensus = make_restore_consensus()
        step = consensus_restore(cm, trainer, consensus)   # agreed min
        sync_shared_quarantine(ds, consensus)              # same drops

    so every rank restores the SAME step and drops the SAME quarantined
    files — preserving the byte-identical-batches SPMD contract above.
    ``consensus_dir`` must be shared across hosts (NFS/FUSE); defaults
    to ``FLAGS.restore_consensus_dir``. ``kwargs`` override any
    ``RestoreConsensus`` parameter (tests inject clocks/timeouts).
    ``epoch`` defaults to the launcher-provided ``PBOX_RESTORE_EPOCH``
    env (its restart counter) so directory reuse across episodes is
    safe by default; the digest-confirm barrier inside every gather
    additionally guarantees stale files can only cause a loud retry /
    timeout, never a silent divergent agreement."""
    import os
    from paddlebox_tpu.config import FLAGS
    from paddlebox_tpu.resilience.consensus import (DirConsensusStore,
                                                    RestoreConsensus)
    d = consensus_dir or FLAGS.restore_consensus_dir
    if not d:
        raise ValueError(
            "restore consensus needs a SHARED dir: pass consensus_dir= "
            "or set FLAGS.restore_consensus_dir")
    kwargs.setdefault("epoch",
                      int(os.environ.get("PBOX_RESTORE_EPOCH", "0")))
    return RestoreConsensus(DirConsensusStore(d), jax.process_index(),
                            jax.process_count(), **kwargs)
