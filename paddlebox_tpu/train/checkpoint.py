"""Unified checkpoint manager — sparse base/delta + dense state, atomic.

Reference surface (SURVEY.md §3.4/§5.4): day-level ``SaveBase`` (full
batch model), incremental ``SaveDelta`` ("xbox delta" for online serving),
dense ``io.save_persistables``, and resume =
``InitializeGPUAndLoadModel(model_path)`` (box_wrapper.cc:1298,1383,1406).

TPU-native packaging: one directory per checkpoint —

    <root>/ckpt-<step>/
        sparse.npz | sparse_delta.npz   (EmbeddingTable save_base/save_delta)
        dense.pkl                       (params + optimizer state + auc)
        meta.json                       (step, kind, base_step)
    <root>/LATEST                       (atomic pointer file)

Writes land in a temp dir then ``os.replace`` — a crash mid-save never
corrupts the latest restorable state (the property the reference gets from
day-level directory convention + AFS rename). ``restore`` replays base +
the delta chain up to the requested step. Retention keeps the last
``keep`` checkpoints but never drops a base an alive delta depends on.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil
from typing import Dict, List, Optional

import jax
import numpy as np

from paddlebox_tpu.resilience import faults
from paddlebox_tpu.resilience.retry import RetryPolicy
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: files whose content digests are recorded in meta.json and verified
#: on restore (meta.json itself can't self-checksum)
_CHECKSUMMED = ("sparse.npz", "sparse_delta.npz", "dense.pkl")


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file's content digest does not match its meta.json
    record — the chain link is corrupt and must not be restored."""


def _digest(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        while True:
            buf = fh.read(chunk)
            if not buf:
                break
            h.update(buf)
    return h.hexdigest()


def _io_retry() -> RetryPolicy:
    """Checkpoint file IO runs under the flag-configured retry policy
    (transient NFS/FUSE hiccups on shared checkpoint roots)."""
    return RetryPolicy.from_flags(site="checkpoint.io",
                                  retryable=(OSError,))


class CheckpointManager:
    def __init__(self, root: str, keep: int = 3) -> None:
        self.root = root
        self.keep = keep
        os.makedirs(root, exist_ok=True)
        self._recover()

    def _recover(self) -> None:
        """Finish interrupted re-saves: a crash between the two renames in
        save() leaves 'ckpt-N.old-<pid>' with no 'ckpt-N' — restore the
        aside copy; if both exist the save completed, drop the aside."""
        for name in os.listdir(self.root):
            if ".old-" not in name or not name.startswith("ckpt-"):
                continue
            aside = os.path.join(self.root, name)
            final = os.path.join(self.root, name.split(".old-")[0])
            if os.path.isdir(final):
                shutil.rmtree(aside, ignore_errors=True)
            else:
                os.replace(aside, final)
                log.warning("recovered interrupted checkpoint %s", final)

    # ---- paths ----
    def _dir(self, step: int) -> str:
        return os.path.join(self.root, f"ckpt-{step:012d}")

    def steps(self) -> List[int]:
        out = []
        for name in os.listdir(self.root):
            if name.startswith("ckpt-"):
                try:
                    out.append(int(name[5:]))
                except ValueError:
                    pass
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        p = os.path.join(self.root, "LATEST")
        try:
            with open(p) as fh:
                s = int(fh.read().strip())
            if os.path.isdir(self._dir(s)):
                return s
        except (OSError, ValueError):
            pass
        # stale/missing pointer: fall back to newest dir on disk
        steps = self.steps()
        return steps[-1] if steps else None

    def _meta(self, step: int) -> dict:
        def read() -> dict:
            path = os.path.join(self._dir(step), "meta.json")
            faults.inject("checkpoint.io", path=path)
            with open(path) as fh:
                return json.load(fh)
        return _io_retry().call(read)

    def verify(self, step: int) -> None:
        """Check every checksummed file in ``ckpt-<step>`` against its
        meta.json digest; raises ``CheckpointCorruptError`` on mismatch.
        Checkpoints written before checksums existed (no ``checksums``
        key) verify trivially."""
        meta = self._meta(step)
        d = self._dir(step)
        for name, want in meta.get("checksums", {}).items():
            p = os.path.join(d, name)
            got = _io_retry().call(_digest, p)
            if got != want:
                raise CheckpointCorruptError(
                    f"checkpoint {d}/{name} is corrupt: sha256 {got[:12]}… "
                    f"!= recorded {want[:12]}… — refuse to restore this "
                    f"chain link. Delete {d} and restore an older "
                    "base (restore(step=...)), or resave from a healthy "
                    "trainer.")

    # ---- save ----
    def save(self, trainer, step: Optional[int] = None,
             delta: bool = False) -> str:
        """Snapshot the trainer. ``delta=True`` = save_delta (rows touched
        since the previous save) referencing the most recent base."""
        step = trainer.global_step if step is None else step
        base_step = None
        prev_step = self.latest_step()  # chain link for gap detection
        if prev_step == step:
            # re-save at the same step: the predecessor is whatever the
            # existing checkpoint pointed at (never itself — _chain loops)
            try:
                old = self._meta(step)
            except (OSError, ValueError, KeyError):
                old = {}
            if delta and old.get("kind") == "base":
                raise ValueError(
                    f"step {step} holds a BASE checkpoint; a delta re-save "
                    "would destroy it and leave an unrestorable chain — "
                    "save a base instead")
            prev_step = old.get("prev_step")
        if delta:
            base_step = self._latest_base()
            if base_step is None:
                raise ValueError("delta save with no base checkpoint yet")
        tmp = os.path.join(self.root, f".tmp-{os.getpid()}-{step}")
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        trainer.sync_table()
        if delta:
            n = trainer.table.save_delta(os.path.join(tmp, "sparse_delta.npz"))
        else:
            n = trainer.table.save_base(os.path.join(tmp, "sparse.npz"))
        def write_dense() -> None:
            faults.inject("checkpoint.io", path=os.path.join(tmp,
                                                             "dense.pkl"))
            with open(os.path.join(tmp, "dense.pkl"), "wb") as fh:
                if hasattr(trainer, "dense_snapshot"):
                    # pod-safe hook: per-shard AUC leaves are not host-
                    # addressable on a multi-controller mesh
                    blob = trainer.dense_snapshot()
                else:
                    blob = jax.device_get(
                        (trainer.state.params, trainer.state.opt_state,
                         trainer.state.auc))
                pickle.dump(blob, fh)
        _io_retry().call(write_dense)
        # content digests: restore refuses a bit-rotted chain link
        # instead of silently loading garbage rows
        checksums: Dict[str, str] = {
            name: _digest(os.path.join(tmp, name))
            for name in _CHECKSUMMED
            if os.path.isfile(os.path.join(tmp, name))}
        with open(os.path.join(tmp, "meta.json"), "w") as fh:
            json.dump({"step": step, "kind": "delta" if delta else "base",
                       "base_step": base_step,
                       "prev_step": prev_step if delta else None,
                       "sparse_rows": n, "checksums": checksums}, fh)
        # chaos seam: a "fail" fault here models the process dying after
        # writing the temp dir but BEFORE the atomic publish — recovery
        # must come from the rename convention (tests/test_resilience.py)
        faults.inject("checkpoint.save_commit", step=step)
        final = self._dir(step)
        if os.path.isdir(final):
            # move the old dir aside BEFORE the swap — a crash between the
            # two renames leaves either the old or the new dir in place,
            # never neither (latest_step falls back to dirs on disk)
            aside = final + f".old-{os.getpid()}"
            os.replace(final, aside)
            os.replace(tmp, final)
            shutil.rmtree(aside, ignore_errors=True)
        else:
            os.replace(tmp, final)
        self._write_latest(step)
        self._retain()
        log.info("checkpoint %s saved at step %d (%d sparse rows)",
                 "delta" if delta else "base", step, n)
        return final

    def _write_latest(self, step: int) -> None:
        tmp = os.path.join(self.root, ".LATEST.tmp")
        with open(tmp, "w") as fh:
            fh.write(str(step))
        os.replace(tmp, os.path.join(self.root, "LATEST"))

    def _latest_base(self) -> Optional[int]:
        for s in reversed(self.steps()):
            if self._meta(s)["kind"] == "base":
                return s
        return None

    def _retain(self) -> None:
        steps = self.steps()
        if len(steps) <= self.keep:
            return
        kept = set(steps[-self.keep:])
        # a delta restores by replaying its base + EVERY intermediate
        # delta (each delta covers only rows touched since the previous
        # save) — the whole chain of every kept checkpoint must survive
        for s in kept.copy():
            try:
                kept.update(self._chain(s))
            except (FileNotFoundError, OSError):
                pass
        for s in steps:
            if s not in kept:
                shutil.rmtree(self._dir(s), ignore_errors=True)

    # ---- restore ----
    def restore(self, trainer, step: Optional[int] = None) -> Optional[int]:
        """Restore to ``step`` (default: latest). Replays the base + delta
        chain for sparse state; returns the restored step or None if no
        checkpoint exists."""
        target = self.latest_step() if step is None else step
        if target is None:
            return None
        chain = self._chain(target)
        for s in chain:  # verify the WHOLE chain before touching state
            self.verify(s)
        first = True
        for s in chain:
            d = self._dir(s)
            meta = self._meta(s)
            if meta["kind"] == "base":
                trainer.table.load(os.path.join(d, "sparse.npz"),
                                   merge=not first)
            else:
                trainer.table.load(os.path.join(d, "sparse_delta.npz"),
                                   merge=True)
            first = False
        def read_dense():
            path = os.path.join(self._dir(target), "dense.pkl")
            faults.inject("checkpoint.io", path=path)
            with open(path, "rb") as fh:
                return pickle.load(fh)
        params, opt_state, auc = _io_retry().call(read_dense)
        if hasattr(trainer, "dense_snapshot"):
            # the trainer handles placement itself (pod staging) — a
            # device_put here would just round-trip device→host→device
            trainer.restore_state(params, opt_state, auc, target)
        else:
            trainer.restore_state(jax.device_put(params),
                                  jax.device_put(opt_state),
                                  jax.device_put(auc), target)
        log.info("restored step %d (chain: %s)", target, chain)
        return target

    def _chain(self, target: int) -> List[int]:
        """base → …deltas… → target, walking each delta's prev_step link
        backwards. A MISSING link raises (each delta covers only rows
        touched since the previous save — a gap would restore silently
        stale rows)."""
        chain = [target]
        cur = target
        while True:
            meta = self._meta(cur)
            if meta["kind"] == "base":
                return chain
            prev = meta.get("prev_step")
            if prev is None:
                # every delta written by this manager records prev_step
                # (the base for the first delta); a missing link means a
                # foreign/corrupt meta — refuse rather than restore with
                # intermediate deltas silently skipped
                raise ValueError(
                    f"delta checkpoint {cur} has no prev_step link — "
                    "unsupported checkpoint format")
            if prev == cur or not os.path.isdir(self._dir(prev)):
                raise FileNotFoundError(
                    f"checkpoint chain broken: {cur} needs {prev} "
                    "(deleted or lost) — restore an older base or resave")
            chain.insert(0, prev)
            cur = prev
