"""Unified checkpoint manager — sparse base/delta + dense state, atomic.

Reference surface (SURVEY.md §3.4/§5.4): day-level ``SaveBase`` (full
batch model), incremental ``SaveDelta`` ("xbox delta" for online serving),
dense ``io.save_persistables``, and resume =
``InitializeGPUAndLoadModel(model_path)`` (box_wrapper.cc:1298,1383,1406).

TPU-native packaging: one directory per checkpoint —

    <root>/ckpt-<step>/
        sparse.npz | sparse_delta.npz   (EmbeddingTable save_base/save_delta)
        dense.pkl                       (params + optimizer state + auc)
        meta.json                       (step, kind, base_step)
    <root>/LATEST                       (atomic pointer file)

Writes land in a temp dir then ``os.replace`` — a crash mid-save never
corrupts the latest restorable state (the property the reference gets from
day-level directory convention + AFS rename). ``restore`` replays base +
the delta chain up to the requested step. Retention keeps the last
``keep`` checkpoints but never drops a base an alive delta depends on.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil
from typing import Dict, List, Optional

import jax
import numpy as np

from paddlebox_tpu.resilience import faults
from paddlebox_tpu.resilience.retry import RetryPolicy
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: files whose content digests are recorded in meta.json and verified
#: on restore (meta.json itself is covered by the meta.sha256 sidecar)
_CHECKSUMMED = ("sparse.npz", "sparse_delta.npz", "dense.pkl",
                "cursor.json", "metrics.pkl", "spill_manifest.json")


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file's content digest does not match its meta.json
    record — the chain link is corrupt and must not be restored."""


def _digest(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        while True:
            buf = fh.read(chunk)
            if not buf:
                break
            h.update(buf)
    return h.hexdigest()


def _io_retry() -> RetryPolicy:
    """Checkpoint file IO runs under the flag-configured retry policy
    (transient NFS/FUSE hiccups on shared checkpoint roots)."""
    return RetryPolicy.from_flags(site="checkpoint.io",
                                  retryable=(OSError,))


def _fsync_path(path: str) -> None:
    """Best-effort durability flush for a file OR directory (directory
    fsync flushes its entries, i.e. renames). Best-effort because some
    FUSE/NFS mounts — the very deployment target of this hardening —
    reject fsync; the write-then-rename convention still holds there,
    so a refusal must not fail the save."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class CheckpointManager:
    def __init__(self, root: str, keep: int = 3,
                 artifacts=None) -> None:
        from paddlebox_tpu.config import FLAGS
        self.root = root
        self.keep = keep
        # the step this manager's TRAINER STATE descends from: set by
        # restore() and save(). After a rollback-restore to an older
        # step, the next delta must link to THAT step — not to
        # latest_step(), which may still point at a newer checkpoint of
        # the abandoned timeline (chaining through it would replay
        # abandoned state into the restore).
        self._lineage_tip: Optional[int] = None
        os.makedirs(root, exist_ok=True)
        # reader leases (artifacts.LeaseRegistry): restore() holds one
        # while it adopts a chain, external readers (serving loads,
        # consensus restores) take one via lease(step) — and _retain
        # routes every sweep decision through them, so a concurrent
        # adoption can never have its chain deleted underneath it
        from paddlebox_tpu.artifacts import ArtifactStore, LeaseRegistry
        self._leases = LeaseRegistry(
            os.path.join(root, ".leases"),
            ttl_sec=FLAGS.artifact_lease_ttl_sec)
        # optional publishing layer (docs/RESILIENCE.md §Publishing):
        # boundary checkpoints — incl. train_stream stream-boundary
        # saves — also publish as lineage-linked ArtifactStore versions
        if artifacts is None and FLAGS.artifact_root:
            artifacts = FLAGS.artifact_root
        if isinstance(artifacts, str):
            artifacts = ArtifactStore(artifacts,
                                      keep=FLAGS.artifact_keep)
        self.artifacts = artifacts
        #: last artifact this manager's lineage published/adopted —
        #: the parent link for the next boundary delta publish — and
        #: the checkpoint step it snapshots
        self._artifact_tip: Optional[str] = None
        self._artifact_tip_step: Optional[int] = None
        self._recover()

    def _recover(self) -> None:
        """Finish interrupted re-saves: a crash between the two renames in
        save() leaves 'ckpt-N.old-<pid>' with no 'ckpt-N' — restore the
        aside copy; if both exist the save completed, drop the aside."""
        for name in os.listdir(self.root):
            if ".old-" not in name or not name.startswith("ckpt-"):
                continue
            aside = os.path.join(self.root, name)
            final = os.path.join(self.root, name.split(".old-")[0])
            if os.path.isdir(final):
                shutil.rmtree(aside, ignore_errors=True)
            else:
                os.replace(aside, final)
                log.warning("recovered interrupted checkpoint %s", final)

    # ---- paths ----
    def _dir(self, step: int) -> str:
        return os.path.join(self.root, f"ckpt-{step:012d}")

    def steps(self) -> List[int]:
        """Steps with a complete-looking ``ckpt-*`` dir. A dir missing
        its ``meta.json`` (a half-deleted checkpoint — retention or an
        operator interrupted mid-rmtree) is skipped with a warning
        instead of blowing up the next ``_retain``/``restore``."""
        out = []
        for name in os.listdir(self.root):
            if not name.startswith("ckpt-"):
                continue
            try:
                s = int(name[5:])
            except ValueError:
                continue
            if not os.path.isfile(os.path.join(self.root, name,
                                               "meta.json")):
                log.warning("ignoring half-deleted checkpoint %s "
                            "(no meta.json)", name)
                continue
            out.append(s)
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        p = os.path.join(self.root, "LATEST")
        try:
            with open(p) as fh:
                s = int(fh.read().strip())
            if os.path.isfile(os.path.join(self._dir(s), "meta.json")):
                return s
        except (OSError, ValueError):
            pass
        # stale/missing pointer: fall back to newest dir on disk
        steps = self.steps()
        return steps[-1] if steps else None

    def _meta(self, step: int) -> dict:
        def read() -> dict:
            path = os.path.join(self._dir(step), "meta.json")
            faults.inject("checkpoint.io", path=path)
            with open(path) as fh:
                return json.load(fh)
        return _io_retry().call(read)

    def verify(self, step: int) -> None:
        """Check every checksummed file in ``ckpt-<step>`` against its
        meta.json digest; raises ``CheckpointCorruptError`` on mismatch.
        meta.json itself is covered by its ``meta.sha256`` sidecar, so a
        torn meta write is detected like any other corrupt chain link.
        Checkpoints written before checksums/sidecars existed verify
        trivially."""
        d = self._dir(step)
        side = os.path.join(d, "meta.sha256")
        if os.path.isfile(side):
            want = _io_retry().call(
                lambda: open(side).read().strip())
            got = _io_retry().call(_digest, os.path.join(d, "meta.json"))
            if got != want:
                raise CheckpointCorruptError(
                    f"checkpoint {d}/meta.json is torn/corrupt: sha256 "
                    f"{got[:12]}… != sidecar {want[:12]}… — refuse to "
                    f"trust this chain link. Delete {d} and restore an "
                    "older base, or resave from a healthy trainer.")
        meta = self._meta(step)
        for name, want in meta.get("checksums", {}).items():
            p = os.path.join(d, name)
            got = _io_retry().call(_digest, p)
            if got != want:
                raise CheckpointCorruptError(
                    f"checkpoint {d}/{name} is corrupt: sha256 {got[:12]}… "
                    f"!= recorded {want[:12]}… — refuse to restore this "
                    f"chain link. Delete {d} and restore an older "
                    "base (restore(step=...)), or resave from a healthy "
                    "trainer.")

    # ---- save ----
    def save(self, trainer, step: Optional[int] = None,
             delta: bool = False, cursor: Optional[dict] = None,
             metrics=None, clear_touched: Optional[bool] = None) -> str:
        """Snapshot the trainer. ``delta=True`` = save_delta (rows touched
        since the previous save) referencing the most recent base.

        ``cursor`` marks a MID-PASS checkpoint: the dict (pass position —
        ``Trainer._pass_cursor``, schema v2: batch position + optional
        ``stream`` block for windowed streaming) lands in ``cursor.json``
        so a restart resumes the pass from this position instead of
        replaying it; ``metrics`` (a MetricRegistry) snapshots the
        host-side metric accumulators alongside (``metrics.pkl``).
        Checkpoints without a cursor are pass-boundary checkpoints — as
        are STREAM-BOUNDARY checkpoints, whose cursor's ``stream`` block
        has an empty open window (``latest_boundary_step`` treats both
        as safe rollback targets).

        ``clear_touched`` overrides the touched-row bookkeeping: the
        default (None) clears on cursor-free saves and keeps on cursor
        saves (mid-pass deltas must stay cumulative — see below); stream
        BOUNDARY saves pass ``clear_touched=True`` explicitly, since
        their cursor records stream position, not a mid-pass state."""
        step = trainer.global_step if step is None else step
        base_step = None
        # chain link: the state we descend from — the last step this
        # manager saved or restored (falls back to latest_step() for a
        # fresh manager continuing an existing root)
        prev_step = (self._lineage_tip if self._lineage_tip is not None
                     else self.latest_step())
        if prev_step == step:
            # re-save at the same step: the predecessor is whatever the
            # existing checkpoint pointed at (never itself — _chain loops)
            try:
                old = self._meta(step)
            except (OSError, ValueError, KeyError):
                old = {}
            if delta and old.get("kind") == "base":
                raise ValueError(
                    f"step {step} holds a BASE checkpoint; a delta re-save "
                    "would destroy it and leave an unrestorable chain — "
                    "save a base instead")
            prev_step = old.get("prev_step")
        if delta:
            base_step = self._latest_base()
            if base_step is None:
                raise ValueError("delta save with no base checkpoint yet")
        tmp = os.path.join(self.root, f".tmp-{os.getpid()}-{step}")
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        trainer.sync_table()
        # drain the async pass epilogue (ps/epilogue) before capturing:
        # a checkpoint published over an in-flight (or silently failed)
        # end_pass write-back would snapshot a host tier missing the
        # pass's rows — preemption/emergency saves come through here too
        fence = getattr(trainer.table, "fence", None)
        if fence is not None:
            fence()
        # mid-pass (cursor) saves must not clear the table's touched
        # set: with the prefetch pipeline preparing ahead, a mid-pass
        # clear drops assigned-but-not-yet-pushed rows from every later
        # delta. A table type without the kwarg fails loudly here —
        # silently clearing would corrupt the chain.
        if clear_touched is None:
            kw = {} if cursor is None else {"clear_touched": False}
        else:
            kw = {"clear_touched": clear_touched}
        if delta:
            n = trainer.table.save_delta(
                os.path.join(tmp, "sparse_delta.npz"), **kw)
        else:
            n = trainer.table.save_base(os.path.join(tmp, "sparse.npz"),
                                        **kw)
        def write_dense() -> None:
            faults.inject("checkpoint.io", path=os.path.join(tmp,
                                                             "dense.pkl"))
            with open(os.path.join(tmp, "dense.pkl"), "wb") as fh:
                if hasattr(trainer, "dense_snapshot"):
                    # pod-safe hook: per-shard AUC leaves are not host-
                    # addressable on a multi-controller mesh
                    blob = trainer.dense_snapshot()
                else:
                    blob = jax.device_get(
                        (trainer.state.params, trainer.state.opt_state,
                         trainer.state.auc))
                pickle.dump(blob, fh)
        _io_retry().call(write_dense)
        if cursor is not None:
            def write_cursor() -> None:
                path = os.path.join(tmp, "cursor.json")
                faults.inject("checkpoint.cursor", path=path, op="save")
                with open(path, "w") as fh:
                    json.dump(cursor, fh)
            _io_retry().call(write_cursor)
            if metrics is not None and len(metrics):
                with open(os.path.join(tmp, "metrics.pkl"), "wb") as fh:
                    pickle.dump(metrics, fh)
        # SSD spill manifest (ps/ssd.py; docs/STORAGE.md): segment paths
        # + sha256 of the table's disk tier AT THIS CHECKPOINT (the
        # manifest call seals the active segment, so every recorded
        # file is immutable from here). The checkpoint itself stays
        # self-contained — save_base/save_delta merged the tier rows —
        # but restore() verifies the recorded segments so a corrupt
        # tier surfaces loudly instead of promoting garbage later.
        manifest_fn = getattr(trainer.table, "spill_manifest", None)
        if manifest_fn is not None:
            manifest = manifest_fn()
            if manifest:
                def write_manifest() -> None:
                    path = os.path.join(tmp, "spill_manifest.json")
                    faults.inject("checkpoint.io", path=path)
                    with open(path, "w") as fh:
                        json.dump(manifest, fh)
                _io_retry().call(write_manifest)
        # content digests: restore refuses a bit-rotted chain link
        # instead of silently loading garbage rows
        checksums: Dict[str, str] = {
            name: _digest(os.path.join(tmp, name))
            for name in _CHECKSUMMED
            if os.path.isfile(os.path.join(tmp, name))}
        with open(os.path.join(tmp, "meta.json"), "w") as fh:
            json.dump({"step": step, "kind": "delta" if delta else "base",
                       "base_step": base_step,
                       "prev_step": prev_step if delta else None,
                       "sparse_rows": n, "checksums": checksums}, fh)
        # meta.sha256 sidecar: a torn meta.json write is detected on
        # verify like any other corrupt chain link
        with open(os.path.join(tmp, "meta.sha256"), "w") as fh:
            fh.write(_digest(os.path.join(tmp, "meta.json")))
        # crash consistency: flush file contents AND the temp dir's
        # entries before the publish rename — otherwise a power cut
        # after os.replace could expose a ckpt dir with empty files
        for name in os.listdir(tmp):
            _fsync_path(os.path.join(tmp, name))
        _fsync_path(tmp)
        # chaos seam: a "fail" fault here models the process dying after
        # writing the temp dir but BEFORE the atomic publish — recovery
        # must come from the rename convention (tests/test_resilience.py)
        faults.inject("checkpoint.save_commit", step=step)
        final = self._dir(step)
        if os.path.isdir(final):
            # move the old dir aside BEFORE the swap — a crash between the
            # two renames leaves either the old or the new dir in place,
            # never neither (latest_step falls back to dirs on disk)
            aside = final + f".old-{os.getpid()}"
            os.replace(final, aside)
            os.replace(tmp, final)
            shutil.rmtree(aside, ignore_errors=True)
        else:
            os.replace(tmp, final)
        _fsync_path(self.root)  # persist the publish rename itself
        self._lineage_tip = step
        self._write_latest(step)
        # BOUNDARY checkpoints (no cursor, or a stream cursor with an
        # empty open window) also publish into the artifact store when
        # one is attached — the day/delta "xbox publish" flow serving
        # consumes (artifacts.py; docs/RESILIENCE.md §Publishing).
        # Mid-pass cursor saves stay checkpoint-only: a consumer must
        # never adopt a state whose pass is half trained.
        stream = cursor.get("stream") if cursor else None
        is_boundary = cursor is None or (
            isinstance(stream, dict) and not stream.get("window_files"))
        if self.artifacts is not None and is_boundary:
            # best-effort: the checkpoint above is already DURABLE — a
            # registry hiccup (ENOSPC, exhausted retries) must not fail
            # the save; the next boundary publish backfills the gap.
            # An InjectedCrash still propagates: it models the process
            # dying, not the registry failing.
            try:
                self._publish_artifact(final, step, delta,
                                       prev_step=prev_step)
            except faults.InjectedCrash:
                raise
            except Exception as e:
                log.error(
                    "artifact publish failed at step %d (checkpoint "
                    "is durable; the next boundary publish will "
                    "backfill the chain): %r", step, e)
        self._retain()
        log.info("checkpoint %s saved at step %d (%d sparse rows%s)",
                 "delta" if delta else "base", step, n,
                 ", mid-pass cursor" if cursor is not None else "")
        return final

    def _write_latest(self, step: int) -> None:
        tmp = os.path.join(self.root, ".LATEST.tmp")
        with open(tmp, "w") as fh:
            fh.write(str(step))
            fh.flush()
            try:
                os.fsync(fh.fileno())
            except OSError:
                pass  # best-effort (FUSE): rename stays atomic
        os.replace(tmp, os.path.join(self.root, "LATEST"))

    # ---- artifact publishing (artifacts.py) ----------------------------
    def _step_artifact_map(self) -> Dict[int, str]:
        """{step: newest published aid} for THIS checkpoint root — ONE
        scan over the store serves a whole backfill/lookup, instead of
        one scan per chain step. The root scope matters: several jobs
        may share one store and step counters overlap — matching on
        step alone could cross-link lineages."""
        me = os.path.abspath(self.root)
        out: Dict[int, str] = {}
        for aid in self.artifacts.versions():   # epoch order: newest
            try:                                # wins per step
                m = self.artifacts.read_manifest(aid, verify=False)
            except Exception:
                continue
            meta = m.get("meta", {})
            if (meta.get("producer") == "checkpoint"
                    and meta.get("root") == me
                    and meta.get("step") is not None):
                out[meta["step"]] = aid
        return out

    def _lookup_step_artifact(self, step: int) -> Optional[str]:
        return self._step_artifact_map().get(step)

    def _is_boundary_step(self, step: int) -> bool:
        """Whether ``ckpt-<step>`` is a BOUNDARY checkpoint (no cursor,
        or a stream cursor with an empty open window) — the
        latest_boundary_step rule, for one step."""
        path = os.path.join(self._dir(step), "cursor.json")
        if not os.path.isfile(path):
            return True
        try:
            with open(path) as fh:
                stream = json.load(fh).get("stream")
        except (OSError, ValueError, AttributeError):
            return False
        return isinstance(stream, dict) \
            and not stream.get("window_files")

    def _backfill_artifacts(self, chain: List[int],
                            boundaries_only: bool = False
                            ) -> Optional[str]:
        """Publish the checkpoint-chain steps missing from the store,
        oldest first, parent-linking successively — the chain-heal
        path. Used (a) by ``restore()`` onto a step that never
        published (publishing would otherwise halt until the next base
        — and linking past the gap would lose the gap's rows), with
        the FULL chain so the restored state is exactly representable;
        and (b) before a delta publish whose predecessor boundary
        failed to publish, with ``boundaries_only=True`` (mid-pass
        deltas are subsets of their boundary's cumulative delta, so
        only unpublished BOUNDARIES break the chain). Leaves
        ``_artifact_tip`` at the newest published link."""
        start = 0
        self._artifact_tip = self._artifact_tip_step = None
        published = self._step_artifact_map()   # ONE store scan
        for i in reversed(range(len(chain))):
            aid = published.get(chain[i])
            if aid is not None:
                self._artifact_tip = aid
                self._artifact_tip_step = chain[i]
                start = i + 1
                break
        for s in chain[start:]:
            if boundaries_only and not self._is_boundary_step(s):
                continue
            try:
                meta = self._meta(s)
            except Exception as e:
                log.warning("artifact backfill stopped at step %d "
                            "(%r)", s, e)
                break
            if self._publish_artifact(
                    self._dir(s), s, meta.get("kind") == "delta",
                    prev_step=meta.get("prev_step"),
                    backfill=True) is None:
                break
        return self._artifact_tip

    def _publish_artifact(self, final: str, step: int, delta: bool,
                          prev_step: Optional[int] = None,
                          backfill: bool = False) -> Optional[str]:
        """Publish the just-committed boundary checkpoint dir as an
        artifact version. Payloads hardlink (same filesystem) so the
        publish is metadata-cost; the files are immutable once the
        checkpoint committed. A delta links to the last artifact this
        lineage published — sound because boundary deltas are
        cumulative since the previous boundary CLEAR (mid-pass saves
        never clear the touched set). When the predecessor boundary
        never published (fresh manager, or its publish failed), the
        chain heals first via ``_backfill_artifacts`` — linking past
        an unpublished boundary would silently drop its rows from the
        artifact chain."""
        kind = "delta" if delta else "base"
        parent = None
        if delta:
            if not backfill and prev_step is not None and (
                    self._artifact_tip is None
                    or self._artifact_tip_step != prev_step):
                # the step we descend from has no published artifact
                # under our tip: publish any missing BOUNDARY
                # ancestors before linking (a tip pointing at the last
                # boundary while prev_step is a mid-pass save is the
                # benign case — backfill finds it published and
                # changes nothing)
                try:
                    chain = self._chain(prev_step)
                except Exception:
                    chain = []
                if chain:
                    self._backfill_artifacts(chain,
                                             boundaries_only=True)
            parent = self._artifact_tip
            if parent is None:
                log.warning(
                    "artifact publish skipped at step %d: delta has no "
                    "published parent in %s (publish a base first)",
                    step, self.artifacts.root)
                return None
        files = {name: os.path.join(final, name)
                 for name in sorted(os.listdir(final))
                 if os.path.isfile(os.path.join(final, name))}
        refs: Dict[str, object] = {}
        spill = os.path.join(final, "spill_manifest.json")
        if os.path.isfile(spill):
            try:
                with open(spill) as fh:
                    m = json.load(fh)
                refs["spill_manifest"] = {
                    "file": "spill_manifest.json",
                    "digest": m.get("digest"),
                    "live_rows": m.get("live_rows"),
                    "shards": len(m.get("shards", {}))}
            except (OSError, ValueError):
                pass
        cpath = os.path.join(final, "cursor.json")
        if os.path.isfile(cpath):
            try:
                with open(cpath) as fh:
                    cur = json.load(fh)
                stream = cur.get("stream") or {}
                refs["cursor"] = {
                    "file": "cursor.json",
                    "files_completed": len(
                        stream.get("files_completed", []) or []),
                    "windows_completed": stream.get("windows_completed"),
                    "global_step": cur.get("global_step")}
                if cur.get("lifecycle"):
                    # feature-aging decisions this boundary was built
                    # under (online.OnlineLearner shrink cycles) — the
                    # manifest records the live-key-set provenance so
                    # a consumer can tell WHICH shrink state a version
                    # serves (docs/ONLINE.md)
                    refs["lifecycle"] = dict(cur["lifecycle"])
            except (OSError, ValueError):
                pass
        boundary = self._is_boundary_step(step)
        aid = self.artifacts.publish(
            files, kind=kind, parent=parent, refs=refs,
            # mid-pass links (restore backfill) are chain-only: an
            # unpinned reader must never land on a half-trained pass
            adoptable=boundary,
            meta={"step": step, "producer": "checkpoint",
                  "root": os.path.abspath(self.root),
                  "boundary": boundary})
        self._artifact_tip = aid
        self._artifact_tip_step = step
        self.artifacts.retain()
        return aid

    def _latest_base(self) -> Optional[int]:
        for s in reversed(self.steps()):
            try:
                if self._meta(s)["kind"] == "base":
                    return s
            except (OSError, ValueError, KeyError) as e:
                # a half-deleted/corrupt dir must not kill save/_retain
                log.warning("skipping unreadable checkpoint %d while "
                            "looking for a base: %r", s, e)
        return None

    def has_base(self) -> bool:
        """True once a base checkpoint exists (delta saves are legal)."""
        return self._latest_base() is not None

    # ---- reader leases (artifacts.py; docs/RESILIENCE.md §Publishing) --
    @staticmethod
    def _lease_name(step: int) -> str:
        return f"step-{step}"

    def lease(self, step: int):
        """Claim ``ckpt-<step>`` against retention while adopting it —
        ``with cm.lease(step): ...`` around any out-of-manager read
        (serving load, consensus restore staging). ``restore()`` takes
        one itself. The returned ``Lease`` fences: after a stale-reap,
        its ``check()``/``heartbeat()`` raise ``ArtifactLeaseLostError``
        instead of letting the reader serve from swept files."""
        return self._leases.acquire(self._lease_name(step))

    def _leased_steps(self) -> set:
        out = set()
        for name in self._leases.active_names():
            if name.startswith("step-"):
                try:
                    out.add(int(name[5:]))
                except ValueError:
                    pass
        return out

    def _retain(self) -> None:
        # finish/clean interrupted re-saves too (same logic as init):
        # a long-running process otherwise accumulates aside dirs from
        # crashes it survived without re-instantiating the manager
        self._recover()
        # provably-stale leases (dead same-host pid / heartbeat older
        # than the TTL) are reaped; LIVE leases defer deletion below
        self._leases.reap_stale()
        # sweep half-deleted carcasses: steps() hides meta-less dirs
        # from restore, but their payloads (GBs of sparse.npz) must
        # not accumulate on disk forever
        for name in os.listdir(self.root):
            if not name.startswith("ckpt-") or ".old-" in name:
                continue
            try:
                int(name[5:])
            except ValueError:
                continue
            if not os.path.isfile(os.path.join(self.root, name,
                                               "meta.json")):
                log.warning("removing half-deleted checkpoint %s", name)
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)
        steps = self.steps()
        if len(steps) <= self.keep:
            return
        kept = set(steps[-self.keep:])
        # a LEASED step is mid-adoption somewhere (serving load,
        # consensus restore, a restore() in flight) — deleting it (or
        # its chain, closed over below) would hand that reader a
        # half-deleted checkpoint; the lease defers the sweep
        leased = self._leased_steps() & set(steps)
        if leased:
            log.info("retention deferring %s (held leases)",
                     sorted(leased))
            kept |= leased
        # a delta restores by replaying its base + EVERY intermediate
        # delta (each delta covers only rows touched since the previous
        # save) — the whole chain of every kept checkpoint must survive
        for s in kept.copy():
            try:
                kept.update(self._chain(s))
            except (OSError, ValueError, KeyError):
                pass  # broken/half-deleted link: keep what we can
        for s in steps:
            if s not in kept and not self._leases.held(
                    self._lease_name(s)):   # late-lease re-check
                shutil.rmtree(self._dir(s), ignore_errors=True)

    # ---- mid-pass cursor (docs/RESILIENCE.md §Preemption) ----
    def load_cursor(self, step: Optional[int] = None) -> Optional[dict]:
        """The resume cursor stored with ``ckpt-<step>`` (default:
        latest), or None for a pass-boundary checkpoint / no checkpoint.
        An unreadable cursor is treated as absent (the pass replays from
        this step's state) rather than fatal."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        path = os.path.join(self._dir(step), "cursor.json")
        faults.inject("checkpoint.cursor", path=path, op="load")
        if not os.path.isfile(path):
            return None
        try:
            with open(path) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            log.warning("unreadable cursor.json at step %s — ignoring "
                        "(full pass replay)", step)
            return None

    def load_metrics(self, step: Optional[int] = None):
        """The MetricRegistry snapshot stored with a mid-pass
        checkpoint, or None."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        path = os.path.join(self._dir(step), "metrics.pkl")
        if not os.path.isfile(path):
            return None
        try:
            with open(path, "rb") as fh:
                return pickle.load(fh)
        except (OSError, ValueError, pickle.UnpicklingError):
            log.warning("unreadable metrics.pkl at step %s — metric "
                        "accumulators restart for this pass", step)
            return None

    def latest_boundary_step(self) -> Optional[int]:
        """Newest checkpoint at a BOUNDARY — the safe rollback target
        when a mid-pass cursor can't be applied (e.g. the dataset
        changed): either no cursor at all (a pass-boundary checkpoint),
        or a v2 STREAM cursor whose open window is empty (a
        stream-boundary checkpoint: every recorded file is fully
        consumed, nothing needs replay). Read WITHOUT the
        ``checkpoint.cursor`` fault seam: this is a scan, not a resume
        — firing the seam here would shift seeded chaos-plan counters."""
        for s in reversed(self.steps()):
            path = os.path.join(self._dir(s), "cursor.json")
            if not os.path.isfile(path):
                return s
            try:
                with open(path) as fh:
                    cur = json.load(fh)
                stream = cur.get("stream")
            except (OSError, ValueError, AttributeError):
                continue  # unreadable cursor: not provably a boundary
            if isinstance(stream, dict) and not stream.get("window_files"):
                return s
        return None

    def verified_steps(self) -> List[int]:
        """Every step whose ENTIRE base+delta chain verifies locally —
        what a process publishes into the restore consensus
        (resilience/consensus.consensus_restore): agreeing over full
        sets lets the mesh pick a step that exists EVERYWHERE even when
        retention windows drifted apart."""
        out: List[int] = []
        verified: Dict[int, bool] = {}

        def ok(link: int) -> bool:
            if link not in verified:
                try:
                    self.verify(link)
                    verified[link] = True
                except Exception as e:
                    log.warning("step %d fails local verification (%r)",
                                link, e)
                    verified[link] = False
            return verified[link]

        for s in self.steps():
            try:
                if all(ok(link) for link in self._chain(s)):
                    out.append(s)
            except Exception as e:
                log.warning("step %d has a broken chain (%r)", s, e)
        return out

    def latest_verified_step(self) -> Optional[int]:
        """Newest step whose whole chain verifies locally, or None."""
        steps = self.verified_steps()
        return steps[-1] if steps else None

    # ---- restore ----
    def restore(self, trainer, step: Optional[int] = None) -> Optional[int]:
        """Restore to ``step`` (default: latest). Replays the base + delta
        chain for sparse state; returns the restored step or None if no
        checkpoint exists."""
        target = self.latest_step() if step is None else step
        if target is None:
            return None
        # lease the target for the whole adoption: a concurrent
        # _retain (another process sharing this root) must defer the
        # sweep of this chain until the restore finishes
        with self.lease(target):
            chain = self._chain(target)
            for s in chain:  # verify the WHOLE chain before touching state
                self.verify(s)
            self._verify_spill_manifest(target)
            first = True
            for s in chain:
                d = self._dir(s)
                meta = self._meta(s)
                if meta["kind"] == "base":
                    trainer.table.load(os.path.join(d, "sparse.npz"),
                                       merge=not first)
                else:
                    trainer.table.load(os.path.join(d, "sparse_delta.npz"),
                                       merge=True)
                first = False
            def read_dense():
                path = os.path.join(self._dir(target), "dense.pkl")
                faults.inject("checkpoint.io", path=path)
                with open(path, "rb") as fh:
                    return pickle.load(fh)
            params, opt_state, auc = _io_retry().call(read_dense)
        if hasattr(trainer, "dense_snapshot"):
            # the trainer handles placement itself (pod staging) — a
            # device_put here would just round-trip device→host→device
            trainer.restore_state(params, opt_state, auc, target)
        else:
            trainer.restore_state(jax.device_put(params),
                                  jax.device_put(opt_state),
                                  jax.device_put(auc), target)
        self._lineage_tip = target
        if self.artifacts is not None:
            # the next boundary delta publish must link to the artifact
            # of the state we now descend from. A restore onto a step
            # that never published (e.g. a mid-pass crash checkpoint)
            # BACKFILLS the missing chain links from the checkpoint
            # dirs — publishing must neither halt until the next base
            # nor link past the gap (the gap's rows would silently
            # leave the artifact chain). Backfilled mid-pass links
            # carry their cursor ref, marking them.
            try:
                tip = self._lookup_step_artifact(target)
                if tip is not None:
                    self._artifact_tip = tip
                    self._artifact_tip_step = target
                else:
                    self._backfill_artifacts(chain)
            except faults.InjectedCrash:
                raise
            except Exception as e:
                # the trainer state is fully restored — a registry
                # failure must not fail the restore; the next boundary
                # publish re-attempts the backfill
                log.error("artifact backfill failed after restore to "
                          "step %d (will retry at the next boundary "
                          "publish): %r", target, e)
        log.info("restored step %d (chain: %s)", target, chain)
        return target

    def _verify_spill_manifest(self, step: int) -> None:
        """Verify the SSD-tier segments recorded with ``ckpt-<step>``
        against their manifest sha256 — the spill-tier link of the
        checksum chain (docs/STORAGE.md). A MISSING segment is fine
        (compaction unlinks dead segments and restore re-imports every
        row from the checkpoint itself); a PRESENT-but-different one is
        real corruption and raising here stops the restore before any
        later promote could read garbage rows."""
        path = os.path.join(self._dir(step), "spill_manifest.json")
        if not os.path.isfile(path):
            return
        try:
            with open(path) as fh:
                manifest = json.load(fh)
        except (OSError, ValueError) as e:
            # the file itself is covered by meta.json checksums — an
            # unreadable manifest that PASSED verify() means a pre-
            # checksum writer; treat as absent
            log.warning("unreadable spill_manifest.json at step %d "
                        "(%r) — skipping tier verification", step, e)
            return
        from paddlebox_tpu.ps.ssd import (SegmentCorruptError,
                                          verify_manifest)
        missing: List[str] = []
        for shard, m in manifest.get("shards", {}).items():
            try:
                missing += verify_manifest(m)
            except SegmentCorruptError as e:
                raise CheckpointCorruptError(
                    f"checkpoint {step} spill manifest (shard {shard}): "
                    f"{e} ") from e
        if missing:
            log.info("spill manifest at step %d: %d segment(s) no "
                     "longer on disk (compacted/reset) — checkpoint is "
                     "self-contained, continuing", step, len(missing))

    def _chain(self, target: int) -> List[int]:
        """base → …deltas… → target, walking each delta's prev_step link
        backwards. A MISSING link raises (each delta covers only rows
        touched since the previous save — a gap would restore silently
        stale rows)."""
        chain = [target]
        cur = target
        while True:
            meta = self._meta(cur)
            if meta["kind"] == "base":
                return chain
            prev = meta.get("prev_step")
            if prev is None:
                # every delta written by this manager records prev_step
                # (the base for the first delta); a missing link means a
                # foreign/corrupt meta — refuse rather than restore with
                # intermediate deltas silently skipped
                raise ValueError(
                    f"delta checkpoint {cur} has no prev_step link — "
                    "unsupported checkpoint format")
            if prev >= cur:
                # a delta can only descend from an OLDER state; a
                # forward link means a foreign/abandoned-timeline meta
                raise ValueError(
                    f"delta checkpoint {cur} links forward to {prev} — "
                    "corrupt or abandoned-timeline chain; restore an "
                    "older base or resave")
            if not os.path.isdir(self._dir(prev)):
                raise FileNotFoundError(
                    f"checkpoint chain broken: {cur} needs {prev} "
                    "(deleted or lost) — restore an older base or resave")
            chain.insert(0, prev)
            cur = prev


def adopt_artifact(trainer, store, version: Optional[str] = None
                   ) -> Optional[int]:
    """Restore a trainer FROM the artifact store alone (no checkpoint
    root needed — the consumer side of the publish flow). Verifies the
    full checksum chain before touching any state, holds the reader
    lease across the whole adoption, and replays base → deltas exactly
    like ``CheckpointManager.restore``. Returns the restored step.

    With ``version=None`` this adopts the newest VERIFIABLE version —
    corrupt tips are refused loudly (``ArtifactCorruptError`` logged +
    ``pbox_artifact_refused_total``) and the adoption degrades to the
    newest chain that checks out."""
    with store.open(version) as h:
        first = True
        for m in h.chain:
            name = ("sparse.npz" if m["kind"] == "base"
                    else "sparse_delta.npz")
            trainer.table.load(h.path(name, m["artifact"]),
                               merge=not first)
            first = False
        with open(h.path("dense.pkl"), "rb") as fh:
            params, opt_state, auc = pickle.load(fh)
        step = int(h.manifest.get("meta", {}).get("step") or 0)
    if hasattr(trainer, "dense_snapshot"):
        trainer.restore_state(params, opt_state, auc, step)
    else:
        trainer.restore_state(jax.device_put(params),
                              jax.device_put(opt_state),
                              jax.device_put(auc), step)
    log.info("adopted artifact %s (step %s)", h.aid, step)
    return step


def state_digest(trainer) -> str:
    """sha256 over the trainer's LOGICAL state: every table row keyed and
    sorted by feasign (row-id assignment order cancels out — a resumed
    run allocates rows in a different order than an uninterrupted one),
    plus the dense params / optimizer / AUC pytree leaves. Two trainers
    with equal digests hold byte-identical model state; the preemption
    e2e (tests/test_preemption.py, scripts/preempt_check.py) asserts
    resume-from-cursor reproduces the uninterrupted digest exactly."""
    import numpy as _np
    trainer.sync_table()
    table = trainer.table
    h = hashlib.sha256()
    with table.host_lock:
        keys, rows = table.index.items()
    order = _np.argsort(keys)
    keys, rows = keys[order], rows[order]
    h.update(_np.ascontiguousarray(keys).tobytes())
    blob = table._gather_host(rows)
    for f in sorted(blob):
        h.update(f.encode())
        h.update(_np.ascontiguousarray(blob[f]).tobytes())
    for leaf in jax.tree_util.tree_leaves(
            jax.device_get((trainer.state.params, trainer.state.opt_state,
                            trainer.state.auc))):
        h.update(_np.ascontiguousarray(_np.asarray(leaf)).tobytes())
    return h.hexdigest()


def elastic_state_digest(trainer) -> str:
    """sha256 over a ShardedTrainer's LOGICAL state, invariant to the
    table's shard count: every shard's rows are gathered, keyed by
    feasign and sorted globally (the ``key % num_shards`` owner and the
    row-id assignment order both cancel out), then the dense params /
    optimizer leaves and the shard-REDUCED AUC (``_finalize_auc`` — the
    same reduction ``dense_snapshot`` persists, so an 8-shard world and
    its re-sharded 6-shard successor digest identically when they hold
    the same model). The elastic gate (scripts/elastic_check.py)
    compares churned runs against an unchurned oracle with it at every
    common pass boundary."""
    trainer.sync_table()
    table = trainer.table
    h = hashlib.sha256()
    data = np.asarray(jax.device_get(table.state.data))
    all_keys, all_rows = [], []
    with table.host_lock:
        per_shard = [table.indexes[s].items() for s in range(table.n)]
    for s, (keys, rows) in enumerate(per_shard):
        all_keys.append(np.ascontiguousarray(keys, np.uint64))
        all_rows.append(data[s][rows])
    keys = (np.concatenate(all_keys) if all_keys
            else np.zeros(0, np.uint64))
    rows = (np.concatenate(all_rows) if all_rows
            else np.zeros((0, data.shape[-1]), np.float32))
    order = np.argsort(keys, kind="stable")
    h.update(np.ascontiguousarray(keys[order]).tobytes())
    h.update(np.ascontiguousarray(rows[order]).tobytes())
    for leaf in jax.tree_util.tree_leaves(
            jax.device_get((trainer.state.params,
                            trainer.state.opt_state))):
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    for leaf in tuple(trainer._finalize_auc(trainer.state.auc)):
        h.update(np.ascontiguousarray(
            np.asarray(jax.device_get(leaf))).tobytes())
    return h.hexdigest()


def sharded_state_digest(trainer) -> str:
    """sha256 over a ShardedTrainer's RAW state bytes: dense params +
    the packed table shards + the per-shard AUC leaves. STRICTER than
    ``state_digest`` (physical row-assignment order matters here, not
    just logical content) — the chunk-schedule parity gates (ISSUE 11:
    tests/test_sharded.py, scripts/scaling_check.py) compare two
    schedules over the SAME batch stream, where bit-identity includes
    the row layout the grouped plan builder promises to preserve."""
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(
            jax.device_get(trainer.state.params)):
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    h.update(np.asarray(
        jax.device_get(trainer.state.table.packed)).tobytes())
    for leaf in jax.device_get(tuple(trainer.state.auc)):
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()
