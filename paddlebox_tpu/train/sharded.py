"""Multi-chip fused train step: data-parallel dense + model-parallel
embedding shards, one jit program under shard_map.

Reference execution model being replaced (SURVEY.md §2.6): one worker thread
per GPU (BoxPSTrainer), NCCL allreduce for dense grads (SyncParam,
boxps_worker.cc:1191-1258), HeterComm P2P for sparse pull/push, MPI for
cross-node. Here ALL of it is one traced program over the mesh: two
``all_to_all`` collectives route embedding rows/grads between shards
(ps/sharded.py), a ``psum`` reduces dense grads, and XLA schedules the
collectives against compute on ICI.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddlebox_tpu.data.batch import SlotBatch
from paddlebox_tpu.metrics import AucState, auc_add_batch, init_auc_state
from paddlebox_tpu.ops import fused_seqpool_cvm
from paddlebox_tpu.ops.seqpool_cvm import fused_seqpool_cvm_slot_group
from paddlebox_tpu.parallel.mesh import DATA_AXIS
from paddlebox_tpu.ps.sgd import SparseSGDConfig
from paddlebox_tpu.ps.sharded import (ShardedEmbeddingTable,
                                      ShardedPullIndex,
                                      chunk_local_positions,
                                      plan_sections, section_offsets)
from paddlebox_tpu.ops.bitpack import (pack_delta_auto, pack_u16m,
                                       pack_u24, unpack_delta16,
                                       unpack_u16m, unpack_u24)
from paddlebox_tpu.ps.table import (TableState, apply_push,
                                    expand_pull, fill_oob_pads,
                                    gather_full_rows, merge_rows,
                                    pull_values)
from paddlebox_tpu.train.step import quantize_floats


class GlobalBatch(NamedTuple):
    """One global batch: per-device blocks stacked on axis 0 (sharded dp)."""

    resp_idx: jax.Array     # int32 [N, N, A]
    serve_rows: jax.Array   # int32 [N, A2]
    serve_valid: jax.Array  # f32   [N, A2]
    serve_slot: jax.Array   # f32   [N, A2]
    gather_idx: jax.Array   # int32 [N, K]
    segments: jax.Array     # int32 [N, K]
    dense: jax.Array        # f32   [N, B, Dd]
    label: jax.Array        # f32   [N, B]
    show: jax.Array         # f32   [N, B]
    clk: jax.Array          # f32   [N, B]


def make_global_arrays(batches: List[SlotBatch],
                       idx: ShardedPullIndex) -> Dict[str, np.ndarray]:
    """Stack N local batches + routing plan into HOST arrays (the
    resident builder consumes these directly — never round-trip the
    plan through device arrays)."""
    dense, label, show, clk = [], [], [], []
    for b in batches:
        dense.append(b.dense)
        label.append(b.label)
        show.append(b.show)
        clk.append(b.clk)
    if getattr(idx, "key_segments", None) is not None:
        # grouped plan (a2a_chunks > 1): the key stream was re-laid
        # group-contiguous, so the matching segment stream comes from
        # the plan, not the batches' original-order segments
        segs = list(idx.key_segments)
        gi = idx.gather_idx
        return dict(
            resp_idx=idx.resp_idx, serve_rows=idx.serve_rows,
            serve_valid=idx.serve_valid, serve_slot=idx.serve_slot,
            gather_idx=gi, segments=np.stack(segs),
            dense=np.stack(dense), label=np.stack(label),
            show=np.stack(show), clk=np.stack(clk))
    k_pad = max(b.keys.shape[0] for b in batches)
    segs = []
    for b in batches:
        s = np.full(k_pad, b.pad_segment, np.int32)
        s[:b.segments.shape[0]] = b.segments
        segs.append(s)
    gi = idx.gather_idx
    if gi.shape[1] < k_pad:
        pad = ((0, 0), (0, k_pad - gi.shape[1]))
        gi = np.pad(gi, pad, constant_values=gi.max())
    return dict(
        resp_idx=idx.resp_idx, serve_rows=idx.serve_rows,
        serve_valid=idx.serve_valid, serve_slot=idx.serve_slot,
        gather_idx=gi, segments=np.stack(segs),
        dense=np.stack(dense), label=np.stack(label),
        show=np.stack(show), clk=np.stack(clk))


def make_global_batch(batches: List[SlotBatch],
                      idx: ShardedPullIndex) -> GlobalBatch:
    """make_global_arrays staged to device (streaming step path)."""
    host = make_global_arrays(batches, idx)
    return GlobalBatch(**{f: jnp.asarray(host[f])
                          for f in GlobalBatch._fields})


def _wire_spec(name: str, ndim: int) -> P:
    """Sharding spec for a packed-wire leaf: [nb, N, ...] with the
    device dim sharded; qmeta is pass-global (replicated)."""
    if name == "qmeta":
        return P()
    return P(*([None, DATA_AXIS] + [None] * (ndim - 2)))


class _LazyJit:
    """Defers jit construction until the wire pytree's structure is
    known (specs depend on it)."""

    def __init__(self, factory) -> None:
        self._factory = factory
        self._jit = None

    def __call__(self, state, wire, start, rng):
        if self._jit is None:
            self._jit = self._factory(wire)
        return self._jit(state, wire, start, rng)


def _decode_wire_step(wire, fmt, i, capacity: int) -> GlobalBatch:
    """Reassemble step i's GlobalBatch from the packed resident wire
    (in-trace; see ShardedResidentPass._encode_wire for the encodings)."""
    def dec_int(name):
        f = fmt[name]
        t = wire[name]
        if f == "u18":
            return unpack_u16m(t[0][i], t[1][i], 2)
        if f == "u24":
            return unpack_u24(t[0][i], t[1][i])
        return t[0][i]

    resp_idx = dec_int("resp_idx")
    if fmt["serve_rows"] == "delta":
        d = wire["serve_rows"]
        srm = wire["srmeta"][0][i]                    # [N, 2] count, base
        dec = jax.vmap(unpack_delta16)(d[0][i], d[1][i], d[2][i],
                                       srm[:, 1])
        a2 = dec.shape[-1]
        pos = jnp.arange(a2, dtype=jnp.int32)[None, :]
        # pads regenerate from the real count: distinct ascending OOB
        # ids (the fill_oob_pads contract)
        serve_rows = jnp.where(pos < srm[:, 0:1], dec,
                               capacity + 1 + pos)
    else:
        serve_rows = dec_int("serve_rows")
    gather_idx = dec_int("gather_idx")
    if fmt["serve_valid"] == "derive":
        serve_valid = (serve_rows <= capacity).astype(jnp.float32)
    else:
        serve_valid = wire["serve_valid"][0][i]
    serve_slot = wire["serve_slot"][0][i].astype(jnp.float32)
    if fmt["segments"] == "trivial":
        meta = wire["meta"][0][i]                     # [N_local, 2]
        k = gather_idx.shape[-1]
        pos = jnp.arange(k, dtype=jnp.int32)[None, :]
        segments = jnp.where(pos < meta[:, 0:1], pos, meta[:, 1:2])
    else:
        segments = dec_int("segments")
    if fmt["dense"] == "q8":
        qm = wire["qmeta"][0]                         # [2, Dd] replicated
        d = wire["dense"][0][i].astype(jnp.float32)
        dense = d * qm[0][None, None, :] + qm[1][None, None, :]
    else:
        dense = wire["dense"][0][i]
    lsc = {}
    for f in ("label", "show", "clk"):
        a = wire[f][0][i]
        lsc[f] = a.astype(jnp.float32)
    return GlobalBatch(resp_idx=resp_idx, serve_rows=serve_rows,
                       serve_valid=serve_valid, serve_slot=serve_slot,
                       gather_idx=gather_idx, segments=segments,
                       dense=dense, **lsc)


class ShardedStepState(NamedTuple):
    table: TableState   # leaves [N, C+1, …] sharded over dp
    params: Any         # replicated
    opt_state: Any      # replicated
    auc: AucState       # leaves [N, …] sharded over dp
    step: jax.Array


def init_sharded_auc(n: int, nbins: Optional[int] = None) -> AucState:
    s = init_auc_state(nbins)
    return AucState(*[jnp.broadcast_to(l[None], (n,) + l.shape).copy()
                      for l in s])


def _assert_elementwise_tx(tx: optax.GradientTransformation) -> None:
    """ZeRO-1 applies ``tx`` to each device's flat param CHUNK, which is
    only correct when the transform is elementwise (update of element i
    depends on grad/param element i alone — adam/adagrad/sgd/…). Probe:
    the update of a half-vector must equal the first half of the update
    of the full vector; transforms with global reductions
    (clip_by_global_norm, scale_by_trust_ratio, …) fail it."""
    g = jnp.linspace(0.5, 4.0, 8)
    p = jnp.ones(8)
    u_full, _ = tx.update(g, tx.init(p), p)
    u_half, _ = tx.update(g[:4], tx.init(p[:4]), p[:4])
    if not np.allclose(np.asarray(u_full)[:4], np.asarray(u_half),
                       rtol=1e-6, atol=1e-12):
        raise ValueError(
            "zero1=True requires an ELEMENTWISE optax transform: the "
            "optimizer runs on per-device param chunks, and this tx "
            "computes cross-element statistics (e.g. "
            "clip_by_global_norm), which would silently become "
            "per-chunk statistics. Apply such transforms before the "
            "reduce-scatter, or disable zero1.")


class ShardedTrainStep:
    """Builds the jitted multi-chip step for a mesh."""

    def __init__(
        self,
        model,
        tx: optax.GradientTransformation,
        sgd_cfg: SparseSGDConfig,
        mesh: Mesh,
        batch_size_per_device: int,
        num_slots: int,
        use_cvm: bool = True,
        cvm_offset: int = 2,
        zero1: bool = False,
        lr_scales: Any = None,
    ) -> None:
        """``lr_scales`` — per-leaf update multipliers (pytree matching
        params, from dense_modes.build_lr_scales): the per-param dense
        lr_map (box_wrapper.cc:1303-1335) applied after tx.update so it
        composes with Adam and the ZeRO-1 flat chunks."""
        self.model = model
        self.tx = tx
        self.lr_scales = lr_scales
        self._zero1_scaled = False  # set at init_state
        self.sgd_cfg = sgd_cfg
        self.mesh = mesh
        self.n = mesh.shape[DATA_AXIS]
        self.batch_size = batch_size_per_device
        self.num_slots = num_slots
        self.use_cvm = use_cvm
        self.cvm_offset = cvm_offset
        # ZeRO-1 dense sharding (reference: BoxPSWorker sharding stage,
        # boxps_worker.cc:601 BuildShardingDepends — params partitioned
        # across devices): each device owns a flat param chunk + its opt
        # state; grads reduce-scatter in, params all-gather out.
        # CONSTRAINT: tx must be an ELEMENTWISE transform (adam/adagrad/
        # sgd/…) — it is applied per flat per-device chunk, so transforms
        # needing a global reduction over the whole param tree (e.g.
        # clip_by_global_norm) would compute per-chunk statistics instead.
        # Enforced by probe: updating a half-vector must equal the first
        # half of updating the full vector.
        if zero1:
            _assert_elementwise_tx(tx)
        self.zero1 = zero1
        self._chunk = 0           # set at init_state
        self._unravel = None

        shard0 = P(DATA_AXIS)
        rep = P()
        state_spec = ShardedStepState(
            # spec-prefix: covers TableState's single packed leaf [N,L,128]
            table=shard0,
            params=rep, opt_state=(shard0 if zero1 else rep),
            auc=AucState(*([shard0] * len(AucState._fields))),
            step=rep)
        self._state_spec = state_spec  # shared with _resident_runner
        # public: multihost.globalize_state stages state by THIS spec
        self.state_spec = state_spec
        batch_spec = GlobalBatch(*([shard0] * len(GlobalBatch._fields)))
        stats_spec = {"loss": rep, "pred": shard0}
        self._batch_spec = batch_spec
        self._stats_spec = stats_spec
        self._sharded = jax.jit(
            jax.shard_map(
                self._device_step, mesh=mesh,
                in_specs=(state_spec, batch_spec, rep),
                out_specs=(state_spec, stats_spec),
                check_vma=False),
            donate_argnums=(0,))
        # chunked-schedule executables, one per distinct section layout
        # (FLAGS.a2a_chunks > 1; ps/sharded.plan_sections). The
        # monolithic ``self._sharded`` above stays byte-for-byte the
        # pre-chunking program — sections=() routes to it.
        self._sharded_chunked: Dict[tuple, object] = {}

    def init_params(self, mf_dim: int, dense_dim: int) -> Any:
        d = self.cvm_offset + 1 + mf_dim if self.use_cvm else 1 + mf_dim
        pooled = jnp.zeros((self.batch_size, self.num_slots, d))
        dense = jnp.zeros((self.batch_size, dense_dim))
        return self.model.init(jax.random.PRNGKey(0), pooled, dense)

    def init_state(self, table: ShardedEmbeddingTable, params: Any) -> ShardedStepState:
        if self.zero1:
            from jax.flatten_util import ravel_pytree

            flat, self._unravel = ravel_pytree(params)
            self._psize = int(flat.size)
            self._chunk = -(-self._psize // self.n)  # ceil
            pad = self.n * self._chunk - self._psize
            chunks = jnp.pad(flat, (0, pad)).reshape(self.n, self._chunk)
            opt_state = jax.vmap(self.tx.init)(chunks)
            self._zero1_scaled = self.lr_scales is not None
            if self._zero1_scaled:
                # lr_map through the flat-chunk layout: ravel per-leaf
                # multipliers exactly as params ravel, pad with 1s. The
                # chunks ride INSIDE opt_state (sharded over the mesh
                # axis) so each device holds only its own [chunk] slice —
                # a closure constant would replicate the full param-size
                # array per device, against ZeRO-1's point
                sflat, _ = ravel_pytree(jax.tree.map(
                    lambda x, s: jnp.full(x.shape, s, jnp.float32),
                    params, self.lr_scales))
                scale_chunks = jnp.pad(
                    sflat, (0, pad), constant_values=1.0).reshape(
                    self.n, self._chunk)
                opt_state = (opt_state, scale_chunks)
        else:
            opt_state = self.tx.init(params)
        return ShardedStepState(
            table=table.state, params=params, opt_state=opt_state,
            auc=init_sharded_auc(self.n), step=jnp.zeros((), jnp.int32))

    # ---- dense grad sync + optimizer (shared by both schedules) ----
    def _dense_sync(self, state: ShardedStepState, g_params, me):
        """psum (SyncParam's allreduce) or ZeRO-1 reduce-scatter /
        update / all-gather → (params, opt_state). Extracted from
        ``_device_step`` unchanged (pure code motion at trace time) so
        the chunked schedule can interleave it with the push exchange."""
        if self.zero1:
            # ZeRO-1: reduce-scatter grads, update the owned flat chunk
            # with per-device opt state, all-gather fresh params
            from jax.flatten_util import ravel_pytree

            g_flat, _ = ravel_pytree(g_params)
            pad = self.n * self._chunk - self._psize
            g_mine = jax.lax.psum_scatter(
                jnp.pad(g_flat, (0, pad)).reshape(self.n, self._chunk),
                DATA_AXIS, scatter_dimension=0, tiled=True)[0]
            p_flat, _ = ravel_pytree(state.params)
            p_mine = jnp.pad(p_flat, (0, pad)).reshape(
                self.n, self._chunk)[me]
            opt_st = state.opt_state
            scale_mine = None
            if getattr(self, "_zero1_scaled", False):
                opt_st, scale_block = opt_st  # [1, chunk] device block
                scale_mine = scale_block[0]
            opt_mine = jax.tree.map(lambda l: l[0], opt_st)
            updates, opt_mine = self.tx.update(g_mine, opt_mine, p_mine)
            if scale_mine is not None:
                # per-param lr_map on this device's flat chunk
                updates = updates * scale_mine
            p_mine = optax.apply_updates(p_mine, updates)
            p_all = jax.lax.all_gather(p_mine, DATA_AXIS, tiled=True)
            params = self._unravel(p_all[:self._psize])
            opt_state = jax.tree.map(lambda l: l[None], opt_mine)
            if scale_mine is not None:
                opt_state = (opt_state, scale_block)
        else:
            # psum == SyncParam's allreduce
            g_params = jax.lax.psum(g_params, DATA_AXIS)
            updates, opt_state = self.tx.update(g_params, state.opt_state,
                                                state.params)
            if self.lr_scales is not None:
                # per-param lr_map (boxps_worker.cc:199-204)
                updates = jax.tree.map(lambda u, s: u * s, updates,
                                       self.lr_scales)
            params = optax.apply_updates(state.params, updates)
        return params, opt_state

    # ---- per-device block program (runs under shard_map) ----
    def _device_step(self, state: ShardedStepState, batch: GlobalBatch,
                     rng: jax.Array, sections: tuple = ()):
        """``sections`` = () runs the monolithic pull → compute → push →
        dense-sync schedule (the pre-ISSUE-11 program, byte-for-byte).
        A grouped plan's ``(a2a_sections, key_sections, slot_sections)``
        runs the CHUNKED schedule: one all_to_all per slot group with
        the previous group's expand_pull → fused_seqpool_cvm pooling
        independent of it (the fused computation-collective
        decomposition), and the push grad all_to_all issued BEFORE the
        independent dense sync so exchange and psum/ZeRO-1 overlap.
        Both schedules are bit-identical (tests/test_sharded.py digest
        parity; docs/PERFORMANCE.md §Sharded-step overlap). Either
        schedule's pooling (fused_seqpool_cvm / the slot-group variant)
        rides the FLAGS.use_pallas_seqpool dispatch seam onto the fused
        Pallas MXU kernel (docs/PERFORMANCE.md §Device kernels)."""
        n, b, s = self.n, self.batch_size, self.num_slots
        me = jax.lax.axis_index(DATA_AXIS)
        # blocks arrive with leading dim 1; drop it
        table = state.table.with_packed(state.table.packed[0])
        auc = AucState(*[l[0] for l in state.auc])
        resp_idx = batch.resp_idx[0]       # [N, A]
        serve_rows = batch.serve_rows[0]   # [A2]
        serve_valid = batch.serve_valid[0]
        serve_slot = batch.serve_slot[0]
        gather_idx = batch.gather_idx[0]   # [K]
        segments = batch.segments[0]
        dense = batch.dense[0]
        label = batch.label[0]
        show = batch.show[0]
        clk = batch.clk[0]
        a = resp_idx.shape[1]
        a2 = serve_rows.shape[0]
        d = 3 + table.mf_dim

        if not sections:
            # ---- pull: serve my rows, exchange, reassemble ----
            # one AoS gather serves the pull AND the push optimizer state
            rows_full = gather_full_rows(table, serve_rows)    # [A2, F]
            serve_vals = pull_values(rows_full, table.mf_dim)  # [A2, D]
            # lane-packed expand (ps/table.expand_pull): narrow-row
            # gathers and their autodiff transposes run at line
            # granularity
            resp = expand_pull(serve_vals,
                               resp_idx.reshape(-1)).reshape(n, a, d)
            recv = jax.lax.all_to_all(resp, DATA_AXIS, 0, 0, tiled=True)
            vals_flat = recv.reshape(n * a, d)

            ins_w = (show > 0).astype(jnp.float32)
            wsum_global = jax.lax.psum(jnp.sum(ins_w), DATA_AXIS)
            batch_show_clk = jnp.stack([show, clk], axis=1)

            def loss_fn(params, vals_flat):
                values_k = expand_pull(vals_flat, gather_idx)
                pooled = fused_seqpool_cvm(
                    values_k, segments, batch_show_clk, b, s,
                    self.use_cvm, self.cvm_offset)
                logits = self.model.apply(params, pooled, dense)
                ls = optax.sigmoid_binary_cross_entropy(logits, label)
                loss_local = jnp.sum(ls * ins_w) / jnp.maximum(
                    wsum_global, 1.0)
                return loss_local, logits

            (loss_local, logits), (g_params, g_vals_flat) = \
                jax.value_and_grad(loss_fn, argnums=(0, 1),
                                   has_aux=True)(state.params, vals_flat)

            # ---- push: route grads back to owners, merge, update ----
            g_back = jax.lax.all_to_all(
                g_vals_flat.reshape(n, a, d), DATA_AXIS, 0, 0, tiled=True)
            g_serve = merge_rows(g_back.reshape(n * a, d),
                                 resp_idx.reshape(n * a), num_segments=a2)
            # PushCopy scaling (box_wrapper.cu:368): negate embed grads ×
            # global batch size (loss above is the global mean)
            gb = jnp.concatenate(
                [g_serve[:, :2], g_serve[:, 2:] * (-1.0 * b * n)], axis=1)
            touched = serve_valid > 0
            table = apply_push(table, serve_rows, gb,
                               self.sgd_cfg, jax.random.fold_in(rng, me),
                               rows_full=rows_full, touched=touched,
                               slot_val=serve_slot)

            # ---- dense sync ----
            params, opt_state = self._dense_sync(state, g_params, me)
        else:
            # ---- chunked exchange-compute schedule (ISSUE 11) ----
            # "Optimizing Distributed ML Communication with Fused
            # Computation-Collective Operations" (PAPERS.md): decompose
            # the pull all_to_all along slot groups; chunk g+1's
            # exchange has no data dependency on chunk g's pooling, so
            # XLA's latency-hiding scheduler can fly the ICI transfer
            # while the MXU pools the previous group.
            a_secs, k_secs, s_secs = sections
            a_off = section_offsets(a_secs)
            k_off = section_offsets(k_secs)
            s_off = section_offsets(s_secs)
            rows_full = gather_full_rows(table, serve_rows)    # [A2, F]
            serve_vals = pull_values(rows_full, table.mf_dim)  # [A2, D]
            recvs = []
            for g, ag in enumerate(a_secs):
                lo = a_off[g]
                resp_g = expand_pull(
                    serve_vals,
                    resp_idx[:, lo:lo + ag].reshape(-1)).reshape(n, ag, d)
                recv_g = jax.lax.all_to_all(resp_g, DATA_AXIS, 0, 0,
                                            tiled=True)
                recvs.append(recv_g.reshape(n * ag, d))

            ins_w = (show > 0).astype(jnp.float32)
            wsum_global = jax.lax.psum(jnp.sum(ins_w), DATA_AXIS)
            batch_show_clk = jnp.stack([show, clk], axis=1)

            def loss_fn(params, recvs):
                # per-group expand → pool; blocks concat in canonical
                # slot order, bit-identical to the monolithic pool
                # (bins are per-slot; the grouped plan is stable)
                parts = []
                for g, (ag, kg, sg) in enumerate(
                        zip(a_secs, k_secs, s_secs)):
                    gi = gather_idx[k_off[g]:k_off[g] + kg]
                    seg = segments[k_off[g]:k_off[g] + kg]
                    # global position owner*A + j → chunk-local (ONE
                    # definition, shared with the probe)
                    local = chunk_local_positions(gi, a, a_off[g], ag)
                    values_k = expand_pull(recvs[g], local)
                    parts.append(fused_seqpool_cvm_slot_group(
                        values_k, seg, batch_show_clk, b, s,
                        s_off[g], s_off[g] + sg,
                        self.use_cvm, self.cvm_offset))
                pooled = jnp.concatenate(parts, axis=1)
                logits = self.model.apply(params, pooled, dense)
                ls = optax.sigmoid_binary_cross_entropy(logits, label)
                loss_local = jnp.sum(ls * ins_w) / jnp.maximum(
                    wsum_global, 1.0)
                return loss_local, logits

            (loss_local, logits), (g_params, g_recvs) = \
                jax.value_and_grad(loss_fn, argnums=(0, 1),
                                   has_aux=True)(state.params,
                                                 tuple(recvs))

            # ---- push: ONE grad all_to_all on the reassembled
            # canonical [n, A, d] wire, issued BEFORE the independent
            # dense sync so the exchange overlaps psum/ZeRO-1 (the
            # monolithic path runs them strictly in sequence); merge /
            # apply_push then see exactly the monolithic layout
            g_vals = jnp.concatenate(
                [gr.reshape(n, ag, d)
                 for gr, ag in zip(g_recvs, a_secs)], axis=1)
            g_back = jax.lax.all_to_all(g_vals, DATA_AXIS, 0, 0,
                                        tiled=True)
            params, opt_state = self._dense_sync(state, g_params, me)
            g_serve = merge_rows(g_back.reshape(n * a, d),
                                 resp_idx.reshape(n * a), num_segments=a2)
            gb = jnp.concatenate(
                [g_serve[:, :2], g_serve[:, 2:] * (-1.0 * b * n)], axis=1)
            touched = serve_valid > 0
            table = apply_push(table, serve_rows, gb,
                               self.sgd_cfg, jax.random.fold_in(rng, me),
                               rows_full=rows_full, touched=touched,
                               slot_val=serve_slot)

        pred = jax.nn.sigmoid(logits)
        auc = auc_add_batch(auc, pred, label, ins_w)
        loss = jax.lax.psum(loss_local, DATA_AXIS)

        new_state = ShardedStepState(
            table=table.with_packed(table.packed[None]),
            params=params, opt_state=opt_state,
            auc=AucState(*[l[None] for l in auc]),
            step=state.step + 1)
        # pred stays device-sharded [N, B]; consumers (dump, registry)
        # fetch it only when configured
        return new_state, {"loss": loss, "pred": pred[None]}

    def _step_fn_for(self, sections: tuple):
        """The jitted step for a chunk-schedule key (() = monolithic).
        One executable per distinct section layout; the resident
        builder's uniform-shape contract keeps that to ~1 per pass."""
        if not sections:
            return self._sharded
        fn = self._sharded_chunked.get(sections)
        if fn is None:
            def step(state, batch, rng, _s=sections):
                return self._device_step(state, batch, rng, sections=_s)

            fn = self._sharded_chunked[sections] = jax.jit(
                jax.shard_map(
                    step, mesh=self.mesh,
                    in_specs=(self._state_spec, self._batch_spec, P()),
                    out_specs=(self._state_spec, self._stats_spec),
                    check_vma=False),
                donate_argnums=(0,))
        return fn

    def __call__(self, state: ShardedStepState, batch: GlobalBatch,
                 rng: jax.Array, sections: tuple = ()):
        return self._step_fn_for(sections)(state, batch, rng)

    # ---- forward-only mesh eval (test-phase run) ----
    def _device_eval(self, table_st: TableState, params, auc_st: AucState,
                     batch: GlobalBatch) -> AucState:
        n, b, s = self.n, self.batch_size, self.num_slots
        table = table_st.with_packed(table_st.packed[0])
        auc = AucState(*[l[0] for l in auc_st])
        resp_idx = batch.resp_idx[0]
        serve_rows = batch.serve_rows[0]
        gather_idx = batch.gather_idx[0]
        segments = batch.segments[0]
        dense = batch.dense[0]
        label = batch.label[0]
        show = batch.show[0]
        clk = batch.clk[0]
        a = resp_idx.shape[1]
        d = 3 + table.mf_dim

        serve_vals = pull_values(gather_full_rows(table, serve_rows),
                                 table.mf_dim)
        resp = serve_vals[resp_idx]
        recv = jax.lax.all_to_all(resp, DATA_AXIS, 0, 0, tiled=True)
        vals_flat = recv.reshape(n * a, d)
        values_k = vals_flat[gather_idx]
        pooled = fused_seqpool_cvm(
            values_k, segments, jnp.stack([show, clk], axis=1), b, s,
            self.use_cvm, self.cvm_offset)
        logits = self.model.apply(params, pooled, dense)
        ins_w = (show > 0).astype(jnp.float32)
        pred = jax.nn.sigmoid(logits)
        auc = auc_add_batch(auc, pred, label, ins_w)
        return AucState(*[l[None] for l in auc]), pred[None]

    def eval(self, table_st: TableState, params, auc_st: AucState,
             batch: GlobalBatch):
        """→ (AucState, pred [N, B]) — pred feeds the metric registry."""
        if not hasattr(self, "_eval_jit"):
            shard0 = P(DATA_AXIS)
            rep = P()
            auc_spec = AucState(*([shard0] * len(AucState._fields)))
            batch_spec = GlobalBatch(
                *([shard0] * len(GlobalBatch._fields)))
            self._eval_jit = jax.jit(jax.shard_map(
                self._device_eval, mesh=self.mesh,
                in_specs=(shard0, rep, auc_spec, batch_spec),
                out_specs=(auc_spec, shard0), check_vma=False),
                donate_argnums=(2,))
        return self._eval_jit(table_st, params, auc_st, batch)

    # ---- resident pass: the whole loop inside one shard_map program ----
    def _resident_runner(self, n_steps: int, fmt=None, capacity=0,
                         collect: bool = False, sections: tuple = ()):
        key = ("resident", n_steps, fmt, capacity, collect, sections)
        cached = getattr(self, "_resident_cache", None)
        if cached is None:
            cached = self._resident_cache = {}
        if key not in cached:
            rep = P()
            state_spec = self._state_spec
            fmt_d = dict(fmt) if fmt else None


            def run(state, wire, start, rng):
                def body(i, carry):
                    st, r, preds = carry
                    gb = (GlobalBatch(*[leaf[i] for leaf in wire])
                          if fmt_d is None else
                          _decode_wire_step(wire, fmt_d, i, capacity))
                    # per-step rng matching the streaming trainer exactly:
                    # it folds the PRE-incremented global_step (1-based)
                    st, stats = self._device_step(
                        st, gb, jax.random.fold_in(r, st.step + 1),
                        sections=sections)
                    if collect:
                        # per-batch predictions collected inside the loop
                        # (the single-chip collect_preds pattern,
                        # device_pass.py run_pass) — stays device-sharded
                        preds = jax.lax.dynamic_update_index_in_dim(
                            preds, stats["pred"], i - start, 0)
                    return st, r, preds

                preds0 = (jnp.zeros((n_steps, 1, self.batch_size),
                                    jnp.float32) if collect
                          else jnp.zeros((), jnp.float32))
                state, _, preds = jax.lax.fori_loop(
                    start, start + n_steps, body, (state, rng, preds0))
                return (state, preds) if collect else state

            def make_specs(we):
                if isinstance(we, dict):
                    return {name: tuple(_wire_spec(name, a.ndim)
                                        for a in arrs)
                            for name, arrs in we.items()}
                return jax.tree.map(
                    lambda a: _wire_spec("", a.ndim), we)

            out_specs = ((state_spec, P(None, DATA_AXIS, None))
                         if collect else state_spec)

            def jit_for(wire_example):
                return jax.jit(
                    jax.shard_map(run, mesh=self.mesh,
                                  in_specs=(state_spec,
                                            make_specs(wire_example),
                                            rep, rep),
                                  out_specs=out_specs, check_vma=False),
                    donate_argnums=(0,))

            # resolved lazily at first call (needs the wire pytree)
            cached[key] = _LazyJit(jit_for)
        return cached[key]

    def run_resident(self, state: ShardedStepState, rp, rng: jax.Array,
                     chunk: int = 0, collect_preds: bool = False):
        """Run every staged global batch of a ShardedResidentPass.
        ``collect_preds`` also returns [nb, N, B] per-batch predictions
        (device-sharded on axis 1) for the post-pass registry replay."""
        rp.upload()
        nb = rp.num_batches
        fmt = getattr(rp, "fmt", None)
        fmt_key = tuple(sorted(fmt.items())) if fmt else None
        c = chunk or nb
        i = 0
        chunks = []
        while i < nb:
            n = min(c, nb - i)
            out = self._resident_runner(
                n, fmt_key, getattr(rp, "capacity", 0) or 0,
                collect=collect_preds,
                sections=getattr(rp, "sections", ()))(
                state, rp.dev, jnp.asarray(i, jnp.int32), rng)
            if collect_preds:
                state, preds = out
                chunks.append(preds)
            else:
                state = out
            i += n
        if not collect_preds:
            return state, None
        return state, (chunks[0] if len(chunks) == 1
                       else jnp.concatenate(chunks, axis=0))


def group_batches(batches, n: int):
    """Pack a batch stream into groups of ``n``; the tail group is padded
    by repeating the last batch with show=0 AND clk=0 (so neither loss,
    metrics, nor the pushed counters see the duplicated instances).
    Shared by every mesh trainer (ShardedTrainer, MultiMfShardedTrainer)."""
    import dataclasses as _dc
    group: List[SlotBatch] = []
    for bt in batches:
        group.append(bt)
        if len(group) == n:
            yield group
            group = []
    if group:
        filler = group[-1]
        dead = _dc.replace(filler, show=np.zeros_like(filler.show),
                           clk=np.zeros_like(filler.clk))
        while len(group) < n:
            group.append(dead)
        yield group


class ShardedTrainer:
    """Multi-chip trainer: groups the batch stream into N-device global
    batches, builds routing plans on host (prefetched), runs the sharded
    step. The BoxPSTrainer::Run role with the mesh replacing worker threads."""

    def __init__(self, model, table: ShardedEmbeddingTable, desc, mesh: Mesh,
                 tx: Optional[optax.GradientTransformation] = None,
                 use_cvm: bool = True, prefetch: int = 4, seed: int = 0,
                 zero1: bool = False, float_wire: str = "f32",
                 lr_map: Optional[dict] = None,
                 lr_map_base: float = 1.0) -> None:
        """``float_wire="q8"`` ships resident-pass dense/label/show/clk
        as the int8 affine wire (opt-in: ~1e-2 dense rounding).

        ``lr_map`` — per-param dense learning-rate overrides, name
        (path-substring) → lr, against ``lr_map_base`` (the tx's base
        lr): each matched leaf's UPDATE scales by lr/lr_map_base, so 0.0
        freezes a param (InitializeGPUAndLoadModel's lr_map,
        box_wrapper.cc:1303-1335; consumed boxps_worker.cc:199-204).
        Respected by both the psum mode and the zero1 flat chunks."""
        import threading as _threading

        from paddlebox_tpu.utils.compile_cache import \
            enable_compilation_cache
        enable_compilation_cache()
        from paddlebox_tpu.config import FLAGS
        # chunked exchange-compute schedule (ISSUE 11): slot-group
        # chunks for the pull all_to_all + push/dense-sync interleave.
        # Read once at construction; 1 = the monolithic schedule.
        self.a2a_chunks = max(1, int(FLAGS.a2a_chunks))
        self.float_wire = float_wire
        self.model = model
        self.table = table
        self.desc = desc
        self.mesh = mesh
        self.n = mesh.shape[DATA_AXIS]
        self.tx = tx or optax.adam(1e-3)
        lr_scales = None
        params = None
        if lr_map:
            from paddlebox_tpu.train.dense_modes import build_lr_scales
            from paddlebox_tpu.train.step import TrainStep
            # deterministic param init (same formula as init_params) so
            # the scales can ride the constructor, not a post-hoc poke
            params = TrainStep.init_params_for(
                model, desc.batch_size, len(desc.sparse_slots),
                table.mf_dim, desc.dense_dim, use_cvm=use_cvm)
            lr_scales = build_lr_scales(params, lr_map, lr_map_base)
        self.step_fn = ShardedTrainStep(
            model, self.tx, table.cfg, mesh, desc.batch_size,
            len(desc.sparse_slots), use_cvm=use_cvm, zero1=zero1,
            lr_scales=lr_scales)
        if params is None:
            params = self.step_fn.init_params(table.mf_dim, desc.dense_dim)
        self.state = self.step_fn.init_state(table, params)
        self._rng = jax.random.PRNGKey(seed + 1)
        self.global_step = 0
        self.prefetch = prefetch
        self._threading = _threading
        self._dump_cfg = None
        # metric-variant registry at pod scale (init_metric /
        # get_metric_msg — the AddAucMonitor feed runs per device row)
        from paddlebox_tpu.metrics import MetricRegistry
        self.metrics = MetricRegistry()

    def set_dump(self, cfg) -> None:
        """Enable per-sample prediction dump for subsequent streaming
        passes — the every-worker DumpField role (boxps_worker.cc:1595);
        pass None to disable. Each device row of the global batch dumps
        in device order (the mesh's worker order).

        On a multi-process pod each process dumps only its ADDRESSABLE
        device rows into its own ``.part-<rank>`` shard — the
        reference's per-worker dump channel (every worker writes its own
        file; no global addressing). Concatenating the rank shards in
        device order reproduces the single-controller dump
        line-for-line (tested 2-process in test_multihost_train.py)."""
        self._dump_cfg = cfg

    @staticmethod
    def _addressable_rows(arr, axis: int = 0):
        """Yield (device_row, row_slice) for the rows of a global array
        this process can address, in device order — the per-worker feed
        contract (each worker sees its own rows; single-controller sees
        all of them). Single-controller yields LAZY device slices (the
        metric feed then stays on device — no per-batch D2H in the hot
        loop); a pod yields np views of the local shards."""
        if jax.process_count() == 1:
            for d in range(arr.shape[axis]):
                yield d, (arr[d] if axis == 0
                          else jnp.take(arr, d, axis=axis))
            return
        seen = set()
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[axis].start or 0)
        for sh in shards:
            i0 = sh.index[axis].start or 0
            data = np.asarray(sh.data)
            for j in range(data.shape[axis]):
                d = i0 + j
                if d in seen:
                    continue  # replicated shard
                seen.add(d)
                yield d, np.take(data, j, axis=axis)

    def _group_iter(self, batches):
        return group_batches(batches, self.n)

    def _stage_batch(self, group, idx) -> "GlobalBatch":
        """Stage one global batch for the step: single-controller puts
        host arrays straight on the mesh; a multi-controller pod routes
        through make_array_from_process_local_data (every process built
        the identical host arrays — the SPMD prep contract,
        train/multihost.py)."""
        if jax.process_count() > 1:
            from paddlebox_tpu.train.multihost import stage_global_batch
            return stage_global_batch(
                self.mesh, make_global_arrays(group, idx))
        return make_global_batch(group, idx)

    def _prefetch_iter(self, batches):
        from paddlebox_tpu.utils.prefetch import prefetch_iter

        def prep(group):
            idx = self.table.prepare_global(group,
                                            groups=self.a2a_chunks)
            return (group, self._stage_batch(group, idx),
                    plan_sections(idx))

        return prefetch_iter(self._group_iter(batches), prep,
                             capacity=self.prefetch,
                             name="sharded.prepare")

    def train_pass(self, dataset, log_prefix: str = "") -> Dict[str, float]:
        from paddlebox_tpu.metrics import auc_compute
        from paddlebox_tpu.utils import Timer
        from paddlebox_tpu.utils.logging import get_logger
        log = get_logger(__name__)
        timer = Timer()
        timer.start()
        nb = 0
        stats = None
        # one DumpWriter per ADDRESSABLE device row — the reference's
        # one-dump-channel-per-worker model (boxps_worker.cc:1595: each
        # of the N per-GPU workers writes its own file). Part files are
        # keyed by DEVICE row, so a pod run's per-rank files are
        # byte-identical to the single-controller run's.
        dump_writers: Dict[int, object] = {}

        def writer_for(d: int):
            w = dump_writers.get(d)
            if w is None:
                import copy

                from paddlebox_tpu.utils.dump import DumpWriter
                cfg = copy.copy(self._dump_cfg)
                cfg.rank = cfg.rank + d
                w = dump_writers[d] = DumpWriter(cfg)
            return w

        if self._dump_cfg is not None:
            # eager part-file creation for every addressable device row:
            # a row whose batches are all tail filler must still leave
            # an (empty) shard, so device-order concatenation consumers
            # never hit a file gap
            for d, dev in enumerate(self.mesh.devices.ravel()):
                if dev.process_index == jax.process_index():
                    writer_for(d)

        for group, gb, secs in self._prefetch_iter(dataset.batches()):
            self.global_step += 1
            rng = jax.random.fold_in(self._rng, self.global_step)
            self.state, stats = self.step_fn(self.state, gb, rng, secs)
            nb += 1
            want_dump = (self._dump_cfg is not None
                         and nb % self._dump_cfg.interval == 0)
            if len(self.metrics) or want_dump:
                # ONE pass over this process's ADDRESSABLE device rows
                # (worker order) feeds the metric registry
                # (AddAucMonitor) and the dump — the per-worker model:
                # each process handles its own rows; registry partials
                # merge across the pod inside compute()
                # (metrics_ext._pod_sum_tree)
                for d, pred_d in self._addressable_rows(stats["pred"]):
                    b = group[d]
                    n_real = int((b.show > 0).sum())
                    if n_real == 0:
                        continue  # tail-group filler (dead batch)
                    if len(self.metrics):
                        self.metrics.add_batch(
                            pred_d, b.label,
                            (b.show > 0).astype(np.float32), uid=b.uid,
                            rank=b.rank, cmatch=b.cmatch)
                    if want_dump:
                        writer_for(d).add_batch(
                            b.ins_ids,
                            {"pred": pred_d, "label": b.label,
                             "show": b.show, "clk": b.clk}, n_real)
        for w in dump_writers.values():
            w.close()
        timer.pause()
        self.table.state = self.state.table
        res = auc_compute(self._finalize_auc(self.state.auc))
        out = res.as_dict()
        out.update(
            batches=nb, elapsed_sec=timer.elapsed_sec(),
            examples_per_sec=res.ins_num / max(timer.elapsed_sec(), 1e-9),
            last_loss=(self._host_scalar(stats["loss"])
                       if stats is not None else float("nan")))
        log.info("%ssharded pass done: %d global batches, %.0f ex/s, auc=%.4f",
                 log_prefix, nb, out["examples_per_sec"], res.auc)
        from paddlebox_tpu.obs.hub import emit_pass_event
        emit_pass_event("train_pass_sharded",
                        dict(out, global_step=self.global_step),
                        table=self.table, examples=int(res.ins_num))
        return out

    def _finalize_auc(self, auc) -> "AucState":
        """Per-shard AUC leaves → one host AucState. On a pod the leaves
        are global arrays whose shards live on other processes — eager
        reduction is illegal there, so the sum runs jitted with a
        replicated out-sharding every process can read."""
        if jax.process_count() > 1:
            if getattr(self, "_auc_reduce_jit", None) is None:
                from jax.sharding import NamedSharding, PartitionSpec
                self._auc_reduce_jit = jax.jit(
                    lambda ls: tuple(jnp.sum(l, axis=0) for l in ls),
                    out_shardings=NamedSharding(self.mesh,
                                                PartitionSpec()))
            reduced = self._auc_reduce_jit(tuple(auc))
            return AucState(*[np.asarray(jax.device_get(x))
                              for x in reduced])
        return AucState(*[jnp.sum(l, axis=0) for l in auc])

    @staticmethod
    def _host_scalar(x) -> float:
        """float() of a step stat that may be a non-fully-addressable
        global array on a pod (every process holds the same replicated
        value in its addressable shard)."""
        shards = getattr(x, "addressable_shards", None)
        if shards:
            return float(np.ravel(np.asarray(shards[0].data))[0])
        return float(x)

    def reset_metrics(self) -> None:
        self.state = self.state._replace(auc=init_sharded_auc(self.n))

    # ---- checkpoint hooks (CheckpointManager trainer contract) ----
    def sync_table(self) -> None:
        self.table.state = self.state.table

    def fence_table(self) -> None:
        """Drain the table's async end_pass epilogue (ps/epilogue);
        surfaces the first write-back failure. Checkpoint capture and
        every host-tier read fence implicitly — this is the explicit
        hook for scripts/benches that white-box the host stores."""
        fence = getattr(self.table, "fence", None)
        if fence is not None:
            fence()

    def adopt_table(self) -> None:
        """Point the jit state at the table's (re)built device state —
        called after a tiered table's begin_pass promotes a new pass
        window into the HBM shards."""
        self.state = self.state._replace(table=self.table.state)

    def globalize_dense_state(self) -> None:
        """Stage a locally-initialized step state onto the global mesh
        following the step's own sharding spec (globalize_state, now
        idempotent on already-global leaves — a multihost table's state
        passes through untouched)."""
        from paddlebox_tpu.train.multihost import globalize_state
        self.state = globalize_state(
            self.mesh, self.state._replace(table=self.table.state),
            self.step_fn.state_spec)

    def dense_snapshot(self):
        """Host snapshot of the dense checkpoint state (CheckpointManager
        hook). Pod-safe: params/opt_state are replicated (addressable
        everywhere); the per-shard AUC leaves are NOT, so they ship as
        the shard-REDUCED host AucState — additive state, restored as
        shard 0's content + zeros (identical totals)."""
        return jax.device_get((self.state.params, self.state.opt_state,
                               self._finalize_auc(self.state.auc)))

    def restore_state(self, params, opt_state, auc, step: int) -> None:
        auc = AucState(*[np.asarray(l) for l in auc])
        n_dims = jax.tree.leaves(init_auc_state())[0].ndim
        if auc[0].ndim == n_dims:
            # REDUCED host AucState (dense_snapshot): rebuild the
            # per-shard layout — all mass on shard 0, zeros elsewhere
            # (the finalize sum is invariant)
            auc = AucState(*[
                np.concatenate([l[None],
                                np.zeros((self.n - 1,) + l.shape,
                                         l.dtype)])
                for l in auc])
        self.state = ShardedStepState(
            table=self.table.state, params=params, opt_state=opt_state,
            auc=AucState(*[jnp.asarray(l) for l in auc])
            if jax.process_count() == 1 else auc,
            step=np.asarray(step, np.int32))
        if jax.process_count() > 1:
            # spec-driven staging (no hand-coded layout): the table leaf
            # — local after table.load, or already-global for multihost
            # tables — stages or passes through per globalize_state
            self.globalize_dense_state()
        else:
            self.state = self.state._replace(
                params=jax.device_put(params),
                opt_state=jax.device_put(opt_state),
                step=jnp.asarray(step, jnp.int32))
        self.global_step = step

    def eval_pass(self, dataset, log_prefix: str = "") -> Dict[str, float]:
        """Forward-only mesh pass: pull + model over the device axis,
        no pushes, no dense update; AUC reduced across shards (the
        test-phase run of the reference workers, at pod scale)."""
        from paddlebox_tpu.metrics import auc_compute
        from paddlebox_tpu.utils import Timer
        from paddlebox_tpu.utils.logging import get_logger
        log = get_logger(__name__)
        timer = Timer()
        timer.start()
        auc = init_sharded_auc(self.n)
        nb = 0
        for group, gb in self._prefetch_iter_eval(dataset.batches()):
            auc, preds = self.step_fn.eval(
                self.state.table, self.state.params, auc, gb)
            nb += 1
            if len(self.metrics):
                # test-phase AddAucMonitor feed over this process's
                # addressable rows (per-worker model — see set_dump)
                for d, pred_d in self._addressable_rows(preds):
                    b = group[d]
                    ins_w = (b.show > 0).astype(np.float32)
                    if not ins_w.any():
                        continue  # tail-group filler
                    self.metrics.add_batch(
                        pred_d, b.label, ins_w, uid=b.uid,
                        rank=b.rank, cmatch=b.cmatch)
        timer.pause()
        res = auc_compute(self._finalize_auc(auc))
        out = res.as_dict()
        out.update(batches=nb, elapsed_sec=timer.elapsed_sec(),
                   examples_per_sec=res.ins_num /
                   max(timer.elapsed_sec(), 1e-9))
        log.info("%ssharded eval pass: %d global batches, auc=%.4f",
                 log_prefix, nb, res.auc)
        return out

    def _prefetch_iter_eval(self, batches):
        from paddlebox_tpu.utils.prefetch import prefetch_iter

        def prep(group):
            # read-only routing: lookup instead of assign (unknown keys
            # serve the zero sentinel row, prepare_eval semantics)
            return group, self._stage_batch(
                group, self.table.prepare_global_eval(group))

        return prefetch_iter(self._group_iter(batches), prep,
                             capacity=self.prefetch,
                             name="sharded.prepare_eval")

    # ---- device-resident passes over the mesh ----
    def build_resident_pass(self, dataset) -> "ShardedResidentPass":
        """Build (and on preloader threads, overlap) one pass's staged
        plan. Tiered tables get the build bracketed in ``plan_scope``:
        new keys become value-less PENDING rows the next begin_pass
        reconciles with their staged host values — which makes
        ``PassPreloader(build_fn=trainer.build_resident_pass)`` legal
        over a pass-window table (preload_into_memory,
        box_wrapper.h:1142-1156). Depth-N preloaders may hold SEVERAL
        future passes' plans pending at once — each build gets its own
        plan_scope bracket, pendings promote at their own begin_pass,
        and the window capacity contract grows to the union of the
        open pass's and every queued pass's working set
        (ps/tiered.py module docstring)."""
        scope = getattr(self.table, "plan_scope", None)
        if scope is None:
            rp = ShardedResidentPass.build(dataset, self)
        else:
            with scope():
                rp = ShardedResidentPass.build(dataset, self)
        # SSD promote prefetch (ps/ssd.py): with a disk tier holding
        # rows, promote this pass's spilled working set host-ward NOW —
        # on a preloader worker this overlaps the open pass's training,
        # so the later stage fetch hits RAM and begin_pass never stalls
        # on segment reads (LoadSSD2Mem inside the build stage)
        pf = getattr(self.table, "prefetch_promote", None)
        if (pf is not None and hasattr(dataset, "pass_keys")
                and getattr(self.table, "has_spilled_rows",
                            lambda: False)()):
            from paddlebox_tpu.train.device_pass import poll_preload_abort
            poll_preload_abort()
            pf(dataset.pass_keys())
        return rp

    def tiered_pass_pipeline(self, datasets,
                             depth: "Optional[int]" = None):
        """The tiered pass pipeline (ISSUE 9): a
        ``train/device_pass.PassPipeline`` wired for this trainer's
        pass-window table — builds (plan_scope + prefetch_promote), the
        H2D wire and the host-tier feed-pass fetch all ride the
        depth-N preloader worker, begin_pass is reconcile-only, and
        end_pass's epilogue lane carries async capacity eviction.
        ``depth=0`` = the sequential kick-per-pass control."""
        from paddlebox_tpu.train.device_pass import PassPipeline
        return PassPipeline(iter(datasets),
                            build_fn=self.build_resident_pass,
                            window_table=self.table, trainer=self,
                            depth=depth)

    def train_passes_tiered(self, datasets, depth: "Optional[int]" = None,
                            log_prefix: str = "") -> list:
        """Drive tiered resident passes end to end through the unified
        pipeline: one call per dataset list, returns the per-pass
        result dicts (the tiered twin of
        Trainer.train_passes_resident)."""
        pipe = self.tiered_pass_pipeline(datasets, depth=depth)
        pipe.start_next()
        sequential = depth == 0   # the no-overlap kick-per-pass control
        results = []
        try:
            while True:
                rp = pipe.wait()
                if rp is None:
                    break
                pipe.begin_pass()
                if not sequential:
                    pipe.start_next()
                results.append(self.train_pass_resident(
                    rp, log_prefix=log_prefix))
                pipe.end_pass()
                if sequential:
                    # the next build+stage only AFTER this pass closed
                    pipe.start_next()
        finally:
            pipe.drain()
        return results

    def _feed_registry_resident(self, rp, preds) -> None:
        """Post-pass metric registry replay (the per-batch AddAucMonitor
        hook, boxps_worker.cc:1267,1337) from predictions collected
        inside the mesh fori_loop — the mesh analogue of the single-chip
        Trainer._feed_registry_resident. One D2H fetch per addressable
        device column ([nb, 1, B] each): on a pod every process replays
        only its own workers' rows (side channels are host-global per
        the SPMD prep contract) and the registry partials merge inside
        compute()."""
        sd = rp.side
        for dcol, pred_col in self._addressable_rows(preds, axis=1):
            # pred_col: [nb, B] — this device column across the pass
            for i in range(rp.num_batches):
                ins_w = (sd["show"][i, dcol] > 0).astype(np.float32)
                if not ins_w.any():
                    continue  # tail-group filler (dead batch)
                self.metrics.add_batch(
                    pred_col[i], sd["label"][i, dcol], ins_w,
                    uid=None if sd["uid"] is None else sd["uid"][i, dcol],
                    rank=(None if sd["rank"] is None
                          else sd["rank"][i, dcol]),
                    cmatch=(None if sd["cmatch"] is None
                            else sd["cmatch"][i, dcol]))

    def train_pass_resident(self, pass_or_dataset,
                            log_prefix: str = "") -> Dict[str, float]:
        """Mesh analogue of Trainer.train_pass_resident: the whole pass's
        global batches (routing plans + features) are staged to HBM,
        sharded over the device axis, and the pass runs as ONE
        lax.fori_loop inside the shard_map program — per-step host work
        and H2D hops are zero; embedding all_to_all / dense psum happen
        inside the loop body exactly as in the streaming step."""
        from paddlebox_tpu.metrics import auc_compute
        from paddlebox_tpu.utils import Timer
        from paddlebox_tpu.utils.logging import get_logger
        log = get_logger(__name__)
        timer = Timer()
        timer.start()
        rp = (pass_or_dataset
              if isinstance(pass_or_dataset, ShardedResidentPass)
              else self.build_resident_pass(pass_or_dataset))
        want_metrics = len(self.metrics) > 0
        if want_metrics and rp.side is None:
            log.warning(
                "registry metrics need the pass's side channels — this "
                "prebuilt ShardedResidentPass predates them; rebuild it "
                "with build_resident_pass, or use train_pass")
            want_metrics = False
        rp.upload()
        # consume span: links back to this pass's build span on the
        # preloader lane (obs/trace — the build→consume flow arrow)
        from paddlebox_tpu.obs import trace
        with trace.span("pass.consume",
                        link_from=getattr(rp, "_trace_span_id", 0)):
            self.state, preds = self.step_fn.run_resident(
                self.state, rp, self._rng, collect_preds=want_metrics)
            jax.block_until_ready(self.state.step)
        rp.mark_trained_rows(self.table)
        if want_metrics:
            self._feed_registry_resident(rp, preds)
        self.global_step += rp.num_batches
        timer.pause()
        self.table.state = self.state.table
        res = auc_compute(self._finalize_auc(self.state.auc))
        out = res.as_dict()
        out.update(batches=rp.num_batches, elapsed_sec=timer.elapsed_sec(),
                   examples_per_sec=rp.num_records /
                   max(timer.elapsed_sec(), 1e-9))
        log.info("%ssharded resident pass: %d global batches, %.0f ex/s, "
                 "auc=%.4f", log_prefix, rp.num_batches,
                 out["examples_per_sec"], res.auc)
        from paddlebox_tpu.obs.hub import emit_pass_event
        ev = dict(out, global_step=self.global_step)
        pr = getattr(self, "_last_exchange_probe", None)
        if pr is not None:
            # measured by train/a2a_probe (the sharded bench runs it);
            # rides the pass event → telemetry_report's "a2a ovl" column
            ev["exchange_overlap_frac"] = pr["exchange_overlap_frac"]
        emit_pass_event("train_pass_resident_sharded", ev,
                        table=self.table, examples=rp.num_records)
        return out


class ShardedResidentPass:
    """A pass's global batches stacked on a leading step axis: every
    GlobalBatch field becomes [nb, ...] (device dim sharded over the mesh
    at upload). Routing plans are rebuilt with forced uniform A/A2/K
    buckets when batches landed in different ones (gather_idx encodes
    owner*A + j, so A must match across the staged pass)."""

    def __init__(self, arrays: Dict[str, np.ndarray], num_records: int,
                 mesh: Mesh, capacity: Optional[int] = None,
                 trivial: bool = False,
                 float_wire: str = "f32") -> None:
        self.arrays = arrays
        self.num_records = num_records
        self.mesh = mesh
        self.dev = None
        # chunk-schedule key of the staged pass's (uniform) plans —
        # (a2a_sections, key_sections, slot_sections), or () for the
        # monolithic schedule. Set by build(); rides into
        # run_resident's per-schedule executable.
        self.sections: tuple = ()
        # host side channels for the post-pass registry replay
        # ({label, show, uid, rank, cmatch} as [nb, N, B], None where a
        # batch lacked the channel) — set by build(); kept OUT of the
        # wire (never uploaded)
        self.side: Optional[Dict[str, Optional[np.ndarray]]] = None
        # packed wire (same bit-diet as the single-chip ResidentPass —
        # the tunnel/DCN H2D is the scarce resource): fmt maps each
        # GlobalBatch field to its encoding, wire holds the host arrays
        self.fmt: Optional[Dict[str, str]] = None
        self.wire: Optional[Dict[str, tuple]] = None
        self.capacity = capacity
        if capacity is not None:
            self._encode_wire(capacity, trivial, float_wire)

    @property
    def num_batches(self) -> int:
        return self.arrays["label"].shape[0]

    @classmethod
    def build(cls, dataset, trainer: "ShardedTrainer"
              ) -> "ShardedResidentPass":
        from paddlebox_tpu.ps.table import next_bucket_fine
        from paddlebox_tpu.train.device_pass import poll_preload_abort
        table = trainer.table
        groups = list(trainer._group_iter(dataset.batches()))
        if not groups:
            raise ValueError("empty pass")
        # a background (preloader) build polls the stop flag between
        # groups — routing-plan prep is the mesh build's long stage, and
        # a SIGTERM must not wait out a multi-second plan build; the
        # plan_scope bracket in build_resident_pass rolls the aborted
        # build's pending rows back
        chunks = getattr(trainer, "a2a_chunks", 1)
        plans = []
        for g in groups:
            poll_preload_abort()
            plans.append(table.prepare_global(g, groups=chunks))
        poll_preload_abort()
        sections: tuple = ()
        if chunks > 1 and all(p.a2a_sections for p in plans):
            # chunked pass: uniform per-GROUP section widths across the
            # staged pass (max per section over plans, the grouped
            # analogue of the A/A2 re-bucket below). Plans off the
            # common shape re-route with forced sections — no grouped
            # _repad_plan surgery; re-preparing re-assigns idempotently.
            # The serve target uses max(serve_capacity) — the SAME pow2
            # ladder the grouped builder bucketed with — so plans of a
            # same-shaped workload usually already match and the
            # re-route is the exception, not the rule (the fine ladder
            # the monolithic branch uses would mismatch every plan and
            # re-route the whole pass).
            c = len(plans[0].a2a_sections)
            a2 = max(p.serve_capacity for p in plans)
            req_secs = tuple(max(p.a2a_sections[g] for p in plans)
                             for g in range(c))
            key_secs = tuple(max(p.key_sections[g] for p in plans)
                             for g in range(c))
            uniformed = []
            for g, p in zip(groups, plans):
                if (p.a2a_sections != req_secs
                        or p.key_sections != key_secs
                        or p.serve_capacity != a2):
                    poll_preload_abort()
                    p = table.prepare_global(
                        g, serve_capacity=a2, groups=chunks,
                        req_sections=req_secs, key_sections=key_secs)
                uniformed.append(p)
            plans = uniformed
            sections = plan_sections(plans[0])
        else:
            if chunks > 1:
                # a batch with non-slot-qualified keys fell back — the
                # whole pass runs the monolithic schedule (shapes must
                # be uniform across the staged pass). Fallen-back plans
                # ARE monolithic already; only the grouped survivors of
                # a mixed pass rebuild.
                rebuilt = []
                for g, p in zip(groups, plans):
                    if p.a2a_sections:
                        poll_preload_abort()
                        p = table.prepare_global(g)
                    rebuilt.append(p)
                plans = rebuilt
                poll_preload_abort()
            # ONE uniform shape per pass either way → the FINE bucket
            # ladder (≤~6% padding) replaces the streaming pow2 buckets
            # (≤100%) for the staged wire. Plans re-PAD host-side (pure
            # array surgery — no second routing/assignment pass on the
            # staging thread).
            a = next_bucket_fine(1, max(p.req_need for p in plans))
            a2 = next_bucket_fine(1, max(p.serve_need for p in plans))
            repadded = []
            for g, p in zip(groups, plans):
                rp = cls._repad_plan(p, a, a2, trainer.n, table.capacity)
                if rp is None:  # ambiguous full bucket — re-route group
                    rp = table.prepare_global(g, req_capacity=a,
                                              serve_capacity=a2)
                repadded.append(rp)
            plans = repadded
        gbs = [make_global_arrays(g, p) for g, p in zip(groups, plans)]
        k = max(gb["gather_idx"].shape[1] for gb in gbs)
        # pad values that stay inert: gather_idx pads → the recv sentinel
        # slot (n*A - 1, zero values), segments pads → the discarded
        # pooling bin (bs * num_slots). A chunked pass's forced uniform
        # sections already give every batch identical widths (and its
        # pads are per-SECTION, placed by the grouped plan builder) —
        # the pad loop is a no-op there.
        pad_of = ({} if sections else
                  {"gather_idx": trainer.n * a - 1,
                   "segments": trainer.desc.batch_size *
                   len(trainer.desc.sparse_slots)})
        arrays: Dict[str, np.ndarray] = {}
        for f in GlobalBatch._fields:
            parts = []
            for gb in gbs:
                arr = gb[f]
                if f in pad_of and arr.shape[1] < k:
                    arr = np.pad(arr, ((0, 0), (0, k - arr.shape[1])),
                                 constant_values=pad_of[f])
                parts.append(arr)
            arrays[f] = np.stack(parts)
        n_rec = sum(int((b.show > 0).sum()) for g in groups for b in g)
        # the trivial-segment meta wire assumes the ORIGINAL slot-ordered
        # key stream; a chunked pass re-laid it group-contiguous, so it
        # ships the (encoded) segment stream instead
        trivial = (not sections
                   and all(getattr(b, "segments_trivial", False)
                           for g in groups for b in g))
        if trivial:
            # num_keys/pad_segment per (step, device) — segments then
            # derive on device instead of shipping [nb, N, K] int32
            arrays["meta"] = np.stack([
                np.array([[b.num_keys, b.pad_segment] for b in g],
                         np.int32) for g in groups])
        rp = cls(arrays, n_rec, trainer.mesh,
                 capacity=trainer.table.capacity, trivial=trivial,
                 float_wire=getattr(trainer, "float_wire", "f32"))
        rp.sections = sections

        def stack_opt(field):
            if any(getattr(b, field) is None for g in groups for b in g):
                return None
            return np.stack([np.stack([getattr(b, field) for b in g])
                             for g in groups])

        # side channels only when the registry will replay them —
        # unconditionally pinning show + uid/rank/cmatch stacks would
        # reintroduce the host-memory cost _encode_wire exists to avoid
        # (double-buffered preloader keeps two passes alive)
        if len(getattr(trainer, "metrics", ())) > 0:
            # label/show reference the pre-encode host arrays (no copy);
            # optional channels stack only if every batch carries them
            rp.side = {"label": arrays["label"], "show": arrays["show"],
                       "uid": stack_opt("uid"), "rank": stack_opt("rank"),
                       "cmatch": stack_opt("cmatch")}
        return rp

    @staticmethod
    def _repad_plan(p: ShardedPullIndex, a: int, a2: int, n: int,
                    capacity: int) -> ShardedPullIndex:
        """Change a plan's A/A2 padding WITHOUT re-running the routing:
        the serve lists and slot indices are identical under any padded
        capacity — only pad regions, the resp_idx pad sentinel (A2-1)
        and gather_idx's owner*A+j stride encode the capacity. Safe
        because in the strict-repad case (new < old) every real index is
        strictly below the old pad value, so pads are unambiguous."""
        if p.req_capacity == a and p.serve_capacity == a2:
            return p
        a_old, a2_old = p.req_capacity, p.serve_capacity
        if p.req_need >= a_old:
            # an exactly-full request bucket makes the gather pad
            # sentinel (n*a_old - 1) ambiguous with a real (owner n-1,
            # j = a_old-1) position — signal the caller to re-prepare
            return None
        # serve side: real prefix length per owner from serve_valid
        # (always < a2_old: the builder's a2_max includes the +1 slot)
        u = p.serve_valid.astype(bool).sum(1)                  # [N]
        serve_rows = np.empty((n, a2), np.int32)
        serve_valid = np.zeros((n, a2), np.float32)
        serve_slot = np.zeros((n, a2), np.float32)
        resp_idx = np.full((n, n, a), a2 - 1, np.int32)
        w = min(a, a_old)
        for s in range(n):
            us = int(u[s])
            serve_rows[s, :us] = p.serve_rows[s, :us]
            fill_oob_pads(serve_rows[s], us, capacity)
            serve_valid[s, :us] = 1.0
            serve_slot[s, :us] = p.serve_slot[s, :us]
            # request prefix per (owner, dst): real serve indices are
            # < u < a2_old-1, so counting non-pad entries is exact
            cnt = (p.resp_idx[s] != a2_old - 1).sum(1)         # [N]
            m = np.arange(w)[None, :] < cnt[:, None]
            resp_idx[s][:, :w][m] = p.resp_idx[s][:, :w][m]
        # gather positions re-stride from owner*A_old + j to owner*A + j;
        # the pad sentinel (n*A_old - 1) maps to the new sentinel (no
        # real position can equal it: j < req_need < a_old)
        gi = p.gather_idx
        pad_mask = gi == n * a_old - 1
        owner, j = gi // a_old, gi % a_old
        gather_idx = np.where(pad_mask, n * a - 1,
                              owner * a + j).astype(np.int32)
        return p._replace(resp_idx=resp_idx, serve_rows=serve_rows,
                          serve_valid=serve_valid, serve_slot=serve_slot,
                          gather_idx=gather_idx, req_capacity=a,
                          serve_capacity=a2)

    def _encode_wire(self, capacity: int, trivial: bool,
                     float_wire: str) -> None:
        """Bit-pack the staged pass (ops/bitpack ladders): index arrays
        to 18/24-bit forms, serve_valid derived from the fill_oob_pads
        contract, slot ids to u16, floats to the q8 wire when exact —
        ~3x fewer bytes over the tunnel/DCN per pass."""
        fmt: Dict[str, str] = {}
        wire: Dict[str, tuple] = {}

        def enc_int(name, arr):
            vmax = int(arr.max(initial=0))
            if int(arr.min(initial=0)) >= 0 and vmax < (1 << 18) \
                    and arr.shape[-1] % 4 == 0:
                fmt[name] = "u18"
                wire[name] = pack_u16m(arr, 2)
            elif int(arr.min(initial=0)) >= 0 and vmax < (1 << 24):
                fmt[name] = "u24"
                wire[name] = pack_u24(arr)
            else:
                fmt[name] = "raw"
                wire[name] = (arr,)

        a = self.arrays
        enc_int("resp_idx", a["resp_idx"])
        # serve_rows: per-(step, shard) rows are ASCENDING (np.unique +
        # ascending OOB pads) → the delta wire (~1 B/row) with the pads
        # REGENERATED on device from the real count (srmeta)
        sr = a["serve_rows"]
        nbk, n, a2 = sr.shape
        flat = sr.reshape(-1, a2)
        counts = (flat <= capacity).sum(1).astype(np.int32)
        from paddlebox_tpu.train.device_pass import ResidentPass
        # THE delta-wire gap-exception budgets (shared with the
        # single-chip uniq wire)
        delta = pack_delta_auto(flat, counts, ResidentPass._EXC8,
                                ResidentPass._EXC)
        if delta is not None:
            fmt["serve_rows"] = "delta"
            wire["serve_rows"] = tuple(
                d.reshape((nbk, n) + d.shape[1:]) for d in delta)
            wire["srmeta"] = (np.stack(
                [counts.reshape(nbk, n),
                 flat[:, 0].reshape(nbk, n).astype(np.int32)],
                axis=-1),)
        else:
            enc_int("serve_rows", sr)
        enc_int("gather_idx", a["gather_idx"])
        derived = (a["serve_rows"] <= capacity).astype(np.float32)
        if np.array_equal(derived, a["serve_valid"]):
            fmt["serve_valid"] = "derive"
        else:
            fmt["serve_valid"] = "raw"
            wire["serve_valid"] = (a["serve_valid"],)
        sl = a["serve_slot"]
        if (sl >= 0).all() and (sl == np.rint(sl)).all() \
                and (sl < 256).all():
            fmt["serve_slot"] = "u8"
            wire["serve_slot"] = (sl.astype(np.uint8),)
        elif (sl >= 0).all() and (sl < 65536).all() \
                and (sl == np.rint(sl)).all():
            fmt["serve_slot"] = "u16"
            wire["serve_slot"] = (sl.astype(np.uint16),)
        else:
            fmt["serve_slot"] = "raw"
            wire["serve_slot"] = (sl,)
        if trivial:
            fmt["segments"] = "trivial"
            wire["meta"] = (a["meta"],)
        else:
            enc_int("segments", a["segments"])
        nbk, n, b, dd = a["dense"].shape
        q = None
        if float_wire == "q8":  # opt-in, as on the single-chip wire
            q = quantize_floats(
                a["dense"].reshape(-1, dd),
                a["label"].reshape(-1), a["show"].reshape(-1),
                a["clk"].reshape(-1),
                valid=a["show"].reshape(-1) > 0)
        if q is not None:
            block, qmeta = q
            fmt["dense"] = "q8"
            wire["dense"] = (block[:, :-3].reshape(nbk, n, b, dd),)
            wire["qmeta"] = (qmeta,)
            for j, f in enumerate(("label", "show", "clk")):
                fmt[f] = "u8"
                wire[f] = (block[:, dd + j].reshape(nbk, n, b),)
        else:
            for f in ("dense", "label", "show", "clk"):
                fmt[f] = "raw"
                wire[f] = (a[f],)
        self.fmt = fmt
        self.wire = wire
        # the packed wire supersedes the unpacked host arrays — keep only
        # what post-pass hooks read (mark_trained_rows, num_batches);
        # under the double-buffered preloader the dead copies would
        # double host memory per staged pass
        self.arrays = {"serve_rows": a["serve_rows"],
                       "label": a["label"]}

    def nbytes(self) -> int:
        """Wire bytes of the staged pass (after upload packing)."""
        if self.dev is not None:
            return sum(a.nbytes for a in jax.tree.leaves(self.dev))
        src = self.wire if self.wire is not None else self.arrays
        return sum(a.nbytes for a in jax.tree.leaves(src))

    def mark_trained_rows(self, table: ShardedEmbeddingTable) -> None:
        """Per-shard touched flags for this pass's served rows, set AFTER
        training (same delta-save rationale as ResidentPass)."""
        sr = self.arrays["serve_rows"]  # [nb, N, A2]
        with table.host_lock:
            for s in range(sr.shape[1]):
                rows = np.unique(sr[:, s])
                rows = rows[rows < table.capacity]
                table._touched[s][rows] = True

    def upload(self, materialize: bool = False) -> None:
        """Stage to HBM with the device dim sharded over the mesh axis.
        ``materialize=True`` forces the transfers now (see
        ResidentPass.upload — lazy uploads serialize into the first
        consuming step on tunneled runtimes)."""
        if self.dev is not None:
            pass
        elif self.wire is not None:
            put = {}
            for f, arrs in self.wire.items():
                put[f] = tuple(
                    jax.device_put(
                        jnp.asarray(a),
                        NamedSharding(self.mesh,
                                      _wire_spec(f, a.ndim)))
                    for a in arrs)
            self.dev = put
        else:
            put = {}
            for f in GlobalBatch._fields:
                arr = self.arrays[f]
                spec = P(*([None, DATA_AXIS] + [None] * (arr.ndim - 2)))
                put[f] = jax.device_put(
                    jnp.asarray(arr), NamedSharding(self.mesh, spec))
            self.dev = GlobalBatch(**put)
        if materialize:
            # ONE blocking wait for every in-flight transfer — per-leaf
            # forced fetches cost a ~0.25 s round-trip EACH on tunneled
            # runtimes
            jax.block_until_ready(list(jax.tree.leaves(self.dev)))
