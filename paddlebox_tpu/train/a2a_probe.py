"""Measured exchange/compute attribution of the sharded step (ISSUE 11).

The chunked schedule (train/sharded._device_step, FLAGS.a2a_chunks)
restructures the DATAFLOW so the embedding all_to_all chunks and the
pooling/dense compute are independent — but whether the hardware
actually overlaps them is a scheduler/backend property that must be
MEASURED, not assumed (CPU meshes serialize collectives; TPU's
latency-hiding scheduler flies them). This module runs the decomposed
step as separately-jitted pieces plus the two fused schedules and
reports:

- per-chunk ``a2a.pull.<k>`` exchange seconds vs ``pool.<k>`` pooling
  seconds (the chunk-width tuning signal —
  ``scripts/profile_sharded_step.py --a2a-chunks`` sweeps it),
- ``exchange_overlap_frac``: the fraction of total exchange time the
  chunked schedule hid relative to the monolithic schedule, from an
  apples-to-apples A/B of the two fused programs over the SAME staged
  wire (a grouped plan is a valid input to both schedules),
- ``exchange_wait_sec``: the non-overlapped exchange remainder,
  reported into the pass critical path (obs/trace.note_pass_part
  ``exchange_wait``) so the next pass event's ``critical_path`` block
  attributes it as its own part.

When tracing is active (obs/trace), each measured piece re-runs once
inside a span on the ``device.a2a`` lane — a depth-2 sharded bench
trace (BENCH_TRACE=1) renders per-chunk ``a2a.pull.*``/``a2a.push``
rows — and every chunk books ``pbox_a2a_chunk_seconds_total{chunk}``.

NOTE: the probe's timed steps are REAL training steps (the step donates
its state); callers run it after every headline number is taken, the
same discipline as the bench's wire-free rerun.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from paddlebox_tpu.obs import trace
from paddlebox_tpu.obs.hub import get_hub
from paddlebox_tpu.ops.seqpool_cvm import fused_seqpool_cvm_slot_group
from paddlebox_tpu.parallel.mesh import DATA_AXIS
from paddlebox_tpu.ps.sharded import (chunk_local_positions,
                                      plan_sections, section_offsets)
from paddlebox_tpu.ps.table import (expand_pull, gather_full_rows,
                                    merge_rows, pull_values)


def _timed(fn, *args, reps: int = 2):
    """(result, best-of-reps seconds) with a warm/compile call first."""
    out = fn(*args)
    jax.block_until_ready(jax.tree.leaves(out))
    best = float("inf")
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(jax.tree.leaves(out))
        best = min(best, time.perf_counter() - t0)
    return out, best


def _respan(name: str, fn, *args, **attrs) -> None:
    """One extra run of a measured piece inside a device-lane span, so
    the Chrome trace shows the chunk rows (only when a span sink is
    attached — inert otherwise)."""
    if not trace.tracing_active():
        return
    with trace.span(name, lane=trace.LANE_DEVICE, **attrs):
        jax.block_until_ready(jax.tree.leaves(fn(*args)))


def probe_exchange(trainer, dataset=None, group: Optional[list] = None,
                   chunks: Optional[int] = None, reps: int = 2) -> Dict:
    """Measure the exchange/compute schedule of ``trainer``'s sharded
    step on one global batch (the first group of ``dataset`` unless
    ``group`` — a list of N SlotBatch — is given). ``chunks`` overrides
    ``trainer.a2a_chunks`` so one trainer can sweep widths (the step
    compiles one executable per schedule either way)."""
    sf = trainer.step_fn
    mesh, n = trainer.mesh, trainer.n
    if group is None:
        if dataset is None:
            raise ValueError("probe_exchange needs a dataset or a group")
        group = next(iter(trainer._group_iter(dataset.batches())))
    c = trainer.a2a_chunks if chunks is None else max(1, int(chunks))
    idx = trainer.table.prepare_global(group, groups=c)
    gb = trainer._stage_batch(group, idx)
    sections = plan_sections(idx)
    a_cap, a2_cap = idx.req_capacity, idx.serve_capacity
    k_tot = idx.gather_idx.shape[1]
    s_tot = sf.num_slots
    if sections:
        a_secs, k_secs, s_secs = sections
    else:
        a_secs, k_secs, s_secs = (a_cap,), (k_tot,), (s_tot,)
    a_off = section_offsets(a_secs)
    k_off = section_offsets(k_secs)
    s_off = section_offsets(s_secs)
    d = 3 + trainer.table.mf_dim
    bsz = sf.batch_size
    shard0, rep = P(DATA_AXIS), P()

    def sm(fn, in_specs, out_specs):
        return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                     out_specs=out_specs,
                                     check_vma=False))

    # ---- serve gather (local HBM, no exchange) ----
    def dev_serve(tstate, serve_rows):
        t = tstate.with_packed(tstate.packed[0])
        return pull_values(gather_full_rows(t, serve_rows[0]),
                           t.mf_dim)[None]

    f_serve = sm(dev_serve, (shard0, shard0), shard0)
    serve_vals, t_serve = _timed(f_serve, trainer.state.table,
                                 gb.serve_rows, reps=reps)

    # ---- per-chunk pull exchange ----
    hub = get_hub()
    a2a_ctr = hub.counter("pbox_a2a_chunk_seconds_total",
                          "measured seconds per sharded exchange chunk")
    recvs: List = []
    t_a2a: List[float] = []
    for g, ag in enumerate(a_secs):
        lo = a_off[g]

        def dev_a2a(serve_vals, resp_idx, _lo=lo, _ag=ag):
            resp = expand_pull(
                serve_vals[0],
                resp_idx[0][:, _lo:_lo + _ag].reshape(-1)
            ).reshape(n, _ag, d)
            recv = jax.lax.all_to_all(resp, DATA_AXIS, 0, 0, tiled=True)
            return recv.reshape(n * _ag, d)[None]

        f = sm(dev_a2a, (shard0, shard0), shard0)
        recv_g, t = _timed(f, serve_vals, gb.resp_idx, reps=reps)
        recvs.append(recv_g)
        t_a2a.append(t)
        a2a_ctr.inc(t, chunk=str(g))
        _respan(f"a2a.pull.{g}", f, serve_vals, gb.resp_idx,
                chunk=g, section=int(ag))

    # ---- per-chunk expand + pool ----
    pooled_parts: List = []
    t_pool: List[float] = []
    for g, (ag, kg, sg) in enumerate(zip(a_secs, k_secs, s_secs)):
        lo_a, lo_k, lo_s = a_off[g], k_off[g], s_off[g]

        def dev_pool(recv_g, gather_idx, segments, show, clk,
                     _la=lo_a, _ag=ag, _lk=lo_k, _kg=kg, _ls=lo_s,
                     _sg=sg):
            gi = gather_idx[0][_lk:_lk + _kg]
            seg = segments[0][_lk:_lk + _kg]
            # the step's own remap (ps/sharded.chunk_local_positions) —
            # the probe must slice exactly what the schedule runs
            local = chunk_local_positions(gi, a_cap, _la, _ag)
            vk = expand_pull(recv_g[0], local)
            bsc = jnp.stack([show[0], clk[0]], axis=1)
            return fused_seqpool_cvm_slot_group(
                vk, seg, bsc, bsz, s_tot, _ls, _ls + _sg,
                sf.use_cvm, sf.cvm_offset)[None]

        f = sm(dev_pool, (shard0,) * 5, shard0)
        args = (recvs[g], gb.gather_idx, gb.segments, gb.show, gb.clk)
        pooled_g, t = _timed(f, *args, reps=reps)
        pooled_parts.append(pooled_g)
        t_pool.append(t)
        _respan(f"pool.{g}", f, *args, chunk=g, keys=int(kg))

    pooled = (pooled_parts[0] if len(pooled_parts) == 1
              else jnp.concatenate(pooled_parts, axis=2))

    # ---- dense fwd+bwd on the pooled input ----
    def dev_dense(params, pooled, dense, label, show):
        ins_w = (show[0] > 0).astype(jnp.float32)
        wsum = jax.lax.psum(jnp.sum(ins_w), DATA_AXIS)

        def lf(p, pl):
            logits = sf.model.apply(p, pl, dense[0])
            ls = optax.sigmoid_binary_cross_entropy(logits, label[0])
            return jnp.sum(ls * ins_w) / jnp.maximum(wsum, 1.0)

        loss, (gp, gpl) = jax.value_and_grad(lf, argnums=(0, 1))(
            params, pooled[0])
        return jax.lax.psum(loss, DATA_AXIS), gpl[None]

    f_dense = sm(dev_dense, (rep, shard0, shard0, shard0, shard0),
                 (rep, shard0))
    _, t_dense = _timed(f_dense, trainer.state.params, pooled, gb.dense,
                        gb.label, gb.show, reps=reps)

    # ---- push exchange + owner-side merge (pseudo-grads: the recv
    # values themselves — same shapes/layout, same transfer) ----
    g_vals = jnp.concatenate(
        [r.reshape(r.shape[0], n, ag, d)
         for r, ag in zip(recvs, a_secs)], axis=2)

    def dev_push(g_vals, resp_idx):
        gbk = jax.lax.all_to_all(g_vals[0], DATA_AXIS, 0, 0, tiled=True)
        return merge_rows(gbk.reshape(n * a_cap, d),
                          resp_idx[0].reshape(n * a_cap),
                          num_segments=a2_cap)[None]

    f_push = sm(dev_push, (shard0, shard0), shard0)
    _, t_push = _timed(f_push, g_vals, gb.resp_idx, reps=reps)
    a2a_ctr.inc(t_push, chunk="push")
    _respan("a2a.push", f_push, g_vals, gb.resp_idx)

    # ---- dense sync (the psum the push overlaps with) ----
    f_sync = sm(lambda t: jax.tree.map(
        lambda l: jax.lax.psum(l, DATA_AXIS), t), rep, rep)
    _, t_sync = _timed(f_sync, trainer.state.params, reps=reps)

    # ---- the A/B: both fused schedules over the SAME staged wire ----
    def run_step(secs):
        def once():
            t0 = time.perf_counter()
            st, _ = trainer.step_fn(trainer.state, gb,
                                    jax.random.fold_in(trainer._rng, 0),
                                    secs)
            jax.block_until_ready(st.step)
            trainer.state = st      # donated input — keep state live
            return time.perf_counter() - t0

        once()                      # warm/compile
        return min(once() for _ in range(max(1, reps)))

    t_mono = run_step(())
    t_chunk = run_step(sections) if sections else t_mono

    exchange_total = sum(t_a2a) + t_push
    overlap_sec = max(0.0, t_mono - t_chunk)
    frac = (min(1.0, overlap_sec / exchange_total)
            if exchange_total > 0 else 0.0)
    wait = max(0.0, exchange_total - overlap_sec)
    # ride the NEXT pass event's critical_path as its own part
    trace.note_pass_part("exchange_wait", wait)
    result = {
        "a2a_chunks": len(a_secs),
        "a2a_sections": [int(x) for x in a_secs],
        "serve_sec": round(t_serve, 6),
        "a2a_pull_sec": [round(t, 6) for t in t_a2a],
        "pool_sec": [round(t, 6) for t in t_pool],
        "dense_sec": round(t_dense, 6),
        "push_sec": round(t_push, 6),
        "dense_sync_sec": round(t_sync, 6),
        "step_monolithic_sec": round(t_mono, 6),
        "step_chunked_sec": round(t_chunk, 6),
        "exchange_sec_total": round(exchange_total, 6),
        "exchange_overlap_sec": round(min(overlap_sec, exchange_total),
                                      6),
        "exchange_overlap_frac": round(frac, 4),
        "exchange_wait_sec": round(wait, 6),
    }
    # later pass events report the measured fraction
    # (ShardedTrainer.train_pass_resident → emit_pass_event →
    # telemetry_report's "a2a ovl" column)
    trainer._last_exchange_probe = result
    return result
