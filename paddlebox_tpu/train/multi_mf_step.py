"""Fused train step + trainer for multi_mf (per-slot embedding dims).

One jit step per batch, same shape as train/step.py's TrainStep but with
C dim classes: per class pull → fused_seqpool_cvm over the class's slots,
then the pooled blocks concatenate in CANONICAL slot order (the
pull_gpups_sparse + seqpool + concat contract with per-slot widths,
feature_value.h:42-185 / ps_gpu_wrapper.cc multi-mf build) before the
dense model; the backward push applies per class table. Gather/scatter on
TPU costs per index, so the class split adds no device cost beyond C
small dispatch chains inside one XLA program. Each class's
``fused_seqpool_cvm`` (forward and push-feeding backward) rides the
``FLAGS.use_pallas_seqpool`` seam onto the fused Pallas MXU kernel
(docs/PERFORMANCE.md §Device kernels)."""

from __future__ import annotations

import math
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.metrics import auc_compute, init_auc_state
from paddlebox_tpu.ops import fused_seqpool_cvm
from paddlebox_tpu.ps.multi_mf import MultiMfEmbeddingTable
from paddlebox_tpu.ps.table import (apply_push, expand_pull,
                                    gather_full_rows, pull_values)
from paddlebox_tpu.train.step import StepState, make_device_batch
from paddlebox_tpu.metrics import auc_add_batch
from paddlebox_tpu.utils.logging import get_logger
from paddlebox_tpu.utils.timer import Timer

log = get_logger(__name__)


class MultiMfTrainStep:
    """Jitted multi-class CTR step over a MultiMfEmbeddingTable."""

    def __init__(self, model, tx: optax.GradientTransformation,
                 table: MultiMfEmbeddingTable, batch_size: int,
                 use_cvm: bool = True, cvm_offset: int = 2,
                 rng_seed: int = 0) -> None:
        self.model = model
        self.tx = tx
        self.table = table
        self.batch_size = batch_size
        self.use_cvm = use_cvm
        self.cvm_offset = cvm_offset
        self.rng = jax.random.PRNGKey(rng_seed)
        self.class_slots = [len(s) for s in table.class_slots]
        self.dims = table.dims
        # canonical reassembly order: (class, rank) per global slot
        self.slot_route = table.slot_route()
        self._jit = jax.jit(self._step, donate_argnums=(0,))

    def init_params(self, dense_dim: int) -> Any:
        width = self.table.pooled_width(self.cvm_offset, self.use_cvm)
        flat = jnp.zeros((self.batch_size, width))
        dense = jnp.zeros((self.batch_size, dense_dim))
        return self.model.init(jax.random.PRNGKey(0), flat, dense)

    def init_state(self, params: Any) -> StepState:
        return StepState(
            table=tuple(t.state for t in self.table.tables),
            params=params, opt_state=self.tx.init(params),
            auc=init_auc_state(), step=jnp.zeros((), jnp.int32))

    # ---- traced ----
    def _pooled(self, vals_list, devs, batch_show_clk):
        parts = []
        for c, dev in enumerate(devs):
            values_k = expand_pull(vals_list[c], dev.gather_idx)
            parts.append(fused_seqpool_cvm(
                values_k, dev.segments, batch_show_clk,
                self.batch_size, self.class_slots[c],
                self.use_cvm, self.cvm_offset))
        # canonical slot order with per-slot widths
        flat = [parts[c][:, r, :] for c, r in self.slot_route]
        return jnp.concatenate(flat, axis=1)

    def _step(self, state: StepState, devs, rng
              ) -> Tuple[StepState, Dict[str, jax.Array]]:
        d0 = devs[0]
        batch_show_clk = jnp.stack([d0.show, d0.clk], axis=1)
        ins_w = (d0.show > 0).astype(jnp.float32)
        rows_fulls = [gather_full_rows(t, dev.unique_rows)
                      for t, dev in zip(state.table, devs)]
        vals_list = [pull_values(rf, t.mf_dim)
                     for rf, t in zip(rows_fulls, state.table)]

        def loss_fn(params, vals_list):
            x = self._pooled(vals_list, devs, batch_show_clk)
            logits = self.model.apply(params, x, d0.dense)
            ls = optax.sigmoid_binary_cross_entropy(logits, d0.label)
            loss = jnp.sum(ls * ins_w) / jnp.maximum(jnp.sum(ins_w), 1.0)
            return loss, logits

        (loss, logits), (g_params, g_vals) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True)(state.params, vals_list)

        new_tables = []
        for c, (t, dev, rf, g) in enumerate(
                zip(state.table, devs, rows_fulls, g_vals)):
            g = jnp.concatenate(
                [g[:, :2], g[:, 2:] * (-1.0 * self.batch_size)], axis=1)
            new_tables.append(apply_push(
                t, dev.unique_rows, g, self.table.tables[c].cfg,
                jax.random.fold_in(rng, c), rows_full=rf))

        updates, opt_state = self.tx.update(g_params, state.opt_state,
                                            state.params)
        params = optax.apply_updates(state.params, updates)
        pred = jax.nn.sigmoid(logits)
        auc = auc_add_batch(state.auc, pred, d0.label, ins_w)
        return StepState(table=tuple(new_tables), params=params,
                         opt_state=opt_state, auc=auc,
                         step=state.step + 1), \
            {"loss": loss, "pred": pred}

    def __call__(self, state, devs, rng):
        return self._jit(state, devs, rng)

    # ---- resident pass runner (whole pass as one fori_loop) ----
    def run_resident(self, state, rp: "MultiMfResidentPass", rng):
        cache = getattr(self, "_resident_cache", None)
        if cache is None:
            cache = self._resident_cache = {}
        nb = rp.num_batches
        if nb not in cache:
            cache[nb] = _mmf_resident_runner(self, nb)
        class_wires, floats = rp.dev
        return cache[nb](state, class_wires, floats,
                         jnp.zeros((), jnp.int32), rng)


class MultiMfTrainer:
    """Streaming trainer over a MultiMfEmbeddingTable (the BoxPSTrainer
    role for mixed-dim tables). Same pass contract as train.Trainer."""

    def __init__(self, model, table: MultiMfEmbeddingTable, desc,
                 tx=None, use_cvm: bool = True, seed: int = 0,
                 prefetch: int = 4) -> None:
        self.table = table
        self.desc = desc
        self.tx = tx or optax.adam(1e-3)
        self.step_fn = MultiMfTrainStep(model, self.tx, table,
                                        desc.batch_size, use_cvm=use_cvm,
                                        rng_seed=seed)
        self.state = self.step_fn.init_state(
            self.step_fn.init_params(desc.dense_dim))
        self._rng = jax.random.PRNGKey(seed + 1)
        self.global_step = 0
        self.prefetch = prefetch

    def train_pass(self, dataset, log_prefix: str = "") -> Dict[str, float]:
        from paddlebox_tpu.utils.prefetch import prefetch_iter

        def do_prep(b):
            cbs = self.table.prepare(b)
            devs = []
            for cb in cbs:
                devs.append(make_device_batch(
                    cb.batch, cb.index,
                    floats=devs[0].floats if devs else None))
            return b, tuple(devs)

        timer = Timer()
        timer.start()
        nb = 0
        n_ex = 0
        stats = None
        for batch, devs in prefetch_iter(dataset.batches(), do_prep,
                                         capacity=self.prefetch):
            n_ex += int((batch.show > 0).sum())
            self.global_step += 1
            rng = jax.random.fold_in(self._rng, self.global_step)
            self.state, stats = self.step_fn(self.state, devs, rng)
            nb += 1
            if FLAGS.check_nan_inf:
                loss = float(stats["loss"])
                if math.isnan(loss) or math.isinf(loss):
                    raise RuntimeError(
                        f"nan/inf loss at step {self.global_step}")
        timer.pause()
        self.sync_table()
        res = auc_compute(self.state.auc)
        out = res.as_dict()
        out.update(batches=nb, elapsed_sec=timer.elapsed_sec(),
                   examples_per_sec=n_ex / max(timer.elapsed_sec(), 1e-9))
        log.info("%smulti-mf pass done: %d batches, %.0f ex/s, auc=%.4f",
                 log_prefix, nb, out["examples_per_sec"], res.auc)
        return out

    def reset_metrics(self) -> None:
        self.state = self.state._replace(auc=init_auc_state())

    def sync_table(self) -> None:
        for t, st in zip(self.table.tables, self.state.table):
            t.state = st

    # ---- device-resident pass (BeginPass staging, multi-mf flavor) ----
    def build_resident_pass(self, dataset) -> "MultiMfResidentPass":
        return MultiMfResidentPass.build(dataset, self.table)

    def train_pass_resident(self, pass_or_dataset,
                            log_prefix: str = "") -> Dict[str, float]:
        """The whole pass staged to HBM and run as ONE lax.fori_loop —
        per-step host work and H2D hops are zero (the multi-mf analogue
        of Trainer.train_pass_resident)."""
        rp = (pass_or_dataset
              if isinstance(pass_or_dataset, MultiMfResidentPass)
              else self.build_resident_pass(pass_or_dataset))
        timer = Timer()
        timer.start()
        rp.upload()
        self.state = self.step_fn.run_resident(self.state, rp, self._rng)
        jax.block_until_ready(self.state.step)
        rp.mark_trained_rows(self.table)
        self.global_step += rp.num_batches
        timer.pause()
        self.sync_table()
        res = auc_compute(self.state.auc)
        out = res.as_dict()
        out.update(batches=rp.num_batches, elapsed_sec=timer.elapsed_sec(),
                   examples_per_sec=rp.num_records /
                   max(timer.elapsed_sec(), 1e-9))
        log.info("%smulti-mf resident pass: %d batches, %.0f ex/s, "
                 "auc=%.4f", log_prefix, rp.num_batches,
                 out["examples_per_sec"], res.auc)
        return out


class MultiMfResidentPass:
    """One pass's per-class DeviceBatch streams stacked on a leading step
    axis: per class ``ints_u [nb, U_c+2]`` and ``ints_k [nb, r, K_c]``,
    plus ONE shared float block ``[nb, B, Dd+3]`` (class sub-batches
    share their floats, as in the streaming path)."""

    def __init__(self, class_ints, floats: np.ndarray,
                 num_records: int) -> None:
        self.class_ints = class_ints      # [(iu, ik)] per class, host
        self.floats = floats
        self.num_records = num_records
        self.dev = None

    @property
    def num_batches(self) -> int:
        return self.floats.shape[0]

    @classmethod
    def build(cls, dataset, table: MultiMfEmbeddingTable
              ) -> "MultiMfResidentPass":
        from paddlebox_tpu.ps.table import fill_oob_pads
        from paddlebox_tpu.train.step import pack_floats
        per_class: List[List] = [[] for _ in range(table.num_classes)]
        floats = []
        n_rec = 0
        for b in dataset.batches():
            n_rec += int((b.show > 0).sum())
            floats.append(pack_floats(b.dense, b.label, b.show, b.clk))
            for c, cb in enumerate(table.prepare(b)):
                per_class[c].append(cb)
        if not floats:
            raise ValueError("empty pass")
        nb = len(floats)
        class_ints = []
        for c, cbs in enumerate(per_class):
            cap = table.tables[c].capacity
            u_max = max(cb.index.unique_rows.shape[0] for cb in cbs)
            k_max = max(cb.index.gather_idx.shape[0] for cb in cbs)
            trivial = all(cb.batch.segments_trivial for cb in cbs)
            iu = np.empty((nb, u_max + 2), np.int32)
            ik = np.empty((nb, 1 if trivial else 2, k_max), np.int32)
            for i, cb in enumerate(cbs):
                idx, sb = cb.index, cb.batch
                u = idx.num_unique
                iu[i, :idx.unique_rows.shape[0]] = idx.unique_rows
                fill_oob_pads(iu[i, :u_max], u, cap)
                iu[i, u_max] = sb.num_keys
                iu[i, u_max + 1] = sb.pad_segment
                ik[i, 0, :idx.gather_idx.shape[0]] = idx.gather_idx
                ik[i, 0, idx.gather_idx.shape[0]:] = u
                if not trivial:
                    k = min(sb.segments.shape[0], k_max)
                    ik[i, 1, :k] = sb.segments[:k]
                    ik[i, 1, k:] = sb.pad_segment
            class_ints.append((iu, ik))
        return cls(class_ints, np.stack(floats), n_rec)

    def upload(self) -> None:
        if self.dev is not None:
            return
        import jax.numpy as _jnp
        self.dev = (
            tuple((jax.device_put(_jnp.asarray(iu)),
                   jax.device_put(_jnp.asarray(ik)))
                  for iu, ik in self.class_ints),
            jax.device_put(_jnp.asarray(self.floats)))

    def mark_trained_rows(self, table: MultiMfEmbeddingTable) -> None:
        """Re-mark this pass's rows touched AFTER training: a delta save
        landing between build (prepare marks at build time) and training
        clears the flags and would otherwise drop the pass's updates from
        the next delta (the ResidentPass.mark_trained_rows rationale)."""
        for c, (iu, _ik) in enumerate(self.class_ints):
            t = table.tables[c]
            rows = np.unique(iu[:, :-2])  # last 2 cols = meta
            rows = rows[(rows >= 0) & (rows < t.capacity)]
            with t.host_lock:
                t._touched[rows] = True


def _mmf_resident_runner(step: MultiMfTrainStep, n_steps: int):
    from paddlebox_tpu.train.step import DeviceBatch

    def run(state, class_wires, floats, start, rng):
        def body(i, carry):
            st, r = carry
            devs = tuple(
                DeviceBatch(ints_u=iu[i], ints_k=ik[i], floats=floats[i])
                for iu, ik in class_wires)
            st, _ = step._step(st, devs,
                               jax.random.fold_in(r, st.step + 1))
            return st, r

        state, _ = jax.lax.fori_loop(start, start + n_steps, body,
                                     (state, rng))
        return state

    return jax.jit(run, donate_argnums=(0,))
