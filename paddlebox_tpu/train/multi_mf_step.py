"""Fused train step + trainer for multi_mf (per-slot embedding dims).

One jit step per batch, same shape as train/step.py's TrainStep but with
C dim classes: per class pull → fused_seqpool_cvm over the class's slots,
then the pooled blocks concatenate in CANONICAL slot order (the
pull_gpups_sparse + seqpool + concat contract with per-slot widths,
feature_value.h:42-185 / ps_gpu_wrapper.cc multi-mf build) before the
dense model; the backward push applies per class table. Gather/scatter on
TPU costs per index, so the class split adds no device cost beyond C
small dispatch chains inside one XLA program."""

from __future__ import annotations

import math
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.metrics import auc_compute, init_auc_state
from paddlebox_tpu.ops import fused_seqpool_cvm
from paddlebox_tpu.ps.multi_mf import MultiMfEmbeddingTable
from paddlebox_tpu.ps.table import (apply_push, expand_pull,
                                    gather_full_rows, pull_values)
from paddlebox_tpu.train.step import StepState, make_device_batch
from paddlebox_tpu.metrics import auc_add_batch
from paddlebox_tpu.utils.logging import get_logger
from paddlebox_tpu.utils.timer import Timer

log = get_logger(__name__)


class MultiMfTrainStep:
    """Jitted multi-class CTR step over a MultiMfEmbeddingTable."""

    def __init__(self, model, tx: optax.GradientTransformation,
                 table: MultiMfEmbeddingTable, batch_size: int,
                 use_cvm: bool = True, cvm_offset: int = 2,
                 rng_seed: int = 0) -> None:
        self.model = model
        self.tx = tx
        self.table = table
        self.batch_size = batch_size
        self.use_cvm = use_cvm
        self.cvm_offset = cvm_offset
        self.rng = jax.random.PRNGKey(rng_seed)
        self.class_slots = [len(s) for s in table.class_slots]
        self.dims = table.dims
        # canonical reassembly order: (class, rank) per global slot
        self.slot_route = [(int(table.class_of_slot[s]),
                            int(table.slot_rank[s]))
                           for s in range(table.num_slots)]
        self._jit = jax.jit(self._step, donate_argnums=(0,))

    def init_params(self, dense_dim: int) -> Any:
        width = self.table.pooled_width(self.cvm_offset, self.use_cvm)
        flat = jnp.zeros((self.batch_size, width))
        dense = jnp.zeros((self.batch_size, dense_dim))
        return self.model.init(jax.random.PRNGKey(0), flat, dense)

    def init_state(self, params: Any) -> StepState:
        return StepState(
            table=tuple(t.state for t in self.table.tables),
            params=params, opt_state=self.tx.init(params),
            auc=init_auc_state(), step=jnp.zeros((), jnp.int32))

    # ---- traced ----
    def _pooled(self, vals_list, devs, batch_show_clk):
        parts = []
        for c, dev in enumerate(devs):
            values_k = expand_pull(vals_list[c], dev.gather_idx)
            parts.append(fused_seqpool_cvm(
                values_k, dev.segments, batch_show_clk,
                self.batch_size, self.class_slots[c],
                self.use_cvm, self.cvm_offset))
        # canonical slot order with per-slot widths
        flat = [parts[c][:, r, :] for c, r in self.slot_route]
        return jnp.concatenate(flat, axis=1)

    def _step(self, state: StepState, devs, rng
              ) -> Tuple[StepState, Dict[str, jax.Array]]:
        d0 = devs[0]
        batch_show_clk = jnp.stack([d0.show, d0.clk], axis=1)
        ins_w = (d0.show > 0).astype(jnp.float32)
        rows_fulls = [gather_full_rows(t, dev.unique_rows)
                      for t, dev in zip(state.table, devs)]
        vals_list = [pull_values(rf, t.mf_dim)
                     for rf, t in zip(rows_fulls, state.table)]

        def loss_fn(params, vals_list):
            x = self._pooled(vals_list, devs, batch_show_clk)
            logits = self.model.apply(params, x, d0.dense)
            ls = optax.sigmoid_binary_cross_entropy(logits, d0.label)
            loss = jnp.sum(ls * ins_w) / jnp.maximum(jnp.sum(ins_w), 1.0)
            return loss, logits

        (loss, logits), (g_params, g_vals) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True)(state.params, vals_list)

        new_tables = []
        for c, (t, dev, rf, g) in enumerate(
                zip(state.table, devs, rows_fulls, g_vals)):
            g = jnp.concatenate(
                [g[:, :2], g[:, 2:] * (-1.0 * self.batch_size)], axis=1)
            new_tables.append(apply_push(
                t, dev.unique_rows, g, self.table.tables[c].cfg,
                jax.random.fold_in(rng, c), rows_full=rf))

        updates, opt_state = self.tx.update(g_params, state.opt_state,
                                            state.params)
        params = optax.apply_updates(state.params, updates)
        pred = jax.nn.sigmoid(logits)
        auc = auc_add_batch(state.auc, pred, d0.label, ins_w)
        return StepState(table=tuple(new_tables), params=params,
                         opt_state=opt_state, auc=auc,
                         step=state.step + 1), \
            {"loss": loss, "pred": pred}

    def __call__(self, state, devs, rng):
        return self._jit(state, devs, rng)


class MultiMfTrainer:
    """Streaming trainer over a MultiMfEmbeddingTable (the BoxPSTrainer
    role for mixed-dim tables). Same pass contract as train.Trainer."""

    def __init__(self, model, table: MultiMfEmbeddingTable, desc,
                 tx=None, use_cvm: bool = True, seed: int = 0,
                 prefetch: int = 4) -> None:
        self.table = table
        self.desc = desc
        self.tx = tx or optax.adam(1e-3)
        self.step_fn = MultiMfTrainStep(model, self.tx, table,
                                        desc.batch_size, use_cvm=use_cvm,
                                        rng_seed=seed)
        self.state = self.step_fn.init_state(
            self.step_fn.init_params(desc.dense_dim))
        self._rng = jax.random.PRNGKey(seed + 1)
        self.global_step = 0
        self.prefetch = prefetch

    def train_pass(self, dataset, log_prefix: str = "") -> Dict[str, float]:
        from paddlebox_tpu.utils.prefetch import prefetch_iter

        def do_prep(b):
            cbs = self.table.prepare(b)
            devs = []
            for cb in cbs:
                devs.append(make_device_batch(
                    cb.batch, cb.index,
                    floats=devs[0].floats if devs else None))
            return b, tuple(devs)

        timer = Timer()
        timer.start()
        nb = 0
        n_ex = 0
        stats = None
        for batch, devs in prefetch_iter(dataset.batches(), do_prep,
                                         capacity=self.prefetch):
            n_ex += int((batch.show > 0).sum())
            self.global_step += 1
            rng = jax.random.fold_in(self._rng, self.global_step)
            self.state, stats = self.step_fn(self.state, devs, rng)
            nb += 1
            if FLAGS.check_nan_inf:
                loss = float(stats["loss"])
                if math.isnan(loss) or math.isinf(loss):
                    raise RuntimeError(
                        f"nan/inf loss at step {self.global_step}")
        timer.pause()
        self.sync_table()
        res = auc_compute(self.state.auc)
        out = res.as_dict()
        out.update(batches=nb, elapsed_sec=timer.elapsed_sec(),
                   examples_per_sec=n_ex / max(timer.elapsed_sec(), 1e-9))
        log.info("%smulti-mf pass done: %d batches, %.0f ex/s, auc=%.4f",
                 log_prefix, nb, out["examples_per_sec"], res.auc)
        return out

    def reset_metrics(self) -> None:
        self.state = self.state._replace(auc=init_auc_state())

    def sync_table(self) -> None:
        for t, st in zip(self.table.tables, self.state.table):
            t.state = st
