"""Device-resident pass mode — the pass's batches live in HBM.

Reference architecture: BoxPS stages the PASS into device memory up front
(``BeginPass`` buffers the pass's embeddings into HBM, box_wrapper.cc:171;
``PreLoadIntoMemory``/``WaitFeedPassDone`` double-buffer pass k+1's data
against pass k's training, box_wrapper.h:1142-1156). The per-batch work in
the CUDA path is then only key-copy + PS lookup.

TPU-native redesign: the same pass-window contract, but the staged object
is the pass's BATCH DATA — per-key row ids + dense features for every
batch, uploaded in three bulk transfers — because on TPU the per-batch
host→device hop is the scarce resource (PCIe/tunnel latency), not HBM.
The train loop then runs as a ``lax.fori_loop`` ON DEVICE: batch slicing,
key dedup (ops/device_unique.py), pull, fwd/bwd, push, dense update and
AUC all inside one XLA program, zero host round-trips per step. The host's
only per-pass jobs are row assignment (native hash index) and the bulk
upload — both overlappable with the previous pass via ``PassPreloader``.

Falls back gracefully: anything this mode can't express (per-step dump
hooks, dynamic NaN aborts mid-pass) still runs via Trainer.train_pass.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.data.dataset import Dataset
from paddlebox_tpu.ops.device_unique import dedup_rows
from paddlebox_tpu.train.step import pack_floats, unpack_floats
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class ResidentPass:
    """One pass's batches, packed host-side then staged to HBM.

    Arrays (nb = #batches, K = uniform per-batch key capacity):
      rows:   int32 [nb, K]      per-key table row; padding → sentinel row
      floats: f32   [nb, B, D+3] [dense | label | show | clk]
      meta:   int32 [nb, 2]      [num_keys, pad_segment]
      segs:   int32 [nb, K] | None   None when every batch has the trivial
              one-key-per-slot layout (segments derived on device)
    """

    def __init__(self, rows: np.ndarray, floats: np.ndarray,
                 meta: np.ndarray, segs: Optional[np.ndarray],
                 num_records: int) -> None:
        self.rows = rows
        self.floats = floats
        self.meta = meta
        self.segs = segs
        self.num_records = num_records
        self.dev: Optional[Tuple[jax.Array, ...]] = None

    @property
    def num_batches(self) -> int:
        return self.rows.shape[0]

    @property
    def key_capacity(self) -> int:
        return self.rows.shape[1]

    @classmethod
    def build(cls, dataset: Dataset, table,
              floats_dtype=np.float32) -> "ResidentPass":
        """Pack a dataset's batches; assigns table rows for every key
        (the FeedPass key registration step, done by the native index).

        ``floats_dtype=jnp.bfloat16`` halves the float block on the wire
        (dense features, label/show/clk — the latter are small integers,
        exact in bf16); the step casts back to f32 on device."""
        col = getattr(dataset, "columnar", None)
        if col is not None:
            return cls._build_columnar(dataset, col, table, floats_dtype)
        rows_l, floats_l, meta_l, segs_l = [], [], [], []
        trivial = True
        nrec = 0
        cap = table.capacity
        for b in dataset.batches():
            nk = b.num_keys
            rk = np.full(b.key_capacity, cap, np.int32)
            with table.host_lock:  # vs shrink/save on the main thread
                r = table.index.assign(b.keys[:nk])
            # NOTE: _touched is deliberately NOT set here — a preloaded
            # pass hasn't trained yet, and a checkpoint save landing
            # between build and training would clear the flags and lose
            # the pass's updates from the next delta. The trainer marks
            # the pass's rows touched AFTER the pass runs
            # (mark_trained_rows).
            rk[:nk] = r
            rows_l.append(rk)
            floats_l.append(pack_floats(b.dense, b.label, b.show, b.clk,
                                        dtype=floats_dtype))
            meta_l.append((nk, b.pad_segment))
            segs_l.append(b.segments.astype(np.int32, copy=False))
            trivial = trivial and getattr(b, "segments_trivial", False)
            nrec += int((b.show > 0).sum())
        if not rows_l:
            raise ValueError("empty pass")
        k_max = max(r.shape[0] for r in rows_l)
        nb = len(rows_l)
        rows = np.full((nb, k_max), cap, np.int32)
        for i, r in enumerate(rows_l):
            rows[i, :r.shape[0]] = r
        if trivial:
            segs = None  # derived on device — skip the [nb, k_max] copy
        else:
            segs = np.empty((nb, k_max), np.int32)
            for i, (s, (nk, pad)) in enumerate(zip(segs_l, meta_l)):
                segs[i, :s.shape[0]] = s
                segs[i, s.shape[0]:] = pad
        return cls(rows, np.stack(floats_l), np.asarray(meta_l, np.int32),
                   segs, nrec)

    @classmethod
    def _build_columnar(cls, dataset: Dataset, col, table,
                        floats_dtype) -> "ResidentPass":
        """Vectorized whole-pass packer for columnar datasets: ONE native
        index.assign over the pass's key stream + bulk reshapes, instead
        of 32+ per-batch SlotBatch constructions (the per-batch python
        path was the pipeline bottleneck — build must stay under the
        device pass time for the preload to fully overlap)."""
        desc = dataset.desc
        bs = desc.batch_size
        s = len(desc.sparse_slots)
        r = col.num_records
        if r == 0:
            raise ValueError("empty pass")
        nb = (r + bs - 1) // bs
        cap = table.capacity
        offsets = col.offsets
        with table.host_lock:  # one pass-wide key→row assignment
            rows_all = table.index.assign(col.keys)
        rows_all = rows_all.astype(np.int32, copy=False)
        # per-batch key spans + uniform padded capacity (one jit variant)
        bounds = offsets[np.minimum(np.arange(nb + 1) * bs, r)]
        nk = np.diff(bounds)
        k_max = desc.key_capacity(int(nk.max()))
        rows = np.full((nb, k_max), cap, np.int32)
        counts = np.diff(offsets)
        # trivial layout = exactly one key per slot per record, slot-order:
        # segments are then derivable on device (DeviceBatch.segments)
        trivial = (col.key_slot.size == r * s and bool((counts == s).all())
                   and bool((col.key_slot.reshape(r, s)
                             == np.arange(s, dtype=np.int32)).all()))
        pad_seg = bs * s
        segs = None
        if not trivial:
            rec_of_key = np.repeat(np.arange(r, dtype=np.int64), counts)
            segs_global = ((rec_of_key % bs) * s
                           + col.key_slot).astype(np.int32)
            segs = np.full((nb, k_max), pad_seg, np.int32)
        for i in range(nb):
            a, b = bounds[i], bounds[i + 1]
            rows[i, :b - a] = rows_all[a:b]
            if segs is not None:
                segs[i, :b - a] = segs_global[a:b]
        # float block: pack the whole pass, zero-pad the tail batch
        floats_full = pack_floats(col.dense, col.label, col.show, col.clk)
        d3 = floats_full.shape[1]
        if nb * bs != r:
            padded = np.zeros((nb * bs, d3), np.float32)
            padded[:r] = floats_full
            floats_full = padded
        floats = floats_full.reshape(nb, bs, d3).astype(
            floats_dtype, copy=False)
        meta = np.stack(
            [nk.astype(np.int32),
             np.full(nb, pad_seg, np.int32)], axis=1)
        return cls(rows, floats, meta, segs, int((col.show > 0).sum()))

    def upload(self) -> None:
        """Stage to HBM — three (four with segs) bulk transfers."""
        if self.dev is not None:
            return
        segs = (jnp.zeros((1, 1), jnp.int32) if self.segs is None
                else jnp.asarray(self.segs))
        self.dev = (jnp.asarray(self.rows), jnp.asarray(self.floats),
                    jnp.asarray(self.meta), segs)

    def nbytes(self) -> int:
        n = self.rows.nbytes + self.floats.nbytes + self.meta.nbytes
        return n + (self.segs.nbytes if self.segs is not None else 0)

    def mark_trained_rows(self, table) -> None:
        """Flag this pass's rows as touched-since-last-save — called by
        the trainer AFTER the pass runs, so delta saves include them
        regardless of when a checkpoint landed relative to the preload.
        Duplicate-tolerant boolean scatter (no sort): every row id in the
        pack is ≤ capacity by construction (padding is the sentinel row),
        and the sentinel flag is harmless — save paths only read rows the
        index owns."""
        rows = self.rows.ravel()
        with table.host_lock:
            table._touched[rows] = True


class _BatchView:
    """Duck-typed DeviceBatch built inside the trace from pass slices."""

    def __init__(self, unique_rows, gather_idx, key_valid, segments,
                 dense, label, show, clk) -> None:
        self.unique_rows = unique_rows
        self.gather_idx = gather_idx
        self.key_valid = key_valid
        self.segments = segments
        self.dense = dense
        self.label = label
        self.show = show
        self.clk = clk


class ResidentPassRunner:
    """jits `chunk` steps of a resident pass as ONE device program
    (lax.fori_loop over the staged batches)."""

    def __init__(self, step, capacity: int, trivial_segments: bool,
                 chunk: int = 0) -> None:
        self.step = step            # TrainStep
        self.capacity = capacity
        self.trivial = trivial_segments
        self.chunk = chunk
        self._jit: Dict[int, object] = {}  # n_steps → compiled runner

    def _make_view(self, rows, floats, meta, segs) -> _BatchView:
        k = rows.shape[0]
        unique_rows, gather_idx = dedup_rows(rows, self.capacity)
        num_keys, pad_seg = meta[0], meta[1]
        pos = jnp.arange(k, dtype=jnp.int32)
        key_valid = (pos < num_keys).astype(jnp.float32)
        if self.trivial:
            segments = jnp.where(pos < num_keys, pos, pad_seg)
        else:
            segments = segs
        dense, label, show, clk = unpack_floats(floats)
        return _BatchView(
            unique_rows, gather_idx, key_valid, segments,
            dense=dense, label=label, show=show, clk=clk)

    def _run(self, n_steps: int):
        if n_steps not in self._jit:
            def run(state, rows_p, floats_p, meta_p, segs_p, start, rng):
                def body(i, carry):
                    state, rng = carry
                    view = self._make_view(
                        rows_p[i], floats_p[i], meta_p[i],
                        segs_p[i % segs_p.shape[0]])
                    # 1-based like Trainer.train_pass's fold of the
                    # pre-incremented global_step
                    rng_i = jax.random.fold_in(rng, state.step + 1)
                    state, _ = self.step._step(state, view, rng_i)
                    return state, rng

                state, _ = jax.lax.fori_loop(
                    start, start + n_steps, body, (state, rng))
                return state

            self._jit[n_steps] = jax.jit(run, donate_argnums=(0,))
        return self._jit[n_steps]

    def run_pass(self, state, rp: ResidentPass, rng: jax.Array,
                 chunk: Optional[int] = None):
        """Run every batch of the staged pass; returns the new state."""
        rp.upload()
        nb = rp.num_batches
        c = chunk if chunk is not None else (self.chunk or nb)
        i = 0
        while i < nb:
            n = min(c, nb - i)
            state = self._run(n)(state, *rp.dev,
                                 jnp.asarray(i, jnp.int32), rng)
            i += n
        return state


class PassPreloader:
    """Double-buffered pass pipeline — preload_into_memory /
    wait_feed_pass_done (box_wrapper.h:1142-1156) for resident passes:
    builds + uploads pass k+1 in a background thread while pass k trains."""

    def __init__(self, datasets: Iterator[Dataset], table=None,
                 floats_dtype=np.float32, build_fn=None) -> None:
        """``build_fn(dataset) -> pass`` overrides the default single-chip
        ResidentPass builder — e.g.
        ``build_fn=sharded_trainer.build_resident_pass`` double-buffers
        mesh passes the same way."""
        if table is None and build_fn is None:
            raise ValueError("need a table or a build_fn")
        self._it = iter(datasets)
        self._table = table
        self._floats_dtype = floats_dtype
        self._build_fn = build_fn
        self._next = None
        self._thread: Optional[threading.Thread] = None
        self._err: Optional[BaseException] = None

    def _load(self, ds: Dataset) -> None:
        try:
            if self._build_fn is not None:
                rp = self._build_fn(ds)
            else:
                rp = ResidentPass.build(ds, self._table,
                                        floats_dtype=self._floats_dtype)
            rp.upload()
            self._next = rp
        except BaseException as e:  # surfaces on next()
            self._err = e

    def start_next(self) -> bool:
        """Kick off background build+upload of the next dataset."""
        ds = next(self._it, None)
        if ds is None:
            return False
        self._next = None
        self._thread = threading.Thread(target=self._load, args=(ds,),
                                        daemon=True)
        self._thread.start()
        return True

    def wait(self) -> Optional[ResidentPass]:
        """Block until the preloaded pass is staged (WaitFeedPassDone)."""
        if self._thread is None:
            return None
        self._thread.join()
        self._thread = None
        if self._err is not None:
            err, self._err = self._err, None
            raise err
        return self._next
