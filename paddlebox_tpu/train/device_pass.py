"""Device-resident pass mode — the pass's batches live in HBM.

Reference architecture: BoxPS stages the PASS into device memory up front
(``BeginPass`` buffers the pass's embeddings into HBM, box_wrapper.cc:171;
``PreLoadIntoMemory``/``WaitFeedPassDone`` double-buffer pass k+1's data
against pass k's training, box_wrapper.h:1142-1156). The per-batch work in
the CUDA path is then only key-copy + PS lookup.

TPU-native redesign: the same pass-window contract, but the staged object
is the pass's BATCH DATA — per-key row ids + dense features for every
batch, uploaded in three bulk transfers — because on TPU the per-batch
host→device hop is the scarce resource (PCIe/tunnel latency), not HBM.
The train loop then runs as a ``lax.fori_loop`` ON DEVICE: batch slicing,
key dedup (ops/device_unique.py), pull, fwd/bwd, push, dense update and
AUC all inside one XLA program, zero host round-trips per step. The host's
only per-pass jobs are row assignment (native hash index) and the bulk
upload — both overlappable with the previous pass via ``PassPreloader``.

Falls back gracefully: anything this mode can't express (per-step dump
hooks, dynamic NaN aborts mid-pass) still runs via Trainer.train_pass.
"""

from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.data.dataset import Dataset
from paddlebox_tpu.ops.bitpack import (pack_delta, pack_delta_auto,
                                       pack_u12, pack_u16m, pack_u18,
                                       pack_u24, unpack_delta16,
                                       unpack_u12, unpack_u16m,
                                       unpack_u18, unpack_u24)
from paddlebox_tpu.ops.device_unique import dedup_rows
from paddlebox_tpu.train.step import (dequantize_floats, pack_floats,
                                      quantize_floats, unpack_floats)
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class PreloadBuildAborted(RuntimeError):
    """A background pass build observed the graceful-stop flag between
    stages and aborted (resilience/preemption): a 2 s build must not eat
    the SIGTERM grace window. Raised only on NON-main threads (an inline
    main-thread build keeps the run_pass stop protocol in charge); the
    preloader treats it as a clean end-of-stream, never an error."""


_PRELOAD_TLS = threading.local()  # .abort: callable set on worker threads


def poll_preload_abort() -> None:
    """Stop poll for background pass builds — called between build
    stages (front/dedup/pack) and periodically inside long loops.
    Honors both the process-wide graceful-stop flag and the owning
    preloader's stop() (via a worker thread-local). A no-op on the
    main thread and when no stop is pending."""
    abort = getattr(_PRELOAD_TLS, "abort", None)
    if abort is not None and abort():
        raise PreloadBuildAborted("pass build aborted (preloader stop)")
    if threading.current_thread() is threading.main_thread():
        return
    from paddlebox_tpu.resilience import preemption
    if preemption.stop_pending():
        raise PreloadBuildAborted(
            f"pass build aborted ({preemption.stop_reason()})")


class ResidentPass:
    """One pass's batches, packed host-side then staged to HBM.

    The pack ships HOST-DEDUPED pull indexes (the DedupKeysAndFillIdx
    step, done once per batch by the native hash index at build time):
    an on-device sort+searchsorted dedup was measured at ~50ms of a 68ms
    step on v5p — ~75% of the whole pass — while the host dedup rides the
    build thread that overlaps the previous pass's training.

    Arrays (nb = #batches, K = uniform per-batch key capacity, U =
    uniform per-batch unique capacity):
      uniq:   int32 [nb, U]      per-batch unique table rows (ascending
              real rows first; padding = DISTINCT out-of-bounds ids, the
              fill_oob_pads contract — gathers clamp to the zero
              sentinel row, scatters drop)
      gidx:   int32 [nb, K]      per-key position in uniq; key padding →
              the first pad position (num_unique)
      floats: f32   [nb, B, D+3] [dense | label | show | clk]
      meta:   int32 [nb, 4]      [num_keys, pad_segment, num_unique,
              first_unique_row (the delta-wire base)]
      segs:   int32 [nb, K] | None   None when every batch has the trivial
              one-key-per-slot layout (segments derived on device)
    """

    def __init__(self, uniq: np.ndarray, gidx: np.ndarray,
                 floats: np.ndarray,
                 meta: np.ndarray, segs: Optional[np.ndarray],
                 num_records: int,
                 qmeta: Optional[np.ndarray] = None,
                 side: Optional[Dict] = None) -> None:
        self.uniq = uniq
        self.gidx = gidx
        self.floats = floats
        self.meta = meta
        self.segs = segs
        self.num_records = num_records
        self.qmeta = qmeta  # f32 [2, D] when floats is the q8 wire
        self.dev: Optional[Tuple[jax.Array, ...]] = None
        # "dedup": uniq/gidx are the host-deduped pull index (default).
        # "compact": built against a slot-arena table — uniq holds the
        # per-key GLOBAL rows, gidx the slot-LOCAL rows; the wire ships
        # the locals + the arena chunk map and the device rebuilds global
        # rows and dedups in-trace (ops/device_unique.py).
        self.wire = "dedup"
        self.chunk_bits: Optional[int] = None
        # columnar side channels for the post-pass metric feed (or None)
        self.side = side
        # per-stage build seconds (front/dedup/index_host/index_dev/
        # pack/h2d), set by
        # build_streamed — the preloader mirrors them into
        # pbox_preload_build_seconds_total{stage=...}
        self.build_stats: Optional[Dict[str, float]] = None

    @property
    def num_batches(self) -> int:
        return self.gidx.shape[0]

    @property
    def key_capacity(self) -> int:
        return self.gidx.shape[1]

    @property
    def unique_capacity(self) -> int:
        return self.uniq.shape[1]

    @classmethod
    def build(cls, dataset: Dataset, table,
              floats_dtype=np.float32) -> "ResidentPass":
        """Pack a dataset's batches; assigns table rows for every key and
        dedups per batch (the FeedPass key registration +
        DedupKeysAndFillIdx steps, both done by the native index).

        ``floats_dtype=jnp.bfloat16`` halves the float block on the wire
        (dense features, label/show/clk — the latter are small integers,
        exact in bf16); the step casts back to f32 on device.

        NOTE: table._touched is deliberately NOT set here — a preloaded
        pass hasn't trained yet, and a checkpoint save landing between
        build and training would clear the flags and lose the pass's
        updates from the next delta. The trainer marks the pass's rows
        touched AFTER the pass runs (mark_trained_rows)."""
        per_batch, floats, qmeta, trivial, nrec, side = cls._front(
            dataset, floats_dtype)
        dedup, u_pad, k_max = cls._dedup_phase(per_batch, table)
        host = cls._pack_chunk(per_batch, dedup, u_pad, k_max, trivial,
                               table.capacity)
        return cls(host[0], host[1], floats, host[2], host[3], nrec,
                   qmeta=qmeta, side=side)

    @classmethod
    def build_streamed(cls, dataset: Dataset, table,
                       floats_dtype=np.float32,
                       threads: int = 4,
                       block: bool = True) -> "ResidentPass":
        """Build with the upload IN FLIGHT. ``jax.device_put`` is async
        on this runtime (measured: the H2D transfer streams while the
        host packs; per-array forced fetches cost a ~0.25 s round-trip
        each). The float block is put before dedup/pack begin, so its
        transfer rides under the host build; the index blocks upload
        CHUNKED (FLAGS.preload_pack_chunk_batches): the wire format is
        chosen once from the dedup results (exactly the choice
        _encode_uniq/_encode_gidx would make on the whole pass), then
        each chunk of batches packs on the thread pool, encodes, and
        starts its H2D transfer while later chunks are still packing —
        pass wall ≈ host build with the tail chunk's transfer exposed,
        instead of build + full index transfer. The device stitches the
        chunks with one concatenate per wire leaf. The only blocking
        wait is one ``block_until_ready`` at the end. Wire bytes match
        upload() exactly; the returned pass is already staged (dev
        set).

        On a background (preloader) thread the build polls the
        graceful-stop flag between stages; an abort waits out the
        already-issued transfers (no orphan H2D competing with the
        emergency checkpoint) before raising PreloadBuildAborted.

        Per-stage seconds land in ``rp.build_stats``
        (front/dedup/index_host/index_dev/pack/h2d —
        docs/PERFORMANCE.md telemetry)."""
        stats: Dict[str, float] = {}
        t0 = time.perf_counter()
        per_batch, floats, qmeta, trivial, nrec, side = cls._front(
            dataset, floats_dtype)
        stats["front"] = time.perf_counter() - t0
        floats_t = jax.device_put(floats)
        qm = jax.device_put(np.zeros((2, 0), np.float32)
                            if qmeta is None else qmeta)
        issued: List = [floats_t, qm]
        try:
            rp = cls._build_streamed_tail(
                per_batch, floats, qmeta, trivial, nrec, side, table,
                floats_t, qm, threads, block, stats, issued)
        except PreloadBuildAborted:
            # drain the transfers this build already issued: an orphan
            # H2D in flight would contend with the emergency
            # checkpoint's D2H during the grace window
            jax.block_until_ready(list(jax.tree.leaves(issued)))
            raise
        rp.build_stats = stats
        return rp

    @classmethod
    def _build_streamed_tail(cls, per_batch, floats, qmeta, trivial,
                             nrec, side, table, floats_t, qm,
                             threads: int, block: bool,
                             stats: Dict[str, float],
                             issued: List) -> "ResidentPass":
        if getattr(table.index, "arena_enabled", False):
            rp = cls._compact_tail(per_batch, floats, qmeta, trivial,
                                   nrec, table, floats_t, qm,
                                   block=block, side=side, stats=stats)
            if rp is not None:
                return rp
            log.warning("compact wire unavailable for this pass "
                        "(foreign rows or width overflow); using dedup "
                        "wire")
        poll_preload_abort()
        t0 = time.perf_counter()
        dedup, u_pad, k_max = cls._dedup_phase(per_batch, table, threads,
                                               stats=stats)
        t_dedup = time.perf_counter() - t0
        # the index stage (key→row assignment inside the dedup phase,
        # host kv or device probe table) reports separately so the
        # stall breakdown names the actual bottleneck; keep the stages
        # a partition of the build wall
        stats["dedup"] = max(0.0, t_dedup - stats.get("index_host", 0.0)
                             - stats.get("index_dev", 0.0))
        poll_preload_abort()
        # wire formats decided ONCE from the dedup results — the exact
        # choice _encode_uniq/_encode_gidx make on the whole pass, so
        # per-chunk encodes are mutually consistent and byte-identical
        # to upload()
        ufmt = cls._choose_uniq_fmt(dedup, u_pad, table.capacity)
        gfmt = cls._choose_gidx_fmt(per_batch, dedup, k_max)
        nb = len(per_batch)
        step = FLAGS.preload_pack_chunk_batches
        step = nb if step <= 0 else min(step, nb)
        t_pack = t_h2d = 0.0
        uniq_parts: List[tuple] = []
        gidx_parts: List[tuple] = []
        host_parts: List[tuple] = []
        with ThreadPoolExecutor(max_workers=threads) as pool:
            futs = [pool.submit(cls._pack_chunk, per_batch[a:a + step],
                                dedup[a:a + step], u_pad, k_max,
                                trivial, table.capacity)
                    for a in range(0, nb, step)]
            for f in futs:
                t0 = time.perf_counter()
                uniq_c, gidx_c, meta_c, segs_c = f.result()
                t_pack += time.perf_counter() - t0
                poll_preload_abort()
                # host encode is pack work; only the device_put
                # dispatch books as h2d (the stage split exists so a
                # starved pipeline names its slow stage correctly)
                t0 = time.perf_counter()
                ue = cls._encode_uniq_fmt(ufmt, uniq_c, meta_c)
                ge = cls._encode_gidx_fmt(gfmt, gidx_c)
                t_pack += time.perf_counter() - t0
                t0 = time.perf_counter()
                up = tuple(jax.device_put(a) for a in ue)
                gp = tuple(jax.device_put(a) for a in ge)
                issued.extend(up)
                issued.extend(gp)
                uniq_parts.append(up)
                gidx_parts.append(gp)
                host_parts.append((uniq_c, gidx_c, meta_c, segs_c))
                t_h2d += time.perf_counter() - t0
        t0 = time.perf_counter()
        if len(host_parts) == 1:
            uniq, gidx, meta, segs = host_parts[0]
            uniq_t, gidx_t = uniq_parts[0], gidx_parts[0]
            t_pack += time.perf_counter() - t0
        else:
            uniq = np.concatenate([p[0] for p in host_parts])
            gidx = np.concatenate([p[1] for p in host_parts])
            meta = np.concatenate([p[2] for p in host_parts])
            segs = (None if trivial else
                    np.concatenate([p[3] for p in host_parts]))
            t_pack += time.perf_counter() - t0
            # stitch the staged chunks device-side: one concatenate per
            # wire leaf, dispatched against the in-flight transfers
            # (device work → the h2d stage, like the puts it chases)
            t0 = time.perf_counter()
            uniq_t = tuple(jnp.concatenate([p[j] for p in uniq_parts],
                                           axis=0)
                           for j in range(len(uniq_parts[0])))
            gidx_t = tuple(jnp.concatenate([p[j] for p in gidx_parts],
                                           axis=0)
                           for j in range(len(gidx_parts[0])))
            t_h2d += time.perf_counter() - t0
        t0 = time.perf_counter()
        segs_enc = (None if segs is None else
                    cls._encode_segs_or_fallback(segs, meta, floats))
        t_pack += time.perf_counter() - t0
        t0 = time.perf_counter()
        segs_t = ((jax.device_put(np.zeros((1, 1), np.int32)),)
                  if segs_enc is None else
                  tuple(jax.device_put(a) for a in segs_enc))
        rp = cls(uniq, gidx, floats, meta, segs, nrec, qmeta=qmeta,
                 side=side)
        rp.dev = (uniq_t, gidx_t, floats_t, jax.device_put(meta),
                  segs_t, qm)
        issued.extend(jax.tree.leaves(rp.dev))
        if block:
            jax.block_until_ready(list(jax.tree.leaves(rp.dev)))
        # block=False: transfers are ISSUED (device_put is eager/async)
        # and the consuming execution will wait on them — the caller's
        # thread is free to start the NEXT pass's host build while this
        # pass's bytes are still on the wire (PassPreloader does this,
        # overlapping host build k+2 with transfer k+1 and training k)
        stats["h2d"] = t_h2d + (time.perf_counter() - t0)
        stats["pack"] = t_pack
        return rp

    @classmethod
    def _encode_segs_or_fallback(cls, segs, meta, floats):
        enc = cls._encode_segs_slotwire(segs, meta, floats.shape[1])
        return enc if enc is not None else cls._encode_gidx(segs)

    @classmethod
    def _choose_uniq_fmt(cls, dedup, u_pad: int, cap: int) -> str:
        """The whole-pass uniq wire decision, computed from the dedup
        results BEFORE packing (so chunks can encode+upload as they
        complete): exactly _encode_uniq's preference order — u8 deltas,
        u16 deltas, 16+8-bit halves, raw int32. Exception counts equal
        pack_delta's (per-row gaps over the real ascending prefix), and
        the u24 bound covers the fill_oob_pads tail (max pad id =
        cap + (u_pad - u))."""
        exc8 = exc16 = 0
        vmax = 0
        for uniq_s, _ in dedup:
            u = len(uniq_s)
            d = np.diff(uniq_s.astype(np.int64, copy=False))
            exc8 = max(exc8, int((d >= (1 << 8)).sum()))
            exc16 = max(exc16, int((d >= (1 << 16)).sum()))
            if u:
                vmax = max(vmax, int(uniq_s[-1]))
            if u < u_pad:
                vmax = max(vmax, cap + (u_pad - u))
        if exc8 <= cls._EXC8:
            return "d8"
        if exc16 <= cls._EXC:
            return "d16"
        return "u24" if vmax < (1 << 24) else "raw"

    @staticmethod
    def _choose_gidx_fmt(per_batch, dedup, k_max: int) -> str:
        """_encode_gidx's decision from dedup stats: per-batch max gidx
        is u (the pad value) when the batch has key pads, else u - 1
        (ranks are dense in [0, u))."""
        gmax = 0
        for (keys, *_), (uniq_s, _) in zip(per_batch, dedup):
            u = len(uniq_s)
            gmax = max(gmax, u if len(keys) < k_max else u - 1)
        return ("u18" if gmax < (1 << 18) and k_max % 4 == 0
                else "raw")

    @classmethod
    def _encode_uniq_fmt(cls, fmt: str, uniq: np.ndarray,
                         meta: np.ndarray):
        """Encode a chunk in the pre-chosen whole-pass format (the
        chunked twin of _encode_uniq — same bytes, decided once)."""
        if fmt == "d8":
            out = pack_delta(uniq, meta[:, 2], cls._EXC8, bits=8)
        elif fmt == "d16":
            out = pack_delta(uniq, meta[:, 2], cls._EXC, bits=16)
        elif fmt == "u24":
            return pack_u24(uniq)
        else:
            return (uniq,)
        assert out is not None, "pre-chosen delta wire must fit"
        return out

    @staticmethod
    def _encode_gidx_fmt(fmt: str, gidx: np.ndarray):
        return pack_u18(gidx) if fmt == "u18" else (gidx,)

    @classmethod
    def _compact_tail(cls, per_batch, floats, qmeta, trivial: bool,
                      nrec: int, table, floats_t, qm,
                      block: bool = True,
                      side: Optional[Dict] = None,
                      stats: Optional[Dict[str, float]] = None
                      ) -> Optional["ResidentPass"]:
        """COMPACT wire for slot-arena tables: ship per-key slot-LOCAL
        rows (≈17 bits at CTR scale — at/near the wire's entropy floor)
        plus the tiny arena chunk map; the device rebuilds global rows
        ((chunk_map[slot, local>>CB] << CB) | low bits) and dedups
        in-trace (ops/device_unique.dedup_rows). Eliminates the whole
        per-batch uniq stream and the host sort/rank work; the trade is
        ~30-50 ms/step of device sort, the right side of the trade
        whenever the wire, not the chip, is the bottleneck. Returns None
        (caller falls back to the dedup wire) when any key's row lives
        outside its slot's arena or the local width overflows 24 bits."""
        nb = len(per_batch)
        k_max = max(kc for _, _, kc, _, _ in per_batch)
        cap = table.capacity
        n_arena = int(table.arena_slots)
        if any(int(sk.max(initial=0)) >= n_arena
               for _, sk, _, _, _ in per_batch):
            return None  # slots beyond the arena → dedup wire
        locs = np.zeros((nb, k_max), np.int32)
        rows_g = np.full((nb, k_max), cap + 1, np.int32)
        meta = np.zeros((nb, 4), np.int32)
        segs = None if trivial else np.empty((nb, k_max), np.int32)
        t0 = time.perf_counter()
        bulk = FLAGS.bulk_pass_assign
        if bulk:
            # whole-pass bulk assign: ONE host_lock round-trip for the
            # pass instead of nb (assign_slotted walks keys in order,
            # so allocation is identical to the per-batch loop)
            keys_all = np.concatenate([k for k, *_ in per_batch])
            slots_all = np.concatenate([s for _, s, *_ in per_batch])
            with table.host_lock:
                r_all, l_all = table.index.assign_slotted(
                    keys_all, slots_all.astype(np.uint16, copy=False))
                table.slot_host[r_all] = slots_all
            if (l_all < 0).any():
                return None
            bounds = np.cumsum([0] + [len(k) for k, *_ in per_batch])
        for i, (keys, slot_of_key, _, pad_seg, seg_arr) in \
                enumerate(per_batch):
            nk = len(keys)
            if bulk:
                a = bounds[i]
                r, l = r_all[a:a + nk], l_all[a:a + nk]
            else:
                su = slot_of_key.astype(np.uint16, copy=False)
                with table.host_lock:
                    r, l = table.index.assign_slotted(keys, su)
                    table.slot_host[r] = slot_of_key
                if (l < 0).any():
                    return None
            locs[i, :nk] = l
            rows_g[i, :nk] = r
            meta[i] = (nk, pad_seg, 0, 0)
            if segs is not None:
                segs[i, :nk] = seg_arr
                segs[i, nk:] = pad_seg
        if stats is not None:  # key-assignment stage (the dedup twin)
            stats["dedup"] = time.perf_counter() - t0
        t0 = time.perf_counter()
        bits = max(int(locs.max()).bit_length(), 1)
        if bits > 24:
            return None
        with table.host_lock:
            cs_map, cr_map = table.index.arena_export()
        n_slots = int(table.arena_slots)
        valid = cs_map < n_slots  # default (slotless) arena excluded
        stride = int(cr_map[valid].max()) + 1 if valid.any() else 1
        # bucket the stride (power-of-two ladder) so the chunk map's
        # shape — and therefore the compiled runner — stays stable as
        # slots grow new chunks across passes
        from paddlebox_tpu.ps.table import next_bucket
        stride = min(next_bucket(8, stride),
                     (cap >> int(table.arena_chunk_bits)) + 1)
        cmap = np.zeros((n_slots, stride), np.int32)
        cmap[cs_map[valid], cr_map[valid]] = \
            np.nonzero(valid)[0].astype(np.int32)
        loc_t = tuple(jax.device_put(a)
                      for a in cls._encode_locals(locs, bits))
        if segs is None:
            segs_t = (jax.device_put(np.zeros((1, 1), np.int32)),)
        else:
            enc = cls._encode_segs_slotwire(segs, meta, floats.shape[1])
            segs_t = (tuple(jax.device_put(a) for a in enc)
                      if enc is not None else
                      tuple(jax.device_put(a)
                            for a in cls._encode_gidx(segs)))
        rp = cls(rows_g, locs, floats, meta, segs, nrec, qmeta=qmeta,
                 side=side)
        rp.wire = "compact"
        rp.chunk_bits = int(table.arena_chunk_bits)
        rp.dev = (loc_t, (jax.device_put(cmap),), floats_t,
                  jax.device_put(meta), segs_t, qm)
        if stats is not None:  # encode + transfer dispatch
            stats["pack"] = time.perf_counter() - t0
        if block:
            jax.block_until_ready(list(jax.tree.leaves(rp.dev)))
        return rp

    @staticmethod
    def _encode_locals(locs: np.ndarray, bits: int):
        """Wire for slot-local rows, narrowest first: u12 byte-pairs
        (1.5 B/key — thousand-slot vocabularies are a few thousand
        entries, the shape whose wire is ~all locals), plain u16,
        16-bit lows + m-bit packed highs (ops/bitpack.pack_u16m), raw
        int32."""
        k = locs.shape[-1]
        if bits <= 12 and k % 2 == 0:
            return pack_u12(locs)
        if bits <= 16:
            return (locs.astype(np.uint16),)
        for m in (1, 2, 4, 8):
            if bits <= 16 + m and k % (8 // m) == 0:
                return pack_u16m(locs, m)
        return (locs,)

    @classmethod
    def _front(cls, dataset: Dataset, floats_dtype):
        """Shared front-end: slice the pass into per-batch key views and
        pack the float block. Returns (per_batch, floats, qmeta, trivial,
        nrec); per_batch entries are (keys, slot_of_key, key_capacity,
        pad_segment, segments-or-None)."""
        col = getattr(dataset, "columnar", None)
        if col is not None:
            return cls._front_columnar(dataset, col, floats_dtype)
        if (floats_dtype == "q8" and FLAGS.q8_streaming_front
                and getattr(dataset, "supports_reiteration", False)):
            # two-phase streaming front: per-column range stats
            # accumulate batch by batch, then a second walk casts each
            # batch straight to the u8 wire — the host never holds a
            # full-pass f32 float block just for the range stats
            # (FLAGS.q8_streaming_front=False restores the staged
            # whole-pass quantization and its winsorized range)
            return cls._front_q8_streaming(dataset)
        per_batch = []
        floats_l = []
        trivial = True
        nrec = 0
        # q8 without a re-iterable dataset stages the whole pass f32
        # for the range stats; other wires cast per batch so the host
        # never holds a full f32 copy
        batch_dtype = np.float32 if floats_dtype == "q8" else floats_dtype
        for b in dataset.batches():
            poll_preload_abort()
            nk = b.num_keys
            slot_of_key = (b.segments[:nk] % b.num_slots).astype(np.int16)
            per_batch.append((b.keys[:nk], slot_of_key, b.key_capacity,
                              b.pad_segment,
                              b.segments[:nk].astype(np.int32, copy=False)))
            floats_l.append(pack_floats(b.dense, b.label, b.show, b.clk,
                                        dtype=batch_dtype))
            nrec += int((b.show > 0).sum())
            trivial = trivial and getattr(b, "segments_trivial", False)
        if not per_batch:
            raise ValueError("empty pass")
        floats = np.stack(floats_l)
        qmeta = None
        if floats_dtype == "q8":
            floats, qmeta = cls._encode_floats(floats, floats_dtype)
        return per_batch, floats, qmeta, trivial, nrec, None

    @classmethod
    def _front_q8_streaming(cls, dataset: Dataset):
        """q8 front without the whole-pass f32 staging: phase 1 walks
        the batches collecting the key views + per-column min/max over
        REAL rows (show > 0, the quantize_floats ``valid`` contract) +
        the exact-u8 label/show/clk checks; phase 2 re-walks the same
        (deterministic, in-memory) batch stream and casts each batch
        straight into the u8 block with the pass-level qmeta. Peak host
        float memory is one batch f32 + the u8 block instead of the
        full pass in f32.

        Divergence from the staged path, by design: the winsorized
        range (quantize_floats' [0.1, 99.9]-percentile clip for
        outlier-dominated columns) needs the full value distribution,
        which streaming min/max cannot see — heavy-tailed columns keep
        the raw min/max range here. When the data doesn't fit the u8
        wire at all, phase 2 falls back to the bf16 cast, exactly like
        _encode_floats."""
        per_batch = []
        trivial = True
        nrec = 0
        lo = hi = None
        n_valid = 0
        first_row = None
        fits = True
        for b in dataset.batches():
            poll_preload_abort()
            nk = b.num_keys
            slot_of_key = (b.segments[:nk] % b.num_slots).astype(np.int16)
            per_batch.append((b.keys[:nk], slot_of_key, b.key_capacity,
                              b.pad_segment,
                              b.segments[:nk].astype(np.int32,
                                                     copy=False)))
            nrec += int((b.show > 0).sum())
            trivial = trivial and getattr(b, "segments_trivial", False)
            d = b.dense.astype(np.float32, copy=False)
            if fits:
                lsc = np.stack([b.label, b.show, b.clk], axis=1)
                if (not np.isfinite(d).all() or (lsc < 0).any()
                        or (lsc > 255).any()
                        or (lsc != np.rint(lsc)).any()):
                    fits = False
            if first_row is None and d.shape[0]:
                first_row = d[:1].copy()
            valid = b.show > 0
            if valid.any():
                stat = d[valid]
                n_valid += stat.shape[0]
                blo, bhi = stat.min(axis=0), stat.max(axis=0)
                lo = blo if lo is None else np.minimum(lo, blo)
                hi = bhi if hi is None else np.maximum(hi, bhi)
        if not per_batch:
            raise ValueError("empty pass")
        if n_valid == 0:  # quantize_floats' stat = d[:1] fallback
            lo = first_row.min(axis=0)
            hi = first_row.max(axis=0)
        if not fits:
            log.warning("q8 float wire: data out of range, using bf16")
            floats = np.stack([
                pack_floats(b.dense, b.label, b.show, b.clk,
                            dtype=jnp.bfloat16)
                for b in dataset.batches()])
            return per_batch, floats, None, trivial, nrec, None
        scale = ((hi - lo) / 255.0)
        scale = np.where(scale > 0, scale, 1.0).astype(np.float32)
        lo = lo.astype(np.float32)
        qmeta = np.stack([scale, lo])
        floats_u8 = None
        for i, b in enumerate(dataset.batches()):
            poll_preload_abort()
            d = b.dense.astype(np.float32, copy=False)
            q = np.clip(np.rint((d - lo[None, :]) / scale[None, :]),
                        0, 255)
            block = np.concatenate(
                [q, np.stack([b.label, b.show, b.clk], axis=1)],
                axis=1).astype(np.uint8)
            if floats_u8 is None:
                floats_u8 = np.zeros((len(per_batch),) + block.shape,
                                     np.uint8)
            floats_u8[i] = block
        return per_batch, floats_u8, qmeta, trivial, nrec, None

    @classmethod
    def _front_columnar(cls, dataset: Dataset, col, floats_dtype):
        """Vectorized whole-pass front for columnar datasets: array slices
        + bulk reshapes — no SlotBatch objects, no per-record python
        (build must stay under the device pass time for the preload to
        fully overlap)."""
        desc = dataset.desc
        bs = desc.batch_size
        s = len(desc.sparse_slots)
        r = col.num_records
        if r == 0:
            raise ValueError("empty pass")
        nb = (r + bs - 1) // bs
        offsets = col.offsets
        bounds = offsets[np.minimum(np.arange(nb + 1) * bs, r)]
        nk_arr = np.diff(bounds)
        # resident pass = ONE uniform shape: the fine ladder pads ≤ ~6%
        # instead of the streaming pow2 bucket's ≤ 100% (pure wire waste
        # on ragged passes whose max-K lands just past a pow2 rung)
        from paddlebox_tpu.ps.table import next_bucket_fine
        k_max = next_bucket_fine(desc.key_bucket_min, int(nk_arr.max()))
        counts = np.diff(offsets)
        # trivial layout = exactly one key per slot per record, slot-order:
        # segments are then derivable on device (DeviceBatch.segments)
        trivial = (col.key_slot.size == r * s and bool((counts == s).all())
                   and bool((col.key_slot.reshape(r, s)
                             == np.arange(s, dtype=np.int32)).all()))
        pad_seg = bs * s
        segs_global = None
        if not trivial:
            rec_of_key = np.repeat(np.arange(r, dtype=np.int64), counts)
            segs_global = ((rec_of_key % bs) * s
                           + col.key_slot).astype(np.int32)
        per_batch = []
        for i in range(nb):
            a, b = int(bounds[i]), int(bounds[i + 1])
            per_batch.append((
                col.keys[a:b], col.key_slot[a:b].astype(np.int16),
                k_max, pad_seg,
                None if trivial else segs_global[a:b]))
        # float block: pack the whole pass, zero-pad the tail batch
        floats_full = pack_floats(col.dense, col.label, col.show, col.clk)
        d3 = floats_full.shape[1]
        if nb * bs != r:
            padded = np.zeros((nb * bs, d3), np.float32)
            padded[:r] = floats_full
            floats_full = padded
        floats = floats_full.reshape(nb, bs, d3)
        floats, qmeta = cls._encode_floats(floats, floats_dtype)
        front = (per_batch, floats, qmeta, trivial,
                 int((col.show > 0).sum()))
        # side channels for the post-pass metric registry feed (record j
        # of batch i == columnar row i*bs + j); references, not copies
        side = {"label": col.label, "show": col.show, "uid": col.uid,
                "rank": col.rank, "cmatch": col.cmatch,
                "batch_size": bs, "num_records": r}
        return front + (side,)

    @staticmethod
    def _encode_floats(floats: np.ndarray, floats_dtype):
        """Apply the requested float wire to a packed f32 block
        [nb, B, D+3]: "q8" → per-column affine uint8 over the whole pass
        (train/step.quantize_floats; range stats over real rows only —
        show > 0 — so zero-filled batch padding doesn't dilute
        precision; falls back to bf16 when the data doesn't fit), else a
        plain dtype cast."""
        if floats_dtype == "q8":
            nb, b, d3 = floats.shape
            flat = floats.reshape(nb * b, d3)
            q = quantize_floats(flat[:, :-3], flat[:, -3], flat[:, -2],
                                flat[:, -1], valid=flat[:, -2] > 0)
            if q is not None:
                block, qmeta = q
                return block.reshape(nb, b, d3), qmeta
            log.warning("q8 float wire: data out of range, using bf16")
            floats_dtype = jnp.bfloat16
        return floats.astype(floats_dtype, copy=False), None

    @classmethod
    def _dedup_phase(cls, per_batch, table, threads: int = 4,
                     stats: Optional[Dict[str, float]] = None):
        """Pass-level dedup + row assignment (the FeedPass registration +
        DedupKeysAndFillIdx steps). Returns
        ([(uniq_sorted, gidx)] per batch, u_pad, k_max). When ``stats``
        is given and the bulk path runs, the assignment time the table
        measured (host kv vs device probe table — see
        EmbeddingTable.last_assign_seconds) lands in ``stats["index"]``.

        BULK path (FLAGS.bulk_pass_assign, default): concatenate every
        batch's keys, ONE first-seen dedup + assign round-trip under
        host_lock (EmbeddingTable.bulk_assign_unique — the dedup itself
        runs outside the lock), then the per-batch sort/rank splits fan
        out over a thread pool (numpy releases the GIL). The old path
        acquired host_lock once PER BATCH with the index assign inside
        — nb serialized lock round-trips on the preloader thread,
        measured as the dominant prologue stall (BENCH_r05). New-row
        allocation order is first-seen over the pass, matching a serial
        batch walk of the native (first-occurrence) index row for row.

        SERIAL fallback (flag off, or tables without bulk_assign_unique):
        the per-batch assign loop, unchanged."""
        bulk = getattr(table, "bulk_assign_unique", None)
        if FLAGS.bulk_pass_assign and bulk is not None:
            keys_all = np.concatenate([k for k, *_ in per_batch])
            slots_all = np.concatenate([s for _, s, *_ in per_batch])
            rows_u, inv = bulk(keys_all, slots_all)
            if stats is not None:
                las = getattr(table, "last_assign_seconds", None)
                if las:
                    # split, not a single stage: a starved pipeline
                    # must name WHICH half of assignment is slow (the
                    # host kv walk vs the device probe-table insert)
                    stats["index_host"] = las.get("index_host", 0.0)
                    stats["index_dev"] = las.get("index_device", 0.0)
            rows_of_key = rows_u[inv]
            bounds = np.cumsum([0] + [len(k) for k, *_ in per_batch])
            poll_preload_abort()

            def batch_dedup(a, b):
                u, g = np.unique(rows_of_key[a:b], return_inverse=True)
                return (u.astype(np.int32, copy=False),
                        g.astype(np.int32, copy=False))

            with ThreadPoolExecutor(max_workers=threads) as pool:
                dedup = list(pool.map(
                    batch_dedup, bounds[:-1], bounds[1:]))
        else:
            dedup = cls._dedup_serial(per_batch, table, threads)
        u_max = max(len(u) + 1 for u, _ in dedup)
        from paddlebox_tpu.ps.table import next_bucket_fine
        u_pad = next_bucket_fine(table.unique_bucket_min, u_max)
        k_max = max(kc for _, _, kc, _, _ in per_batch)
        return dedup, u_pad, k_max

    @classmethod
    def _dedup_serial(cls, per_batch, table, threads: int = 4):
        """The per-batch assign loop (pre-bulk reference): one
        host_lock acquisition + index round-trip per batch."""

        def sort_rank(rows_u, inv):
            u = len(rows_u)
            order = np.argsort(rows_u, kind="stable")
            rank = np.empty(u, np.int32)
            rank[order] = np.arange(u, dtype=np.int32)
            return rows_u[order], rank[inv]

        # arena tables assign slotted even on the dedup wire, so keys
        # seen here first don't land in the default arena and poison the
        # compact wire for every later pass
        slotted = getattr(table.index, "arena_enabled", False)
        futs = []
        with ThreadPoolExecutor(max_workers=threads) as pool:
            for keys, slot_of_key, *_ in per_batch:
                with table.host_lock:  # vs shrink/save on the main thread
                    if slotted:
                        rows_u, inv = table.index.assign_unique_slotted(
                            keys, slot_of_key.astype(np.uint16,
                                                     copy=False))
                    else:
                        rows_u, inv = table.index.assign_unique(keys)
                    # slot = host metadata (slot_host), not wire bytes
                    table.record_slots(rows_u, inv, slot_of_key)
                futs.append(pool.submit(sort_rank, rows_u, inv))
            return [f.result() for f in futs]

    @classmethod
    def _pack_chunk(cls, per_batch, dedup, u_pad: int, k_max: int,
                    trivial: bool, cap: int):
        """Pack a run of batches into uniform host arrays
        (uniq, gidx, meta, segs-or-None) — SORTED unique rows so the wire
        ships byte-cut deltas and the table scatter gets nondecreasing
        line indices."""
        from paddlebox_tpu.ps.table import fill_oob_pads
        nb = len(per_batch)
        uniq = np.empty((nb, u_pad), np.int32)
        gidx = np.empty((nb, k_max), np.int32)
        meta = np.empty((nb, 4), np.int32)
        segs = None if trivial else np.empty((nb, k_max), np.int32)
        for i, ((keys, _, _, pad_seg, seg_arr),
                (uniq_s, gidx_i)) in enumerate(zip(per_batch, dedup)):
            nk, u = len(keys), len(uniq_s)
            uniq[i, :u] = uniq_s
            fill_oob_pads(uniq[i], u, cap)
            gidx[i, :nk] = gidx_i
            gidx[i, nk:] = u  # key pads → first OOB pad position
            meta[i] = (nk, pad_seg, u, uniq[i, 0])
            if segs is not None:
                segs[i, :nk] = seg_arr
                segs[i, nk:] = pad_seg
        return uniq, gidx, meta, segs

    def upload(self, materialize: bool = False) -> None:
        """Stage to HBM, bit-packing the index arrays for the wire (H2D
        bandwidth is the scarce resource — ops/bitpack.py): uniq rides as
        16+8-bit halves when rows fit 24 bits, gidx as 16-bit lows plus
        packed 2-bit highs when positions fit 18 bits; the step
        reassembles in-register.

        ``materialize=True`` forces the bytes onto the device NOW (a tiny
        fetch per array): plain ``jnp.asarray`` is lazy on tunneled
        runtimes and the deferred transfer would otherwise serialize into
        the first training step that consumes the pass — the preloader
        materializes from its thread so the transfer rides alongside the
        previous pass's compute."""
        if self.dev is None:
            uniq = tuple(jnp.asarray(a) for a in
                         self._encode_uniq(self.uniq, self.meta))
            gidx = tuple(jnp.asarray(a) for a in
                         self._encode_gidx(self.gidx))
            if self.segs is None:
                segs = (jnp.zeros((1, 1), jnp.int32),)
            else:
                enc = self._encode_segs_slotwire(
                    self.segs, self.meta,
                    self.floats.shape[1])
                segs = tuple(jnp.asarray(a) for a in
                             (enc if enc is not None
                              else self._encode_gidx(self.segs)))
            qm = (jnp.zeros((2, 0), jnp.float32) if self.qmeta is None
                  else jnp.asarray(self.qmeta))
            self.dev = (uniq, gidx, jnp.asarray(self.floats),
                        jnp.asarray(self.meta), segs, qm)
        if materialize:
            # one blocking wait; per-leaf fetches cost ~0.25 s each
            jax.block_until_ready(list(jax.tree.leaves(self.dev)))

    _EXC = 32    # per-batch budget of >=2^16 delta gaps in the u16 wire
    _EXC8 = 64   # per-batch budget of >=2^8 gaps in the u8 wire

    @classmethod
    def _encode_uniq(cls, uniq: np.ndarray, meta: np.ndarray):
        """Wire encoding for the (ascending) per-batch unique rows, in
        preference order: u8 DELTAS + sparse gap exceptions (1 B/value —
        the common case once the table is warm, mean row gap is
        rows_assigned/u), u16 deltas (2 B), 16+8-bit halves (3 B), raw
        int32. The device reconstructs with one cumsum (_make_view).
        Hand-built passes that violate the delta wire's preconditions
        (unsorted rows, old 3-column meta without the base) fall through
        to the order-agnostic encodings."""
        if meta.shape[1] >= 4 and bool((meta[:, 3] == uniq[:, 0]).all()):
            delta = pack_delta_auto(uniq, meta[:, 2], cls._EXC8, cls._EXC)
            if delta is not None:
                return delta
        if int(uniq.max()) < (1 << 24):
            return pack_u24(uniq)
        return (uniq,)

    @staticmethod
    def _encode_gidx(gidx: np.ndarray):
        if (int(gidx.max(initial=0)) < (1 << 18)
                and gidx.shape[1] % 4 == 0):
            return pack_u18(gidx)
        return (gidx,)

    @staticmethod
    def _encode_segs_slotwire(segs: np.ndarray, meta: np.ndarray,
                              batch_size: int):
        """Segment wire for non-trivial layouts, narrowest first.

        GRID wire: when keys are ordered by (record, slot) — the
        BatchBuilder layout — the whole segment stream collapses to
        per-(record, slot) key COUNTS, one u8 [B, S] grid: ~S B/record
        instead of ~1 B/key (ragged at ~5 keys/slot: 130 → 26 B/record).
        The device rebuilds segments with one grid cumsum + boundary-
        mark scatter + key cumsum (the same scatter+cumsum identity as
        the record decode — no searchsorted).

        SLOT wire (fallback): per-key SLOT ids (u8) + per-record key
        COUNTS (u16) — needs only record-grouping, not slot order.

        Preconditions for either (else None → the u18 wire): S ≤ 255,
        pad_segment == B·S, keys record-grouped; GRID additionally needs
        nondecreasing slots within each record and per-cell counts ≤
        255. Pads decode for free in both (indices saturate at B·S)."""
        nb, k = segs.shape
        b = batch_size
        s = int(meta[0, 1]) // b          # pad_segment == bs * S
        if s <= 0 or s > 255 or int(meta[0, 1]) != b * s:
            return None
        rec = segs // s
        # GRID only when it is actually the smaller wire: b*s bytes vs
        # the SLOT wire's k + 2b per batch (sparse many-slot batches —
        # avg keys/record below S — would otherwise ship MORE bytes)
        grid_ok = b * s < k + 2 * b
        grid = (np.zeros((nb, b * s), np.int64) if grid_ok else None)
        counts = np.zeros((nb, b), np.int64)
        for i in range(nb):
            nk = int(meta[i, 0])
            r = rec[i, :nk]
            if nk and (np.diff(r) < 0).any():
                return None               # keys not record-grouped
            if nk and int(r.max()) >= b:
                return None
            if segs[i, nk:].size and (segs[i, nk:] != b * s).any():
                return None               # pads must be the discard bin
            # GRID additionally needs the composite segment id itself
            # to be nondecreasing (slot order within each record)
            if grid_ok and nk and (np.diff(segs[i, :nk]) < 0).any():
                grid_ok = False
            if grid_ok:
                grid[i] = np.bincount(segs[i, :nk], minlength=b * s)
                counts[i] = grid[i].reshape(b, s).sum(axis=1)
            else:
                counts[i] = np.bincount(r, minlength=b)
        if grid_ok and int(grid.max()) <= 255:
            return (grid.reshape(nb, b, s).astype(np.uint8),)
        # (counts are complete either way: grid-path batches derived
        # them from their grid row before any fallback flip)
        if int(counts.max()) > 65535:
            return None
        # numpy out, like every sibling encoder — transfer timing stays
        # with the caller
        return (segs % s).astype(np.uint8), counts.astype(np.uint16)

    def nbytes(self) -> int:
        """Wire bytes (after upload packing; host estimate before)."""
        if self.dev is not None:
            return sum(a.nbytes for a in jax.tree.leaves(self.dev))
        n = (self.uniq.nbytes + self.gidx.nbytes
             + self.floats.nbytes + self.meta.nbytes)
        return n + (self.segs.nbytes if self.segs is not None else 0)

    def mark_trained_rows(self, table) -> None:
        """Flag this pass's rows as touched-since-last-save — called by
        the trainer AFTER the pass runs, so delta saves include them
        regardless of when a checkpoint landed relative to the preload.
        Duplicate-tolerant boolean scatter after dropping the OOB pad
        ids (save paths only read rows the index owns)."""
        rows = self.uniq.ravel()
        rows = rows[rows <= table.capacity]
        with table.host_lock:
            table._touched[rows] = True


class _BatchView:
    """Duck-typed DeviceBatch built inside the trace from pass slices."""

    def __init__(self, unique_rows, gather_idx, key_valid, segments,
                 dense, label, show, clk,
                 segments_trivial=False) -> None:
        self.unique_rows = unique_rows
        self.gather_idx = gather_idx
        self.key_valid = key_valid
        self.segments = segments
        self.dense = dense
        self.label = label
        self.show = show
        self.clk = clk
        self.segments_trivial = segments_trivial

    @property
    def pool_segments(self):
        return None if self.segments_trivial else self.segments


class ResidentPassRunner:
    """jits `chunk` steps of a resident pass as ONE device program
    (lax.fori_loop over the staged batches)."""

    def __init__(self, step, capacity: int, trivial_segments: bool,
                 chunk: int = 0, wire: str = "dedup",
                 num_slots: Optional[int] = None,
                 chunk_bits: Optional[int] = None) -> None:
        self.step = step            # TrainStep
        self.capacity = capacity
        self.trivial = trivial_segments
        self.chunk = chunk
        self.wire = wire            # "dedup" | "compact"
        self.num_slots = num_slots  # compact: derive slot = pos % S
        self.chunk_bits = chunk_bits
        self._jit: Dict[int, object] = {}  # n_steps → compiled runner

    @staticmethod
    def _decode_segs(segs, meta=None, k_pad=None):
        """segments arrive raw, as a u18-packed pair (ops/bitpack), as
        the GRID wire (u8 [B, S] per-cell key counts), as the SLOT wire
        (u8 slots + u16 per-record counts — see _encode_segs_slotwire),
        or as a bare array (hand-built passes / direct test calls). The
        kinds are distinguished statically by leaf count/dtype/rank
        (u18 lows are uint16; the GRID leaf is the only 2-D uint8).
        Both count wires decode with the scatter+cumsum identity —
        out[p] = #{cells whose cumulative count <= p} == the
        searchsorted(cum, arange, "right") this replaced, measured 14x
        faster (56 → 3.9 ms at K=557k, scripts/profile_keypath.py)."""

        def cum_decode(counts_flat, k):
            # empty cells stack duplicate boundary marks, hence .add;
            # positions past the total saturate at the cell count
            cum = jnp.cumsum(counts_flat)
            marks = jnp.zeros(k, jnp.int32).at[cum].add(1, mode="drop")
            return jnp.cumsum(marks)

        if isinstance(segs, tuple):
            if (len(segs) == 1 and segs[0].dtype == jnp.uint8
                    and segs[0].ndim == 2):
                # GRID wire: segment id = owning (record, slot) cell,
                # saturating at B*S == pad_segment for pads
                if k_pad is None:
                    raise ValueError(
                        "GRID segment wire needs k_pad (the padded key "
                        "count) — pass it when calling _decode_segs "
                        "directly")
                return cum_decode(segs[0].reshape(-1).astype(jnp.int32),
                                  k_pad)
            if len(segs) == 2 and segs[0].dtype == jnp.uint8:
                slot = segs[0].astype(jnp.int32)          # [K]
                counts = segs[1].astype(jnp.int32)        # [B]
                k = slot.shape[0]
                s = meta[1] // counts.shape[0]            # pad_seg // B
                # pads: rec saturates at B and slot pads are 0, so the
                # reconstruction lands exactly on pad_segment == B*S
                return cum_decode(counts, k) * s + slot
            if len(segs) == 2:
                return unpack_u16m(segs[0], segs[1], 2)
            return segs[0]
        return segs

    def _make_view(self, uniq_t, gidx_t, floats, meta,
                   segs, qmeta) -> _BatchView:
        if self.wire == "compact":
            return self._make_view_compact(uniq_t, gidx_t[0], floats,
                                           meta, segs, qmeta)
        if len(uniq_t) == 3:
            # u16-delta wire (ops/bitpack.unpack_delta16); the pad
            # region is derived (fill_oob_pads pattern: distinct, > cap)
            u_pad = uniq_t[0].shape[0]
            upos = jnp.arange(u_pad, dtype=jnp.int32)
            uniq = jnp.where(upos < meta[2],
                             unpack_delta16(*uniq_t, base=meta[3]),
                             self.capacity + 1 + upos)
        elif len(uniq_t) == 2:
            uniq = unpack_u24(*uniq_t)
        else:
            uniq = uniq_t[0]
        gidx = (unpack_u18(*gidx_t) if len(gidx_t) == 2 else gidx_t[0])
        k = gidx.shape[0]
        num_keys, pad_seg = meta[0], meta[1]
        pos = jnp.arange(k, dtype=jnp.int32)
        if self.trivial:
            segments = jnp.where(pos < num_keys, pos, pad_seg)
        else:
            segments = self._decode_segs(segs, meta, k_pad=k)
        key_valid = (pos < num_keys).astype(jnp.float32)
        if floats.dtype == jnp.uint8:  # q8 wire (quantize_floats)
            dense, label, show, clk = dequantize_floats(floats, qmeta)
        else:
            dense, label, show, clk = unpack_floats(floats)
        return _BatchView(
            uniq, gidx, key_valid, segments,
            dense=dense, label=label, show=show, clk=clk,
            segments_trivial=self.trivial)

    def _make_view_compact(self, loc_t, cmap, floats, meta, segs,
                           qmeta) -> _BatchView:
        """Decode the compact wire: slot-local rows → global rows via the
        arena chunk map, then in-trace dedup (DedupKeysAndFillIdx on the
        chip — ops/device_unique.py)."""
        if len(loc_t) == 2:
            k = loc_t[0].shape[-1]
            m = 8 * loc_t[1].shape[-1] // k
            local = unpack_u16m(loc_t[0], loc_t[1], m)
        elif loc_t[0].dtype == jnp.uint8:   # u12 byte-pair wire
            local = unpack_u12(loc_t[0])
        else:
            local = loc_t[0].astype(jnp.int32)
        k = local.shape[-1]
        num_keys, pad_seg = meta[0], meta[1]
        pos = jnp.arange(k, dtype=jnp.int32)
        s = self.num_slots
        if self.trivial:
            segments = jnp.where(pos < num_keys, pos, pad_seg)
            slot = pos % s
        else:
            segments = self._decode_segs(segs, meta, k_pad=k)
            slot = segments % s
        cb = self.chunk_bits
        stride = cmap.shape[1]
        chunk = cmap.reshape(-1)[slot * stride + (local >> cb)]
        rows = (chunk << cb) | (local & ((1 << cb) - 1))
        rows = jnp.where(pos < num_keys, rows, self.capacity)
        uniq, gidx = dedup_rows(rows, self.capacity)
        key_valid = (pos < num_keys).astype(jnp.float32)
        if floats.dtype == jnp.uint8:
            dense, label, show, clk = dequantize_floats(floats, qmeta)
        else:
            dense, label, show, clk = unpack_floats(floats)
        return _BatchView(
            uniq, gidx, key_valid, segments,
            dense=dense, label=label, show=show, clk=clk,
            segments_trivial=self.trivial)

    def _run(self, n_steps: int, collect: bool = False):
        key = (n_steps, collect)
        if key not in self._jit:
            def run(state, uniq_t, gidx_t, floats_p, meta_p,
                    segs_p, qmeta, start, rng):
                def body(i, carry):
                    state, rng, preds = carry
                    # compact wire: gidx slot carries the PASS-global
                    # arena chunk map, not per-batch data — don't index
                    gi = (gidx_t if self.wire == "compact"
                          else tuple(a[i] for a in gidx_t))
                    # one shared index: the packed pair's leading
                    # dims are equal; the modulo only serves the
                    # [1, 1] dummy of the trivial layout
                    si = i % segs_p[0].shape[0]
                    sg = tuple(a[si] for a in segs_p)
                    view = self._make_view(
                        tuple(a[i] for a in uniq_t), gi, floats_p[i],
                        meta_p[i], sg, qmeta)
                    # 1-based like Trainer.train_pass's fold of the
                    # pre-incremented global_step
                    rng_i = jax.random.fold_in(rng, state.step + 1)
                    state, stats = self.step._step(state, view, rng_i)
                    if collect:
                        # per-batch predictions stay resident for the
                        # metric registry feed (AddAucMonitor role)
                        preds = jax.lax.dynamic_update_index_in_dim(
                            preds, stats["pred"], i - start, 0)
                    return state, rng, preds

                preds0 = (jnp.zeros((n_steps, floats_p.shape[1]),
                                    jnp.float32) if collect
                          else jnp.zeros((), jnp.float32))
                state, _, preds = jax.lax.fori_loop(
                    start, start + n_steps, body, (state, rng, preds0))
                return state, preds

            self._jit[key] = jax.jit(run, donate_argnums=(0,))
        return self._jit[key]

    def run_pass(self, state, rp: ResidentPass, rng: jax.Array,
                 chunk: Optional[int] = None, collect_preds: bool = False):
        """Run every batch of the staged pass → (state, preds or None);
        ``collect_preds`` returns [nb, B] per-batch device predictions
        (the post-pass metric registry feed)."""
        rp.upload()
        nb = rp.num_batches
        c = chunk if chunk is not None else (self.chunk or nb)
        i = 0
        chunks = []
        while i < nb:
            n = min(c, nb - i)
            state, preds = self._run(n, collect_preds)(
                state, *rp.dev, jnp.asarray(i, jnp.int32), rng)
            if collect_preds:
                chunks.append(preds)
            i += n
        if not collect_preds:
            return state, None
        return state, (chunks[0] if len(chunks) == 1
                       else jnp.concatenate(chunks, axis=0))


class PassPreloader:
    """Depth-N pass pipeline — preload_into_memory /
    wait_feed_pass_done (box_wrapper.h:1142-1156) for resident passes:
    ONE persistent worker thread builds + uploads passes ahead of
    training through a bounded queue of ``depth`` passes
    (FLAGS.preload_depth, default 2). Pass k+2's build starts the
    moment k+1's finishes — no join-per-consume, so a slow build no
    longer serializes into the next pass boundary (the depth-1
    alternating-stall pattern of BENCH_r05).

    With the tiered tables' ASYNC EPILOGUE (ps/epilogue,
    FLAGS.async_end_pass) the steady-state pipeline is FOUR-deep: pass
    k-1's end_pass write-back drains on the epilogue worker, pass k
    trains on device, pass k+1 sits staged in HBM, and this worker
    builds pass k+2 — the pass boundary costs one reconcile+scatter,
    with the prologue build, the H2D wire and the epilogue D2H all off
    the critical path. The epilogue's fence rules keep it safe: a plan
    build here only assigns value-less PENDING rows (plan_scope — legal
    for several queued future passes at once; the window must hold the
    union of the open pass's and every queued pass's working set), and
    the overlapped ``stage`` fetch drains in-flight write-backs before
    reading the host tier (HostStore.read_barrier).

    HBM budget guard: after each build the staged wire bytes
    (``rp.nbytes()``) are measured and the EFFECTIVE depth clamps to
    ``max(1, budget // bytes_per_pass)`` (FLAGS.preload_hbm_budget_mb)
    — an oversized pass degrades the pipeline to double-buffering,
    loudly, instead of stacking passes until HBM OOMs. The clamp is
    monotone (never re-raises) so one giant pass bounds the rest of
    the run conservatively.

    Preemption: the worker polls the graceful-stop flag before every
    build, and the builders poll it between stages
    (poll_preload_abort) — on request_stop the pipeline stops building
    within one stage, already-staged passes stay consumable, and
    ``drain()`` joins the worker so no orphan H2D is in flight at
    emergency-checkpoint time.

    A build failure is held and re-raised by the ``wait()`` that would
    have returned that pass — passes built BEFORE the failure are
    served first (they are valid), and every wait() after the raise
    returns None."""

    def __init__(self, datasets: Iterator[Dataset], table=None,
                 floats_dtype=np.float32, build_fn=None,
                 block_transfers: bool = False,
                 depth: Optional[int] = None,
                 hbm_budget_bytes: Optional[int] = None) -> None:
        """``build_fn(dataset) -> pass`` overrides the default single-chip
        ResidentPass builder — e.g.
        ``build_fn=sharded_trainer.build_resident_pass`` pipelines mesh
        passes the same way. ``depth`` overrides FLAGS.preload_depth;
        ``hbm_budget_bytes`` overrides FLAGS.preload_hbm_budget_mb."""
        if table is None and build_fn is None:
            raise ValueError("need a table or a build_fn")
        self._it = iter(datasets)
        self._table = table
        self._floats_dtype = floats_dtype
        self._build_fn = build_fn
        self._block = block_transfers
        depth = FLAGS.preload_depth if depth is None else depth
        # depth=0 → MANUAL mode: the worker builds one pass per
        # start_next() credit instead of free-running (the depth-1
        # era's strict kick-per-pass protocol; bench's no-overlap
        # control uses it)
        self._manual = depth == 0
        self._credits = 0
        self.depth = max(1, depth)
        self._budget = (FLAGS.preload_hbm_budget_mb * (1 << 20)
                        if hbm_budget_bytes is None else hbm_budget_bytes)
        self._cv = threading.Condition()
        self._q: collections.deque = collections.deque()
        self._building = False
        self._exhausted = False   # source iterator drained
        self._stopped = False     # stop()/abort — no further builds
        self._err: Optional[BaseException] = None
        self._worker: Optional[threading.Thread] = None
        self._effective_depth = self.depth
        self.depth_clamped = False
        # cumulative per-stage build seconds + build count (bench)
        self.build_stage_sec: Dict[str, float] = {}
        self.builds = 0
        self.build_sec_total = 0.0
        self.wait_sec_total = 0.0

    # ---- worker --------------------------------------------------------
    def _build(self, ds: Dataset):
        if self._build_fn is not None:
            rp = self._build_fn(ds)
            # forced materialization moves the pass's bytes NOW, riding
            # alongside the open pass's compute (see
            # ResidentPass.upload); a lazy upload would instead
            # serialize into that pass's first step
            rp.upload(materialize=True)
            return rp
        # build+upload overlapped; transfers stay IN FLIGHT
        # (block=False) so this thread can start the next pass's host
        # build immediately — the training step consuming the pass
        # waits on its own args
        return ResidentPass.build_streamed(
            ds, self._table, floats_dtype=self._floats_dtype,
            block=self._block)

    def _run(self) -> None:
        from paddlebox_tpu.obs import trace
        from paddlebox_tpu.resilience import preemption
        # lets the builders' stage polls see THIS preloader's stop()
        # (poll_preload_abort) so an in-flight build aborts promptly
        _PRELOAD_TLS.abort = lambda: self._stopped
        trace.set_lane(trace.LANE_PRELOAD)
        while True:
            with self._cv:
                while not self._stopped and (
                        len(self._q) + (1 if self._building else 0)
                        >= self._effective_depth
                        or (self._manual and self._credits <= 0)):
                    self._cv.wait()
                if self._stopped:
                    return
                if self._manual:
                    self._credits -= 1
                self._building = True
            rp = None
            try:
                if preemption.stop_pending():
                    raise PreloadBuildAborted(
                        f"preload stopped ({preemption.stop_reason()})")
                ds = next(self._it, None)
                if ds is None:
                    with self._cv:
                        self._building = False
                        self._exhausted = True
                        self._cv.notify_all()
                    return
                t0 = time.perf_counter()
                # the pass trace's build span on the preload.worker
                # lane; its id rides the pass so the main-thread
                # consume span can link back (the build→consume flow
                # arrow — obs/trace, docs/OBSERVABILITY.md §Tracing)
                with trace.span("pass.build",
                                pass_seq=self.builds + 1) as _sp:
                    rp = self._build(ds)
                if _sp.span_id:
                    try:
                        rp._trace_span_id = _sp.span_id
                    except AttributeError:
                        pass  # slotted pass objects skip the link
                self._note_built(rp, time.perf_counter() - t0)
            except PreloadBuildAborted as e:
                log.warning("pass preload pipeline stopped: %s", e)
                with self._cv:
                    self._building = False
                    self._stopped = True
                    self._cv.notify_all()
                return
            except BaseException as e:  # held for the consuming wait()
                with self._cv:
                    self._building = False
                    self._err = e
                    self._cv.notify_all()
                return
            with self._cv:
                self._building = False
                dropped = self._stopped
                if not dropped:
                    self._q.append(rp)
                depth = len(self._q)
                self._cv.notify_all()
            if dropped:
                # drained mid-build: wait out the pass's issued
                # transfers before dropping it, so drain() really means
                # "no preload H2D in flight"
                dev = getattr(rp, "dev", None)
                if dev is not None:
                    jax.block_until_ready(list(jax.tree.leaves(dev)))
                return
            self._mirror_queue(depth)

    def _note_built(self, rp, build_sec: float) -> None:
        """Accounting + the HBM budget clamp, off the queue lock."""
        self.builds += 1
        self.build_sec_total += build_sec
        stages = getattr(rp, "build_stats", None)
        hub = self._hub()
        if stages:
            for stage, sec in stages.items():
                self.build_stage_sec[stage] = \
                    self.build_stage_sec.get(stage, 0.0) + sec
                if hub is not None:
                    hub.counter(
                        "pbox_preload_build_seconds_total",
                        "pass preload build seconds by stage"
                        ).inc(sec, stage=stage)
        if hub is not None:
            hub.counter("pbox_preload_builds_total",
                        "passes built by the preload pipeline").inc()
        if self._budget <= 0:
            return
        try:
            nbytes = int(rp.nbytes())
        except Exception:
            return  # passes without a wire-bytes estimate stay unguarded
        if nbytes <= 0:
            return
        fit = max(1, int(self._budget // nbytes))
        with self._cv:
            if fit >= self._effective_depth:
                return
            self._effective_depth = fit
            self.depth_clamped = True
        log.warning(
            "preload HBM budget: a staged pass is ~%.1f MB but the "
            "budget is %.1f MB — clamping preload depth %d -> %d "
            "(raise FLAGS.preload_hbm_budget_mb to restore the deeper "
            "pipeline)", nbytes / 1e6, self._budget / 1e6, self.depth,
            fit)
        if self._hub() is not None:
            self._hub().counter(
                "pbox_preload_depth_clamps_total",
                "preload depth reductions forced by the HBM budget"
                ).inc()

    # ---- consumer ------------------------------------------------------
    def start_next(self) -> bool:
        """Ensure the pipeline worker is running. Returns False only
        when the source is KNOWN exhausted and nothing remains to hand
        out — i.e. the next ``wait()`` would return None. (Compat shim
        for the depth-1 era's kick-per-pass protocol: extra calls are
        free, and lockstep start_next/wait loops keep working.)"""
        with self._cv:
            if self._manual:
                self._credits += 1
                self._cv.notify_all()
        if self._worker is None:
            self._worker = threading.Thread(
                target=self._run, daemon=True, name="pbox-preload")
            self._worker.start()
        with self._cv:
            return not (self._exhausted and not self._q
                        and not self._building and self._err is None)

    def wait(self) -> Optional[ResidentPass]:
        """Block until the next pipelined pass is staged
        (WaitFeedPassDone) and pop it; None at end-of-stream (or after
        ``stop()``/a raised build failure). The blocked seconds are the
        pipeline's prologue stall — exported as
        ``pbox_preload_wait_seconds_total`` so a starved pipeline
        (build slower than train) is visible next to the epilogue's
        fence-wait counter (docs/PERFORMANCE.md).

        With ``FLAGS.pipeline_wait_timeout_sec > 0`` a wait during
        which no build completes for that long raises
        ``PipelineHangError`` (ps/epilogue) naming the preload stage —
        a wedged build worker becomes a loud failure instead of an
        indefinite stall."""
        from paddlebox_tpu.ps.epilogue import hang_timeout, \
            wait_with_deadline
        if self._worker is None:
            return None
        t0 = time.perf_counter()
        err = None
        with self._cv:
            wait_with_deadline(
                self._cv,
                done=lambda: bool(self._q) or self._exhausted
                or self._stopped or self._err is not None,
                progress=lambda: self.builds,
                stage="preload.build",
                message=lambda: (
                    f"pass preload wait hung: stage 'preload.build' "
                    f"made no progress for {hang_timeout():.1f}s — 0 "
                    f"staged pass(es) queued (building="
                    f"{self._building}, builds_done={self.builds}, "
                    f"effective_depth={self._effective_depth}, "
                    f"worker_alive="
                    f"{self._worker.is_alive()})"))
            waited = time.perf_counter() - t0
            if self._q:
                rp = self._q.popleft()
            else:
                rp = None
                if self._err is not None:
                    # the failure surfaces exactly where the broken
                    # pass would have been consumed; later waits → None
                    err, self._err = self._err, None
                    self._stopped = True
            depth = len(self._q)
            self._cv.notify_all()  # a build slot just freed
        self.wait_sec_total += waited
        hub = self._hub()
        if hub is not None:
            if waited > 1e-4:
                hub.counter("pbox_preload_wait_seconds_total",
                            "seconds the trainer blocked on pass preload"
                            ).inc(waited)
                # critical-path attribution: the blocked wait is the
                # consuming pass's build-starvation stall (obs/trace —
                # rides the next pass event's critical_path block)
                from paddlebox_tpu.obs import trace
                trace.note_pass_part("build_wait", waited)
            hub.gauge("pbox_preload_queue_depth",
                      "staged passes queued ahead of training"
                      ).set(depth)
        if err is not None:
            raise err
        if rp is not None:
            rp.upload()  # no-op unless a build_fn skipped it
        return rp

    # ---- shutdown ------------------------------------------------------
    def stop(self) -> None:
        """Stop building: no new builds start; an in-flight build
        aborts at its next stage poll. Already-staged passes remain
        consumable via wait()."""
        with self._cv:
            self._stopped = True
            self._cv.notify_all()

    def drain(self, timeout: Optional[float] = None) -> None:
        """stop() + join the worker, then settle the staged passes'
        transfers — after this returns, no preload H2D is in flight
        (the graceful-shutdown hook: call before the emergency
        checkpoint's D2H so they don't contend for the wire)."""
        self.stop()
        w = self._worker
        if w is not None and w.is_alive():
            w.join(timeout)
        # queued passes were built with block=False, so their wire may
        # still be in flight even though the build finished; they stay
        # consumable — we only wait the transfers out
        with self._cv:
            staged = list(self._q)
        for rp in staged:
            dev = getattr(rp, "dev", None)
            if dev is not None:
                jax.block_until_ready(list(jax.tree.leaves(dev)))

    @property
    def staged(self) -> int:
        """Passes currently staged (built, unconsumed)."""
        with self._cv:
            return len(self._q)

    def _mirror_queue(self, depth: int) -> None:
        hub = self._hub()
        if hub is not None:
            hub.gauge("pbox_preload_queue_depth",
                      "staged passes queued ahead of training"
                      ).set(depth)

    @staticmethod
    def _hub():
        from paddlebox_tpu.obs.hub import get_hub
        hub = get_hub()
        return hub if hub.active else None


class PassPipeline:
    """ONE pass-pipeline abstraction — build → stage → consume →
    epilogue — shared by resident and tiered modes (ISSUE 9; ROADMAP's
    cross-cutting unification).

    Every pass mode decomposes into the same four phases:

      build    host pack of the pass (routing plans / dedup / wire
               encode) — ``build_fn`` (e.g. ``ResidentPass.build_streamed``
               or ``ShardedTrainer.build_resident_pass``)
      stage    moving the pass's bytes to where training reads them:
               the chunked H2D wire upload, plus — for pass-WINDOW
               tables — the host-tier feed-pass fetch (``table.stage``)
      consume  ``begin_pass`` reconcile (window tables) + the resident
               train loop over the staged pass
      epilogue ``end_pass`` write-back on the PassEpilogue lane, which
               also carries async capacity eviction and SSD watermark
               demotion (ps/tiered.py, ps/epilogue.py)

    For a plain resident table (``window_table=None``) this is exactly
    the depth-N ``PassPreloader``: build+stage ride the persistent
    worker, consume is the training loop, the epilogue is empty. For a
    pass-window table (``TieredShardedEmbeddingTable`` /
    ``MultihostTieredShardedTable``) each build is followed ON THE
    WORKER by the host-tier stage fetch, QUEUED in pass order
    (``table.stage(queue=True)``) — so by the time ``wait()`` hands a
    pass out, its plan is baked (plan_scope pending rows), its wire is
    in HBM, its host values are fetched, and its spilled rows are
    promoted (``prefetch_promote`` inside the build): ``begin_pass()``
    is reconcile-only, and ``end_pass()`` submits a write-back whose
    lane slot also evicts ahead for the NEXT queued stage
    (``_evict_ahead``). Plan builds stay serialized per ``plan_scope``
    on the single worker; the window capacity contract is the union
    over the open pass and every queued pass (ps/tiered.py module
    docstring).

    Driver shape (the bench / trainers):

        pipe = PassPipeline(datasets, build_fn=tr.build_resident_pass,
                            window_table=table, trainer=tr)
        pipe.start_next()
        while (rp := pipe.wait()) is not None:
            pipe.begin_pass()                  # reconcile-only
            pipe.start_next()
            tr.train_pass_resident(rp)
            pipe.end_pass()                    # submit; lane drains
        pipe.drain()

    ``depth=0`` gives the manual kick-per-pass sequential control (the
    no-overlap oracle for the pipeline gates)."""

    def __init__(self, datasets: Iterator, build_fn,
                 window_table=None, trainer=None,
                 depth: Optional[int] = None,
                 keys_of=None) -> None:
        import contextlib
        self.table = window_table
        self.trainer = trainer
        self._keys_of = keys_of or (lambda ds: ds.pass_keys())
        # fence-wait attribution baseline: the table's counters are
        # CUMULATIVE over its lifetime, and a fresh pipeline over a
        # long-lived table must not book historical fence waits into
        # its first pass's critical_path block
        self._fence_wait_mark = 0.0
        if window_table is not None:
            eps = getattr(window_table, "endpass_stats", None)
            if eps is not None:
                self._fence_wait_mark = float(
                    eps().get("critical_fence_wait_sec", 0.0))
        # key sets of built-and-staged passes, in build order — consumed
        # by begin_pass() to validate the head queued stage
        self._key_q: collections.deque = collections.deque()
        self._lock = threading.Lock()
        if window_table is None:
            build = build_fn
        else:
            def build(ds):
                keys = self._keys_of(ds)
                scope = getattr(window_table, "plan_scope", None)
                cm = (scope() if scope is not None
                      else contextlib.nullcontext())
                pin = getattr(window_table, "pin_working_set", None)
                # the OUTER plan_scope brackets build AND stage: an
                # abort (preemption/stop) or fetch failure between them
                # rolls the pass's pending plan rows back — a dead
                # build must not pin window capacity (the
                # rollback-under-abort contract,
                # tests/test_tiered_sharded.py)
                with cm:
                    # pin the working set for the WHOLE build+stage
                    # span: the plan bakes row ids for resident keys
                    # too, so eviction must not touch them from the
                    # first row lookup on (the pin hands over to the
                    # queued stage when stage() completes)
                    if pin is not None:
                        pin(keys)
                    try:
                        t0 = time.perf_counter()
                        rp = build_fn(ds)
                        t_build = time.perf_counter() - t0
                        poll_preload_abort()
                        # host fetch ON this worker, queued in pass
                        # order — by the time wait() hands the pass out
                        # its stage is complete and begin_pass is
                        # reconcile-only
                        t0 = time.perf_counter()
                        window_table.stage(keys, background=False,
                                           queue=True)
                        t_stage = time.perf_counter() - t0
                    except BaseException:
                        if pin is not None:
                            window_table.unpin_working_set()
                        raise
                # per-stage worker seconds for the preloader's
                # build_stage_sec mirror (builders that already report
                # stages — build_streamed — keep their finer split)
                stats = dict(getattr(rp, "build_stats", None) or {})
                stats.setdefault("build", t_build)
                stats["stage_fetch"] = t_stage
                try:
                    rp.build_stats = stats
                except AttributeError:
                    pass  # slotted pass objects skip the attribution
                with self._lock:
                    self._key_q.append(keys)
                return rp
        self.pre = PassPreloader(iter(datasets), build_fn=build,
                                 depth=depth)

    # ---- prologue (build + stage on the worker) ----------------------
    def start_next(self) -> bool:
        return self.pre.start_next()

    def wait(self):
        """Next staged pass (build + H2D wire + host fetch complete),
        or None at end-of-stream; the blocked seconds are the
        pipeline's prologue stall (PassPreloader.wait)."""
        return self.pre.wait()

    # ---- consume / epilogue (pass-window tables) ---------------------
    def begin_pass(self) -> int:
        """Consume the head queued stage: reconcile the staged working
        set into the HBM window (steady state: no fetch wait, no inline
        eviction — both already rode background lanes) and point the
        trainer's jit state at it."""
        if self.table is None:
            return 0
        with self._lock:
            if not self._key_q:
                raise RuntimeError("begin_pass with no staged pass — "
                                   "call wait() first")
            keys = self._key_q[0]
        # pop only AFTER the table accepted the pass: a raising
        # begin_pass leaves both queues ALIGNED — the table restores a
        # consumed stage to its queue head on failure (ps/tiered), so
        # drain() still releases every pin and the error surfaces
        # consistently (a partially-promoted pass must not be blindly
        # retried; see the table-side note)
        n = self.table.begin_pass(keys)
        with self._lock:
            if self._key_q and self._key_q[0] is keys:
                self._key_q.popleft()
        # boundary attribution for the upcoming pass event
        # (obs/trace critical_path): the begin-stall pieces the table
        # just measured (~0 in steady state — the point of the pipeline)
        from paddlebox_tpu.obs import trace
        lp = getattr(self.table, "last_pass_stats", None) or {}
        for stage, key in (("stage_wait", "stage_wait_sec"),
                           ("evict_scatter", "evict_scatter_sec"),
                           ("evict_emergency", "evict_emergency_sec"),
                           ("ssd_promote", "ssd_promote_wait_sec")):
            trace.note_pass_part(stage, float(lp.get(key, 0.0) or 0.0))
        if self.trainer is not None:
            self.trainer.adopt_table()
        return n

    def end_pass(self) -> int:
        """Close the open pass: write-back submits to the epilogue lane
        (async), which also runs the next queued stage's capacity
        eviction and any SSD watermark demotion. The submit cost and
        the main-thread fence wait it exposed are reported into the
        NEXT pass event's critical_path block (they stall the next
        boundary, not the pass that already emitted its event)."""
        if self.table is None:
            return 0
        if self.trainer is not None:
            self.trainer.sync_table()
        t0 = time.perf_counter()
        n = self.table.end_pass()
        from paddlebox_tpu.obs import trace
        trace.note_pass_part("end_submit", time.perf_counter() - t0)
        eps = getattr(self.table, "endpass_stats", None)
        if eps is not None:
            cur = float(eps().get("critical_fence_wait_sec", 0.0))
            mark, self._fence_wait_mark = self._fence_wait_mark, cur
            trace.note_pass_part("fence_wait", cur - mark)
        return n

    # ---- shutdown ----------------------------------------------------
    def drain(self, timeout: Optional[float] = None) -> None:
        """Stop building, join the worker, settle in-flight transfers,
        and DISCARD queued stages that will never begin (releasing
        their plan-pending pins — ps/tiered.discard_queued_stages)."""
        self.pre.drain(timeout)
        if self.table is not None:
            discard = getattr(self.table, "discard_queued_stages", None)
            if discard is not None:
                discard()
        with self._lock:
            self._key_q.clear()

    # ---- accounting pass-throughs (bench / telemetry) ----------------
    @property
    def depth(self) -> int:
        return self.pre.depth

    @property
    def builds(self) -> int:
        return self.pre.builds

    @property
    def build_sec_total(self) -> float:
        return self.pre.build_sec_total

    @property
    def wait_sec_total(self) -> float:
        return self.pre.wait_sec_total

    @property
    def build_stage_sec(self) -> Dict[str, float]:
        return self.pre.build_stage_sec

    @property
    def depth_clamped(self) -> bool:
        return self.pre.depth_clamped
