"""Online-serving loader — the consumer of base/delta model exports.

Reference flow (SURVEY.md §3.4): ``SaveBase`` writes the day-level batch
model plus the "xbox" serving model, ``SaveDelta`` ships incremental row
updates to the online serving fleet; serving processes load base + apply
deltas and answer embedding lookups / CTR predictions
(box_wrapper.cc:1383,1406; the closed xbox server consumed these files).

TPU-native equivalent: the same ``.npz`` artifacts written by
``EmbeddingTable.save_base/save_delta`` (or the CheckpointManager) load
into a read-only ``ServingModel`` that answers:

- ``embed_lookup(keys)`` — raw feature rows for feature-store style use;
- ``predict(batch)``     — full CTR forward (pull → fused_seqpool_cvm →
  dense net), eval semantics: unknown keys read as zeros, nothing trains.

Kept deliberately dependency-light: one table + a flax module + params,
jit-compiled per batch bucket; suitable for a CPU host or a TPU chip.
"""

from __future__ import annotations

import os
import pickle
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.artifacts import (ArtifactLineageError,
                                     manifest_beside, verify_payload)
from paddlebox_tpu.data.batch import SlotBatch
from paddlebox_tpu.data.schema import DataFeedDesc
from paddlebox_tpu.ops import fused_seqpool_cvm
from paddlebox_tpu.ps.sgd import SparseSGDConfig
from paddlebox_tpu.ps.table import (EmbeddingTable, expand_pull,
                                    gather_full_rows, pull_values)
from paddlebox_tpu.train.step import (DeviceBatch, make_device_batch,
                                      unpack_floats)
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class ServingModel:
    """Read-only base+delta consumer (the xbox-server role)."""

    def __init__(self, model, desc: DataFeedDesc, mf_dim: int,
                 capacity: int = 1 << 20, use_cvm: bool = True,
                 cvm_offset: int = 2, need_filter: bool = False,
                 quant_ratio: int = 0) -> None:
        """The seqpool knobs (cvm_offset/need_filter/quant_ratio) must
        match the TrainStep that produced the dense params, exactly as in
        TrainStep._step — they change the pooled features."""
        self.model = model
        self.desc = desc
        self.use_cvm = use_cvm
        self.cvm_offset = cvm_offset
        self.need_filter = need_filter
        self.quant_ratio = quant_ratio
        self.table = EmbeddingTable(mf_dim=mf_dim, capacity=capacity,
                                    cfg=SparseSGDConfig())
        self.params = None
        self._host_data: Optional[np.ndarray] = None  # lookup cache
        b = self.desc.batch_size
        s = len(self.desc.sparse_slots)

        @jax.jit
        def _fwd(table_state, params, dev: DeviceBatch):
            from paddlebox_tpu.train.step import ctr_forward
            return ctr_forward(
                table_state, params, self.model, dev, b, s,
                self.use_cvm, self.cvm_offset, self.need_filter,
                self.quant_ratio)

        self._fwd = _fwd  # jit retraces per batch-bucket shape itself

    # ---- artifact loading ----
    # Published-version state (artifacts.py): the id the loaded state
    # descends from, and the open handle's lease when adopted through
    # an ArtifactStore — docs/RESILIENCE.md §Publishing.
    _adopted_aid: Optional[str] = None
    _handle = None

    def _verify_managed(self, path: str, parent_check: bool) -> Optional[str]:
        """When ``path`` sits inside a published version dir (a
        MANIFEST.json lives next to it), verify the payload's sha256
        and — for deltas — that the version's parent IS the currently
        loaded version. Returns the manifest's artifact id, or None
        for a plain legacy file. Refuses LOUDLY on any mismatch: an
        out-of-order / wrong-parent / bit-flipped delta must never
        merge silently (ISSUE 14 satellite)."""
        m = manifest_beside(path)   # raises ArtifactCorruptError if torn
        if m is None:
            if parent_check and self._adopted_aid is not None:
                raise ArtifactLineageError(
                    f"refusing unmanaged delta {path}: this model was "
                    f"adopted from artifact {self._adopted_aid} and a "
                    "manifest-less file cannot be lineage-verified — "
                    "publish the delta or load_base a fresh state")
            return None
        verify_payload(m, path)     # sha256 — refuses corrupt payloads
        if parent_check and m.get("parent") != self._adopted_aid:
            raise ArtifactLineageError(
                f"refusing out-of-order delta {os.path.basename(path)}: "
                f"artifact {m.get('artifact')} descends from "
                f"{m.get('parent')!r} but the loaded state is "
                f"{self._adopted_aid!r} — apply the chain in lineage "
                "order")
        return m.get("artifact")

    def load_base(self, path: str) -> int:
        """Replace the table with a save_base artifact. A base inside a
        published version dir is checksum-verified first and pins the
        lineage every later ``apply_delta`` must extend."""
        aid = self._verify_managed(path, parent_check=False)
        n = self.table.load(path, merge=False)
        self._adopted_aid = aid
        self._rebase_handle(aid)
        self._host_data = None
        log.info("serving: loaded base %s (%d rows%s)", path, n,
                 f", artifact {aid}" if aid else "")
        return n

    def apply_delta(self, path: str) -> int:
        """Apply a save_delta artifact on top (incremental row updates).

        Deltas published through the artifact layer are verified BEFORE
        they touch the table: payload sha256 against the manifest, and
        the manifest's parent link against the currently loaded
        version — a wrong-parent or bit-flipped delta raises
        (``ArtifactLineageError`` / ``ArtifactCorruptError``) instead
        of silently merging. Plain legacy files (no MANIFEST.json next
        to them) keep the unverified behavior — unless the loaded
        state itself came from an artifact, in which case an
        unverifiable delta is refused too."""
        aid = self._verify_managed(path, parent_check=True)
        n = self.table.load(path, merge=True)
        if aid is not None:
            self._adopted_aid = aid
        self._rebase_handle(self._adopted_aid)
        self._host_data = None
        log.info("serving: applied delta %s (%d rows%s)", path, n,
                 f", artifact {aid}" if aid else "")
        return n

    def _rebase_handle(self, aid: Optional[str]) -> None:
        """Path-based loads rebase the lineage; a handle still leasing
        the PREVIOUS version would silently pin it (and its chain)
        against retention while nothing serves from it — drop the
        lease unless the handle matches the new state."""
        if self._handle is not None and self._handle.aid != aid:
            self._handle.close()
            self._handle = None

    # ---- store adoption (the lease-fenced consumer path) ----
    def adopt(self, store, version: Optional[str] = None) -> str:
        """Adopt a published version from an ``ArtifactStore``: takes a
        reader lease, verifies the FULL checksum+lineage chain before
        touching any state, then loads base → deltas (and the dense
        params when the version carries them). With ``version=None``
        adopts the newest VERIFIABLE version (corrupt tips are refused
        loudly and skipped). Returns the adopted artifact id; the lease
        is held until ``release()``/the next ``adopt`` so retention can
        never sweep the version mid-serve."""
        handle = store.open(version)
        self._load_from(handle, start=0, fresh=True)
        log.info("serving: adopted artifact %s (chain %s)", handle.aid,
                 [m["artifact"] for m in handle.chain])
        return handle.aid

    def _load_from(self, handle, start: int, fresh: bool) -> None:
        """Load a (suffix of a) verified chain from an open handle,
        then swap it in as the held lease. The handle is closed on any
        failure — no lease leaks, and the caller's old handle stays
        live until the new state fully loaded."""
        try:
            first = fresh
            for m in handle.chain[start:]:
                name = ("sparse.npz" if m["kind"] == "base"
                        else "sparse_delta.npz")
                self.table.load(handle.path(name, m["artifact"]),
                                merge=not first)
                first = False
            if "dense.pkl" in handle.manifest.get("files", {}):
                self.load_dense(handle.path("dense.pkl"))
        except BaseException:
            handle.close()
            raise
        if self._handle is not None:
            self._handle.close()
        self._handle = handle
        self._adopted_aid = handle.aid
        self._host_data = None

    def hot_reload(self, store) -> Optional[str]:
        """Advance to the newest verifiable version, applying ONLY the
        new deltas when its chain extends the adopted state (the
        delta hot-reload path); falls back to a full re-adopt when the
        lineage diverged. No-op (returns None) when already current."""
        handle = store.open()
        if handle.aid == self._adopted_aid:
            handle.close()
            return None
        chain_ids = [m["artifact"] for m in handle.chain]
        if self._adopted_aid in chain_ids:
            # the new tip extends us: apply only the new deltas
            self._load_from(
                handle, start=chain_ids.index(self._adopted_aid) + 1,
                fresh=False)
        else:
            # diverged lineage (rollback / new base): full re-adopt
            self._load_from(handle, start=0, fresh=True)
        log.info("serving: hot-reloaded to artifact %s", handle.aid)
        return handle.aid

    def release(self) -> None:
        """Drop the artifact lease (retention may sweep the version)."""
        if self._handle is not None:
            self._handle.close()
            self._handle = None

    def load_dense(self, path: str) -> None:
        """Load dense params — accepts the trainer's ``.dense.pkl``
        (params, opt_state) or a CheckpointManager ``dense.pkl``
        (params, opt_state, auc); only params are used."""
        with open(path, "rb") as fh:
            blob = pickle.load(fh)
        self.params = jax.device_put(
            blob[0] if isinstance(blob, tuple) else blob)

    # ---- queries ----
    def embed_lookup(self, keys: np.ndarray) -> np.ndarray:
        """[n] uint64 → [n, 3+mf] pull values (show, clk, w, embedx…);
        unknown keys → zeros. Serves from a cached host mirror of the
        table (invalidated by load_base/apply_delta)."""
        if self._host_data is None:
            self._host_data = np.asarray(
                jax.device_get(self.table.state.data))
        return self.table.host_pull(keys, data=self._host_data)

    def predict(self, batch: SlotBatch,
                return_valid: bool = False):
        """CTR predictions for one batch (unknown keys pull zeros).

        A batch shorter than ``desc.batch_size`` is padded; padding
        entries hold the net's output on zero rows, NOT real
        predictions — pass ``return_valid=True`` to also get the 0/1
        validity mask and filter them."""
        if self.params is None:
            raise RuntimeError("load_dense first")
        idx = self.table.prepare_eval(batch)
        dev = make_device_batch(batch, idx)
        pred, ins_w = self._fwd(self.table.state, self.params, dev)
        if return_valid:
            return np.asarray(pred), np.asarray(ins_w)
        return np.asarray(pred)


class MultiMfServingModel:
    """Read-only base+delta consumer for MULTI-MF saves (per-slot
    embedding dims, feature_value.h:42-185): loads the per-dim-class
    artifacts written by ``MultiMfEmbeddingTable.save_base/save_delta``
    (``{path}.mf{D}.npz``), answers per-slot-width lookups and full CTR
    predictions through the canonical slot-ordered pooled concat — the
    same forward as ``MultiMfTrainStep``."""

    def __init__(self, model, desc: DataFeedDesc, slot_mf_dims,
                 capacity: int = 1 << 20, use_cvm: bool = True,
                 cvm_offset: int = 2) -> None:
        from paddlebox_tpu.ps.multi_mf import MultiMfEmbeddingTable
        self.model = model
        self.desc = desc
        self.use_cvm = use_cvm
        self.cvm_offset = cvm_offset
        self.table = MultiMfEmbeddingTable(
            slot_mf_dims, capacity=capacity, cfg=SparseSGDConfig())
        self.params = None
        t = self.table
        route = tuple((int(t.class_of_slot[s]), int(t.slot_rank[s]))
                      for s in range(t.num_slots))
        class_slots = tuple(len(s) for s in t.class_slots)
        mf_dims = tuple(t.dims)

        @jax.jit
        def _fwd(table_states, params, devs):
            # per-class pull → seqpool over the class's slots →
            # canonical slot-order concat — MultiMfTrainStep._pooled's
            # forward, compiled once per batch bucket
            d0 = devs[0]
            show_clk = jnp.stack([d0.show, d0.clk], axis=1)
            parts = []
            for c, (st, dev) in enumerate(zip(table_states, devs)):
                vals_u = pull_values(
                    gather_full_rows(st, dev.unique_rows), mf_dims[c])
                values_k = expand_pull(vals_u, dev.gather_idx)
                parts.append(fused_seqpool_cvm(
                    values_k, dev.segments, show_clk,
                    d0.label.shape[0], class_slots[c],
                    self.use_cvm, self.cvm_offset))
            flat = jnp.concatenate(
                [parts[c][:, r, :] for c, r in route], axis=1)
            logits = self.model.apply(params, flat, d0.dense)
            return (jax.nn.sigmoid(logits),
                    (d0.show > 0).astype(jnp.float32))

        self._fwd = _fwd

    # ---- artifact loading (multi-mf save format) ----
    def load_base(self, path: str) -> int:
        """Load a MultiMfEmbeddingTable.save_base artifact set."""
        n = self.table.load(path, merge=False)
        log.info("serving: loaded multi-mf base %s (%d rows)", path, n)
        return n

    def apply_delta(self, path: str) -> int:
        n = self.table.load(path, merge=True)
        log.info("serving: applied multi-mf delta %s (%d rows)", path, n)
        return n

    load_dense = ServingModel.load_dense

    # ---- queries ----
    def embed_lookup(self, keys: np.ndarray,
                     slots: np.ndarray) -> np.ndarray:
        """[n] keys + their slot ids → [n, 3 + max_mf] pull values with
        PER-SLOT widths (columns beyond the key's slot width are zero) —
        the dy_mf CopyForPull contract. Unknown keys read zeros."""
        return self.table.pull(keys, slots)

    def slot_width(self, slot: int) -> int:
        """Embedding width (3 + mf_dim) served for a slot."""
        return 3 + int(self.table.slot_mf_dims[slot])

    def predict(self, batch: SlotBatch, return_valid: bool = False):
        """CTR predictions via the jitted multi-mf forward (eval
        semantics: unknown keys zeros, nothing trains)."""
        if self.params is None:
            raise RuntimeError("load_dense first")
        subs, _ = self.table.split_batch(batch)
        devs = []
        for sub, t in zip(subs, self.table.tables):
            idx = t.prepare_eval(sub)
            devs.append(make_device_batch(
                sub, idx, floats=devs[0].floats if devs else None))
        pred, ins_w = self._fwd(
            tuple(t.state for t in self.table.tables),
            self.params, tuple(devs))
        if return_valid:
            return np.asarray(pred), np.asarray(ins_w)
        return np.asarray(pred)
