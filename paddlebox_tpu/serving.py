"""Online-serving loader — the consumer of base/delta model exports.

Reference flow (SURVEY.md §3.4): ``SaveBase`` writes the day-level batch
model plus the "xbox" serving model, ``SaveDelta`` ships incremental row
updates to the online serving fleet; serving processes load base + apply
deltas and answer embedding lookups / CTR predictions
(box_wrapper.cc:1383,1406; the closed xbox server consumed these files).

TPU-native equivalent: the same ``.npz`` artifacts written by
``EmbeddingTable.save_base/save_delta`` (or the CheckpointManager) load
into a read-only ``ServingModel`` that answers:

- ``embed_lookup(keys)`` — raw feature rows for feature-store style use;
- ``predict(batch)``     — full CTR forward (pull → fused_seqpool_cvm →
  dense net), eval semantics: unknown keys read as zeros, nothing trains.

Kept deliberately dependency-light: one table + a flax module + params,
jit-compiled per batch bucket; suitable for a CPU host or a TPU chip.
"""

from __future__ import annotations

import pickle
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.data.batch import SlotBatch
from paddlebox_tpu.data.schema import DataFeedDesc
from paddlebox_tpu.ops import fused_seqpool_cvm
from paddlebox_tpu.ps.sgd import SparseSGDConfig
from paddlebox_tpu.ps.table import EmbeddingTable
from paddlebox_tpu.train.step import (DeviceBatch, make_device_batch,
                                      unpack_floats)
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class ServingModel:
    """Read-only base+delta consumer (the xbox-server role)."""

    def __init__(self, model, desc: DataFeedDesc, mf_dim: int,
                 capacity: int = 1 << 20, use_cvm: bool = True,
                 cvm_offset: int = 2, need_filter: bool = False,
                 quant_ratio: int = 0) -> None:
        """The seqpool knobs (cvm_offset/need_filter/quant_ratio) must
        match the TrainStep that produced the dense params, exactly as in
        TrainStep._step — they change the pooled features."""
        self.model = model
        self.desc = desc
        self.use_cvm = use_cvm
        self.cvm_offset = cvm_offset
        self.need_filter = need_filter
        self.quant_ratio = quant_ratio
        self.table = EmbeddingTable(mf_dim=mf_dim, capacity=capacity,
                                    cfg=SparseSGDConfig())
        self.params = None
        self._host_data: Optional[np.ndarray] = None  # lookup cache
        b = self.desc.batch_size
        s = len(self.desc.sparse_slots)

        @jax.jit
        def _fwd(table_state, params, dev: DeviceBatch):
            from paddlebox_tpu.train.step import ctr_forward
            return ctr_forward(
                table_state, params, self.model, dev, b, s,
                self.use_cvm, self.cvm_offset, self.need_filter,
                self.quant_ratio)

        self._fwd = _fwd  # jit retraces per batch-bucket shape itself

    # ---- artifact loading ----
    def load_base(self, path: str) -> int:
        """Replace the table with a save_base artifact."""
        n = self.table.load(path, merge=False)
        self._host_data = None
        log.info("serving: loaded base %s (%d rows)", path, n)
        return n

    def apply_delta(self, path: str) -> int:
        """Apply a save_delta artifact on top (incremental row updates)."""
        n = self.table.load(path, merge=True)
        self._host_data = None
        log.info("serving: applied delta %s (%d rows)", path, n)
        return n

    def load_dense(self, path: str) -> None:
        """Load dense params — accepts the trainer's ``.dense.pkl``
        (params, opt_state) or a CheckpointManager ``dense.pkl``
        (params, opt_state, auc); only params are used."""
        with open(path, "rb") as fh:
            blob = pickle.load(fh)
        self.params = jax.device_put(
            blob[0] if isinstance(blob, tuple) else blob)

    # ---- queries ----
    def embed_lookup(self, keys: np.ndarray) -> np.ndarray:
        """[n] uint64 → [n, 3+mf] pull values (show, clk, w, embedx…);
        unknown keys → zeros. Serves from a cached host mirror of the
        table (invalidated by load_base/apply_delta)."""
        if self._host_data is None:
            self._host_data = np.asarray(
                jax.device_get(self.table.state.data))
        return self.table.host_pull(keys, data=self._host_data)

    def predict(self, batch: SlotBatch,
                return_valid: bool = False):
        """CTR predictions for one batch (unknown keys pull zeros).

        A batch shorter than ``desc.batch_size`` is padded; padding
        entries hold the net's output on zero rows, NOT real
        predictions — pass ``return_valid=True`` to also get the 0/1
        validity mask and filter them."""
        if self.params is None:
            raise RuntimeError("load_dense first")
        idx = self.table.prepare_eval(batch)
        dev = make_device_batch(batch, idx)
        pred, ins_w = self._fwd(self.table.state, self.params, dev)
        if return_valid:
            return np.asarray(pred), np.asarray(ins_w)
        return np.asarray(pred)
