"""Online-serving loader — the consumer of base/delta model exports.

Reference flow (SURVEY.md §3.4): ``SaveBase`` writes the day-level batch
model plus the "xbox" serving model, ``SaveDelta`` ships incremental row
updates to the online serving fleet; serving processes load base + apply
deltas and answer embedding lookups / CTR predictions
(box_wrapper.cc:1383,1406; the closed xbox server consumed these files).

TPU-native equivalent: the same ``.npz`` artifacts written by
``EmbeddingTable.save_base/save_delta`` (or the CheckpointManager) load
into a read-only ``ServingModel`` that answers:

- ``embed_lookup(keys)`` — raw feature rows for feature-store style use;
- ``predict(batch)``     — full CTR forward (pull → fused_seqpool_cvm →
  dense net), eval semantics: unknown keys read as zeros, nothing trains;
- ``predict_many(...)``  — the batched inference path: micro-batches a
  request stream through ONE snapshot (docs/SERVING.md).

Concurrent serving (ISSUE 15 tentpole — serve-while-training): queries
never read mutable loader state. Every adoption **materializes an
immutable ``ServingSnapshot``** (copy-on-publish: a frozen key index +
the persistent jax table value + a host mirror + the dense params +
the artifact id, all captured together) and swaps it in with a single
atomic pointer assignment. A query fences ONCE (one attribute read of
``self._snap``) and then works exclusively off that snapshot — it can
never block on, or be torn by, a concurrent hot-reload; a snapshot that
has been swapped out keeps answering readers already inside it (the
data is fully in-memory — no file access after materialization, so even
a retention sweep of its version cannot hurt in-flight queries).

The **background hot-reload loop** (:class:`ReloadLoop`) polls the
``ArtifactStore`` tip, verifies-before-swap on the lease/chain machinery
(artifacts.py) and on a corrupt or torn tip DEGRADES LOUDLY — keeps
serving the prior snapshot, books
``pbox_serving_reload_{adopted,refused,degraded}_total`` and the
``pbox_serving_staleness_sec`` gauge, re-polls on the seeded
RetryPolicy backoff — and never crashes or blocks the query path.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.artifacts import (ArtifactCorruptError,
                                     ArtifactLineageError,
                                     manifest_beside, verify_payload)
from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.data.batch import BatchBuilder, SlotBatch
from paddlebox_tpu.data.schema import DataFeedDesc
from paddlebox_tpu.ops import fused_seqpool_cvm
from paddlebox_tpu.ps.sgd import SparseSGDConfig
from paddlebox_tpu.ps.table import (EmbeddingTable, TableState,
                                    expand_pull, gather_full_rows,
                                    pull_values)
from paddlebox_tpu.train.step import DeviceBatch, make_device_batch
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


def _counter(name: str, help_: str, **labels) -> None:
    try:
        from paddlebox_tpu.obs.hub import get_hub
        get_hub().counter(name, help_).inc(**labels)
    except Exception:
        log.debug("serving counter failed", exc_info=True)


def _emit(event: str, **fields) -> None:
    try:
        from paddlebox_tpu.obs.hub import get_hub
        hub = get_hub()
        if hub.active:
            hub.emit(event, **fields)
    except Exception:
        log.debug("serving event emit failed", exc_info=True)


class ServingSnapshot:
    """One immutable, fully-materialized read view: a frozen
    ``EmbeddingTable`` (private index, the persistent jax state value),
    a host mirror for lock-free lookups, the dense params and the
    artifact identity they were captured with. NOTHING mutates a
    snapshot after construction — the serving contract; the private
    ``host_lock`` inside ``prepare_eval`` is uncontended by design (no
    writer ever takes a snapshot's lock)."""

    __slots__ = ("aid", "epoch", "created_unix", "adopted_ts", "table",
                 "params", "host_data", "rows")

    def __init__(self, table: EmbeddingTable, params,
                 host_data: np.ndarray, aid: Optional[str],
                 epoch: Optional[int],
                 created_unix: Optional[float]) -> None:
        self.table = table
        self.params = params
        self.host_data = host_data
        self.aid = aid
        self.epoch = epoch
        self.created_unix = created_unix
        self.adopted_ts = time.time()
        self.rows = len(table.index)

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        """[n] uint64 → [n, 3+mf] pull values off the host mirror;
        unknown keys → zeros. Pure numpy over frozen arrays — lock-free
        and immune to concurrent reloads."""
        return self.table.host_pull(keys, data=self.host_data)

    def digest(self) -> str:
        """sha256 over the snapshot's logical rows sorted by feasign —
        ONE definition shared with the writer-side fingerprint: the
        frozen table's ``EmbeddingTable.rows_digest`` (the table is
        immutable, so this is as read-only as everything else here)."""
        return self.table.rows_digest()


class ServingModel:
    """Read-only base+delta consumer (the xbox-server role)."""

    def __init__(self, model, desc: DataFeedDesc, mf_dim: int,
                 capacity: int = 1 << 20, use_cvm: bool = True,
                 cvm_offset: int = 2, need_filter: bool = False,
                 quant_ratio: int = 0) -> None:
        """The seqpool knobs (cvm_offset/need_filter/quant_ratio) must
        match the TrainStep that produced the dense params, exactly as in
        TrainStep._step — they change the pooled features."""
        self.model = model
        self.desc = desc
        self.use_cvm = use_cvm
        self.cvm_offset = cvm_offset
        self.need_filter = need_filter
        self.quant_ratio = quant_ratio
        self.mf_dim = mf_dim
        self.capacity = capacity
        self._cfg = SparseSGDConfig()
        #: the LOADER table: the single-writer working state the
        #: load/adopt paths mutate. Queries never read it — they read
        #: the immutable snapshot materialized from it.
        self.table = EmbeddingTable(mf_dim=mf_dim, capacity=capacity,
                                    cfg=self._cfg)
        self.params = None
        import functools
        s = len(self.desc.sparse_slots)

        @functools.partial(jax.jit, static_argnums=(3,))
        def _fwd(table_state, params, dev: DeviceBatch, bs: int):
            from paddlebox_tpu.train.step import ctr_forward
            return ctr_forward(
                table_state, params, self.model, dev, bs, s,
                self.use_cvm, self.cvm_offset, self.need_filter,
                self.quant_ratio)

        # jit retraces per (batch bucket, batch size): the full desc
        # bucket for predict(), plus one variant per predict_many
        # micro-batch width — that's what makes serving_batch_max a
        # REAL latency knob (chunks compute chunk-wide dense forwards,
        # not full-bucket ones)
        self._fwd = _fwd
        # ---- concurrent-serving state (docs/SERVING.md) ----
        # one atomic pointer: queries read it ONCE (the fence) and then
        # never touch model state again. Writers (adopt/hot_reload/
        # load_*) serialize on _reload_lock and assign a fully-built
        # replacement — the swap is a plain attribute store.
        self._snap: Optional[ServingSnapshot] = None
        self._reload_lock = threading.RLock()
        # False after a failed/partial chain load: the next reload must
        # re-adopt from scratch instead of stacking deltas on a state
        # of unknown completeness
        self._loader_clean = True
        self._last_reload_ts: Optional[float] = None
        self._staleness_sec: float = 0.0

    # ---- artifact loading ----
    # Published-version state (artifacts.py): the id the loaded state
    # descends from, and the open handle's lease when adopted through
    # an ArtifactStore — docs/RESILIENCE.md §Publishing.
    _adopted_aid: Optional[str] = None
    _handle = None

    @property
    def adopted_aid(self) -> Optional[str]:
        return self._adopted_aid

    def _verify_managed(self, path: str, parent_check: bool) -> Optional[dict]:
        """When ``path`` sits inside a published version dir (a
        MANIFEST.json lives next to it), verify the payload's sha256
        and — for deltas — that the version's parent IS the currently
        loaded version. Returns the manifest, or None for a plain
        legacy file. Refuses LOUDLY on any mismatch: an out-of-order /
        wrong-parent / bit-flipped delta must never merge silently
        (ISSUE 14 satellite)."""
        m = manifest_beside(path)   # raises ArtifactCorruptError if torn
        if m is None:
            if parent_check and self._adopted_aid is not None:
                raise ArtifactLineageError(
                    f"refusing unmanaged delta {path}: this model was "
                    f"adopted from artifact {self._adopted_aid} and a "
                    "manifest-less file cannot be lineage-verified — "
                    "publish the delta or load_base a fresh state")
            return None
        verify_payload(m, path)     # sha256 — refuses corrupt payloads
        if parent_check and m.get("parent") != self._adopted_aid:
            raise ArtifactLineageError(
                f"refusing out-of-order delta {os.path.basename(path)}: "
                f"artifact {m.get('artifact')} descends from "
                f"{m.get('parent')!r} but the loaded state is "
                f"{self._adopted_aid!r} — apply the chain in lineage "
                "order")
        return m

    def load_base(self, path: str) -> int:
        """Replace the table with a save_base artifact. A base inside a
        published version dir is checksum-verified first and pins the
        lineage every later ``apply_delta`` must extend."""
        with self._reload_lock:
            m = self._verify_managed(path, parent_check=False)
            aid = m.get("artifact") if m else None
            self._loader_clean = False
            n = self.table.load(path, merge=False)
            self._loader_clean = True
            self._adopted_aid = aid
            self._rebase_handle(aid)
            self._refresh_snapshot(m)
        log.info("serving: loaded base %s (%d rows%s)", path, n,
                 f", artifact {aid}" if aid else "")
        return n

    def apply_delta(self, path: str) -> int:
        """Apply a save_delta artifact on top (incremental row updates).

        Deltas published through the artifact layer are verified BEFORE
        they touch the table: payload sha256 against the manifest, and
        the manifest's parent link against the currently loaded
        version — a wrong-parent or bit-flipped delta raises
        (``ArtifactLineageError`` / ``ArtifactCorruptError``) instead
        of silently merging. Plain legacy files (no MANIFEST.json next
        to them) keep the unverified behavior — unless the loaded
        state itself came from an artifact, in which case an
        unverifiable delta is refused too."""
        with self._reload_lock:
            m = self._verify_managed(path, parent_check=True)
            aid = m.get("artifact") if m else None
            self._loader_clean = False
            n = self.table.load(path, merge=True)
            self._loader_clean = True
            if aid is not None:
                self._adopted_aid = aid
            self._rebase_handle(self._adopted_aid)
            self._refresh_snapshot(m)
        log.info("serving: applied delta %s (%d rows%s)", path, n,
                 f", artifact {aid}" if aid else "")
        return n

    def _rebase_handle(self, aid: Optional[str]) -> None:
        """Path-based loads rebase the lineage; a handle still leasing
        the PREVIOUS version would silently pin it (and its chain)
        against retention while nothing serves from it — drop the
        lease unless the handle matches the new state."""
        if self._handle is not None and self._handle.aid != aid:
            self._handle.close()
            self._handle = None

    # ---- snapshot materialization (copy-on-publish) --------------------
    def _materialize(self, manifest: Optional[dict]) -> ServingSnapshot:
        """Freeze the loader's current state into an immutable
        snapshot. Cheap by construction: the jax table state is a
        persistent value (every load builds a NEW ``TableState``), so
        only the key index is copied; the one host D2H mirrors the
        packed rows for lock-free lookups."""
        loader = self.table
        with loader.host_lock:
            keys, rows = loader.index.items()
        state = loader.state
        frozen = EmbeddingTable(mf_dim=loader.mf_dim,
                                capacity=loader.capacity, cfg=loader.cfg)
        frozen.slot_host = loader.slot_host.copy()
        if len(keys):
            order = np.argsort(rows)
            got = frozen.index.assign(keys[order])
            if not np.array_equal(got, rows[order]):
                # allocator gave the fresh index a different layout
                # (holes after a shrink, arena tables): repack the
                # state — and the per-row slot metadata — into the
                # frozen index's row order instead of assuming row
                # identity
                data = np.asarray(jax.device_get(state.data))
                logical = np.zeros_like(data)
                logical[got] = data[rows[order]]
                state = TableState.from_logical(logical, loader.capacity,
                                                ext=loader.opt_ext)
                frozen.slot_host = np.zeros_like(loader.slot_host)
                frozen.slot_host[got] = loader.slot_host[rows[order]]
        frozen.state = state
        host_data = np.asarray(jax.device_get(state.data))
        m = manifest or {}
        return ServingSnapshot(
            frozen, self.params, host_data,
            aid=self._adopted_aid,
            epoch=m.get("epoch"), created_unix=m.get("created_unix"))

    def _refresh_snapshot(self, manifest: Optional[dict]) -> None:
        """Build-then-swap (caller holds ``_reload_lock``): readers on
        the old snapshot finish there; new fences see the new one."""
        self._snap = self._materialize(manifest)
        self._last_reload_ts = time.time()
        self._staleness_sec = 0.0

    def _ensure_snapshot(self) -> ServingSnapshot:
        """THE query fence: one atomic read. The slow path (first query
        before any load, or after a legacy path-based load sequence)
        materializes under the reload lock; store adoptions always
        swap eagerly so concurrent queries never take this lock."""
        snap = self._snap
        if snap is not None:
            return snap
        with self._reload_lock:
            if self._snap is None:
                self._refresh_snapshot(None)
            return self._snap

    def snapshot(self) -> ServingSnapshot:
        """The currently-serving immutable snapshot (public fence —
        callers doing multi-query work pin one and reuse it)."""
        return self._ensure_snapshot()

    def serving_status(self) -> dict:
        """The /healthz ``serving`` block (obs/hub.set_serving_probe):
        adopted version id, adoption epoch, last reload wall clock,
        snapshot staleness vs the newest published version, and the
        SLO verdict against ``FLAGS.serving_staleness_max_sec``."""
        snap = self._snap
        stale_max = FLAGS.serving_staleness_max_sec
        return {
            "adopted": self._adopted_aid,
            "epoch": snap.epoch if snap is not None else None,
            "rows": snap.rows if snap is not None else 0,
            "last_reload_ts": self._last_reload_ts,
            "staleness_sec": round(self._staleness_sec, 3),
            "stale": bool(stale_max > 0
                          and self._staleness_sec > stale_max),
        }

    def register_health(self, hub=None) -> None:
        """Register this model as the process's serving health surface:
        /healthz grows the ``serving`` block and /readyz starts
        answering 503-until-first-adoption (obs/hub). NOT automatic on
        ``adopt`` — auxiliary consumers (replay oracles, verification
        readers) adopt too, and the last registration wins; the health
        surface belongs to the model explicitly registered (or driven
        by a :class:`ReloadLoop`, whose ``start`` registers it)."""
        from paddlebox_tpu.obs.hub import get_hub
        (hub or get_hub()).set_serving_probe(self.serving_status)

    # ---- store adoption (the lease-fenced consumer path) ----
    def adopt(self, store, version: Optional[str] = None) -> str:
        """Adopt a published version from an ``ArtifactStore``: takes a
        reader lease, verifies the FULL checksum+lineage chain before
        touching any state, then loads base → deltas (and the dense
        params when the version carries them), materializes the
        immutable snapshot and swaps it in. With ``version=None``
        adopts the newest VERIFIABLE version (corrupt tips are refused
        loudly and skipped). Returns the adopted artifact id; the lease
        is held until ``release()``/the next ``adopt`` so retention can
        never sweep the version mid-serve."""
        with self._reload_lock:
            handle = store.open(version)
            self._load_from(handle, start=0, fresh=True)
            log.info("serving: adopted artifact %s (chain %s)",
                     handle.aid, [m["artifact"] for m in handle.chain])
            return handle.aid

    def _load_from(self, handle, start: int, fresh: bool) -> None:
        """Load a (suffix of a) verified chain from an open handle into
        the loader, then materialize + swap the snapshot and take over
        the lease. The handle is closed on any failure — no lease
        leaks, the old snapshot keeps serving, and the loader is marked
        dirty so the next reload re-adopts from scratch."""
        try:
            if fresh:
                # copy-on-publish: a FRESH loader (never the serving
                # snapshot's index) absorbs the chain
                self.table = EmbeddingTable(mf_dim=self.mf_dim,
                                            capacity=self.capacity,
                                            cfg=self._cfg)
            self._loader_clean = False
            first = fresh
            for m in handle.chain[start:]:
                name = ("sparse.npz" if m["kind"] == "base"
                        else "sparse_delta.npz")
                self.table.load(handle.path(name, m["artifact"]),
                                merge=not first)
                first = False
            if "dense.pkl" in handle.manifest.get("files", {}):
                # raw read — the snapshot below publishes table AND
                # params together (load_dense's own swap would pair
                # new params with the still-serving OLD table)
                self.params = self._read_dense(
                    handle.path("dense.pkl"))
            self._loader_clean = True
        except BaseException:
            handle.close()
            raise
        if self._handle is not None:
            self._handle.close()
        self._handle = handle
        self._adopted_aid = handle.aid
        self._refresh_snapshot(handle.manifest)
        _counter("pbox_serving_reload_adopted_total",
                 "serving snapshot adoptions",
                 kind=handle.manifest.get("kind", "base"))

    def hot_reload(self, store) -> Optional[str]:
        """Advance to the newest verifiable version, applying ONLY the
        new deltas when its chain extends the adopted state (the
        delta hot-reload path); falls back to a full re-adopt when the
        lineage diverged or a previous load left the loader dirty.
        No-op (returns None) when already current. Queries keep
        serving the prior snapshot for the whole duration — the new
        one swaps in only after it fully verified AND materialized."""
        with self._reload_lock:
            handle = store.open()
            if handle.aid == self._adopted_aid:
                handle.close()
                self._staleness_sec = 0.0
                return None
            chain_ids = [m["artifact"] for m in handle.chain]
            if self._adopted_aid in chain_ids and self._loader_clean:
                # the new tip extends us: apply only the new deltas
                self._load_from(
                    handle,
                    start=chain_ids.index(self._adopted_aid) + 1,
                    fresh=False)
            else:
                # diverged lineage (rollback / new base) or dirty
                # loader: full re-adopt
                self._load_from(handle, start=0, fresh=True)
            log.info("serving: hot-reloaded to artifact %s", handle.aid)
            return handle.aid

    def release(self) -> None:
        """Drop the artifact lease (retention may sweep the version).
        Idempotent under concurrent callers; readers inside the current
        snapshot are unaffected — its data is in-memory."""
        with self._reload_lock:
            if self._handle is not None:
                self._handle.close()
                self._handle = None

    def note_staleness(self, sec: float) -> None:
        """ReloadLoop's staleness report (serving epoch age vs the
        newest published version) — rides /healthz and the
        ``pbox_serving_staleness_sec`` gauge."""
        self._staleness_sec = float(sec)

    @staticmethod
    def _read_dense(path: str):
        with open(path, "rb") as fh:
            blob = pickle.load(fh)
        return jax.device_put(
            blob[0] if isinstance(blob, tuple) else blob)

    def load_dense(self, path: str) -> None:
        """Load dense params — accepts the trainer's ``.dense.pkl``
        (params, opt_state) or a CheckpointManager ``dense.pkl``
        (params, opt_state, auc); only params are used. A serving
        snapshot already in place gets a PARAMS-ONLY swap (same frozen
        table, new params published atomically) so a dense-only
        refresh reaches queries immediately — and never blocks them."""
        self.params = self._read_dense(path)
        with self._reload_lock:
            snap = self._snap
            if snap is not None:
                self._snap = ServingSnapshot(
                    snap.table, self.params, snap.host_data,
                    aid=snap.aid, epoch=snap.epoch,
                    created_unix=snap.created_unix)

    # ---- queries (snapshot-pinned; docs/SERVING.md) ----
    def _observe_latency(self, op: str, sec: float) -> None:
        try:
            from paddlebox_tpu.obs.hub import get_hub
            from paddlebox_tpu.obs.instruments import \
                SERVING_LATENCY_BUCKETS
            hub = get_hub()
            if hub.active:
                hub.histogram(
                    "pbox_serving_latency_seconds",
                    "serving query latency (per lookup/predict call)",
                    buckets=SERVING_LATENCY_BUCKETS).observe(sec, op=op)
        except Exception:
            log.debug("serving latency observe failed", exc_info=True)

    def embed_lookup(self, keys: np.ndarray) -> np.ndarray:
        """[n] uint64 → [n, 3+mf] pull values (show, clk, w, embedx…);
        unknown keys → zeros. Served lock-free off the current
        snapshot's host mirror."""
        t0 = time.perf_counter()
        out = self._ensure_snapshot().lookup(keys)
        self._observe_latency("lookup", time.perf_counter() - t0)
        return out

    def _predict_on(self, snap: ServingSnapshot, batch: SlotBatch,
                    return_valid: bool):
        if snap.params is None:
            raise RuntimeError("load_dense first")
        idx = snap.table.prepare_eval(batch)
        dev = make_device_batch(batch, idx)
        pred, ins_w = self._fwd(snap.table.state, snap.params, dev,
                                batch.batch_size)
        if return_valid:
            return np.asarray(pred), np.asarray(ins_w)
        return np.asarray(pred)

    def predict(self, batch: SlotBatch,
                return_valid: bool = False):
        """CTR predictions for one batch (unknown keys pull zeros).

        A batch shorter than ``desc.batch_size`` is padded; padding
        entries hold the net's output on zero rows, NOT real
        predictions — pass ``return_valid=True`` to also get the 0/1
        validity mask and filter them."""
        t0 = time.perf_counter()
        out = self._predict_on(self._ensure_snapshot(), batch,
                               return_valid)
        self._observe_latency("predict", time.perf_counter() - t0)
        return out

    def predict_many(self, requests, return_valid: bool = False):
        """The batched inference path: run a request stream through ONE
        pinned snapshot (a hot-reload mid-stream cannot mix versions
        inside the call). ``requests`` is either an iterable of
        ``SlotBatch`` (pre-batched traffic) or a sequence of
        ``SlotRecord`` — records are micro-batched into chunks of at
        most ``FLAGS.serving_batch_max`` (0 = the desc batch size, one
        compiled bucket; a smaller cap builds CHUNK-SIZED batches, so
        each forward computes a chunk-wide dense net — the actual
        per-query latency trade, at the cost of one extra compiled
        variant per chunk width) and only the valid predictions are
        returned. Chunks build and run STREAMED — a long request list
        never materializes all its padded batches up front. Returns
        the concatenated [N] predictions (plus the validity mask with
        ``return_valid=True``); each micro-batch observes its own
        latency sample in ``pbox_serving_latency_seconds``."""
        import dataclasses

        snap = self._ensure_snapshot()
        reqs = list(requests)
        preds: List[np.ndarray] = []
        valids: List[np.ndarray] = []

        def run(batch: SlotBatch, n_valid: int) -> None:
            t0 = time.perf_counter()
            pred, ins_w = self._predict_on(snap, batch,
                                           return_valid=True)
            self._observe_latency("predict",
                                  time.perf_counter() - t0)
            preds.append(pred[:n_valid])
            valids.append(ins_w[:n_valid])

        if reqs and not isinstance(reqs[0], SlotBatch):
            cap = self.desc.batch_size
            m = FLAGS.serving_batch_max
            chunk = cap if m <= 0 else max(1, min(int(m), cap))
            builder = BatchBuilder(
                self.desc if chunk == cap
                else dataclasses.replace(self.desc, batch_size=chunk))
            for i in range(0, len(reqs), chunk):
                part = reqs[i:i + chunk]
                run(builder.build(part), len(part))
        else:
            for b in reqs:
                run(b, b.batch_size)
        if not preds:
            empty = np.empty(0, np.float32)
            return (empty, empty) if return_valid else empty
        pred = np.concatenate(preds)
        if return_valid:
            return pred, np.concatenate(valids)
        return pred


class ReloadLoop:
    """Background hot-reload: polls the ``ArtifactStore`` tip every
    ``FLAGS.serving_reload_poll_sec`` and advances the serving snapshot
    through ``ServingModel.hot_reload``. The robustness contract
    (docs/SERVING.md §Reload/degrade state machine):

    - **verify-before-swap**: adoption rides the store's lease + full
      checksum-chain verification; the snapshot swaps only after the
      new state fully materialized.
    - **degrade, never crash or block**: any poll failure (corrupt tip,
      torn manifest, transient IO past its retries, an injected
      ``serving.reload`` fault) leaves the prior snapshot serving,
      books ``pbox_serving_reload_refused_total{reason}`` + a
      ``serving_reload_refused`` event, and re-polls on the seeded
      RetryPolicy backoff schedule (site ``serving.reload``). A tip
      that exists but cannot be adopted (corrupt → store degraded to
      an older version) additionally books
      ``pbox_serving_reload_degraded_total`` and the staleness gauge —
      the degrade state is loud.
    - **staleness**: ``pbox_serving_staleness_sec`` = how long a newer
      adoptable version has been published without the serving
      snapshot advancing (0 when current); past
      ``FLAGS.serving_staleness_max_sec`` the /healthz serving block
      flips ``stale``.
    """

    def __init__(self, model: ServingModel, store,
                 poll_sec: Optional[float] = None) -> None:
        self.model = model
        self.store = store
        self.poll_sec = (FLAGS.serving_reload_poll_sec
                         if poll_sec is None else float(poll_sec))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._backoff = None   # armed after a failed poll
        # poll outcome counts (mirrored into pbox_serving_reload_*):
        # gates read these without needing an active hub
        self.polls = 0
        self.adopted = 0
        self.refused = 0
        self.degraded = 0

    # ---- one poll ------------------------------------------------------
    def poll_once(self) -> Optional[str]:
        """One reload poll. Returns the newly adopted artifact id (None
        when already current or the poll failed). NEVER raises — the
        query path must survive any reload failure."""
        from paddlebox_tpu.resilience import faults
        self.polls += 1
        try:
            faults.inject("serving.reload", op="poll",
                          adopted=self.model.adopted_aid or "")
            aid = self.model.hot_reload(self.store)
        except Exception as e:
            self.refused += 1
            reason = ("corrupt" if isinstance(e, ArtifactCorruptError)
                      else "lineage" if isinstance(e, ArtifactLineageError)
                      else "empty" if isinstance(e, FileNotFoundError)
                      else "io")
            _counter("pbox_serving_reload_refused_total",
                     "hot-reload polls that failed (prior snapshot "
                     "kept serving)", reason=reason)
            _emit("serving_reload_refused", reason=reason,
                  error=repr(e), adopted=self.model.adopted_aid or "")
            log.error("serving hot-reload REFUSED (%s) — keeping the "
                      "prior snapshot (%s): %s", reason,
                      self.model.adopted_aid, e)
            try:
                # black-box seam (obs/flightrec): a refused reload IS
                # the serving.reload degrade anomaly — one debounced
                # postmortem bundle while the prior snapshot serves on
                from paddlebox_tpu.obs import flightrec
                flightrec.trigger(
                    "reload_degrade", reason=reason, error=repr(e),
                    adopted=self.model.adopted_aid or "")
            except Exception:
                log.debug("flightrec trigger failed", exc_info=True)
            self._arm_backoff()
            self._note_staleness()
            return None
        self._backoff = None
        if aid is not None:
            self.adopted += 1
            _emit("serving_reload", artifact=aid,
                  rows=self.model.serving_status()["rows"])
        self._note_staleness()
        return aid

    def _note_staleness(self) -> None:
        """Serving epoch age vs the newest published version: 0 when
        the snapshot IS the tip; otherwise how long the newer tip has
        existed unadopted (a corrupt tip counts — that is exactly the
        degraded state the gauge must show)."""
        lag, tip = 0.0, None
        try:
            adopted = self.model.adopted_aid
            for aid in reversed(self.store.versions()):
                try:
                    m = self.store.read_manifest(aid, verify=False)
                except Exception:
                    m = None   # torn manifest: still a newer tip
                if m is not None and not m.get("adoptable", True):
                    continue   # chain-only link: never a serving tip
                tip = aid
                if aid != adopted:
                    created = (m or {}).get("created_unix")
                    if created is None:
                        try:
                            created = os.stat(
                                self.store.version_dir(aid)).st_mtime
                        except OSError:
                            created = time.time()
                    lag = max(0.0, time.time() - float(created))
                break
        except Exception:
            log.debug("staleness probe failed", exc_info=True)
        self.model.note_staleness(lag)
        if lag > 0.0 and tip is not None:
            self.degraded += 1
            _counter("pbox_serving_reload_degraded_total",
                     "polls that left serving BEHIND the newest "
                     "published version")
            _emit("serving_degraded", tip=tip,
                  adopted=self.model.adopted_aid or "",
                  staleness_sec=round(lag, 3))
            try:
                # black-box seam (obs/flightrec): serving left BEHIND
                # the tip after a poll (refused reload OR a tip the
                # store itself rejected — e.g. a corrupt delta never
                # reaches hot_reload). Debounce collapses the per-poll
                # repeats into one bundle
                from paddlebox_tpu.obs import flightrec
                flightrec.trigger(
                    "reload_degrade", reason="stale behind tip",
                    tip=tip, adopted=self.model.adopted_aid or "",
                    staleness_sec=round(lag, 3))
            except Exception:
                log.debug("flightrec trigger failed", exc_info=True)
            if FLAGS.serving_staleness_max_sec > 0 \
                    and lag > FLAGS.serving_staleness_max_sec:
                log.error(
                    "serving snapshot STALE: %s published %.1fs ago, "
                    "still serving %s (SLO %.1fs)", tip, lag,
                    self.model.adopted_aid,
                    FLAGS.serving_staleness_max_sec)
        try:
            from paddlebox_tpu.obs.hub import get_hub
            get_hub().gauge("pbox_serving_staleness_sec",
                            "serving snapshot age vs newest published "
                            "version").set(lag)
            if self.model._snap is not None:
                self._emit_stats()
        except Exception:
            log.debug("staleness gauge failed", exc_info=True)

    def _emit_stats(self) -> None:
        """Per-poll ``serving_stats`` event: adopted version, staleness
        and the latency quantiles so a run's JSONL alone shows the
        serving SLO timeline (scripts/telemetry_report.py renders the
        ``serve p99`` column from these)."""
        from paddlebox_tpu.obs.hub import get_hub
        hub = get_hub()
        if not hub.active:
            return
        from paddlebox_tpu.obs.instruments import \
            SERVING_LATENCY_BUCKETS
        h = hub.histogram("pbox_serving_latency_seconds",
                          "serving query latency (per lookup/predict "
                          "call)", buckets=SERVING_LATENCY_BUCKETS)
        status = self.model.serving_status()
        fields = dict(adopted=status["adopted"] or "",
                      staleness_sec=status["staleness_sec"])
        total = 0
        for op in ("lookup", "predict"):
            s = h.snapshot(op=op)
            if s["count"]:
                total += s["count"]
                fields[f"{op}_p50_ms"] = round(
                    h.quantile(0.5, op=op) * 1e3, 4)
                fields[f"{op}_p99_ms"] = round(
                    h.quantile(0.99, op=op) * 1e3, 4)
        fields["queries"] = total
        hub.emit("serving_stats", **fields)

    def _arm_backoff(self) -> None:
        if self._backoff is None:
            from paddlebox_tpu.resilience.retry import RetryPolicy
            self._backoff = RetryPolicy.from_flags(
                site="serving.reload").delays()

    # ---- thread lifecycle ----------------------------------------------
    def start(self) -> "ReloadLoop":
        if self._thread is not None:
            return self
        self.model.register_health()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serving-reload")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:   # poll_once is defensive; belt anyway
                log.warning("reload poll crashed", exc_info=True)
            if self._backoff is not None:
                delay = next(self._backoff, self.poll_sec)
            else:
                delay = self.poll_sec
            self._stop.wait(delay)

    def stop(self, join: bool = True) -> None:
        self._stop.set()
        t = self._thread
        if join and t is not None:
            t.join(timeout=30)
        self._thread = None

    def __enter__(self) -> "ReloadLoop":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class MultiMfServingModel:
    """Read-only base+delta consumer for MULTI-MF saves (per-slot
    embedding dims, feature_value.h:42-185): loads the per-dim-class
    artifacts written by ``MultiMfEmbeddingTable.save_base/save_delta``
    (``{path}.mf{D}.npz``), answers per-slot-width lookups and full CTR
    predictions through the canonical slot-ordered pooled concat — the
    same forward as ``MultiMfTrainStep``."""

    def __init__(self, model, desc: DataFeedDesc, slot_mf_dims,
                 capacity: int = 1 << 20, use_cvm: bool = True,
                 cvm_offset: int = 2) -> None:
        from paddlebox_tpu.ps.multi_mf import MultiMfEmbeddingTable
        self.model = model
        self.desc = desc
        self.use_cvm = use_cvm
        self.cvm_offset = cvm_offset
        self.table = MultiMfEmbeddingTable(
            slot_mf_dims, capacity=capacity, cfg=SparseSGDConfig())
        self.params = None
        t = self.table
        route = tuple((int(t.class_of_slot[s]), int(t.slot_rank[s]))
                      for s in range(t.num_slots))
        class_slots = tuple(len(s) for s in t.class_slots)
        mf_dims = tuple(t.dims)

        @jax.jit
        def _fwd(table_states, params, devs):
            # per-class pull → seqpool over the class's slots →
            # canonical slot-order concat — MultiMfTrainStep._pooled's
            # forward, compiled once per batch bucket
            d0 = devs[0]
            show_clk = jnp.stack([d0.show, d0.clk], axis=1)
            parts = []
            for c, (st, dev) in enumerate(zip(table_states, devs)):
                vals_u = pull_values(
                    gather_full_rows(st, dev.unique_rows), mf_dims[c])
                values_k = expand_pull(vals_u, dev.gather_idx)
                parts.append(fused_seqpool_cvm(
                    values_k, dev.segments, show_clk,
                    d0.label.shape[0], class_slots[c],
                    self.use_cvm, self.cvm_offset))
            flat = jnp.concatenate(
                [parts[c][:, r, :] for c, r in route], axis=1)
            logits = self.model.apply(params, flat, d0.dense)
            return (jax.nn.sigmoid(logits),
                    (d0.show > 0).astype(jnp.float32))

        self._fwd = _fwd

    # ---- artifact loading (multi-mf save format) ----
    def load_base(self, path: str) -> int:
        """Load a MultiMfEmbeddingTable.save_base artifact set."""
        n = self.table.load(path, merge=False)
        log.info("serving: loaded multi-mf base %s (%d rows)", path, n)
        return n

    def apply_delta(self, path: str) -> int:
        n = self.table.load(path, merge=True)
        log.info("serving: applied multi-mf delta %s (%d rows)", path, n)
        return n

    def load_dense(self, path: str) -> None:
        """Load dense params (same file formats as ServingModel)."""
        self.params = ServingModel._read_dense(path)

    # ---- queries ----
    def embed_lookup(self, keys: np.ndarray,
                     slots: np.ndarray) -> np.ndarray:
        """[n] keys + their slot ids → [n, 3 + max_mf] pull values with
        PER-SLOT widths (columns beyond the key's slot width are zero) —
        the dy_mf CopyForPull contract. Unknown keys read zeros."""
        return self.table.pull(keys, slots)

    def slot_width(self, slot: int) -> int:
        """Embedding width (3 + mf_dim) served for a slot."""
        return 3 + int(self.table.slot_mf_dims[slot])

    def predict(self, batch: SlotBatch, return_valid: bool = False):
        """CTR predictions via the jitted multi-mf forward (eval
        semantics: unknown keys zeros, nothing trains)."""
        if self.params is None:
            raise RuntimeError("load_dense first")
        subs, _ = self.table.split_batch(batch)
        devs = []
        for sub, t in zip(subs, self.table.tables):
            idx = t.prepare_eval(sub)
            devs.append(make_device_batch(
                sub, idx, floats=devs[0].floats if devs else None))
        pred, ins_w = self._fwd(
            tuple(t.state for t in self.table.tables),
            self.params, tuple(devs))
        if return_valid:
            return np.asarray(pred), np.asarray(ins_w)
        return np.asarray(pred)
