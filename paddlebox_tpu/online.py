"""Always-on online learning: ONE supervised train→publish→serve daemon.

``OnlineLearner`` composes the pieces the repo already gates in
isolation into the production shape PaddleBox actually runs
(docs/ONLINE.md):

- **train**: ``Trainer.train_stream`` windows over arriving files
  (``FLAGS.stream_window_files``), with the full preemption contract —
  SIGTERM mid-window writes an emergency boundary checkpoint +
  ``RESUME.json``; a restarted daemon resumes the open window
  at-least-once.
- **publish**: stream-boundary checkpoints auto-publish into the
  ``ArtifactStore`` (``FLAGS.artifact_root``) as lineage-linked
  versions — the xbox base/delta feed.
- **serve**: a ``serving.ReloadLoop`` adopts published versions into an
  immutable snapshot concurrently with training (verify-before-swap,
  degrade-never-crash).
- **feature lifecycle**: every ``FLAGS.shrink_every_windows`` completed
  windows (the dataset's monotone ``windows_completed`` clock, so the
  cadence survives preemption/resume) a shrink cycle ages the model —
  ``table.shrink`` decays show/clk/delta_score and drops
  below-threshold rows through whatever tier stack the table owns
  (device window → HostStore RAM → SsdTier, fenced against the async
  epilogue, compacted so dead rows free disk). The cycle's decisions
  ride the boundary cursor (``Trainer.lifecycle``) and the next
  boundary checkpoint is forced to a BASE save — deltas cannot carry a
  whole-table decay, and a restore must replay to the same live-key
  set.

The **supervisor loop** classifies leg failures on the RetryPolicy
transient/deterministic split (site ``online.supervise``): transient
failures restore the last consistent checkpoint and retry on the
seeded backoff schedule (mode ``degraded`` while retrying);
deterministic ones degrade LOUDLY — training dead but serving
answering → ``serve_only``; serving dead → ``train_only`` — instead of
dying. A failed shrink cycle (site ``online.shrink``) retries
transients on its own policy and otherwise SKIPS the cycle loudly
(``pbox_online_shrink_skipped_total`` + a ``shrink_skipped``
flight-recorder trigger) without stalling training.

``/healthz`` aggregates the three legs into one verdict: the hub's
``online`` block (``TelemetryHub.set_online_probe``) carries
``{mode, windows_completed, files_backlog, last_publish_ts,
last_shrink_ts, shrunk_rows_total, ...}``.

``scripts/onlinelearn.py`` is the CLI; ``scripts/online_check.py``
gates the whole composition (long-horizon plateau soak, kill/chaos
legs, serving replay-oracle bit-consistency).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

#: daemon modes, most to least capable (docs/ONLINE.md state machine)
MODES = ("full", "train_only", "serve_only", "degraded")


class OnlineLearner:
    """Supervised always-on train→publish→serve daemon (ONE process).

    Parameters
    ----------
    trainer:
        The ``train.Trainer`` (its table is what shrink cycles age).
    dataset_fn:
        Zero-arg factory for a FRESH windowed ``QueueDataset`` — called
        per train-leg attempt so a supervised restart re-adopts the
        stream cursor exactly like a process restart would.
    checkpoint:
        ``CheckpointManager`` (publishes boundary artifacts when
        ``FLAGS.artifact_root`` attached an ``ArtifactStore``).
    serving / store:
        Optional ``serving.ServingModel`` + the ``ArtifactStore`` its
        reload loop polls. Both or neither; without them the daemon
        runs mode ``train_only``.
    filelist_fn / max_windows / max_idle_polls:
        Passed through to ``Trainer.train_stream`` (``max_windows``
        counts across supervised restarts, not per attempt).
    shrink_every_windows:
        Override for ``FLAGS.shrink_every_windows`` (0 = aging off).
    """

    def __init__(self, trainer, dataset_fn: Callable[[], object],
                 checkpoint, *, serving=None, store=None,
                 filelist_fn: Optional[Callable[[], List[str]]] = None,
                 max_windows: Optional[int] = None,
                 max_idle_polls: Optional[int] = None,
                 reload_poll_sec: Optional[float] = None,
                 shrink_every_windows: Optional[int] = None) -> None:
        if (serving is None) != (store is None):
            raise ValueError("serving and store come together: the "
                             "serve leg adopts published versions from "
                             "the store")
        self.trainer = trainer
        self.dataset_fn = dataset_fn
        self.checkpoint = checkpoint
        self.serving = serving
        self.store = store
        self.filelist_fn = filelist_fn
        self.max_windows = max_windows
        self.max_idle_polls = max_idle_polls
        self.reload_poll_sec = reload_poll_sec
        self.shrink_every = (FLAGS.shrink_every_windows
                             if shrink_every_windows is None
                             else int(shrink_every_windows))
        self._lock = threading.Lock()
        # supervisor-owned base mode; online_status() refines it to
        # "degraded" while a transient retry backoff is in flight
        self._mode_base = "full" if serving is not None else "train_only"
        self._retrying = False
        self._loop = None            # serving.ReloadLoop
        self._windows_this_run = 0   # daemon-level window budget clock
        self._backlog = 0
        self._last_publish_step: Optional[int] = None
        self.last_publish_ts: Optional[float] = None
        self.last_shrink_ts: Optional[float] = None
        self._last_shrink_window = 0
        self.shrink_cycles = 0
        self.shrunk_rows_total = 0
        self.shrink_skipped_total = 0
        self.leg_failures = 0
        self.totals: Dict[str, float] = {}

    # ---- status / healthz ----------------------------------------------
    def online_status(self) -> Dict:
        """The /healthz ``online`` block (hub.set_online_probe). Safe
        from any thread; never raises on a half-started daemon."""
        with self._lock:
            mode = self._mode_base
            if self._retrying:
                mode = "degraded"
            elif mode == "full" and self.serving is not None:
                try:
                    sst = self.serving.serving_status()
                    if sst.get("stale"):
                        # training healthy but the snapshot stopped
                        # advancing — the composed verdict degrades
                        mode = "degraded"
                except Exception:
                    pass
            wc = self._dataset_windows()
            return {
                "mode": mode,
                "windows_completed": wc,
                "files_backlog": int(self._backlog),
                "last_publish_ts": self.last_publish_ts,
                "last_shrink_ts": self.last_shrink_ts,
                "shrunk_rows_total": int(self.shrunk_rows_total),
                "shrink_cycles": int(self.shrink_cycles),
                "shrink_skipped_total": int(self.shrink_skipped_total),
                "windows_since_shrink": (
                    max(0, wc - self._last_shrink_window)
                    if self.shrink_every > 0 else 0),
                "leg_failures": int(self.leg_failures),
                "serving": self.serving is not None,
            }

    def _dataset_windows(self) -> int:
        ds = getattr(self, "_dataset", None)
        return int(getattr(ds, "windows_completed", 0) or 0)

    # ---- lifecycle bookkeeping -----------------------------------------
    def _seed_from_cursor(self) -> None:
        """Resume the shrink cadence + counters from the newest
        checkpoint cursor's lifecycle block — a restarted daemon must
        not re-age (or forget it aged) the rows the checkpoint already
        captured."""
        try:
            cur = self.checkpoint.load_cursor() if self.checkpoint \
                else None
        except Exception:
            cur = None
        lc = (cur or {}).get("lifecycle")
        if not lc:
            return
        with self._lock:
            self.trainer.lifecycle = dict(lc)
            self.shrink_cycles = int(lc.get("cycles", 0) or 0)
            self.shrunk_rows_total = int(
                lc.get("shrunk_rows_total", 0) or 0)
            self._last_shrink_window = int(
                lc.get("last_shrink_window", 0) or 0)
        log.info("online: resumed lifecycle state — %d cycles, %d rows "
                 "shrunk, last at window %d", self.shrink_cycles,
                 self.shrunk_rows_total, self._last_shrink_window)

    def _live_rows(self) -> int:
        """Live logical rows across the table's tier stack (device
        window / host RAM / SSD — whichever the table owns)."""
        t = self.trainer.table
        host = getattr(t, "host", None)
        if host is not None:
            ssd = getattr(host, "ssd", None)
            return len(host) + (len(ssd) if ssd is not None else 0)
        hosts = getattr(t, "hosts", None)
        if hosts:
            n = 0
            for h in hosts:
                n += len(h)
                if getattr(h, "ssd", None) is not None:
                    n += len(h.ssd)
            return n
        return int(t.feature_count)

    # ---- per-window hook (runs on the training thread) -----------------
    def _on_window(self, widx: int, dataset) -> None:
        from paddlebox_tpu.obs.hub import get_hub
        hub = get_hub()
        self._dataset = dataset
        with self._lock:
            self._windows_this_run += 1
            try:
                self._backlog = len(dataset.pending_files())
            except Exception:
                pass
        # publish observation: the boundary save of window N-1 landed
        # before this hook ran — a step advance means a publish
        if self.checkpoint is not None:
            st = self.checkpoint.latest_step()
            if st is not None and st != self._last_publish_step:
                with self._lock:
                    self._last_publish_step = st
                    self.last_publish_ts = time.time()
        # serve-leg liveness: the reload loop's thread must be running
        if self._loop is not None and self._mode_base == "full":
            th = getattr(self._loop, "_thread", None)
            if th is not None and not th.is_alive():
                self._degrade("serve", RuntimeError(
                    "reload loop thread died"), to_mode="train_only")
        wc = int(getattr(dataset, "windows_completed", 0) or 0)
        if self.shrink_every > 0:
            hub.gauge("pbox_online_windows_since_shrink",
                      "completed windows since the last shrink cycle "
                      "(shrink-overdue alert input)").set(
                          max(0, wc - self._last_shrink_window))
            if wc - self._last_shrink_window >= self.shrink_every:
                self._shrink_cycle(wc)

    def _shrink_cycle(self, window: int) -> None:
        """One feature-lifecycle cycle at a window boundary: fence +
        age the table (whole tier stack), record the decision in the
        boundary cursor, and force the next boundary save to a BASE —
        published at THIS boundary (stream_save_now). Transient
        failures retry on the seeded ``online.shrink`` policy; a hard
        failure skips the cycle loudly without stalling training."""
        from paddlebox_tpu.obs import flightrec
        from paddlebox_tpu.obs.hub import get_hub
        from paddlebox_tpu.resilience import faults
        from paddlebox_tpu.resilience.retry import RetryPolicy
        hub = get_hub()
        t0 = time.perf_counter()
        # the jit step state owns the freshest device rows — sync the
        # facade before aging, re-adopt the rebuilt state after
        self.trainer.sync_table()

        def attempt() -> int:
            faults.inject("online.shrink", window=window)
            return int(self.trainer.table.shrink())

        try:
            freed = RetryPolicy.from_flags(
                site="online.shrink").call(attempt)
        except Exception as e:
            # deterministic failure or retries exhausted: SKIP this
            # cycle loudly; training continues, the cadence re-fires
            # shrink_every windows from now
            with self._lock:
                self.shrink_skipped_total += 1
                self._last_shrink_window = window
            hub.counter("pbox_online_shrink_skipped_total",
                        "shrink cycles skipped after a hard/exhausted "
                        "failure").inc()
            if hub.active:
                hub.emit("online_shrink_skipped", window=window,
                         error=repr(e))
            flightrec.trigger("shrink_skipped", reason=repr(e),
                              window=window)
            log.error("online: shrink cycle at window %d SKIPPED (%r) "
                      "— training continues, next attempt in %d "
                      "windows", window, e, self.shrink_every)
            self.trainer.adopt_table()
            return
        self.trainer.adopt_table()
        live = self._live_rows()
        now = time.time()
        with self._lock:
            self.shrink_cycles += 1
            self.shrunk_rows_total += freed
            self._last_shrink_window = window
            self.last_shrink_ts = now
            # the decisions ride every subsequent cursor: a restore
            # replays to the same live-key set and the daemon resumes
            # its cadence from it (docs/ONLINE.md)
            self.trainer.lifecycle = {
                "version": 1,
                "cycles": int(self.shrink_cycles),
                "last_shrink_window": int(window),
                "shrunk_rows_total": int(self.shrunk_rows_total),
                "live_rows": int(live),
                "decay": float(FLAGS.show_click_decay_rate),
                "delete_threshold": float(FLAGS.shrink_delete_threshold),
            }
        # a delta save cannot carry a whole-table decay — force a BASE,
        # and publish it at THIS boundary so no training lands between
        # the shrink and its persisted snapshot
        self.trainer.stream_force_base = True
        self.trainer.stream_save_now = True
        hub.counter("pbox_online_shrink_cycles_total",
                    "completed feature-lifecycle shrink cycles").inc()
        hub.counter("pbox_online_shrunk_rows_total",
                    "rows dropped by shrink cycles").inc(freed)
        if hub.active:
            hub.emit("online_shrink", window=window, freed=int(freed),
                     live_rows=int(live),
                     elapsed_sec=round(time.perf_counter() - t0, 4))
        log.info("online: shrink cycle %d at window %d freed %d rows "
                 "(%d live) in %.3fs", self.shrink_cycles, window,
                 freed, live, time.perf_counter() - t0)

    # ---- legs ----------------------------------------------------------
    def _start_serving(self) -> None:
        if self.serving is None:
            return
        from paddlebox_tpu.resilience import faults
        from paddlebox_tpu.serving import ReloadLoop
        try:
            faults.inject("online.supervise", leg="serve")
            self.serving.register_health()
            self._loop = ReloadLoop(self.serving, self.store,
                                    poll_sec=self.reload_poll_sec)
            self._loop.poll_once()  # adopt an existing tip before the
            self._loop.start()      # first query, if one is published
        except Exception as e:
            self._degrade("serve", e, to_mode="train_only")

    def _stop_serving(self) -> None:
        if self._loop is not None:
            try:
                self._loop.stop()
            except Exception:
                log.warning("online: reload loop stop failed",
                            exc_info=True)
            self._loop = None

    def _serving_answering(self) -> bool:
        if self.serving is None:
            return False
        try:
            return self.serving.serving_status().get("adopted") \
                is not None
        except Exception:
            return False

    def _train_leg(self) -> Dict[str, float]:
        ds = self.dataset_fn()
        self._dataset = ds
        mw = None
        if self.max_windows is not None:
            mw = max(0, self.max_windows - self._windows_this_run)
            if mw == 0:
                return dict(self.totals)
        return self.trainer.train_stream(
            ds, self.checkpoint, filelist_fn=self.filelist_fn,
            max_windows=mw, max_idle_polls=self.max_idle_polls,
            log_prefix="online ")

    def _restore_for_retry(self) -> None:
        """Roll the trainer back to the last consistent checkpoint
        before re-entering the train leg — the in-process equivalent of
        a supervised process restart (the fresh dataset re-adopts the
        stream cursor inside train_stream)."""
        if self.checkpoint is None \
                or self.checkpoint.latest_step() is None:
            return
        try:
            self.checkpoint.restore(self.trainer)
        except Exception:
            log.error("online: rollback restore failed — retrying the "
                      "train leg on live state", exc_info=True)

    def _degrade(self, leg: str, exc: BaseException,
                 to_mode: str) -> None:
        from paddlebox_tpu.obs import flightrec
        from paddlebox_tpu.obs.hub import get_hub
        hub = get_hub()
        with self._lock:
            self.leg_failures += 1
            self._mode_base = to_mode
        hub.counter("pbox_online_leg_failures_total",
                    "supervised leg failures by leg/disposition").inc(
                        leg=leg, disposition="degrade")
        if hub.active:
            hub.emit("online_degrade", leg=leg, mode=to_mode,
                     error=repr(exc))
        flightrec.trigger("online_degrade", reason=repr(exc), leg=leg,
                          mode=to_mode)
        log.error("online: %s leg failed DETERMINISTICALLY (%r) — "
                  "degrading to %s (the daemon stays up)", leg, exc,
                  to_mode)

    @staticmethod
    def _stop_aware_sleep(sec: float) -> None:
        from paddlebox_tpu.resilience import preemption
        deadline = time.monotonic() + sec
        while not preemption.stop_pending():
            left = deadline - time.monotonic()
            if left <= 0:
                return
            time.sleep(min(0.05, left))

    def _serve_idle(self) -> None:
        """serve_only steady state: the reload loop keeps adopting,
        the supervisor just waits for a stop (bounded runs return
        immediately — tests must not idle forever)."""
        from paddlebox_tpu.resilience import preemption
        if self.max_windows is not None \
                or self.max_idle_polls is not None:
            return
        while not preemption.stop_pending():
            time.sleep(0.05)

    # ---- the supervisor ------------------------------------------------
    def run(self) -> Dict[str, float]:
        """Run the daemon until the source dries up (bounded runs) or a
        graceful stop arrives (``PreemptedError`` propagates to the
        launcher, which exits ``EXIT_RESUME``). Returns the train-leg
        totals. Transient leg failures retry on the seeded
        ``online.supervise`` policy; deterministic ones degrade — this
        method raises only for preemption or a failure with nothing
        left to supervise."""
        from paddlebox_tpu.obs.hub import get_hub
        from paddlebox_tpu.resilience import faults, preemption
        from paddlebox_tpu.resilience.retry import (RetryPolicy,
                                                    is_retryable)
        if FLAGS.graceful_shutdown:
            preemption.install_signal_handlers()
        hub = get_hub()
        hub.set_online_probe(self.online_status)
        self._seed_from_cursor()
        self._start_serving()
        self.trainer.on_window_complete = self._on_window
        policy = RetryPolicy.from_flags(site="online.supervise")
        backoff = None
        fail_window = -1
        try:
            while True:
                if self._mode_base == "serve_only":
                    self._serve_idle()
                    if preemption.stop_pending():
                        if self.checkpoint is not None:
                            # no training state to snapshot (the train
                            # leg is dead) — but the restart contract
                            # still wants the marker so the launcher
                            # relaunches with resume semantics
                            preemption.write_resume_marker(
                                self.checkpoint.root,
                                step=int(self.trainer.global_step),
                                reason=preemption.stop_reason())
                        raise preemption.PreemptedError(
                            f"preempted "
                            f"({preemption.stop_reason()}) while "
                            "serve_only",
                            step=int(self.trainer.global_step))
                    break
                try:
                    faults.inject("online.supervise", leg="train",
                                  mode=self._mode_base)
                    self.totals = self._train_leg()
                    with self._lock:
                        self._retrying = False
                    break  # source drained / window budget hit
                except preemption.PreemptedError:
                    raise  # graceful shutdown — launcher's contract
                except Exception as e:
                    with self._lock:
                        self.leg_failures += 1
                    if self._windows_this_run > fail_window:
                        backoff = None  # progress since last failure
                    fail_window = self._windows_this_run
                    delay = None
                    if is_retryable(e):
                        if backoff is None:
                            backoff = policy.delays()
                        delay = next(backoff, None)
                    if delay is None:
                        # deterministic, or transient retries exhausted
                        if self._serving_answering():
                            self._degrade("train", e,
                                          to_mode="serve_only")
                            continue
                        log.error("online: train leg failed with no "
                                  "serving leg to fall back to — "
                                  "daemon dies: %r", e)
                        raise
                    with self._lock:
                        self._retrying = True
                    hub.counter(
                        "pbox_online_leg_failures_total",
                        "supervised leg failures by leg/disposition"
                    ).inc(leg="train", disposition="retry")
                    if hub.active:
                        hub.emit("online_leg_retry", leg="train",
                                 delay_sec=round(delay, 4),
                                 error=repr(e))
                    log.warning("online: train leg failed transiently "
                                "(%r) — retrying in %.3fs", e, delay)
                    self._stop_aware_sleep(delay)
                    self._restore_for_retry()
        finally:
            self.trainer.on_window_complete = None
            self._stop_serving()
            hub.set_online_probe(None)
        return dict(self.totals)
