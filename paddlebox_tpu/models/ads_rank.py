"""AdsRank — PV (page-view) ads ranking model with rank attention.

The production BoxPS pattern this mirrors: PV-merged batches flatten each
search result page's ads into instances with a ``rank_offset`` matrix
(PaddleBoxDataFeed::GetRankOffset, data_feed.cu:1319), and the net mixes
per-ad features with a per-(own-rank, other-rank) attention over co-shown
ads (``rank_attention`` op, operators/rank_attention_op.*) plus slot-wise
``batch_fc`` towers (operators/batch_fc_op.*). This module is the model
half; paddlebox_tpu/data/pv.py builds the batches.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from paddlebox_tpu.ops.rank_attention import rank_attention


class AdsRank(nn.Module):
    """pooled [B, S, D] + dense [B, Dd] + rank_offset [B, 1+2K] → logits [B].

    d_model: per-ad projection width fed to rank attention.
    max_rank: K, max co-shown ads attended per ad (must match the
      PvBatchBuilder's max_rank).
    """

    d_model: int = 64
    max_rank: int = 3
    hidden: Sequence[int] = (128, 64)
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, pooled: jax.Array, dense: jax.Array,
                 rank_offset: jax.Array) -> jax.Array:
        b, s, d = pooled.shape
        feats = jnp.concatenate(
            [pooled.reshape(b, s * d), dense], axis=1)
        proj = nn.Dense(self.d_model, dtype=self.compute_dtype,
                        name="ad_proj")(feats).astype(jnp.float32)

        # per-(own-rank, co-rank) attention parameter blocks
        rank_param = self.param(
            "rank_param", nn.initializers.normal(0.02),
            (self.max_rank * self.max_rank, self.d_model, self.d_model))
        ra = rank_attention(proj, rank_offset, rank_param,
                            max_rank=self.max_rank, enable_input_bp=True)

        h = jnp.concatenate([proj, ra], axis=1)
        for i, w in enumerate(self.hidden):
            h = nn.relu(nn.Dense(w, dtype=self.compute_dtype,
                                 name=f"mlp_{i}")(h).astype(jnp.float32))
        return nn.Dense(1, dtype=jnp.float32, name="head")(h)[:, 0]
