"""AdsRank — PV (page-view) ads ranking model with rank attention.

The production BoxPS pattern this mirrors: PV-merged batches flatten each
search result page's ads into instances with a ``rank_offset`` matrix
(PaddleBoxDataFeed::GetRankOffset, data_feed.cu:1319), and the net mixes
per-ad features with a per-(own-rank, other-rank) attention over co-shown
ads (``rank_attention`` op, operators/rank_attention_op.*) plus slot-wise
``batch_fc`` towers (operators/batch_fc_op.*). This module is the model
half; paddlebox_tpu/data/pv.py builds the batches.

The optional towers exercise the full device-side CTR op family
(ISSUE 13 — the PV bench lane runs with all three on):

- ``slot_fc``: a per-slot ``batch_fc`` projection over the pooled
  embeddings (the reference's slot-wise tower, batch_fc_op default
  mode — [S, B, D] × [S, D, D] + [S, D]).
- ``cross_norm``: a ``cross_norm_hadamard`` block over the
  (projection, attention) pair — the [a, b, a⊙b, a·b] normalized
  cross features (cross_norm_hadamard_op, one field of width
  ``d_model``). The caller owns the ``DataNormSummary`` (pass it as
  ``cross_summary``; update it outside the grad with
  ``ops.cross_norm_update``, the data_norm summary-training pattern).
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from paddlebox_tpu.ops.batch_fc import batch_fc
from paddlebox_tpu.ops.cross_norm import cross_norm_hadamard
from paddlebox_tpu.ops.data_norm import DataNormSummary
from paddlebox_tpu.ops.rank_attention import rank_attention


class AdsRank(nn.Module):
    """pooled [B, S, D] + dense [B, Dd] + rank_offset [B, 1+2K] → logits [B].

    d_model: per-ad projection width fed to rank attention.
    max_rank: K, max co-shown ads attended per ad (must match the
      PvBatchBuilder's max_rank).
    slot_fc: per-slot batch_fc tower over the pooled embeddings.
    cross_norm: normalized hadamard-cross block over (proj, attention)
      — requires ``cross_summary`` at call time.
    """

    d_model: int = 64
    max_rank: int = 3
    hidden: Sequence[int] = (128, 64)
    compute_dtype: jnp.dtype = jnp.bfloat16
    slot_fc: bool = False
    cross_norm: bool = False

    @nn.compact
    def __call__(self, pooled: jax.Array, dense: jax.Array,
                 rank_offset: jax.Array,
                 cross_summary: Optional[DataNormSummary] = None
                 ) -> jax.Array:
        b, s, d = pooled.shape
        if self.slot_fc:
            w = self.param("slot_fc_w", nn.initializers.normal(0.02),
                           (s, d, d))
            bias = self.param("slot_fc_b", nn.initializers.zeros, (s, d))
            pooled = nn.relu(
                batch_fc(pooled.swapaxes(0, 1), w, bias)).swapaxes(0, 1)
        feats = jnp.concatenate(
            [pooled.reshape(b, s * d), dense], axis=1)
        proj = nn.Dense(self.d_model, dtype=self.compute_dtype,
                        name="ad_proj")(feats).astype(jnp.float32)

        # per-(own-rank, co-rank) attention parameter blocks
        rank_param = self.param(
            "rank_param", nn.initializers.normal(0.02),
            (self.max_rank * self.max_rank, self.d_model, self.d_model))
        ra = rank_attention(proj, rank_offset, rank_param,
                            max_rank=self.max_rank, enable_input_bp=True)

        h = jnp.concatenate([proj, ra], axis=1)
        if self.cross_norm:
            if cross_summary is None:
                raise ValueError(
                    "AdsRank(cross_norm=True) needs a cross_summary "
                    "(ops.init_cross_norm_summary(1, d_model))")
            cx = cross_norm_hadamard(h, cross_summary, 1, self.d_model)
            h = jnp.concatenate([h, cx], axis=1)
        for i, w in enumerate(self.hidden):
            h = nn.relu(nn.Dense(w, dtype=self.compute_dtype,
                                 name=f"mlp_{i}")(h).astype(jnp.float32))
        return nn.Dense(1, dtype=jnp.float32, name="head")(h)[:, 0]
