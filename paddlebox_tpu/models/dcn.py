"""DCN-v2 — deep & cross network v2 (BASELINE.json config #3).

Cross layers: x_{l+1} = x_0 ⊙ (W_l x_l + b_l) + x_l (the v2 full-matrix
form), stacked alongside a deep tower, combined for the logit. The cross
layers are dense matmuls — MXU-native — over the flattened pooled
embeddings + dense features.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class CrossLayer(nn.Module):
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x0: jax.Array, xl: jax.Array) -> jax.Array:
        d = x0.shape[-1]
        w = nn.Dense(d, dtype=self.compute_dtype,
                     kernel_init=nn.initializers.glorot_uniform())(xl)
        return x0 * w + xl


class DCNv2(nn.Module):
    num_cross_layers: int = 3
    hidden: Sequence[int] = (400, 400)
    compute_dtype: jnp.dtype = jnp.bfloat16
    structure: str = "parallel"  # "parallel" | "stacked"

    @nn.compact
    def __call__(self, pooled: jax.Array, dense: jax.Array) -> jax.Array:
        b = pooled.shape[0]
        x0 = jnp.concatenate(
            [pooled.reshape(b, -1), dense], axis=1).astype(self.compute_dtype)

        xc = x0
        for _ in range(self.num_cross_layers):
            xc = CrossLayer(self.compute_dtype)(x0, xc)

        if self.structure == "stacked":
            xd = xc  # deep tower consumes the cross output
        else:
            xd = x0
        for h in self.hidden:
            xd = nn.Dense(h, dtype=self.compute_dtype,
                          kernel_init=nn.initializers.glorot_uniform())(xd)
            xd = nn.relu(xd)
        feat = xd if self.structure == "stacked" \
            else jnp.concatenate([xc, xd], axis=1)
        return nn.Dense(1, dtype=jnp.float32)(feat)[:, 0].astype(jnp.float32)
