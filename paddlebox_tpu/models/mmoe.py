"""MMoE — multi-gate mixture-of-experts multi-task CTR tower.

Reference context: PaddleBox serves multi-task CTR models (the metric
registry ships a MultiTaskMetricMsg variant, fleet/metrics.h:198-567, and
the MoE building blocks live in python/paddle/incubate/distributed/
models/moe/); the canonical dense architecture pairing them is MMoE
(multi-gate mixture of experts) — shared expert towers, one softmax gate
per task, one logit head per task.

TPU-native notes: experts run as ONE batched einsum over the expert dim
(``bd,edh->ebh`` — a single MXU matmul per layer, no per-expert python
loop), gates are tiny softmax Dense layers fused into it by XLA. The
module returns [B, num_tasks] logits; single-task callers (the standard
trainer) read task 0 via ``MMoESingle``.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class MMoE(nn.Module):
    num_experts: int = 4
    num_tasks: int = 2
    expert_hidden: Sequence[int] = (256, 128)
    tower_hidden: Sequence[int] = (64,)
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, pooled: jax.Array, dense: jax.Array) -> jax.Array:
        """(pooled [B, S, D], dense [B, Dd]) → logits [B, num_tasks]."""
        b = pooled.shape[0]
        x = jnp.concatenate([pooled.reshape(b, -1), dense],
                            axis=1).astype(self.compute_dtype)
        d_in = x.shape[-1]

        # all experts in one einsum per layer: [B, d] x [E, d, h] → [E, B, h]
        # (params stay fp32 like nn.Dense's param_dtype; cast at use)
        h = jnp.broadcast_to(x, (self.num_experts,) + x.shape)
        din = d_in
        for li, width in enumerate(self.expert_hidden):
            w = self.param(f"expert_w{li}",
                           nn.initializers.glorot_uniform(),
                           (self.num_experts, din, width), jnp.float32)
            bias = self.param(f"expert_b{li}", nn.initializers.zeros,
                              (self.num_experts, 1, width), jnp.float32)
            h = nn.relu(jnp.einsum(
                "ebd,edh->ebh", h, w.astype(self.compute_dtype))
                + bias.astype(self.compute_dtype))
            din = width
        experts = h  # [E, B, H]

        logits = []
        for t in range(self.num_tasks):
            gate = nn.softmax(
                nn.Dense(self.num_experts, dtype=self.compute_dtype,
                         name=f"gate{t}")(x), axis=-1)       # [B, E]
            mixed = jnp.einsum("be,ebh->bh", gate, experts)  # [B, H]
            y = mixed
            for wi, width in enumerate(self.tower_hidden):
                y = nn.relu(nn.Dense(width, dtype=self.compute_dtype,
                                     name=f"tower{t}_{wi}")(y))
            logits.append(nn.Dense(1, dtype=jnp.float32,
                                   name=f"head{t}")(y.astype(jnp.float32)))
        return jnp.concatenate(logits, axis=-1)  # [B, T]


class MMoESingle(nn.Module):
    """Task-0 view of MMoE — plugs into the standard single-label
    TrainStep (apply(params, pooled, dense) → [B])."""

    num_experts: int = 4
    num_tasks: int = 2
    expert_hidden: Sequence[int] = (256, 128)
    tower_hidden: Sequence[int] = (64,)

    @nn.compact
    def __call__(self, pooled: jax.Array, dense: jax.Array) -> jax.Array:
        out = MMoE(self.num_experts, self.num_tasks, self.expert_hidden,
                   self.tower_hidden, name="mmoe")(pooled, dense)
        return out[:, 0]
