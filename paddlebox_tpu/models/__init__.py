from paddlebox_tpu.models.ctr_dnn import CtrDnn
from paddlebox_tpu.models.deepfm import DeepFM
from paddlebox_tpu.models.wide_deep import WideDeep
from paddlebox_tpu.models.dcn import DCNv2
from paddlebox_tpu.models.ads_rank import AdsRank
from paddlebox_tpu.models.mmoe import MMoE, MMoESingle

MODEL_REGISTRY = {
    "ctr_dnn": CtrDnn,
    "deepfm": DeepFM,
    "wide_deep": WideDeep,
    "dcn_v2": DCNv2,
    "ads_rank": AdsRank,
    "mmoe": MMoESingle,
}

__all__ = ["CtrDnn", "DeepFM", "WideDeep", "DCNv2", "AdsRank",
           "MMoE", "MMoESingle", "MODEL_REGISTRY"]
