from paddlebox_tpu.models.ctr_dnn import CtrDnn
from paddlebox_tpu.models.deepfm import DeepFM

MODEL_REGISTRY = {
    "ctr_dnn": CtrDnn,
    "deepfm": DeepFM,
}

__all__ = ["CtrDnn", "DeepFM", "MODEL_REGISTRY"]
