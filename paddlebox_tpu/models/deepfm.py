"""DeepFM — wide (1st-order) + FM (2nd-order) + deep tower
(BASELINE.json config #2: DeepFM on Criteo).

The table's pull layout maps onto DeepFM naturally: ``embed_w`` (1-dim wide
weight per feature, reference FeatureValue lr field) is the FM first-order
term; ``embedx`` (mf vector) feeds both the FM pairwise term and the deep
tower — exactly how the reference's CTR models consume
pull_box_sparse outputs (embed + embedx split,
pull_box_extended_sparse_op semantics).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class DeepFM(nn.Module):
    hidden: Sequence[int] = (400, 400)
    compute_dtype: jnp.dtype = jnp.bfloat16
    cvm_offset: int = 2

    @nn.compact
    def __call__(self, pooled: jax.Array, dense: jax.Array) -> jax.Array:
        b, s, d = pooled.shape
        co = self.cvm_offset
        wide = pooled[..., co]           # [B, S] per-slot 1st-order weights
        vecs = pooled[..., co + 1:]      # [B, S, mf] FM factors

        # first order: Σ wide + linear(dense)
        first = jnp.sum(wide, axis=1) + nn.Dense(
            1, dtype=jnp.float32)(dense)[:, 0]

        # FM second order: 0.5 * Σ_k [(Σ_s v)² - Σ_s v²]
        vs = vecs.astype(jnp.float32)
        sum_sq = jnp.square(jnp.sum(vs, axis=1))
        sq_sum = jnp.sum(jnp.square(vs), axis=1)
        fm = 0.5 * jnp.sum(sum_sq - sq_sum, axis=1)

        # deep tower over [cvm stats + vectors + dense]
        x = jnp.concatenate(
            [pooled.reshape(b, -1), dense], axis=1).astype(self.compute_dtype)
        for h in self.hidden:
            x = nn.Dense(h, dtype=self.compute_dtype,
                         kernel_init=nn.initializers.glorot_uniform())(x)
            x = nn.relu(x)
        deep = nn.Dense(1, dtype=jnp.float32)(x)[:, 0]

        return (first + fm + deep).astype(jnp.float32)
