"""ctr_dnn — the PaddleRec classic CTR MLP (BASELINE.json config #1).

Reference model shape: pooled slot embeddings (+CVM columns) concatenated
with dense features into an MLP tower; the reference builds it from
``_pull_box_sparse`` + ``fused_seqpool_cvm`` + stacked ``fc`` ops
(python/paddle/fluid/layers/nn.py:793, contrib/layers/nn.py:1750).

Input here is the fused_seqpool_cvm output: ``pooled [B, S, D]`` where
D = cvm_offset(2) + embed_w(1) + mf_dim. bfloat16 matmuls on the MXU with
f32 params/accumulation.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class CtrDnn(nn.Module):
    hidden: Sequence[int] = (400, 400, 400)
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, pooled: jax.Array, dense: jax.Array) -> jax.Array:
        b = pooled.shape[0]
        x = jnp.concatenate(
            [pooled.reshape(b, -1), dense], axis=1).astype(self.compute_dtype)
        for h in self.hidden:
            x = nn.Dense(h, dtype=self.compute_dtype,
                         kernel_init=nn.initializers.glorot_uniform())(x)
            x = nn.relu(x)
        logit = nn.Dense(1, dtype=jnp.float32,
                         kernel_init=nn.initializers.glorot_uniform())(x)
        return logit[:, 0].astype(jnp.float32)
