"""Wide&Deep — linear (wide) + MLP (deep) joint model
(BASELINE.json config #3).

Wide part: the per-feature 1-dim ``embed_w`` weights (pull layout col 2)
summed per instance + a linear layer over dense features — the reference
builds this from pull_box_sparse's embed output + partial_sum/concat wide
graphs. Deep part: pooled embedx vectors + dense through an MLP tower.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class WideDeep(nn.Module):
    hidden: Sequence[int] = (400, 400, 400)
    compute_dtype: jnp.dtype = jnp.bfloat16
    cvm_offset: int = 2

    @nn.compact
    def __call__(self, pooled: jax.Array, dense: jax.Array) -> jax.Array:
        b = pooled.shape[0]
        co = self.cvm_offset
        wide_sparse = jnp.sum(pooled[..., co], axis=1)       # Σ embed_w
        wide_dense = nn.Dense(1, dtype=jnp.float32,
                              name="wide_linear")(dense)[:, 0]

        x = jnp.concatenate(
            [pooled.reshape(b, -1), dense], axis=1).astype(self.compute_dtype)
        for h in self.hidden:
            x = nn.Dense(h, dtype=self.compute_dtype,
                         kernel_init=nn.initializers.glorot_uniform())(x)
            x = nn.relu(x)
        deep = nn.Dense(1, dtype=jnp.float32, name="deep_out")(x)[:, 0]
        return (wide_sparse + wide_dense + deep).astype(jnp.float32)
