"""Native (C++) host-side components, loaded via ctypes.

Build: ``make -C paddlebox_tpu/native`` or automatic on first import (g++,
~1s). Python fallbacks keep the framework fully functional without a
toolchain; the native index is ~50x faster on the per-batch key→row hot path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libpbox_native.so")
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_TRIED = False


_SRCS = ("kv_index.cpp", "slot_parser.cpp")


def _build() -> bool:
    """Compile to a temp file then atomically rename, so concurrent importers
    never CDLL a half-written .so. Honors CXX/CXXFLAGS like the Makefile."""
    srcs = [os.path.join(_DIR, s) for s in _SRCS]
    cxx = os.environ.get("CXX", "g++")
    flags = os.environ.get(
        "CXXFLAGS", "-O3 -march=native -std=c++17 -fPIC").split()
    tmp = _SO + f".tmp{os.getpid()}"
    try:
        subprocess.run([cxx, *flags, "-shared", *srcs, "-o", tmp],
                       check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError) as e:
        log.warning("native build failed (%s); using python fallbacks", e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load_native() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None if unavailable."""
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if not os.path.exists(_SO) or any(
                os.path.getmtime(_SO) <
                os.path.getmtime(os.path.join(_DIR, s)) for s in _SRCS):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            log.warning("native load failed (%s); using python fallbacks", e)
            return None
        lib.kv_create.restype = ctypes.c_void_p
        lib.kv_create.argtypes = [ctypes.c_int64, ctypes.c_int32]
        lib.kv_destroy.argtypes = [ctypes.c_void_p]
        lib.kv_size.restype = ctypes.c_int64
        lib.kv_size.argtypes = [ctypes.c_void_p]
        lib.kv_assign.restype = ctypes.c_int64
        lib.kv_assign.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                  ctypes.c_int64, ctypes.c_void_p]
        lib.kv_lookup.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                  ctypes.c_int64, ctypes.c_void_p]
        lib.kv_release.restype = ctypes.c_int64
        lib.kv_release.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                   ctypes.c_int64, ctypes.c_void_p]
        lib.kv_items.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                 ctypes.c_void_p]
        lib.kv_assign_unique.restype = ctypes.c_int64
        lib.kv_assign_unique.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                         ctypes.c_int64, ctypes.c_void_p,
                                         ctypes.c_void_p]
        lib.kv_lookup_unique.restype = ctypes.c_int64
        lib.kv_lookup_unique.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                         ctypes.c_int64, ctypes.c_int32,
                                         ctypes.c_void_p, ctypes.c_void_p]
        lib.kv_arena_enable.restype = ctypes.c_int32
        lib.kv_arena_enable.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                        ctypes.c_int32]
        lib.kv_assign_slotted.restype = ctypes.c_int64
        lib.kv_assign_slotted.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                          ctypes.c_void_p, ctypes.c_int64,
                                          ctypes.c_void_p, ctypes.c_void_p]
        lib.kv_assign_unique_slotted.restype = ctypes.c_int64
        lib.kv_assign_unique_slotted.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p]
        lib.kv_dedup_first_seen.restype = ctypes.c_int64
        lib.kv_dedup_first_seen.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                            ctypes.c_void_p, ctypes.c_void_p,
                                            ctypes.c_void_p]
        lib.kv_arena_chunk_count.restype = ctypes.c_int32
        lib.kv_arena_chunk_count.argtypes = [ctypes.c_void_p]
        lib.kv_arena_export.restype = ctypes.c_int32
        lib.kv_arena_export.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                        ctypes.c_void_p]
        lib.criteo_parse.restype = ctypes.c_int64
        lib.criteo_parse.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                     ctypes.c_int64, ctypes.c_void_p,
                                     ctypes.c_void_p, ctypes.c_void_p]
        lib.slot_text_parse.restype = ctypes.c_int64
        lib.slot_text_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p]
        _LIB = lib
        return _LIB
