// Native bulk text parser — file bytes → columnar batch arrays.
//
// Role in the reference: the C++ DataFeed parse path
// (paddle/fluid/framework/data_feed.cc — MultiSlotDataFeed text parsing
// and the dlopen'd ISlotParser fast parsers, data_feed.h:450,1984). The
// reference parses line→SlotRecord objects; here the TPU-native pipeline
// is columnar end-to-end, so the native parser emits flat arrays the
// ColumnarRecords store adopts directly — no per-record Python objects,
// no per-line interpreter round trip (~40x over the python parser).
//
// Formats:
//   criteo_parse: Criteo display-ads TSV "label \t I1..I13 \t C1..C26"
//     — dense log1p(max(v,0)), missing dense → 0, categorical hex salted
//     with (slot+1)<<52 (matching python CriteoParser bit-for-bit).
//   slot_text_parse: the generic MultiSlotDataFeed wire format
//     "<n> v0..vn-1" per slot in schema order, described by a compact
//     slot-spec array (see slot_text_parse docs below).

#include <cctype>
#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

inline const char* next_line(const char* p, const char* end) {
  const char* nl = static_cast<const char*>(
      memchr(p, '\n', static_cast<size_t>(end - p)));
  return nl ? nl + 1 : end;
}

// strtof over a [p, q) field with python-float() semantics: surrounding
// whitespace tolerated (python strips it), the remaining token must parse
// COMPLETELY (float("1x") raises), and C99 hex-float forms are rejected
// (float("0x1p1") raises).
inline bool parse_float(const char* p, const char* q, float* out) {
  while (p < q && isspace(static_cast<unsigned char>(*p))) ++p;
  while (q > p && isspace(static_cast<unsigned char>(*(q - 1)))) --q;
  if (p >= q) return false;
  char tmp[64];
  size_t n = static_cast<size_t>(q - p);
  if (n >= sizeof(tmp)) return false;  // longer than any real number
  for (size_t i = 0; i < n; ++i) {
    if (p[i] == 'x' || p[i] == 'X') return false;  // hex-float form
  }
  memcpy(tmp, p, n);
  tmp[n] = 0;
  char* endp = nullptr;
  float v = strtof(tmp, &endp);
  if (endp != tmp + n) return false;
  *out = v;
  return true;
}

// a token must END at whitespace/line end — otherwise strtol("2.5")
// would accept what python's int("2.5") rejects
inline bool at_token_end(const char* c, const char* line_end) {
  return c >= line_end || isspace(static_cast<unsigned char>(*c));
}

// python float() rejects C99 hex-float forms ("0x1p1") that strtof takes
inline bool token_has_hex_marker(const char* c, const char* line_end) {
  for (; c < line_end && !isspace(static_cast<unsigned char>(*c)); ++c) {
    if (*c == 'x' || *c == 'X') return true;
  }
  return false;
}

inline bool parse_hex64(const char* p, const char* q, uint64_t* out) {
  if (p >= q) return false;
  uint64_t v = 0;
  for (const char* c = p; c < q; ++c) {
    int d;
    if (*c >= '0' && *c <= '9') d = *c - '0';
    else if (*c >= 'a' && *c <= 'f') d = *c - 'a' + 10;
    else if (*c >= 'A' && *c <= 'F') d = *c - 'A' + 10;
    else return false;
    v = (v << 4) | static_cast<uint64_t>(d);
  }
  *out = v;
  return true;
}

}  // namespace

extern "C" {

// Criteo TSV → columnar. keys_out [max_rec*26] u64, dense_out
// [max_rec*13] f32, label_out [max_rec] f32. Malformed lines are
// skipped. Returns records parsed (<= max_rec; extra lines ignored).
int64_t criteo_parse(const char* buf, int64_t len, int64_t max_rec,
                     uint64_t* keys_out, float* dense_out,
                     float* label_out) {
  const char* p = buf;
  const char* end = buf + len;
  const uint64_t kShift = 52;
  const uint64_t kMask = (1ull << kShift) - 1ull;
  int64_t n = 0;
  while (p < end && n < max_rec) {
    const char* line_end = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    if (!line_end) line_end = end;
    // split into 40 tab-separated fields (lines with more tabs → skipped)
    const char* f[41];
    int nf = 0;
    f[0] = p;
    for (const char* c = p; c < line_end && nf < 40; ++c) {
      if (*c == '\t') {
        f[++nf] = c + 1;
      }
    }
    if (nf == 39) {
      const char* fe[40];
      for (int i = 0; i < 39; ++i) fe[i] = f[i + 1] - 1;
      fe[39] = line_end;
      float label;
      if (parse_float(f[0], fe[0], &label)) {
        float* dd = dense_out + n * 13;
        for (int i = 0; i < 13; ++i) {
          float v;
          dd[i] = parse_float(f[1 + i], fe[1 + i], &v)
                      ? log1pf(v > 0.f ? v : 0.f) : 0.f;
        }
        uint64_t* kk = keys_out + n * 26;
        for (int i = 0; i < 26; ++i) {
          uint64_t h;
          if (!parse_hex64(f[14 + i], fe[14 + i], &h)) h = 0xFFFFFFFFull;
          kk[i] = (static_cast<uint64_t>(i + 1) << kShift) | (h & kMask);
        }
        label_out[n] = label;
        ++n;
      }
    }
    p = (line_end < end) ? line_end + 1 : end;
  }
  return n;
}

// Generic MultiSlotDataFeed text: per line, for each slot in schema
// order: "<count> v0 ... v<count-1>". Slot spec per slot (int32 pairs):
//   kind: 0 = uint64 sparse (used), 1 = float dense (used, `dim` vals),
//         2 = label, 3 = show, 4 = clk, 5 = skip (unused slot)
//   dim:  expected value count for kind 1 (others ignore it)
// Outputs (caller-allocated):
//   keys_out [key_cap] u64 + key_slot_out [key_cap] i32 — flat sparse
//   rec_key_offsets [max_rec+1] i64 — per-record key spans
//   dense_out [max_rec * dense_dim] f32, label/show/clk [max_rec] f32
// Returns records parsed; -1 if key_cap overflowed (caller doubles).
int64_t slot_text_parse(const char* buf, int64_t len, const int32_t* spec,
                        int64_t num_slots, int64_t dense_dim,
                        int64_t max_rec, int64_t key_cap,
                        uint64_t* keys_out, int32_t* key_slot_out,
                        int64_t* rec_key_offsets, float* dense_out,
                        float* label_out, float* show_out, float* clk_out) {
  const char* p = buf;
  const char* end = buf + len;
  int64_t n = 0;
  int64_t nkeys = 0;
  rec_key_offsets[0] = 0;
  while (p < end && n < max_rec) {
    const char* line_end = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    if (!line_end) line_end = end;
    const char* c = p;
    int64_t rec_keys_start = nkeys;
    float* dd = dense_out + n * dense_dim;
    int64_t dpos = 0;
    float label = 0.f, show = 1.f, clk = 0.f;
    bool has_label = false, has_clk = false;
    bool ok = true;
    int32_t sparse_slot_id = 0;
    for (int64_t s = 0; ok && s < num_slots; ++s) {
      int32_t kind = spec[s * 2];
      int32_t dim = spec[s * 2 + 1];
      // read count — guard the line end BEFORE strtol: it would treat
      // '\n' as skippable whitespace and consume the NEXT line's tokens
      while (c < line_end && isspace(static_cast<unsigned char>(*c))) ++c;
      if (c >= line_end) { ok = false; break; }
      char* endp = nullptr;
      long cnt = strtol(c, &endp, 10);
      if (endp == c || cnt < 0 || !at_token_end(endp, line_end)) {
        ok = false;
        break;
      }
      c = endp;
      if (kind == 1 && cnt != dim) { ok = false; break; }
      // group presence sets python's defaults even for empty groups
      // (label/clk = 0.0 when the group exists with zero values)
      if (kind == 2) { has_label = true; label = 0.f; }
      if (kind == 4) { has_clk = true; clk = 0.f; }
      for (long i = 0; ok && i < cnt; ++i) {
        while (c < line_end && isspace(static_cast<unsigned char>(*c))) ++c;
        if (c >= line_end) { ok = false; break; }
        if (kind == 5) {  // unused slot: consume the token unparsed
          while (c < line_end && !isspace(static_cast<unsigned char>(*c)))
            ++c;
        } else if (kind == 0) {
          // negatives wrap and over-range saturates in strtoull, but both
          // overflow python's uint64 cast → DROP the line on both paths
          // ('+5' parses as 5 on both)
          if (*c == '-') { ok = false; break; }
          char* ep = nullptr;
          errno = 0;
          uint64_t v = strtoull(c, &ep, 10);
          if (ep == c || errno == ERANGE
              || !at_token_end(ep, line_end)) { ok = false; break; }
          c = ep;
          if (nkeys >= key_cap) return -1;
          keys_out[nkeys] = v;
          key_slot_out[nkeys] = sparse_slot_id;
          ++nkeys;
        } else {
          if (token_has_hex_marker(c, line_end)) { ok = false; break; }
          char* ep = nullptr;
          float v = strtof(c, &ep);
          if (ep == c || !at_token_end(ep, line_end)) { ok = false; break; }
          c = ep;
          if (kind == 1) {
            if (dpos < dense_dim) dd[dpos++] = v;
          } else if (kind == 2 && i == 0) {
            label = v;
          } else if (kind == 3 && i == 0) {
            show = v;
          } else if (kind == 4 && i == 0) {
            clk = v;
          }
        }
      }
      if (kind == 0) ++sparse_slot_id;
    }
    if (ok) {
      for (int64_t i = dpos; i < dense_dim; ++i) dd[i] = 0.f;
      label_out[n] = label;
      show_out[n] = show;
      clk_out[n] = has_clk ? clk : (has_label ? label : 0.f);
      ++n;
      rec_key_offsets[n] = nkeys;
    } else {
      nkeys = rec_keys_start;  // drop the partial record's keys
    }
    p = (line_end < end) ? line_end + 1 : end;
  }
  return n;
}

}  // extern "C"
