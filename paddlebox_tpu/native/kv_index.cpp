// Host key→row hash index — native core of the embedding PS host side.
//
// Role in the reference: the GPU-resident concurrent hash map
// (paddle/fluid/framework/fleet/heter_ps/hashtable.h:113, vendored cuDF
// concurrent_unordered_map) plus BoxPS's DedupKeysAndFillIdx host logic
// (box_wrapper_impl.h:129). In the TPU design the index lives on HOST
// (device tables are static SoA arrays addressed by row), so the hot path
// is a batched uint64→int32 assign/lookup called per global batch from the
// prefetch thread; this open-addressing table makes it ~50x faster than the
// python dict it replaces.
//
// Layout: power-of-2 bucket array of {key, row} plus a 1-byte state array
// (EMPTY/FULL/TOMBSTONE — tombstones keep probe chains intact after
// release()). Linear probing with a splitmix64-mixed hash. Not thread-safe
// per instance (one prepare thread per table shard).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

enum : uint8_t { EMPTY = 0, FULL = 1, TOMB = 2 };

inline uint64_t mix(uint64_t k) {
  // splitmix64 finalizer — avalanche for clustered feasign ids
  k += 0x9e3779b97f4a7c15ull;
  k = (k ^ (k >> 30)) * 0xbf58476d1ce4e5b9ull;
  k = (k ^ (k >> 27)) * 0x94d049bb133111ebull;
  return k ^ (k >> 31);
}

// Optional slot-arena row allocator: rows are carved from fixed-size,
// chunk-aligned extents owned by one slot each, so a slot's rows cluster
// into few chunks and a (slot, local) pair addresses any row with
// local < n_chunks(slot) * chunk_size — the compact resident-pass wire
// ships per-key LOCAL rows in ~17 bits instead of per-batch dedup
// streams (train/device_pass.py). Mirrors the reference's slot-grouped
// pull/push layouts (multi-mf build groups keys by slot dim class,
// ps_gpu_wrapper.cc BuildGPUTask); here the grouping buys wire entropy.
struct Arena {
  int32_t chunk_bits = 0;  // 0 = disabled
  int32_t n_slots = 0;     // fixed at enable time (slot ids < n_slots)
  int32_t next_chunk = 0;
  int32_t max_chunks = 0;
  std::vector<int32_t> chunk_slot;   // [max_chunks] owning slot or -1
  std::vector<int32_t> chunk_rank;   // [max_chunks] rank within its slot
  std::vector<int32_t> slot_nchunks;            // [n_slots]
  std::vector<int32_t> slot_tail_chunk;         // [n_slots] current chunk
  std::vector<int32_t> slot_fill;               // rows used in tail chunk
  std::vector<std::vector<int32_t>> slot_free;  // freed global rows

  bool enabled() const { return chunk_bits > 0; }

  void init(int32_t bits, int32_t slots, int32_t max_rows) {
    chunk_bits = bits;
    n_slots = slots;
    max_chunks = (max_rows + (1 << bits) - 1) >> bits;
    chunk_slot.assign(max_chunks, -1);
    chunk_rank.assign(max_chunks, -1);
    slot_nchunks.assign(n_slots, 0);
    slot_tail_chunk.assign(n_slots, -1);
    slot_fill.assign(n_slots, 0);
    slot_free.assign(n_slots, {});
  }

  // allocate a global row from slot s's arena; -2 when out of chunks
  int32_t alloc(int32_t s, int32_t max_rows) {
    if (!slot_free[s].empty()) {
      int32_t r = slot_free[s].back();
      slot_free[s].pop_back();
      return r;
    }
    int32_t cs = 1 << chunk_bits;
    if (slot_tail_chunk[s] < 0 || slot_fill[s] == cs) {
      if (next_chunk >= max_chunks) return -2;
      int32_t c = next_chunk++;
      chunk_slot[c] = s;
      chunk_rank[c] = slot_nchunks[s]++;
      slot_tail_chunk[s] = c;
      slot_fill[s] = 0;
    }
    int32_t row = (slot_tail_chunk[s] << chunk_bits) + slot_fill[s]++;
    return row < max_rows ? row : -2;  // final partial chunk guard
  }

  // clamp out-of-range slot ids to the default (slotless) arena — the
  // caller's compact wire then sees local = -1 and falls back, instead
  // of the out-of-bounds vector writes a raw slot id would cause
  int32_t clamp_slot(int32_t s) const {
    return (s >= 0 && s < n_slots) ? s : n_slots;
  }

  // slot-local address of a global row; -1 when the row's owning arena
  // is not `s` (key previously assigned slotless or under another slot)
  int32_t local_of(int32_t row, int32_t s) const {
    if (s < 0 || s >= n_slots) return -1;  // incl. the default arena id
    int32_t c = row >> chunk_bits;
    if (chunk_slot[c] != s) return -1;
    return (chunk_rank[c] << chunk_bits) | (row & ((1 << chunk_bits) - 1));
  }
};

struct KvIndex {
  std::vector<uint64_t> keys;
  std::vector<int32_t> rows;
  std::vector<uint8_t> state;
  std::vector<int32_t> free_rows;
  uint64_t mask = 0;
  int64_t size = 0;        // live entries
  int64_t tombs = 0;       // tombstoned buckets (reclaimed only by rehash)
  int32_t next_row = 0;
  int32_t max_rows = 0;
  Arena arena;

  // per-call dedup scratch, keyed by row (rows are unique per key):
  // seen_epoch[row] == cur_epoch marks "already emitted this call";
  // seen_pos[row] is its position in the call's unique list. Lazily sized
  // max_rows+1 so the lookup sentinel row can participate too.
  std::vector<uint32_t> seen_epoch;
  std::vector<int32_t> seen_pos;
  uint32_t cur_epoch = 0;

  uint32_t next_epoch() {
    if (seen_epoch.empty()) {
      seen_epoch.assign(static_cast<size_t>(max_rows) + 1, 0);
      seen_pos.assign(static_cast<size_t>(max_rows) + 1, 0);
    }
    if (++cur_epoch == 0) {  // wrapped: stale marks could alias — clear
      std::fill(seen_epoch.begin(), seen_epoch.end(), 0);
      cur_epoch = 1;
    }
    return cur_epoch;
  }

  explicit KvIndex(int64_t capacity_hint, int32_t max_rows_) {
    uint64_t cap = 64;
    while (cap < static_cast<uint64_t>(capacity_hint) * 2) cap <<= 1;
    keys.assign(cap, 0);
    rows.assign(cap, -1);
    state.assign(cap, EMPTY);
    mask = cap - 1;
    max_rows = max_rows_;
  }

  // Rehash. Doubles when genuinely loaded; rebuilds at the same size when
  // the pressure is tombstones (assign/release churn) — reclaiming them so
  // probe chains always terminate at an EMPTY slot.
  void grow() {
    std::vector<uint64_t> ok = std::move(keys);
    std::vector<int32_t> orows = std::move(rows);
    std::vector<uint8_t> ost = std::move(state);
    uint64_t ocap = mask + 1;
    uint64_t ncap = (size * 10 >= static_cast<int64_t>(ocap) * 5)
                        ? (ocap << 1) : ocap;
    keys.assign(ncap, 0);
    rows.assign(ncap, -1);
    state.assign(ncap, EMPTY);
    mask = ncap - 1;
    for (uint64_t i = 0; i < ocap; ++i) {
      if (ost[i] == FULL) {
        uint64_t h = mix(ok[i]) & mask;
        while (state[h] == FULL) h = (h + 1) & mask;
        keys[h] = ok[i];
        rows[h] = orows[i];
        state[h] = FULL;
      }
    }
    tombs = 0;
  }

  // returns row, or -2 if table full (new key, no rows left).
  // feat_slot >= 0 routes new-key allocation to that slot's arena when
  // arena mode is on; -1 = slotless (default arena in arena mode).
  int32_t assign_one(uint64_t k, int32_t feat_slot = -1) {
    // tombstones count toward occupancy: without this, churn
    // (assign/release cycles) exhausts EMPTY slots and probes loop forever
    if ((size + tombs + 1) * 10 >= static_cast<int64_t>(mask + 1) * 7) grow();
    uint64_t h = mix(k) & mask;
    int64_t first_tomb = -1;
    for (;;) {
      uint8_t st = state[h];
      if (st == FULL && keys[h] == k) return rows[h];
      if (st == EMPTY) break;
      if (st == TOMB && first_tomb < 0) first_tomb = static_cast<int64_t>(h);
      h = (h + 1) & mask;
    }
    int32_t row;
    if (arena.enabled()) {
      int32_t s = arena.clamp_slot(feat_slot);
      row = arena.alloc(s, max_rows);
      if (row == -2) return -2;
    } else if (!free_rows.empty()) {
      row = free_rows.back();
      free_rows.pop_back();
    } else if (next_row < max_rows) {
      row = next_row++;
    } else {
      return -2;
    }
    uint64_t slot = first_tomb >= 0 ? static_cast<uint64_t>(first_tomb) : h;
    keys[slot] = k;
    rows[slot] = row;
    state[slot] = FULL;
    ++size;
    return row;
  }

  int32_t lookup_one(uint64_t k) const {
    uint64_t h = mix(k) & mask;
    for (;;) {
      uint8_t st = state[h];
      if (st == FULL && keys[h] == k) return rows[h];
      if (st == EMPTY) return -1;
      h = (h + 1) & mask;
    }
  }

  int32_t release_one(uint64_t k) {
    uint64_t h = mix(k) & mask;
    for (;;) {
      uint8_t st = state[h];
      if (st == FULL && keys[h] == k) {
        int32_t row = rows[h];
        state[h] = TOMB;
        rows[h] = -1;
        if (arena.enabled()) {  // rows return to their OWNING arena
          arena.slot_free[arena.chunk_slot[row >> arena.chunk_bits]]
              .push_back(row);
        } else {
          free_rows.push_back(row);
        }
        --size;
        ++tombs;
        return row;
      }
      if (st == EMPTY) return -1;
      h = (h + 1) & mask;
    }
  }
};

}  // namespace

extern "C" {

void* kv_create(int64_t capacity_hint, int32_t max_rows) {
  return new KvIndex(capacity_hint, max_rows);
}

void kv_destroy(void* p) { delete static_cast<KvIndex*>(p); }

int64_t kv_size(void* p) { return static_cast<KvIndex*>(p)->size; }

// assign rows for n keys; returns number assigned before the table filled
// (== n on success). rows_out[i] = row of keys[i].
int64_t kv_assign(void* p, const uint64_t* in, int64_t n, int32_t* rows_out) {
  KvIndex* kv = static_cast<KvIndex*>(p);
  constexpr int64_t PF = 16;
  for (int64_t i = 0; i < n; ++i) {
    if (i + PF < n) {
      uint64_t h = mix(in[i + PF]) & kv->mask;
      __builtin_prefetch(&kv->state[h]);
      __builtin_prefetch(&kv->keys[h]);
    }
    int32_t r = kv->assign_one(in[i]);
    if (r == -2) return i;
    rows_out[i] = r;
  }
  return n;
}

void kv_lookup(void* p, const uint64_t* in, int64_t n, int32_t* rows_out) {
  const KvIndex* kv = static_cast<KvIndex*>(p);
  for (int64_t i = 0; i < n; ++i) rows_out[i] = kv->lookup_one(in[i]);
}

// release n keys; rows_out[i] = freed row or -1; returns count freed.
int64_t kv_release(void* p, const uint64_t* in, int64_t n, int32_t* rows_out) {
  KvIndex* kv = static_cast<KvIndex*>(p);
  int64_t freed = 0;
  for (int64_t i = 0; i < n; ++i) {
    rows_out[i] = kv->release_one(in[i]);
    if (rows_out[i] >= 0) ++freed;
  }
  return freed;
}

// Fused DedupKeysAndFillIdx + assign (box_wrapper_impl.h:129 done host-side
// in ONE pass): dedup n keys in first-occurrence order, assign a row to each
// unique key, write the unique rows to uniq_rows_out (buffer sized n) and
// the key→unique-position inverse map to inverse_out (sized n). Returns the
// unique count, or -1 if the table filled. Replaces np.unique's O(n log n)
// sort with O(n) hashing — the prepare-thread hot path.
int64_t kv_assign_unique(void* p, const uint64_t* in, int64_t n,
                         int32_t* uniq_rows_out, int32_t* inverse_out) {
  KvIndex* kv = static_cast<KvIndex*>(p);
  uint32_t epoch = kv->next_epoch();
  int64_t u = 0;
  constexpr int64_t PF = 16;
  for (int64_t i = 0; i < n; ++i) {
    if (i + PF < n) {
      uint64_t h = mix(in[i + PF]) & kv->mask;
      __builtin_prefetch(&kv->state[h]);
      __builtin_prefetch(&kv->keys[h]);
    }
    int32_t row = kv->assign_one(in[i]);
    if (row == -2) return -1;
    if (kv->seen_epoch[row] != epoch) {
      kv->seen_epoch[row] = epoch;
      kv->seen_pos[row] = static_cast<int32_t>(u);
      uniq_rows_out[u] = row;
      ++u;
    }
    inverse_out[i] = kv->seen_pos[row];
  }
  return u;
}

// Read-only variant (eval/inference): unknown keys all share ONE unique
// entry holding sentinel_row (the zero row), so no index mutation happens.
int64_t kv_lookup_unique(void* p, const uint64_t* in, int64_t n,
                         int32_t sentinel_row, int32_t* uniq_rows_out,
                         int32_t* inverse_out) {
  KvIndex* kv = static_cast<KvIndex*>(p);
  uint32_t epoch = kv->next_epoch();
  int64_t u = 0;
  int32_t miss_pos = -1;
  for (int64_t i = 0; i < n; ++i) {
    int32_t row = kv->lookup_one(in[i]);
    if (row < 0) {
      if (miss_pos < 0) {
        miss_pos = static_cast<int32_t>(u);
        uniq_rows_out[u] = sentinel_row;
        ++u;
      }
      inverse_out[i] = miss_pos;
      continue;
    }
    if (kv->seen_epoch[row] != epoch) {
      kv->seen_epoch[row] = epoch;
      kv->seen_pos[row] = static_cast<int32_t>(u);
      uniq_rows_out[u] = row;
      ++u;
    }
    inverse_out[i] = kv->seen_pos[row];
  }
  return u;
}

// ---- slot arena (compact resident-pass wire) ----

// Enable chunked slot-arena allocation. Must be called before any row is
// assigned (returns -1 otherwise). slot ids must be < n_slots; slotless
// assigns draw from an internal default arena.
int32_t kv_arena_enable(void* p, int32_t chunk_bits, int32_t n_slots) {
  KvIndex* kv = static_cast<KvIndex*>(p);
  if (kv->size != 0 || kv->next_row != 0 || kv->arena.enabled()) return -1;
  kv->arena.init(chunk_bits, n_slots + 1, kv->max_rows);
  kv->arena.n_slots = n_slots;  // default arena = id n_slots (internal)
  return 0;
}

// Per-key slotted assign: rows_out[i] = global row (or the call stops at
// i and returns i when the table/arena fills); local_out[i] = slot-local
// row, or -1 when the key's row lives in another slot's arena (assigned
// earlier slotless or under a different slot) — callers seeing any -1
// fall back to the dedup wire for that pass.
int64_t kv_assign_slotted(void* p, const uint64_t* in, const uint16_t* slots,
                          int64_t n, int32_t* rows_out, int32_t* local_out) {
  KvIndex* kv = static_cast<KvIndex*>(p);
  // The per-key cost is cache misses on the bucket arrays (the table is
  // far larger than LLC at CTR scale); software-prefetch the probe
  // window a fixed distance ahead — measured ~2x on the 213k-key batch
  // assign that gates the preload pipeline.
  constexpr int64_t PF = 16;
  for (int64_t i = 0; i < n; ++i) {
    if (i + PF < n) {
      uint64_t h = mix(in[i + PF]) & kv->mask;
      __builtin_prefetch(&kv->state[h]);
      __builtin_prefetch(&kv->keys[h]);
    }
    int32_t s = static_cast<int32_t>(slots[i]);
    int32_t r = kv->assign_one(in[i], s);
    if (r == -2) return i;
    rows_out[i] = r;
    if (local_out) local_out[i] = kv->arena.local_of(r, s);
  }
  return n;
}

// Slotted variant of kv_assign_unique (same dedup contract): new keys
// allocate in their slot's arena.
int64_t kv_assign_unique_slotted(void* p, const uint64_t* in,
                                 const uint16_t* slots, int64_t n,
                                 int32_t* uniq_rows_out,
                                 int32_t* inverse_out) {
  KvIndex* kv = static_cast<KvIndex*>(p);
  uint32_t epoch = kv->next_epoch();
  int64_t u = 0;
  constexpr int64_t PF = 16;
  for (int64_t i = 0; i < n; ++i) {
    if (i + PF < n) {
      uint64_t h = mix(in[i + PF]) & kv->mask;
      __builtin_prefetch(&kv->state[h]);
      __builtin_prefetch(&kv->keys[h]);
    }
    int32_t row = kv->assign_one(in[i], static_cast<int32_t>(slots[i]));
    if (row == -2) return -1;
    if (kv->seen_epoch[row] != epoch) {
      kv->seen_epoch[row] = epoch;
      kv->seen_pos[row] = static_cast<int32_t>(u);
      uniq_rows_out[u] = row;
      ++u;
    }
    inverse_out[i] = kv->seen_pos[row];
  }
  return u;
}

// Export the chunk ownership map: chunk_slot_out/chunk_rank_out sized
// kv_arena_chunk_count(); returns the number of allocated chunks.
// chunk_map[slot, rank] = chunk id reconstructs vectorized host-side.
int32_t kv_arena_chunk_count(void* p) {
  return static_cast<KvIndex*>(p)->arena.next_chunk;
}

int32_t kv_arena_export(void* p, int32_t* chunk_slot_out,
                        int32_t* chunk_rank_out) {
  const KvIndex* kv = static_cast<KvIndex*>(p);
  int32_t n = kv->arena.next_chunk;
  std::memcpy(chunk_slot_out, kv->arena.chunk_slot.data(),
              sizeof(int32_t) * n);
  std::memcpy(chunk_rank_out, kv->arena.chunk_rank.data(),
              sizeof(int32_t) * n);
  return n;
}

// Standalone first-seen dedup — NO index instance, a call-local
// open-addressing table over the batch only. One O(n) pass replaces the
// python oracle's three (np.unique + argsort + rank scatter,
// ps/table.dedup_first_seen): uniq_out gets the distinct keys in
// first-occurrence order, first_out their first stream positions,
// inv_out each key's unique rank. Buffers sized n. Returns the unique
// count. (ISSUE 19 satellite: the stage=dedup build-seconds cut.)
int64_t kv_dedup_first_seen(const uint64_t* in, int64_t n,
                            uint64_t* uniq_out, int64_t* first_out,
                            int32_t* inv_out) {
  uint64_t cap = 64;
  while (cap < static_cast<uint64_t>(n) * 2) cap <<= 1;
  uint64_t mask = cap - 1;
  std::vector<uint64_t> keys(cap);
  std::vector<int32_t> pos(cap, -1);
  int64_t u = 0;
  constexpr int64_t PF = 16;
  for (int64_t i = 0; i < n; ++i) {
    if (i + PF < n) {
      uint64_t ph = mix(in[i + PF]) & mask;
      __builtin_prefetch(&pos[ph]);
      __builtin_prefetch(&keys[ph]);
    }
    uint64_t k = in[i];
    uint64_t h = mix(k) & mask;
    while (pos[h] >= 0 && keys[h] != k) h = (h + 1) & mask;
    if (pos[h] < 0) {
      keys[h] = k;
      pos[h] = static_cast<int32_t>(u);
      uniq_out[u] = k;
      first_out[u] = i;
      ++u;
    }
    inv_out[i] = pos[h];
  }
  return u;
}

// dump all live (key,row) pairs; buffers must hold kv_size entries.
void kv_items(void* p, uint64_t* keys_out, int32_t* rows_out) {
  const KvIndex* kv = static_cast<KvIndex*>(p);
  int64_t j = 0;
  for (uint64_t i = 0; i <= kv->mask; ++i) {
    if (kv->state[i] == FULL) {
      keys_out[j] = kv->keys[i];
      rows_out[j] = kv->rows[i];
      ++j;
    }
  }
}

}  // extern "C"
