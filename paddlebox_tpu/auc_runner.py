"""AucRunner — feature-importance evaluation by slot replacement.

Reference: fleet/box_wrapper.h:908-1009 (``InitializeAucRunner``,
``GetRandomReplace``, ``RecordReplace``/``RecordReplaceBack``,
``FlipPhase``) and box_wrapper.cc:212-335: during an eval phase, the
feasigns of chosen slots are replaced with feasigns sampled from OTHER
records (reservoir candidate pool: ``RecordCandidateList``,
data_feed.h:1484), destroying that slot's per-instance signal while
preserving its marginal distribution; the AUC drop vs the un-replaced
phase measures the slot's importance.

TPU-native redesign: replacement is immutable — ``record_replace``
returns NEW SlotRecord objects (originals are kept for
``record_replace_back``), so there is no in-place mutation racing the
reader threads, and the replaced pass flows through the normal
dataset→batch→jit path unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.data.record import SlotRecord
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


@dataclasses.dataclass
class RecordCandidateList:
    """Reservoir sample of per-slot feasign arrays (data_feed.h:1484)."""

    capacity: int
    slots: Sequence[int]
    _pool: Dict[int, List[np.ndarray]] = dataclasses.field(
        default_factory=dict)
    _seen: int = 0

    def add_all(self, records: Sequence[SlotRecord],
                rng: np.random.Generator) -> None:
        for rec in records:
            self._seen += 1
            for s in self.slots:
                pool = self._pool.setdefault(s, [])
                vals = rec.slot_keys(s).copy()
                if len(pool) < self.capacity:
                    pool.append(vals)
                else:
                    j = int(rng.integers(0, self._seen))
                    if j < self.capacity:
                        pool[j] = vals

    def sample(self, slot: int, rng: np.random.Generator) -> np.ndarray:
        pool = self._pool.get(slot) or [np.empty(0, np.uint64)]
        return pool[int(rng.integers(0, len(pool)))]

    @property
    def size(self) -> int:
        return min(self._seen, self.capacity)


class AucRunner:
    """Slot-replacement evaluation driver.

    Usage (mirrors the reference pass protocol):
        runner = AucRunner(slots_to_replace=[3, 7], pool_size=10000)
        runner.init_pass(records)              # build candidate pools
        replaced = runner.record_replace(records)   # eval pass input
        ... run eval pass on `replaced`, compare AUC ...
        records = runner.record_replace_back()      # originals
    """

    def __init__(self, slots_to_replace: Sequence[int],
                 pool_size: int = 10000, seed: int = 0) -> None:
        self.slots = list(slots_to_replace)
        self.pool_size = pool_size
        self._rng = np.random.default_rng(seed)
        self.candidates = RecordCandidateList(pool_size, self.slots)
        self._originals: Optional[List[SlotRecord]] = None
        self.phase = 1  # 1 = normal (join), 0 = replaced (eval)

    def init_pass(self, records: Sequence[SlotRecord]) -> None:
        """Collect candidate feasigns (LoadAucRunnerData role)."""
        self.candidates.add_all(records, self._rng)
        log.info("auc_runner: candidate pool size %d for slots %s",
                 self.candidates.size, self.slots)

    def flip_phase(self) -> None:
        self.phase = 1 - self.phase

    def _replace_one(self, rec: SlotRecord) -> SlotRecord:
        off = rec.slot_offsets
        num_slots = len(off) - 1
        pieces = []
        new_off = np.zeros_like(off)
        for s in range(num_slots):
            vals = (self.candidates.sample(s, self._rng)
                    if s in self.slots else rec.slot_keys(s))
            pieces.append(vals)
            new_off[s + 1] = new_off[s] + len(vals)
        keys = (np.concatenate(pieces).astype(np.uint64) if pieces
                else np.empty(0, np.uint64))
        return dataclasses.replace(rec, keys=keys, slot_offsets=new_off)

    def record_replace(
            self, records: Sequence[SlotRecord]) -> List[SlotRecord]:
        """Return records with the chosen slots' feasigns swapped for
        random candidates (RecordReplace, box_wrapper.h:970)."""
        self._originals = list(records)
        out = [self._replace_one(r) for r in records]
        self.flip_phase()
        return out

    def record_replace_back(self) -> List[SlotRecord]:
        """Restore the un-replaced records (RecordReplaceBack)."""
        if self._originals is None:
            raise RuntimeError("record_replace_back before record_replace")
        out, self._originals = self._originals, None
        self.flip_phase()
        return out

    # ---- end-to-end convenience ----
    def slot_importance(self, eval_fn, records: Sequence[SlotRecord],
                        ) -> Dict[int, float]:
        """AUC drop per slot: eval_fn(records) -> auc. Runs one baseline
        eval plus one replaced eval per slot (each slot in isolation)."""
        base = eval_fn(list(records))
        out: Dict[int, float] = {}
        all_slots = self.slots
        for s in all_slots:
            self.slots = [s]
            replaced = self.record_replace(records)
            auc = eval_fn(replaced)
            self.record_replace_back()
            out[s] = base - auc
        self.slots = all_slots
        return out
