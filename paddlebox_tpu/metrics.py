"""Training metrics: bucketed AUC + error stats, cross-device reducible.

Reference: paddle/fluid/framework/fleet/metrics.{h,cc} —
``BasicAucCalculator`` (metrics.h:46): 1e6-bucket pos/neg tables keyed by
``int(pred * table_size)``, cross-worker allreduce_sum of the tables before
computing AUC/actual_ctr/predicted_ctr/MAE/RMSE (metrics.cc:288-304);
``Metric``/``MetricMsg`` name registry with phase filtering (metrics.h:198).

TPU-native redesign: the bucket tables are device arrays updated with one
``segment_sum`` per batch inside the jit train step (no host sync in the hot
loop); multi-chip reduction is a ``psum`` over the data axis (or host-side
np.sum over per-shard states) instead of MPI/Gloo allreduce. Final compute
is host numpy on the tiny [2, nbins] pull.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.config import FLAGS
from paddlebox_tpu.utils.logging import get_logger

log = get_logger(__name__)


class AucState(NamedTuple):
    pos: jax.Array        # f32 [nbins]
    neg: jax.Array        # f32 [nbins]
    abs_err: jax.Array    # f32 scalar
    sqr_err: jax.Array    # f32 scalar
    pred_sum: jax.Array   # f32 scalar
    label_sum: jax.Array  # f32 scalar
    ins_num: jax.Array    # f32 scalar


def init_auc_state(nbins: Optional[int] = None) -> AucState:
    n = nbins or FLAGS.auc_num_buckets
    # distinct buffers per field: StepState is donated in the jit step and
    # aliased leaves would be donated twice
    return AucState(jnp.zeros(n, jnp.float32), jnp.zeros(n, jnp.float32),
                    *(jnp.zeros((), jnp.float32) for _ in range(5)))


def auc_add_batch(state: AucState, pred: jax.Array, label: jax.Array,
                  weight: jax.Array) -> AucState:
    """Jittable accumulate (BasicAucCalculator::add_data, metrics.h:68).
    ``weight`` masks padding instances (0) and can carry show weights."""
    n = state.pos.shape[0]
    b = jnp.clip((pred * n).astype(jnp.int32), 0, n - 1)
    w = weight.astype(jnp.float32)
    lw = label.astype(jnp.float32) * w
    # ONE histogram scatter for both tables (TPU scatters carry a large
    # fixed per-call cost — measured ~20ms/call on v5p regardless of
    # update count): pos buckets at [0, n), neg at [n, 2n)
    both = jax.ops.segment_sum(
        jnp.concatenate([lw, w - lw]),
        jnp.concatenate([b, b + n]), num_segments=2 * n)
    pos = state.pos + both[:n]
    neg = state.neg + both[n:]
    err = (pred - label) * w
    return AucState(
        pos=pos, neg=neg,
        abs_err=state.abs_err + jnp.sum(jnp.abs(err)),
        sqr_err=state.sqr_err + jnp.sum(err * err),
        pred_sum=state.pred_sum + jnp.sum(pred * w),
        label_sum=state.label_sum + jnp.sum(label.astype(jnp.float32) * w),
        ins_num=state.ins_num + jnp.sum(w),
    )


def auc_compute_global(state: AucState, collective) -> AucResult:
    """Cross-worker AUC (BasicAucCalculator's MPI reduce,
    metrics.cc:288-304): allreduce the bucket tables and scalar error
    sums over the host collective (distributed.collective.TcpCollective)
    and compute ONE global AUC, identical on every rank. Uses the f64
    host compute path regardless of FLAGS.auc_device_reduce."""
    host = [np.asarray(jax.device_get(x)) for x in state]
    reduced = collective.allreduce_sum(host)
    return auc_compute(AucState(*reduced))


@dataclasses.dataclass
class AucResult:
    auc: float
    actual_ctr: float
    predicted_ctr: float
    mae: float
    rmse: float
    ins_num: float

    def as_dict(self) -> Dict[str, float]:
        return dataclasses.asdict(self)


@jax.jit
def _auc_reduce(state: AucState) -> jax.Array:
    """On-device scalar reduction of the bucket tables → [8] vector
    [area, tot_pos, tot_neg, abs_err, sqr_err, pred_sum, label_sum,
    ins_num]. XLA's tree reductions/scans keep f32 error ~log2(nbins)·eps,
    so AUC agrees with the f64 host path to ~1e-5."""
    pos, neg = state.pos, state.neg
    cum_neg_below = jnp.cumsum(neg) - neg
    area = jnp.sum(pos * (cum_neg_below + 0.5 * neg))
    return jnp.stack([area, jnp.sum(pos), jnp.sum(neg), state.abs_err,
                      state.sqr_err, state.pred_sum, state.label_sum,
                      state.ins_num])


def auc_compute(state: AucState) -> AucResult:
    """Final compute (BasicAucCalculator::compute, metrics.cc: bucket scan
    → area / (pos_total * neg_total)). Default = exact f64 host compute
    (pulls the full tables). Set FLAGS.auc_device_reduce=True to reduce on
    device and fetch 8 scalars instead — the tunneled/remote-device
    optimization (~1e-5 AUC drift in f32)."""
    if FLAGS.auc_device_reduce and isinstance(state.pos, jax.Array):
        (area, tot_pos, tot_neg, abs_err, sqr_err, pred_sum, label_sum,
         ins) = (float(x) for x in np.asarray(
             jax.device_get(_auc_reduce(state)), np.float64))
        auc = area / (tot_pos * tot_neg) if tot_pos > 0 and tot_neg > 0 \
            else 0.5
        ins_safe = max(ins, 1e-12)
        return AucResult(
            auc=auc, actual_ctr=label_sum / ins_safe,
            predicted_ctr=pred_sum / ins_safe, mae=abs_err / ins_safe,
            rmse=float(np.sqrt(sqr_err / ins_safe)), ins_num=ins)
    # ONE batched pull for all 7 leaves — per-leaf device_get costs a
    # ~0.25 s roundtrip EACH on tunneled runtimes
    h = AucState(*jax.device_get(tuple(state)))
    pos = np.asarray(h.pos, np.float64)
    neg = np.asarray(h.neg, np.float64)
    tot_pos, tot_neg = pos.sum(), neg.sum()
    cum_neg_below = np.concatenate([[0.0], np.cumsum(neg)[:-1]])
    # P(pos-bucket > neg-bucket) + 0.5 P(tie), summed per bucket
    area = np.sum(pos * (cum_neg_below + 0.5 * neg))
    auc = float(area / (tot_pos * tot_neg)) if tot_pos > 0 and tot_neg > 0 else 0.5
    ins = float(h.ins_num)
    ins_safe = max(ins, 1e-12)
    return AucResult(
        auc=auc,
        actual_ctr=float(h.label_sum) / ins_safe,
        predicted_ctr=float(h.pred_sum) / ins_safe,
        mae=float(h.abs_err) / ins_safe,
        rmse=float(np.sqrt(float(h.sqr_err) / ins_safe)),
        ins_num=ins,
    )


def auc_merge(states: Tuple[AucState, ...]) -> AucState:
    """Cross-worker table reduce (metrics.cc:288-304) — host-side merge of
    per-worker states (the in-jit path uses psum on the data axis instead)."""
    return AucState(*[
        jnp.sum(jnp.stack([getattr(s, f) for s in states]), axis=0)
        for f in AucState._fields
    ])


class Metric:
    """Named metric with phase filter (MetricMsg, metrics.h:198 /
    box_wrapper.h:265). method: 'auc' (others in metrics_ext)."""

    def __init__(self, name: str, label: str = "label", pred: str = "pred",
                 phase: int = -1, nbins: Optional[int] = None) -> None:
        self.name = name
        self.label_var = label
        self.pred_var = pred
        self.phase = phase  # -1: all phases (join/update)
        self.state = init_auc_state(nbins)

    def add(self, pred: jax.Array, label: jax.Array,
            weight: jax.Array) -> None:
        self.state = auc_add_batch(self.state, pred, label, weight)

    def compute(self) -> AucResult:
        return auc_compute(self.state)

    def reset(self) -> None:
        self.state = init_auc_state(self.state.pos.shape[0])


class MetricRegistry:
    """init_metric/get_metric_msg surface (pybind box_helper_py.cc:99-160).

    ``method`` selects the metric variant (metrics_ext.METRIC_METHODS):
    auc | cmatch_rank_auc | mask_auc | cmatch_rank_mask_auc |
    multi_task_auc | continue_value | nan_inf | wuauc."""

    def __init__(self) -> None:
        self._metrics: Dict[str, object] = {}
        self.phase = 1  # 1=join, 0=update (FlipPhase semantics)
        self._warned_missing: set = set()

    def init_metric(self, name: str, method: str = "auc", **kwargs):
        from paddlebox_tpu.metrics_ext import METRIC_METHODS
        try:
            cls = METRIC_METHODS[method]
        except KeyError:
            raise ValueError(
                f"unknown metric method {method!r}; "
                f"one of {sorted(METRIC_METHODS)}") from None
        m = cls(name, **kwargs)
        self._metrics[name] = m
        return m

    def get(self, name: str):
        return self._metrics[name]

    def get_metric_msg(self, name: str) -> Dict[str, float]:
        out = self._metrics[name].compute()
        return out.as_dict() if isinstance(out, AucResult) else out

    def add_batch(self, pred, label, weight=None, **inputs) -> None:
        """Feed every phase-active metric from one batch — the per-batch
        AddAucMonitor hook (boxps_worker.cc:1267). ``inputs`` carries the
        side channels (uid/rank/cmatch/mask…); None values are dropped so
        metrics that don't need them never see them. A metric whose
        REQUIRED side channels are absent from this feed is skipped (with
        a one-time warning) instead of crashing the pass."""
        kw = {k: v for k, v in inputs.items() if v is not None}
        for name, m in self.active().items():
            missing = [r for r in getattr(m, "REQUIRED", ()) if r not in kw]
            if missing:
                if name not in self._warned_missing:
                    self._warned_missing.add(name)
                    log.warning(
                        "metric %r skipped: feed lacks required side "
                        "channel(s) %s", name, missing)
                continue
            # keywords throughout: some variants take only (pred, **_)
            m.add(pred, label=label, weight=weight, **kw)

    def flip_phase(self) -> None:
        self.phase = 1 - self.phase

    def __len__(self) -> int:
        return len(self._metrics)

    def active(self) -> Dict[str, Metric]:
        return {k: m for k, m in self._metrics.items()
                if m.phase in (-1, self.phase)}

    def reset_all(self) -> None:
        for m in self._metrics.values():
            m.reset()
