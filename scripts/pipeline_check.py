#!/usr/bin/env python
"""Deterministic async-epilogue gate (docs/PERFORMANCE.md).

Runs the SAME small 3-pass tiered job twice — once with the
asynchronous end_pass epilogue (FLAGS.async_end_pass=True, the
default) and once fully synchronous — and asserts:

(a) the final host-tier state digests are IDENTICAL (the async
    epilogue's fence rules preserve the bit-for-bit delta==full
    semantics of the pass lifecycle), and
(b) the async run measured end_pass overlap > 0 (write-back seconds
    that never blocked the main thread — the epilogue actually left
    the critical path).

The job drives the tiered table's pass protocol directly with a
deterministic device mutation per pass (value = f(key, pass)) over
sliding ~90%-overlap working sets, staging pass k+1 overlapped while
pass k is open — the production pipeline shape (stage_pass /
pre_build_thread) without a model in the loop, so the gate is fast and
bit-exact by construction. ``python scripts/pipeline_check.py`` prints
one JSON line; tests/test_pipeline_check.py runs a smaller variant in
tier-1.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import time
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def _pass_keys(p: int, keys_per_pass: int, overlap_frac: float
               ) -> np.ndarray:
    """Sliding key window: consecutive passes share ~overlap_frac."""
    step = max(1, int(round(keys_per_pass * (1.0 - overlap_frac))))
    base = 1 + p * step
    return np.arange(base, base + keys_per_pass, dtype=np.uint64)


def _train_mutate(table, p: int) -> None:
    """Deterministic stand-in for a training pass: every resident
    working-set row's embed_w becomes f(key, p); rows marked touched as
    prepare()/mark_trained_rows would."""
    import jax

    from paddlebox_tpu.ps.table import FIELD_COL
    data = np.asarray(jax.device_get(table.state.data)).copy()
    with table.host_lock:
        for s in range(table.n):
            keys, rows = table.indexes[s].items()
            if not len(rows):
                continue
            data[s][rows, FIELD_COL["embed_w"]] = (
                keys.astype(np.float64) * 0.001 + (p + 1)).astype(
                    np.float32)
            data[s][rows, FIELD_COL["show"]] += 1.0
            table._touched[s][rows] = True
        data[:, table.capacity, :] = 0.0  # sentinel stays zero
        table.state = type(table.state).from_logical(
            data, table.capacity, ext=table.opt_ext)


def host_tier_digest(table) -> str:
    """sha256 over every shard's sorted (keys, fields) export — fences
    the epilogue implicitly (HostStore.read_barrier)."""
    h = hashlib.sha256()
    for s in range(table.n):
        keys, fields = table.hosts[s].export_rows()
        order = np.argsort(keys)
        h.update(np.ascontiguousarray(keys[order]).tobytes())
        for f in sorted(fields):
            h.update(f.encode())
            h.update(np.ascontiguousarray(fields[f][order]).tobytes())
    return h.hexdigest()


def _run_job(async_mode: bool, passes: int, shards: int,
             keys_per_pass: int, overlap_frac: float,
             capacity_per_shard: int) -> Dict:
    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.tiered import TieredShardedEmbeddingTable
    with flags_scope(async_end_pass=async_mode,
                     warmup_pass_scatter=False):
        table = TieredShardedEmbeddingTable(
            shards, mf_dim=2, capacity_per_shard=capacity_per_shard,
            cfg=SparseSGDConfig(mf_create_thresholds=0.0,
                                mf_initial_range=0.0))
        key_sets = [_pass_keys(p, keys_per_pass, overlap_frac)
                    for p in range(passes)]
        table.stage(key_sets[0], background=False)
        table.begin_pass(key_sets[0])
        for p in range(passes):
            _train_mutate(table, p)
            if p + 1 < passes:
                # the production overlap shape: pass p+1's host fetch
                # rides pass p's open window (stage_pass)
                table.stage(key_sets[p + 1], background=True)
            table.end_pass()
            # stand-in for the next pass's TRAIN time: the gate asserts
            # overlap > 0, which needs the worker some wall-clock before
            # the next fence point — on a starved single-core runner the
            # worker might otherwise only get scheduled inside a fence,
            # clamping overlap to 0 with no code defect (a main-thread
            # sleep yields the core exactly like device compute would)
            time.sleep(0.02)
            if p + 1 < passes:
                table.begin_pass(key_sets[p + 1])
        digest = host_tier_digest(table)  # fences the epilogue
        eps = table.endpass_stats()
        return {"digest": digest,
                "rows": table.feature_count(),
                "endpass": {k: round(v, 6) if isinstance(v, float) else v
                            for k, v in eps.items()}}


def run_check(passes: int = 3, shards: int = 4, keys_per_pass: int = 512,
              overlap_frac: float = 0.9,
              capacity_per_shard: int = 1024) -> Dict:
    """The gate. Raises AssertionError on any violated invariant;
    returns the evidence record."""
    assert passes >= 3, "the gate's pipeline shape needs >= 3 passes"
    sync = _run_job(False, passes, shards, keys_per_pass, overlap_frac,
                    capacity_per_shard)
    async_ = _run_job(True, passes, shards, keys_per_pass, overlap_frac,
                      capacity_per_shard)
    assert async_["rows"] == sync["rows"], (
        f"row count diverged: async {async_['rows']} != sync "
        f"{sync['rows']}")
    assert async_["digest"] == sync["digest"], (
        "async end_pass produced a DIFFERENT host-tier state than the "
        f"synchronous path: {async_['digest'][:16]}… != "
        f"{sync['digest'][:16]}…")
    eps = async_["endpass"]
    assert eps["jobs_run"] >= passes, (
        f"expected >= {passes} async write-back jobs, ran "
        f"{eps['jobs_run']}")
    assert eps["pending"] == 0, "digest fenced, yet jobs still pending"
    assert eps["overlap_sec"] > 0.0, (
        "async epilogue measured ZERO overlap — every write-back second "
        f"blocked the main thread ({eps})")
    return {
        "check": "pipeline_check",
        "ok": True,
        "passes": passes,
        "shards": shards,
        "keys_per_pass": keys_per_pass,
        "overlap_frac_keys": overlap_frac,
        "digest": async_["digest"],
        "rows": async_["rows"],
        "async_endpass": async_["endpass"],
    }


def main() -> None:
    shards = int(os.environ.get("PIPECHECK_SHARDS", "4"))
    passes = int(os.environ.get("PIPECHECK_PASSES", "3"))
    keys = int(os.environ.get("PIPECHECK_KEYS", "4096"))
    out = run_check(passes=passes, shards=shards, keys_per_pass=keys,
                    capacity_per_shard=max(1024, keys))
    print(json.dumps(out))


if __name__ == "__main__":
    main()
