#!/usr/bin/env python
"""Deterministic pass-pipeline gates (docs/PERFORMANCE.md).

EPILOGUE gate (``run_check``): runs the SAME small 3-pass tiered job
twice — once with the asynchronous end_pass epilogue
(FLAGS.async_end_pass=True, the default) and once fully synchronous —
and asserts:

(a) the final host-tier state digests are IDENTICAL (the async
    epilogue's fence rules preserve the bit-for-bit delta==full
    semantics of the pass lifecycle), and
(b) the async run measured end_pass overlap > 0 (write-back seconds
    that never blocked the main thread — the epilogue actually left
    the critical path).

The job drives the tiered table's pass protocol directly with a
deterministic device mutation per pass (value = f(key, pass)) over
sliding ~90%-overlap working sets, staging pass k+1 overlapped while
pass k is open — the production pipeline shape (stage_pass /
pre_build_thread) without a model in the loop, so the gate is fast and
bit-exact by construction.

PROLOGUE gate (``run_prologue_check``, ISSUE 5): the depth-N preload
pipeline's twin —

(a) scheduling property: with deterministic sleep-timed builds
    (bimodal, avg build < train — the BENCH_r05 shape), the depth-N
    pipeline's steady-state per-pass wait drops vs depth-1 (the queue
    absorbs the slow builds instead of joining on each), and
(b) bit-identity: a REAL 4-pass single-chip resident training job run
    at depth N produces the exact logical-state digest
    (train/checkpoint.state_digest: table rows keyed+sorted by
    feasign, dense params, optimizer, AUC) of the depth-1 run — the
    deeper pipeline changes scheduling only, never results.

``python scripts/pipeline_check.py`` prints one JSON line per gate;
tests/test_pipeline_check.py runs smaller variants in tier-1.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import time
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def _pass_keys(p: int, keys_per_pass: int, overlap_frac: float
               ) -> np.ndarray:
    """Sliding key window: consecutive passes share ~overlap_frac."""
    step = max(1, int(round(keys_per_pass * (1.0 - overlap_frac))))
    base = 1 + p * step
    return np.arange(base, base + keys_per_pass, dtype=np.uint64)


def _train_mutate(table, p: int) -> None:
    """Deterministic stand-in for a training pass: every resident
    working-set row's embed_w becomes f(key, p); rows marked touched as
    prepare()/mark_trained_rows would."""
    import jax

    from paddlebox_tpu.ps.table import FIELD_COL
    data = np.asarray(jax.device_get(table.state.data)).copy()
    with table.host_lock:
        for s in range(table.n):
            keys, rows = table.indexes[s].items()
            if not len(rows):
                continue
            data[s][rows, FIELD_COL["embed_w"]] = (
                keys.astype(np.float64) * 0.001 + (p + 1)).astype(
                    np.float32)
            data[s][rows, FIELD_COL["show"]] += 1.0
            table._touched[s][rows] = True
        data[:, table.capacity, :] = 0.0  # sentinel stays zero
        table.state = type(table.state).from_logical(
            data, table.capacity, ext=table.opt_ext)


def host_tier_digest(table) -> str:
    """sha256 over every shard's sorted (keys, fields) export — fences
    the epilogue implicitly (HostStore.read_barrier)."""
    h = hashlib.sha256()
    for s in range(table.n):
        keys, fields = table.hosts[s].export_rows()
        order = np.argsort(keys)
        h.update(np.ascontiguousarray(keys[order]).tobytes())
        for f in sorted(fields):
            h.update(f.encode())
            h.update(np.ascontiguousarray(fields[f][order]).tobytes())
    return h.hexdigest()


def _run_job(async_mode: bool, passes: int, shards: int,
             keys_per_pass: int, overlap_frac: float,
             capacity_per_shard: int) -> Dict:
    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.tiered import TieredShardedEmbeddingTable
    with flags_scope(async_end_pass=async_mode,
                     warmup_pass_scatter=False):
        table = TieredShardedEmbeddingTable(
            shards, mf_dim=2, capacity_per_shard=capacity_per_shard,
            cfg=SparseSGDConfig(mf_create_thresholds=0.0,
                                mf_initial_range=0.0))
        key_sets = [_pass_keys(p, keys_per_pass, overlap_frac)
                    for p in range(passes)]
        table.stage(key_sets[0], background=False)
        table.begin_pass(key_sets[0])
        for p in range(passes):
            _train_mutate(table, p)
            if p + 1 < passes:
                # the production overlap shape: pass p+1's host fetch
                # rides pass p's open window (stage_pass)
                table.stage(key_sets[p + 1], background=True)
            table.end_pass()
            # stand-in for the next pass's TRAIN time: the gate asserts
            # overlap > 0, which needs the worker some wall-clock before
            # the next fence point — on a starved single-core runner the
            # worker might otherwise only get scheduled inside a fence,
            # clamping overlap to 0 with no code defect (a main-thread
            # sleep yields the core exactly like device compute would)
            time.sleep(0.02)
            if p + 1 < passes:
                table.begin_pass(key_sets[p + 1])
        digest = host_tier_digest(table)  # fences the epilogue
        eps = table.endpass_stats()
        return {"digest": digest,
                "rows": table.feature_count(),
                "endpass": {k: round(v, 6) if isinstance(v, float) else v
                            for k, v in eps.items()}}


def run_check(passes: int = 3, shards: int = 4, keys_per_pass: int = 512,
              overlap_frac: float = 0.9,
              capacity_per_shard: int = 1024) -> Dict:
    """The gate. Raises AssertionError on any violated invariant;
    returns the evidence record."""
    assert passes >= 3, "the gate's pipeline shape needs >= 3 passes"
    sync = _run_job(False, passes, shards, keys_per_pass, overlap_frac,
                    capacity_per_shard)
    async_ = _run_job(True, passes, shards, keys_per_pass, overlap_frac,
                      capacity_per_shard)
    assert async_["rows"] == sync["rows"], (
        f"row count diverged: async {async_['rows']} != sync "
        f"{sync['rows']}")
    assert async_["digest"] == sync["digest"], (
        "async end_pass produced a DIFFERENT host-tier state than the "
        f"synchronous path: {async_['digest'][:16]}… != "
        f"{sync['digest'][:16]}…")
    eps = async_["endpass"]
    assert eps["jobs_run"] >= passes, (
        f"expected >= {passes} async write-back jobs, ran "
        f"{eps['jobs_run']}")
    assert eps["pending"] == 0, "digest fenced, yet jobs still pending"
    assert eps["overlap_sec"] > 0.0, (
        "async epilogue measured ZERO overlap — every write-back second "
        f"blocked the main thread ({eps})")
    return {
        "check": "pipeline_check",
        "ok": True,
        "passes": passes,
        "shards": shards,
        "keys_per_pass": keys_per_pass,
        "overlap_frac_keys": overlap_frac,
        "digest": async_["digest"],
        "rows": async_["rows"],
        "async_endpass": async_["endpass"],
    }


# ---- prologue gate: the depth-N preload pipeline (ISSUE 5) ----------


class _TimedPass:
    """Synthetic staged-pass token for the scheduling-property check:
    the preloader only needs upload()/nbytes() from it."""

    def upload(self, materialize: bool = False) -> None:
        pass

    def nbytes(self) -> int:
        return 0


def measure_preload_waits(depth: int, passes: int, train_sec: float,
                          build_secs) -> List[float]:
    """Per-pass consumer wait with sleep-timed builds: deterministic by
    construction (the waits are structural — build/train overlap
    arithmetic — not load-dependent)."""
    from paddlebox_tpu.train.device_pass import PassPreloader

    def build(d: float) -> _TimedPass:
        time.sleep(d)
        return _TimedPass()

    durations = [build_secs[i % len(build_secs)] for i in range(passes)]
    pre = PassPreloader(iter(durations), build_fn=build, depth=depth,
                        hbm_budget_bytes=0)
    pre.start_next()
    waits: List[float] = []
    while True:
        t0 = time.perf_counter()
        rp = pre.wait()
        if rp is None:
            break
        waits.append(time.perf_counter() - t0)
        pre.start_next()
        time.sleep(train_sec)  # stand-in for device train time
    pre.drain()
    return waits


def _make_pass_dataset(desc, num_records: int, seed: int):
    """Tiny synthetic in-memory pass (criteo-shaped, 4 sparse slots)."""
    import numpy as np

    from paddlebox_tpu.data import InMemoryDataset
    from paddlebox_tpu.data.record import SlotRecord
    rng = np.random.default_rng(seed)
    n_slots = len(desc.sparse_slots)
    offsets = np.arange(n_slots + 1, dtype=np.int32)
    ds = InMemoryDataset(desc)
    for i in range(num_records):
        label = float(rng.random() < 0.3)
        ds.records.append(SlotRecord(
            keys=(rng.integers(0, 500, size=n_slots)
                  + np.arange(n_slots) * 500).astype(np.uint64),
            slot_offsets=offsets,
            dense=rng.normal(size=desc.dense_dim).astype(np.float32),
            label=label, show=1.0, clk=label))
    return ds


def _resident_job_digest(depth: int, passes: int,
                         num_records: int) -> str:
    """One small single-chip resident training job driven through the
    depth-``depth`` preload pipeline → logical-state digest."""
    import optax

    from paddlebox_tpu.data import DataFeedDesc, SlotDef
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.train import Trainer
    from paddlebox_tpu.train.checkpoint import state_digest
    slots = [SlotDef("label", "float", 1), SlotDef("dense", "float", 4)]
    slots += [SlotDef(f"C{i}", "uint64") for i in range(1, 5)]
    desc = DataFeedDesc(slots=slots, batch_size=64, label_slot="label",
                        key_bucket_min=256)
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 12, cfg=cfg,
                           unique_bucket_min=256)
    tr = Trainer(DeepFM(hidden=(8,)), table, desc, tx=optax.adam(1e-2),
                 seed=7)
    datasets = [_make_pass_dataset(desc, num_records, seed=s % 2)
                for s in range(passes)]
    results = tr.train_passes_resident(datasets, depth=depth)
    assert len(results) == passes
    return state_digest(tr)


def run_prologue_check(passes: int = 9, train_sec: float = 0.1,
                       build_secs=(0.02, 0.16),
                       real_passes: int = 4,
                       real_records: int = 192,
                       depth: int = 2) -> Dict:
    """The depth-N preload gate. Raises AssertionError on any violated
    invariant; returns the evidence record."""
    assert passes >= 6, "steady-state needs a few passes past warmup"
    # the wait arithmetic is deterministic for an ideal scheduler, but
    # a loaded CI box can delay one worker wakeup by ~100 ms and eat
    # the margin — measure up to 3 times and gate on the best attempt
    # (a scheduling PROPERTY holds if any clean measurement shows it;
    # noise only ever inflates waits)
    steady1 = steadyn = 0.0
    w1 = wn = []
    for attempt in range(3):
        w1 = measure_preload_waits(1, passes, train_sec, build_secs)
        wn = measure_preload_waits(depth, passes, train_sec, build_secs)
        assert len(w1) == len(wn) == passes
        # steady state skips the first two passes (cold build + fill)
        steady1 = sum(w1[2:])
        steadyn = sum(wn[2:])
        if steady1 > train_sec / 4 and steadyn <= 0.5 * steady1:
            break
    # with avg build < train, depth-1 still waits on every slow build;
    # the depth-N queue buffers them — wait must at least halve (it
    # lands near zero; 0.5 leaves room for scheduler wakeup noise)
    assert steady1 > train_sec / 4, (
        f"depth-1 baseline shows no prologue stall ({steady1:.3f}s) — "
        "the gate's build/train timing no longer exercises the "
        f"pipeline (waits: {w1})")
    assert steadyn <= 0.5 * steady1, (
        f"depth-{depth} steady-state preload wait {steadyn:.3f}s did "
        f"not drop >=50% vs depth-1 {steady1:.3f}s "
        f"(depth-1 {w1}, depth-{depth} {wn})")
    d1 = _resident_job_digest(1, real_passes, real_records)
    dn = _resident_job_digest(depth, real_passes, real_records)
    assert dn == d1, (
        f"depth-{depth} resident training produced a DIFFERENT "
        f"logical state than depth-1: {dn[:16]}… != {d1[:16]}…")
    return {
        "check": "prologue_check",
        "ok": True,
        "depth": depth,
        "passes": passes,
        "steady_wait_sec_depth1": round(steady1, 4),
        f"steady_wait_sec_depth{depth}": round(steadyn, 4),
        "wait_drop_frac": round(1.0 - steadyn / max(steady1, 1e-9), 4),
        "real_passes": real_passes,
        "digest": dn,
    }


# ---- tiered prologue gate: the unified pass pipeline (ISSUE 9) -----


class _StagedPassToken:
    """Synthetic staged-pass token for the tiered pipeline gate (the
    preloader needs only upload()/nbytes())."""

    def upload(self, materialize: bool = False) -> None:
        pass

    def nbytes(self) -> int:
        return 0


def _train_mutate_keys(table, keys: np.ndarray, p: int) -> None:
    """Deterministic stand-in for training ONE pass: only the pass's
    WORKING-SET rows mutate (embed_w = f(key, p)) and get marked
    touched — exactly the trainer's footprint (mark_trained_rows).
    Unlike ``_train_mutate`` it never touches other resident rows, so
    future passes' plan-pending rows stay value-less and pinned (the
    depth-N pipeline keeps several pending at once)."""
    import jax

    from paddlebox_tpu.ps.table import FIELD_COL
    data = np.asarray(jax.device_get(table.state.data)).copy()
    with table.host_lock:
        for s, ks in enumerate(table._split_by_owner(keys)):
            rows = table.indexes[s].lookup(ks)
            ok = rows >= 0
            ks, rows = ks[ok], rows[ok]
            if not len(rows):
                continue
            data[s][rows, FIELD_COL["embed_w"]] = (
                ks.astype(np.float64) * 0.001 + (p + 1)).astype(
                    np.float32)
            data[s][rows, FIELD_COL["show"]] += 1.0
            table._touched[s][rows] = True
        data[:, table.capacity, :] = 0.0  # sentinel stays zero
        table.state = type(table.state).from_logical(
            data, table.capacity, ext=table.opt_ext)


def _tiered_pipeline_job(depth: int, passes: int, shards: int,
                         keys_per_pass: int, overlap_frac: float,
                         capacity_per_shard: int, build_delay: float,
                         train_sec: float) -> Dict:
    """One tiered job through train/device_pass.PassPipeline at the
    given depth: the build_fn mimics a routing-plan build (plan-assigns
    the pass keys — PassPipeline brackets it in plan_scope, so new keys
    become pending rows) plus a deterministic ``build_delay`` sleep
    standing in for the dedup/pack/H2D work; the host fetch then rides
    the same worker (stage queue). Training is the deterministic
    ``_train_mutate`` device mutation + a ``train_sec`` sleep standing
    in for device compute. depth=0 = the sequential kick-per-pass
    oracle (build+stage strictly between passes). Returns the host-tier
    digest and the per-pass critical-path boundary stall
    (preload wait + begin_pass)."""
    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.tiered import TieredShardedEmbeddingTable
    from paddlebox_tpu.train.device_pass import PassPipeline
    with flags_scope(async_end_pass=True, warmup_pass_scatter=False):
        table = TieredShardedEmbeddingTable(
            shards, mf_dim=2, capacity_per_shard=capacity_per_shard,
            cfg=SparseSGDConfig(mf_create_thresholds=0.0,
                                mf_initial_range=0.0))
        key_sets = [_pass_keys(p, keys_per_pass, overlap_frac)
                    for p in range(passes)]

        def build(keys_arr) -> _StagedPassToken:
            # the routing-plan assign of a real build (ps/sharded
            # prepare_global under plan_scope): new keys become
            # value-less PENDING rows the begin_pass reconcile fills
            for s, ks in enumerate(table._split_by_owner(keys_arr)):
                if not len(ks):
                    continue
                with table.host_lock:
                    pre = table.indexes[s].lookup(ks)
                    table.indexes[s].assign(ks)
                    if (pre < 0).any():
                        table._note_plan_assigned(s, ks[pre < 0])
            time.sleep(build_delay)   # dedup/pack/H2D stand-in
            return _StagedPassToken()

        pipe = PassPipeline(iter(key_sets), build_fn=build,
                            window_table=table, depth=depth,
                            keys_of=lambda k: k)
        pipe.start_next()
        stalls: List[float] = []
        for p in range(passes):
            t0 = time.perf_counter()
            rp = pipe.wait()
            assert rp is not None
            pipe.begin_pass()
            stalls.append(time.perf_counter() - t0)
            if depth > 0:
                pipe.start_next()
            _train_mutate_keys(table, key_sets[p], p)
            time.sleep(train_sec)     # device-compute stand-in
            pipe.end_pass()
            if depth == 0:
                # sequential oracle: the next build+stage only AFTER
                # this pass fully closed (kick-per-pass credit)
                pipe.start_next()
        pipe.drain()
        table.fence()
        digest = host_tier_digest(table)
        return {"digest": digest, "rows": table.feature_count(),
                "stalls": stalls}


def run_tiered_prologue_check(passes: int = 5, shards: int = 4,
                              keys_per_pass: int = 512,
                              overlap_frac: float = 0.9,
                              capacity_per_shard: int = 1024,
                              build_delay: float = 0.05,
                              train_sec: float = 0.1,
                              depth: int = 2) -> Dict:
    """The tiered pipeline gate (ISSUE 9): (a) a depth-``depth`` tiered
    run through the unified PassPipeline reproduces the depth-0
    sequential oracle's host-tier state digest BIT-FOR-BIT, ×2 seeded
    runs (the pipeline changes scheduling only, never results — and
    both runs of each depth agree, proving determinism), and (b) the
    steady-state begin_delta boundary stall (preload wait + begin_pass)
    drops ≥50% vs the no-overlap control. Raises AssertionError on any
    violated invariant; returns the evidence record."""
    assert passes >= 4, "steady state needs passes past the cold fill"

    def pair():
        seq = _tiered_pipeline_job(0, passes, shards, keys_per_pass,
                                   overlap_frac, capacity_per_shard,
                                   build_delay, train_sec)
        pipe = _tiered_pipeline_job(depth, passes, shards, keys_per_pass,
                                    overlap_frac, capacity_per_shard,
                                    build_delay, train_sec)
        return seq, pipe

    # ×2 seeded runs: the digest must agree between depths AND between
    # repeat runs (determinism of the whole pipeline machinery)
    digests = []
    steady0 = steadyn = 0.0
    s0 = sn = []
    for attempt in range(3):   # ≥2 always; 3rd is a timing-noise retry
        seq, pipe = pair()
        assert pipe["rows"] == seq["rows"], (pipe["rows"], seq["rows"])
        assert pipe["digest"] == seq["digest"], (
            f"depth-{depth} tiered pipeline produced a DIFFERENT "
            f"host-tier state than the sequential oracle: "
            f"{pipe['digest'][:16]}… != {seq['digest'][:16]}…")
        digests.append(pipe["digest"])
        s0, sn = seq["stalls"], pipe["stalls"]
        steady0 = sum(s0[2:])
        steadyn = sum(sn[2:])
        if len(digests) >= 2 and steady0 > build_delay \
                and steadyn <= 0.5 * steady0:
            break
    assert len(set(digests)) == 1, (
        f"tiered pipeline digest changed between seeded runs: {digests}")
    assert steady0 > build_delay, (
        f"sequential control shows no boundary stall ({steady0:.3f}s) — "
        f"the gate's build/train timing no longer exercises the "
        f"pipeline (stalls: {s0})")
    assert steadyn <= 0.5 * steady0, (
        f"depth-{depth} steady-state begin_delta stall {steadyn:.3f}s "
        f"did not drop >=50% vs the sequential control {steady0:.3f}s "
        f"(control {s0}, depth-{depth} {sn})")
    return {
        "check": "tiered_prologue_check",
        "ok": True,
        "depth": depth,
        "passes": passes,
        "runs": 2 * len(digests),
        "steady_stall_sec_seq": round(steady0, 4),
        f"steady_stall_sec_depth{depth}": round(steadyn, 4),
        "stall_drop_frac": round(1.0 - steadyn / max(steady0, 1e-9), 4),
        "digest": digests[0],
    }


def main() -> None:
    shards = int(os.environ.get("PIPECHECK_SHARDS", "4"))
    passes = int(os.environ.get("PIPECHECK_PASSES", "3"))
    keys = int(os.environ.get("PIPECHECK_KEYS", "4096"))
    out = run_check(passes=passes, shards=shards, keys_per_pass=keys,
                    capacity_per_shard=max(1024, keys))
    print(json.dumps(out))
    print(json.dumps(run_prologue_check()))
    print(json.dumps(run_tiered_prologue_check()))


if __name__ == "__main__":
    main()
