#!/usr/bin/env python
"""Bench perf-regression gate over the recorded trajectory (ISSUE 10).

The per-round ``BENCH_r0*.json`` artifacts record every bench round's
headline rows, but nothing ever compared them — a throughput regression
(tiered at 0.14x before PR 8) surfaced only when a human re-read the
numbers. This script makes the trajectory machine-readable and gates on
it:

- ``--fold``: parse every ``BENCH_r0*.json`` artifact (the driver's
  ``{n, cmd, rc, tail}`` wrapper — bench rows are the JSON lines inside
  ``tail``; raw bench stdout / JSONL also parses) into
  ``BENCH_trajectory.json``: one row per (metric, mode, shape) per
  round, carrying value/unit plus ``device_busy_frac`` and
  ``begin_delta_steady_sec`` when the round reported them.
- ``bench.py`` APPENDS its live headline rows to the trajectory after
  each run (``record_result``; ``BENCH_TRAJECTORY=0`` disables,
  ``BENCH_TRAJECTORY=/path`` overrides) and prints a loud REGRESSION
  banner when a fresh row lands below the gate.
- ``--check``: for every (metric, mode, shape) key, compare the LATEST
  row against the best earlier row; fail (exit 1) when the latest value
  drops more than ``--max-drop-frac`` below the best. Skips gracefully
  (exit 0, a note) when no trajectory file exists yet.

Threshold: the default ``--max-drop-frac 0.5`` tolerates the documented
shared-tunnel weather on raw ex/s (BENCH_SHAPES.md: 2-3x swings between
rounds; the wire-normalized companion metric is stable and gates much
tighter in practice) while still catching architecture-level
regressions like the pre-PR 8 tiered collapse (8.5k vs a 28k best =
0.70 drop — flagged). Override per run with ``BENCH_GATE_MAX_DROP``.

Stdlib only — runs anywhere the artifacts land. Wired into tier-1 by
``tests/test_perf_gate.py`` (synthetic degradation flagged, real
trajectory passes).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

DEFAULT_MAX_DROP = 0.5
#: per-row fields copied into the trajectory when the bench reported
#: them (the "where did the time go" companions of the headline value).
#: n_chips/a2a_chunks/exchange_overlap_frac ride the multichip scaling
#: rows (``sharded.n{N}.{shape}.*``, BENCH_MODE=multichip — ISSUE 11).
#: pv_batch_size/instances_per_pass ride the PV rank-attention lane
#: rows (``adsrank_pv_*``, BENCH_MODE=pv — ISSUE 13).
EXTRA_FIELDS = ("device_busy_frac", "begin_delta_steady_sec",
                "end_pass_overlap_frac", "vs_baseline", "n_chips",
                "a2a_chunks", "exchange_overlap_frac",
                "pv_batch_size", "instances_per_pass",
                "qps", "queries", "batch")

#: metric-name suffixes gated LOWER-is-better: latency rows
#: (``serving.{shape}.p99_ms``, BENCH_MODE=serve — ISSUE 15) regress
#: when the latest value RISES past best*(1+max_drop_frac), the mirror
#: of the throughput rule. Everything else stays higher-is-better.
LOWER_IS_BETTER_SUFFIXES = ("_ms",)


def lower_is_better(metric: str) -> bool:
    return str(metric).endswith(LOWER_IS_BETTER_SUFFIXES)


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_trajectory_path() -> str:
    return os.path.join(_repo_root(), "BENCH_trajectory.json")


def row_key(row: Dict) -> Tuple[str]:
    """Gate key. The metric name already encodes mode and shape
    (``…_tiered``, ``…_zipf_tiered``, ``…_sharded``, ``…_streaming``,
    the wire-normalized ``…_per_wire_mb_per_sec``), and early rounds'
    rows predate the explicit mode/shape fields — keying on anything
    more would split one metric's history into phantom keys across
    rounds."""
    return (str(row.get("metric", "")),)


def _rows_from_lines(lines, source: str) -> List[Dict]:
    rows = []
    for line in lines:
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(d, dict) or "metric" not in d:
            continue
        v = d.get("value")
        if not isinstance(v, (int, float)):
            continue
        row = {"source": source, "metric": d["metric"],
               "value": float(v), "unit": d.get("unit", "")}
        for k in ("mode", "shape"):
            if d.get(k):
                row[k] = d[k]
        for k in EXTRA_FIELDS:
            if isinstance(d.get(k), (int, float)):
                row[k] = d[k]
        rows.append(row)
    return rows


def parse_bench_artifact(path: str) -> List[Dict]:
    """Bench rows out of one artifact: the driver wrapper ({..., tail})
    or raw bench output / JSONL."""
    source = os.path.splitext(os.path.basename(path))[0]
    with open(path) as fh:
        text = fh.read()
    try:
        outer = json.loads(text)
    except json.JSONDecodeError:
        outer = None
    if isinstance(outer, dict) and "tail" in outer:
        return _rows_from_lines(str(outer["tail"]).splitlines(), source)
    return _rows_from_lines(text.splitlines(), source)


def load_trajectory(path: str) -> Optional[Dict]:
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or "rows" not in data:
        raise ValueError(f"{path}: not a trajectory file")
    return data


def _write(path: str, data: Dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(data, fh, indent=1)
        fh.write("\n")
    os.replace(tmp, path)


def fold(repo_root: Optional[str] = None,
         out_path: Optional[str] = None) -> Dict:
    """Recorded artifacts → BENCH_trajectory.json (sorted by family,
    then round). Besides the driver's ``BENCH_r0*`` rounds this folds
    the multichip scaling rounds (``MULTICHIP_r0*``, ISSUE 11), the
    kernel-microbench rounds (``KERNELS_r0*``,
    ``scripts/profile_keypath.py --set kernels`` — ISSUE 12) and the
    serving-lane rounds (``SERVE_r0*``, BENCH_MODE=serve — ISSUE 15)
    and the elastic-churn rounds (``ELASTIC_r0*``,
    ``scripts/elastic_check.py --artifact`` — ISSUE 18), so a rebuild
    keeps their gate history instead of silently dropping it."""
    root = repo_root or _repo_root()
    out = out_path or os.path.join(root, "BENCH_trajectory.json")
    rows: List[Dict] = []
    for pattern in ("BENCH_r[0-9]*.json", "MULTICHIP_r[0-9]*.json",
                    "KERNELS_r[0-9]*.json", "SERVE_r[0-9]*.json",
                    "ONLINE_r[0-9]*.json", "ELASTIC_r[0-9]*.json"):
        for path in sorted(glob.glob(os.path.join(root, pattern))):
            rows.extend(parse_bench_artifact(path))
    data = {"version": 1, "rows": rows}
    _write(out, data)
    return data


def append_row(row: Dict, path: str) -> None:
    """Append one live bench row (bench.py's per-run record)."""
    data = load_trajectory(path) or {"version": 1, "rows": []}
    data["rows"].append(row)
    _write(path, data)


def check_rows(rows: List[Dict],
               max_drop_frac: float = DEFAULT_MAX_DROP
               ) -> Tuple[List[str], List[str]]:
    """(failures, summary) over the trajectory: per key, the LATEST
    row vs the best EARLIER row. A single-row key has no history and
    passes by definition."""
    by_key: Dict[Tuple, List[Dict]] = {}
    for r in rows:
        by_key.setdefault(row_key(r), []).append(r)
    flagged: List[Tuple[float, str]] = []
    summary: List[str] = []
    for key in sorted(by_key):
        hist = by_key[key]
        latest = hist[-1]
        prior = hist[:-1]
        label = "/".join(k for k in key if k)
        if not prior:
            summary.append(f"  {label}: {latest['value']:g} "
                           f"(1 row, no history)")
            continue
        if lower_is_better(key[0]):
            # latency keys: best = the LOWEST recorded value; the gate
            # fails when the latest RISES past best*(1+max_drop_frac)
            best = min(prior, key=lambda r: r["value"])
            ceil = best["value"] * (1.0 + max_drop_frac)
            drop = (latest["value"] / best["value"] - 1.0
                    if best["value"] > 0 else 0.0)
            line = (f"  {label}: latest {latest['value']:g} "
                    f"({latest.get('source', '?')}) vs best "
                    f"{best['value']:g} ({best.get('source', '?')}) — "
                    f"rise {drop:+.1%}, ceiling {ceil:g}")
            bad = latest["value"] > ceil
        else:
            best = max(prior, key=lambda r: r["value"])
            floor = best["value"] * (1.0 - max_drop_frac)
            drop = 1.0 - latest["value"] / best["value"] \
                if best["value"] > 0 else 0.0
            line = (f"  {label}: latest {latest['value']:g} "
                    f"({latest.get('source', '?')}) vs best "
                    f"{best['value']:g} ({best.get('source', '?')}) — "
                    f"drop {drop:+.1%}, floor {floor:g}")
            bad = latest["value"] < floor
        if bad:
            flagged.append((drop, "PERF REGRESSION:" + line))
        else:
            summary.append(line)
    # EVERY regressed key reports in one run, worst drop first — a
    # multichip round regressing several sharded.n{N}.{shape} keys at
    # once must name them all, not just the first (ISSUE 11)
    failures = [line for _, line in
                sorted(flagged, key=lambda t: -t[0])]
    return failures, summary


def check(path: str,
          max_drop_frac: float = DEFAULT_MAX_DROP,
          ignore_live: bool = False) -> int:
    """CLI --check body: 0 = pass/skip, 1 = regression.
    ``ignore_live`` gates only the RECORDED rounds (BENCH_r0*
    artifacts), skipping rows bench.py appended live — what tier-1
    runs, so a slow shared dev box can't fail CI through a live row
    while the committed trajectory stays gated."""
    data = load_trajectory(path)
    if data is None:
        print(f"perf_gate: no trajectory at {path} — nothing to gate "
              "yet (run --fold or a bench round first); skipping",
              file=sys.stderr)
        return 0
    rows = data["rows"]
    if ignore_live:
        rows = [r for r in rows if r.get("source") != "live"]
    failures, summary = check_rows(rows, max_drop_frac)
    for line in summary:
        print(line)
    for line in failures:
        print(line, file=sys.stderr)
    if failures:
        print(f"perf_gate: {len(failures)} metric(s) below "
              f"{max_drop_frac:.0%} of their recorded best",
              file=sys.stderr)
        return 1
    print(f"perf_gate: OK ({len(summary)} metric key(s), "
          f"max allowed drop {max_drop_frac:.0%})")
    return 0


def record_result(result: Dict, path: Optional[str] = None,
                  max_drop_frac: Optional[float] = None) -> List[str]:
    """bench.py's hook: append a just-measured row to the trajectory,
    then gate THAT key against its recorded best — returns the failure
    lines (empty = fine), already printed loudly to stderr. Never
    raises: a broken trajectory file must not eat a bench run."""
    try:
        p = path or os.environ.get("BENCH_TRAJECTORY") \
            or default_trajectory_path()
        drop = (float(os.environ.get("BENCH_GATE_MAX_DROP",
                                     DEFAULT_MAX_DROP))
                if max_drop_frac is None else max_drop_frac)
        row = {"source": "live", "recorded_at": round(time.time(), 3),
               "metric": result.get("metric"),
               "value": float(result["value"]),
               "unit": result.get("unit", "")}
        for k in ("mode", "shape"):
            if result.get(k):
                row[k] = result[k]
        for k in EXTRA_FIELDS:
            if isinstance(result.get(k), (int, float)):
                row[k] = result[k]
        append_row(row, p)
        data = load_trajectory(p)
        keyed = [r for r in data["rows"] if row_key(r) == row_key(row)]
        failures, _ = check_rows(keyed, drop)
        for line in failures:
            print(line, file=sys.stderr)
        return failures
    except Exception as e:  # pragma: no cover - defensive
        print(f"perf_gate: trajectory record failed: {e}",
              file=sys.stderr)
        return []


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--fold", action="store_true",
                    help="rebuild the trajectory from BENCH_r0*.json")
    ap.add_argument("--check", action="store_true",
                    help="gate the latest row per metric key against "
                    "its recorded best")
    ap.add_argument("--trajectory", default=None,
                    help="trajectory path (default: repo-root "
                    "BENCH_trajectory.json)")
    ap.add_argument("--max-drop-frac", type=float,
                    default=float(os.environ.get("BENCH_GATE_MAX_DROP",
                                                 DEFAULT_MAX_DROP)),
                    help="fail when latest < best*(1-this) "
                    f"(default {DEFAULT_MAX_DROP})")
    ap.add_argument("--ignore-live", action="store_true",
                    help="gate only the recorded rounds, skipping "
                    "live bench-appended rows (what tier-1 uses)")
    args = ap.parse_args(argv)
    path = args.trajectory or default_trajectory_path()
    if not args.fold and not args.check:
        ap.print_help()
        return 2
    if args.fold:
        data = fold(out_path=path)
        keys = {row_key(r) for r in data["rows"]}
        print(f"perf_gate: folded {len(data['rows'])} rows "
              f"({len(keys)} metric keys) -> {path}")
    if args.check:
        return check(path, args.max_drop_frac,
                     ignore_live=args.ignore_live)
    return 0


if __name__ == "__main__":
    sys.exit(main())
