#!/usr/bin/env python
"""Seeded end-to-end preemption check (ISSUE 3 acceptance criteria).

Proves the preemption survival kit end-to-end, deterministically:

1. **baseline** — an uninterrupted pass records its logical state
   digest (``train.checkpoint.state_digest``: per-key table rows +
   dense/opt/AUC leaves, row-assignment order cancelled out).
2. **preempted** — the same seeded run under a
   ``preempt.signal:fail:nth=K`` fault plan (a simulated SIGTERM at the
   K-th batch boundary) with periodic in-pass cursor checkpoints
   (``FLAGS_ckpt_every_batches``): the pass raises ``PreemptedError``
   after writing an emergency checkpoint + ``RESUME.json`` marker.
3. **restart** — a fresh trainer restores the emergency checkpoint and
   ``run_pass`` resumes from the cursor, replaying ONLY the batches
   after it; the final digest must equal the baseline digest exactly,
   and the resume marker must be consumed.

The whole scenario runs twice with the same seed and the outcome
summaries must be identical — preemption recovery is reproducible, not
lucky. The telemetry JSONL must carry the new event catalog entries
(``preempt_requested``, ``emergency_checkpoint``, ``cursor_resume``).

Usage::

    JAX_PLATFORMS=cpu python scripts/preempt_check.py [--seed 7]
                                                      [--preempt-at 4]

Exit code 0 == resumed byte-identically + deterministic.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_scenario(workdir: str, seed: int, preempt_at: int) -> dict:
    """One full preemption round-trip; returns the outcome summary."""
    import optax

    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.obs.hub import reset_hub
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.resilience import preemption
    from paddlebox_tpu.resilience.faults import FaultPlan, installed
    from paddlebox_tpu.resilience.preemption import PreemptedError
    from paddlebox_tpu.train import Trainer
    from paddlebox_tpu.train.checkpoint import (CheckpointManager,
                                                state_digest)

    reset_hub()
    preemption.clear_stop()
    jsonl = os.path.join(workdir, "telemetry.jsonl")
    files = generate_criteo_files(os.path.join(workdir, "data"),
                                  num_files=2, rows_per_file=160,
                                  vocab_per_slot=40, seed=seed)
    ckpt_root = os.path.join(workdir, "ckpt")
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)

    with flags_scope(seed=seed, telemetry_jsonl=jsonl,
                     ckpt_every_batches=3):
        desc = DataFeedDesc.criteo(batch_size=32)
        desc.key_bucket_min = 2048

        def mk() -> Trainer:
            table = EmbeddingTable(mf_dim=4, capacity=1 << 12, cfg=cfg,
                                   unique_bucket_min=2048)
            return Trainer(CtrDnn(hidden=(8,)), table, desc,
                           tx=optax.adam(1e-2), seed=seed)

        ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
        ds.set_filelist(files)
        ds.load_into_memory()

        # (1) baseline: uninterrupted pass
        baseline = mk()
        out_base = baseline.train_pass(ds)
        digest_base = state_digest(baseline)
        total_batches = int(out_base["batches"])

        # (2) preempted run: simulated SIGTERM at the K-th boundary
        trainer = mk()
        cm = CheckpointManager(ckpt_root)
        plan = FaultPlan.parse(f"preempt.signal:fail:nth={preempt_at}",
                               seed=seed)
        preempted = False
        try:
            with installed(plan):
                trainer.run_pass(ds, checkpoint=cm)
        except PreemptedError as e:
            preempted = True
            assert e.checkpointed, "emergency checkpoint missing"
        assert preempted, "preempt fault never fired"
        cursor = cm.load_cursor()
        assert cursor is not None, "no resume cursor on latest ckpt"
        assert cursor["batch_index"] == preempt_at, cursor
        marker = preemption.read_resume_marker(ckpt_root)
        assert marker and marker["exit_code"] == preemption.EXIT_RESUME

        # (3) restart: fresh trainer resumes from the cursor
        preemption.clear_stop()
        resumed = mk()
        cm2 = CheckpointManager(ckpt_root)
        restored = cm2.restore(resumed)
        assert restored == cursor["global_step"], (restored, cursor)
        out_res = resumed.run_pass(ds, checkpoint=cm2)
        replayed = int(out_res["batches"])
        assert replayed == total_batches - preempt_at, (
            f"replayed {replayed}, want {total_batches - preempt_at}")
        assert preemption.read_resume_marker(ckpt_root) is None, \
            "resume marker not consumed"
        digest_resumed = state_digest(resumed)
        assert digest_resumed == digest_base, (
            "resumed state diverged from the uninterrupted run:\n"
            f"  baseline {digest_base}\n  resumed  {digest_resumed}")

    with open(jsonl) as fh:
        events = [json.loads(line) for line in fh]
    names = {e["event"] for e in events}
    for want in ("preempt_requested", "emergency_checkpoint",
                 "cursor_resume"):
        assert want in names, f"telemetry missing {want!r}: {sorted(names)}"

    return dict(
        total_batches=total_batches,
        preempted_at=int(cursor["batch_index"]),
        replayed_batches=replayed,
        digest=digest_base,
        digest_match=digest_resumed == digest_base,
        fault_stats=plan.stats(),
        events={n: sum(1 for e in events if e["event"] == n)
                for n in ("preempt_requested", "emergency_checkpoint",
                          "inpass_checkpoint", "cursor_resume")},
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--preempt-at", type=int, default=4,
                    help="batch boundary the simulated SIGTERM lands on")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh temp dir)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir for inspection")
    args = ap.parse_args()

    base = args.workdir or tempfile.mkdtemp(prefix="pbox_preempt_")
    outcomes = []
    try:
        for run in (1, 2):  # same seed twice: outcome must be identical
            wd = os.path.join(base, f"run{run}")
            os.makedirs(wd, exist_ok=True)
            print(f"--- preemption run {run} (seed={args.seed}, "
                  f"preempt at batch {args.preempt_at}) ---")
            outcomes.append(run_scenario(wd, args.seed, args.preempt_at))
            print(json.dumps(outcomes[-1], indent=2, sort_keys=True))
        if outcomes[0] != outcomes[1]:
            print("FAIL: preemption outcome differs across "
                  "identically-seeded runs:")
            print(json.dumps(outcomes[0], sort_keys=True))
            print(json.dumps(outcomes[1], sort_keys=True))
            return 1
        print(f"PASS: preempted run resumed from the cursor "
              f"byte-identically ({outcomes[0]['replayed_batches']} of "
              f"{outcomes[0]['total_batches']} batches replayed); "
              f"outcome deterministic across 2 runs (seed={args.seed})")
        return 0
    finally:
        if not args.keep and args.workdir is None:
            shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
