#!/usr/bin/env python
"""Seeded end-to-end elastic membership churn gate (ISSUE 18).

Drives a virtual-device stream job (8 windows, 4 simulated hosts x 2
chips each) through the full lose-and-regain ladder of
``train.multihost.ElasticStreamRunner`` + ``distributed.elastic``:

1. host ``h1`` dies after the first window boundary: one missed
   heartbeat poll is ABSORBED (``dead_checks=2`` hysteresis), the
   second confirms the death — the survivors agree the boundary step
   over ``RestoreConsensus``, re-shard the embedding table to the
   6-chip world (``key % num_shards`` re-import) and continue,
2. ``h1`` rejoins two windows later and is re-admitted at the NEXT
   boundary (joins carry no hysteresis) — re-shard back to 8 chips,
3. a FALSE-DEAD heartbeat on ``h2`` (one aged lease, refreshed before
   the next poll) produces ZERO spurious scale events or re-shards,
4. the straggler watchdog's shrink-and-continue rung
   (``obs.watchdog.shrink_and_continue_action``) evicts a wedged
   ``h3`` — eviction bypasses the hysteresis and the next boundary
   re-shards down without it,
5. a transient ``elastic.kv`` fault is retried on the seeded
   RetryPolicy with no membership flap, and a transient
   ``elastic.rendezvous`` poll failure is absorbed by the rendezvous
   window,
6. a REAL rank loss: a heartbeat-only peer process is SIGKILLed and the
   manager confirms the death through genuine TTL expiry (the one
   wall-clock leg; every in-scenario lease transition is a
   deterministic ``os.utime`` age-out).

Asserted, per run:

- the world-per-window schedule is exactly
  ``[4, 4, 3, 3, 4, 4, 4, 3]`` hosts with re-shards at boundaries
  B1 (8->6 chips), B3 (6->8) and B6 (8->6), and nowhere else,
- at EVERY re-shard ``digest_after == digest`` — the shard-count
  invariant ``elastic_state_digest`` proves the re-import lossless,
- the churned run bit-matches an UNCHURNED oracle at every common
  boundary up to and including the first re-shard (after it the mesh
  width legitimately changes the batch grouping, so bit-equality to an
  8-chip-forever run is no longer the contract),
- a SCHEDULE ORACLE — the same runner driven by a scripted controller
  with the same world-per-window schedule but none of the detection
  machinery — bit-matches the churned run at EVERY boundary: manager,
  consensus, KV store and eviction are a training-math no-op,
- no window (hence no file) trains twice past a completed boundary,
- the restart pointer tracks the newest boundary,

and the whole scenario runs twice with the same seed — the
(timing-stripped) outcomes must be identical.

Perf rows (printed as JSON lines; ``--artifact`` writes an
``ELASTIC_r*.json`` round for ``perf_gate --fold``):
``elastic.reshard_stall_ms`` (boundary-to-resumed wall time) and
``elastic.degraded_throughput_frac`` (degraded-world examples/sec over
full-world examples/sec — the bounded-throughput-dip row).

Usage::

    JAX_PLATFORMS=cpu python scripts/elastic_check.py [--seed 7]
                                                      [--rows 192]

Exit code 0 == churn survived, digests match, deterministic x2.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: gate geometry: 4 hosts x 2 virtual chips, 8 stream windows, one
#: file per window. The schedule drives every ladder rung (see module
#: docstring); WORLD_SCHEDULE is the hosts-per-window ground truth.
HOSTS = ("h0", "h1", "h2", "h3")
DEV_PER_HOST = 2
NUM_WINDOWS = 8
WORLD_SCHEDULE = [4, 4, 3, 3, 4, 4, 4, 3]
RESHARD_AT = {1: (4, 3), 3: (3, 4), 6: (4, 3)}
JOB = "elastic_gate"
TTL = 3600.0  # in-scenario death is an explicit utime age-out, never a race

#: heartbeat-only peer for the SIGKILL leg: registers and sleeps; the
#: parent kills it and waits for genuine TTL expiry
_PEER_SRC = r"""
import sys, time
from paddlebox_tpu.distributed.elastic import ElasticManager, FileKVStore
root, host, ttl = sys.argv[1], sys.argv[2], float(sys.argv[3])
m = ElasticManager(FileKVStore(root), "sigkill_leg", host, 2,
                   ttl=ttl, heartbeat_period=ttl / 5.0)
m.register()
print("registered", flush=True)
time.sleep(600)
"""


def _strip_timing(records: list) -> list:
    """Runner records minus wall-clock fields — the x2-comparable view."""
    out = []
    for r in records:
        c = {k: v for k, v in r.items() if k != "train_sec"}
        if "reshard" in r:
            c["reshard"] = {k: v for k, v in r["reshard"].items()
                            if k != "stall_sec"}
        out.append(c)
    return out


class ScheduledController:
    """Scripted ``ElasticController`` twin: replays a boundary->decision
    schedule with NONE of the detection machinery (no manager, no KV, no
    consensus — ``agree_boundary`` IS the local step). Driving the same
    ``ElasticStreamRunner`` with it yields the schedule oracle: digest
    parity against the churned run proves detection/consensus/eviction
    never touch the training math."""

    def __init__(self, decisions: dict) -> None:
        self.decisions = dict(decisions)
        self._window = -1

    def publish(self, path: str, pass_id: int) -> None:
        self._window = pass_id

    def poll(self):
        return self.decisions.get(self._window)

    def agree_boundary(self, local_step, survivors=None):
        return local_step

    def note_reshard(self, old_np, new_np, step=-1) -> None:
        pass


def _run_sigkill_leg(workdir: str) -> dict:
    """Leg (6): a real heartbeat-only peer process SIGKILLed mid-job;
    the survivor confirms the death through genuine TTL expiry (with
    ``dead_checks=2`` hysteresis: the first expired poll is absorbed)."""
    from paddlebox_tpu.distributed.elastic import (ElasticManager,
                                                   FileKVStore)
    root = os.path.join(workdir, "elastic_sigkill")
    ttl = 1.0
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, "-c", _PEER_SRC, root, "px", str(ttl)],
        env=env, stdout=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()
        if "registered" not in line:
            raise RuntimeError(f"sigkill peer failed to register: {line!r}")
        mgr = ElasticManager(FileKVStore(root), "sigkill_leg", "m0", 2,
                             ttl=ttl, heartbeat_period=0.1, dead_checks=2)
        mgr.register()
        assert mgr.scale_event() is None  # baseline: {m0, px}
        assert mgr.alive_hosts() == ["m0", "px"], mgr.alive_hosts()
        proc.send_signal(signal.SIGKILL)
        proc.wait()
        # the lease outlives the process: no event before TTL expiry
        assert mgr.scale_event() is None, "dead peer detected before TTL"
        deadline = time.time() + 30.0
        polls, event = 0, None
        while event is None and time.time() < deadline:
            time.sleep(ttl / 2.0)
            polls += 1
            event = mgr.scale_event()
        assert event == ["m0"], f"sigkill leg: no scale event ({polls} polls)"
        assert mgr.last_event["lost"] == ["px"], mgr.last_event
        assert polls >= 2, "hysteresis must absorb the first expired poll"
        mgr.deregister()
        return {"sigkill_lost": ["px"], "sigkill_survivors": event,
                "sigkill_hysteresis_held": True}
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def run_scenario(workdir: str, seed: int, rows: int) -> dict:
    """One full churn round-trip; returns the timing-stripped outcome."""
    import jax
    if len(jax.devices()) < len(HOSTS) * DEV_PER_HOST:
        return {"skip": f"{len(jax.devices())} devices"}
    import numpy as np
    import optax

    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    from paddlebox_tpu.distributed.elastic import (ElasticManager,
                                                   FileKVStore)
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.obs.hub import reset_hub
    from paddlebox_tpu.obs.watchdog import (LocalHeartbeatStore,
                                            StragglerWatchdog,
                                            shrink_and_continue_action)
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.sharded import ShardedEmbeddingTable
    from paddlebox_tpu.resilience.consensus import RestoreConsensus
    from paddlebox_tpu.resilience.faults import FaultPlan, installed
    from paddlebox_tpu.train.checkpoint import CheckpointManager
    from paddlebox_tpu.train.multihost import (ElasticController,
                                               ElasticStreamRunner)
    from paddlebox_tpu.train.sharded import ShardedTrainer

    reset_hub()
    files = generate_criteo_files(os.path.join(workdir, "data"),
                                  num_files=NUM_WINDOWS,
                                  rows_per_file=rows,
                                  vocab_per_slot=60, seed=seed)
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0,
                          learning_rate=0.1, mf_learning_rate=0.1)
    with flags_scope(seed=seed, log_period_steps=10 ** 6,
                     read_thread_num=1, retry_base_delay_sec=0.01,
                     retry_max_delay_sec=0.05):
        desc = DataFeedDesc.criteo(batch_size=16)
        desc.key_bucket_min = 1024

        datasets = []
        for path in files:  # loaded ONCE; every run sees identical batches
            ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
            ds.set_filelist([path])
            ds.load_into_memory()
            datasets.append(ds)

        ds_calls: dict = {}

        def dataset_fn(label: str):
            ds_calls[label] = []

            def make_dataset(widx: int):
                ds_calls[label].append(widx)
                return datasets[widx]
            return make_dataset

        def world_fn(ckpt_root: str):
            def make_world(np_hosts: int):
                n_dev = np_hosts * DEV_PER_HOST
                table = ShardedEmbeddingTable(
                    n_dev, mf_dim=4, capacity_per_shard=4096, cfg=cfg,
                    req_bucket_min=256, serve_bucket_min=256)
                tr = ShardedTrainer(DeepFM(hidden=(16, 16)), table, desc,
                                    make_mesh(n_dev),
                                    tx=optax.adam(2e-3), seed=seed)
                return tr, CheckpointManager(ckpt_root)
            return make_world

        # ---- elastic plane: shared-dir leases for the 4 virtual hosts.
        # h0 is this process (real manager + heartbeat thread); h1-h3
        # are lease files whose life is scripted with utime age-outs —
        # TTL is huge, so every death below is deterministic.
        store = FileKVStore(os.path.join(workdir, "elastic"))

        def lease_path(host: str) -> str:
            return store._path(f"paddlebox/{JOB}/nodes/{host}")

        def put_lease(host: str) -> None:
            store.put(f"paddlebox/{JOB}/nodes/{host}",
                      json.dumps({"host": host}).encode())

        def age_lease(host: str) -> None:
            old = time.time() - 2 * TTL
            os.utime(lease_path(host), (old, old))

        for h in HOSTS[1:]:
            put_lease(h)
        mgr = ElasticManager(store, JOB, "h0", len(HOSTS),
                             min_np=2, max_np=len(HOSTS), ttl=TTL,
                             heartbeat_period=0.05, dead_checks=2)

        # (5a) transient elastic.kv fault retried on the seeded policy
        # (before register(), so the heartbeat thread can't race the
        # nth=1 counter) — membership view intact
        with installed(FaultPlan.parse("elastic.kv:fail:nth=1",
                                       seed=seed)) as kvp:
            alive = mgr.alive_hosts()
        assert kvp.stats()["elastic.kv:fail"]["fired"] == 1, kvp.stats()
        assert alive == sorted(HOSTS[1:]), alive

        mgr.register()
        # (5b) transient rendezvous poll absorbed inside wait_for_np
        with installed(FaultPlan.parse("elastic.rendezvous:fail:nth=1",
                                       seed=seed)) as rvp:
            hosts0 = mgr.wait_for_np(timeout=30.0)
        assert rvp.stats()["elastic.rendezvous:fail"]["fired"] == 1
        assert hosts0 == sorted(HOSTS), hosts0

        consensus = RestoreConsensus(
            os.path.join(workdir, "consensus"), 0, 1, timeout=30.0)
        controller = ElasticController(mgr, consensus)
        assert controller.poll() is None  # steady 4-host baseline

        # ---- watchdog leg state (fires at B6 via on_boundary below)
        wd_evicted: list = []

        def run_watchdog_rung() -> None:
            tvar = [1000.0]
            hb = LocalHeartbeatStore()

            def evict(reports) -> None:
                for r in reports:
                    host = HOSTS[r.process]
                    wd_evicted.append((host, r.reason))
                    controller.evict(host, f"watchdog:{r.reason}")
            wd = StragglerWatchdog(
                hb, 0, len(HOSTS), step_lag=100, heartbeat_timeout=30.0,
                clock=lambda: tvar[0],
                escalations=[(0.0, shrink_and_continue_action(evict))])
            hb.publish(3, 100, 1005.0)  # h3 wedged: last beat long ago
            tvar[0] = 1040.0
            for p in (0, 1, 2):
                hb.publish(p, 100, tvar[0])
            reports = wd.poll_once()
            assert [r.process for r in reports] == [3], reports

        def on_boundary(widx: int, trainer) -> None:
            if widx == 0:
                age_lease("h1")       # h1 dies: miss 1 at B0, dead at B1
            elif widx == 3:
                put_lease("h1")       # h1 rejoins: admitted at B3
            elif widx == 4:
                age_lease("h2")       # false-dead: one missed poll...
            elif widx == 5:
                store.touch(f"paddlebox/{JOB}/nodes/h2")  # ...recovers
            elif widx == 6:
                run_watchdog_rung()   # h3 wedged -> shrink-and-continue

        # ---- (1-4) the churned run
        churn_runner = ElasticStreamRunner(
            world_fn(os.path.join(workdir, "ckpt_churn")),
            dataset_fn("churn"), NUM_WINDOWS, controller=controller,
            on_boundary=on_boundary)
        records = churn_runner.run(len(HOSTS))
        mgr.deregister()

        assert [r["np"] for r in records] == WORLD_SCHEDULE, records
        assert ds_calls["churn"] == list(range(NUM_WINDOWS)), (
            "a window trained twice past a completed boundary: "
            f"{ds_calls['churn']}")
        for w, r in enumerate(records):
            if w in RESHARD_AT:
                old_np, new_np = RESHARD_AT[w]
                rs = r.get("reshard")
                assert rs, f"expected re-shard at boundary B{w}"
                assert (rs["old_np"], rs["new_np"]) == (old_np, new_np), rs
                assert rs["agreed_step"] == r["step"], rs
                assert rs["digest_after"] == r["digest"], (
                    f"B{w} re-shard was NOT a lossless re-import:\n"
                    f"  boundary {r['digest']}\n  after    "
                    f"{rs['digest_after']}")
            else:
                assert "reshard" not in r, (
                    f"spurious re-shard at boundary B{w}: {r}")
        assert records[1]["reshard"]["lost"] == ["h1"]
        assert records[3]["reshard"]["joined"] == ["h1"]
        assert records[6]["reshard"]["lost"] == ["h3"]
        assert wd_evicted == [("h3", "stale")], wd_evicted
        assert mgr.reshard_count == len(RESHARD_AT)
        ptr = mgr.latest_checkpoint()
        assert ptr and ptr["pass_id"] == NUM_WINDOWS - 1, ptr

        # ---- unchurned oracle: 4 hosts forever; common prefix must
        # bit-match through the first re-shard boundary
        oracle = ElasticStreamRunner(
            world_fn(os.path.join(workdir, "ckpt_oracle")),
            dataset_fn("oracle"), NUM_WINDOWS).run(len(HOSTS))
        prefix = [w for w in range(NUM_WINDOWS)
                  if w <= min(RESHARD_AT)]
        for w in prefix:
            assert oracle[w]["step"] == records[w]["step"]
            assert oracle[w]["digest"] == records[w]["digest"], (
                f"churned run diverged from the unchurned oracle at "
                f"boundary B{w} (before any world change):\n"
                f"  oracle  {oracle[w]['digest']}\n"
                f"  churned {records[w]['digest']}")

        # ---- schedule oracle: same world schedule, zero detection
        # machinery — EVERY boundary must bit-match the churned run
        decisions = {w: {"np": new_np, "hosts": [], "lost": [],
                         "joined": []}
                     for w, (_, new_np) in RESHARD_AT.items()}
        sched = ElasticStreamRunner(
            world_fn(os.path.join(workdir, "ckpt_sched")),
            dataset_fn("sched"), NUM_WINDOWS,
            controller=ScheduledController(decisions)).run(len(HOSTS))
        for w in range(NUM_WINDOWS):
            assert sched[w]["np"] == records[w]["np"]
            assert sched[w]["step"] == records[w]["step"]
            assert sched[w]["digest"] == records[w]["digest"], (
                f"elastic machinery perturbed training math at B{w}:\n"
                f"  scheduled {sched[w]['digest']}\n"
                f"  churned   {records[w]['digest']}")

    # ---- (6) real SIGKILL'd rank, genuine TTL expiry
    sigkill = _run_sigkill_leg(workdir)

    # ---- perf rows (wall-clock; excluded from the x2 outcome)
    full_eps = [rows / r["train_sec"] for r in records
                if r["np"] == len(HOSTS) and r["train_sec"] > 0]
    deg_eps = [rows / r["train_sec"] for r in records
               if r["np"] < len(HOSTS) and r["train_sec"] > 0]
    stalls = [r["reshard"]["stall_sec"] for r in records
              if "reshard" in r]
    dip_frac = ((sum(deg_eps) / len(deg_eps))
                / (sum(full_eps) / len(full_eps))
                if full_eps and deg_eps else 0.0)
    stall_ms = 1000.0 * sum(stalls) / max(len(stalls), 1)
    assert dip_frac > 0.05, (
        f"degraded-world throughput collapsed: {dip_frac:.3f} of the "
        "full-world rate (bound is deliberately generous — this only "
        "catches a pathological stall)")
    perf_rows = [
        {"metric": "elastic.reshard_stall_ms",
         "value": round(stall_ms, 3), "unit": "ms"},
        {"metric": "elastic.degraded_throughput_frac",
         "value": round(dip_frac, 4), "unit": "frac"},
    ]
    for row in perf_rows:
        print(json.dumps(row))

    return dict(
        ok=True,
        world_schedule=[r["np"] for r in records],
        windows=_strip_timing(records),
        oracle_prefix_match=prefix,
        schedule_oracle_match=NUM_WINDOWS,
        dataset_order=ds_calls["churn"],
        watchdog_evicted=wd_evicted,
        reshard_count=len(RESHARD_AT),
        kv_fault_fired=1, rendezvous_fault_fired=1,
        restart_pointer_pass=ptr["pass_id"],
        perf_metrics=sorted(r["metric"] for r in perf_rows),
        **sigkill,
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--rows", type=int, default=192,
                    help="examples per window file (the tier-1 wrapper "
                         "runs a reduced-N 96)")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh temp dir)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir for inspection")
    ap.add_argument("--artifact", default=None,
                    help="write an ELASTIC_r*.json round artifact "
                         "(perf_gate --fold input) with the perf rows")
    args = ap.parse_args()

    import jax
    if len(jax.devices()) < len(HOSTS) * DEV_PER_HOST:
        print(f"elastic_check: SKIP — {len(jax.devices())} devices "
              f"(needs {len(HOSTS) * DEV_PER_HOST}: XLA_FLAGS="
              "--xla_force_host_platform_device_count=8)")
        return 0

    base = args.workdir or tempfile.mkdtemp(prefix="pbox_elastic_")
    outcomes, tail = [], []
    try:
        for run in (1, 2):  # same seed twice: outcome must be identical
            wd = os.path.join(base, f"run{run}")
            os.makedirs(wd, exist_ok=True)
            print(f"--- elastic run {run} (seed={args.seed}, "
                  f"rows={args.rows}) ---")
            import io
            from contextlib import redirect_stdout
            buf = io.StringIO()
            with redirect_stdout(buf):
                outcomes.append(run_scenario(wd, args.seed, args.rows))
            sys.stdout.write(buf.getvalue())
            tail.append(buf.getvalue())
            print(json.dumps(outcomes[-1], indent=2, sort_keys=True))
        if outcomes[0] != outcomes[1]:
            print("FAIL: elastic outcome differs across "
                  "identically-seeded runs:")
            print(json.dumps(outcomes[0], sort_keys=True))
            print(json.dumps(outcomes[1], sort_keys=True))
            return 1
        if args.artifact:
            with open(args.artifact, "w") as fh:
                json.dump({"ok": True, "seed": args.seed,
                           "tail": tail[-1]}, fh, indent=1)
            print(f"elastic_check: wrote {args.artifact}")
        print(f"PASS: lost+regained a host mid-stream with lossless "
              f"consensus re-shards at boundaries "
              f"{sorted(RESHARD_AT)}, zero spurious re-shards on the "
              f"false-dead leg, watchdog shrink-and-continue evicted "
              f"the wedged rank, SIGKILL'd peer confirmed via TTL; "
              f"outcome deterministic across 2 runs (seed={args.seed})")
        return 0
    finally:
        if not args.keep and args.workdir is None:
            shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
