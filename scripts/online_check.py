#!/usr/bin/env python
"""Long-horizon soak gate for the always-on online-learning daemon
(ISSUE 17 acceptance criteria, docs/ONLINE.md).

Proves the supervised train→publish→serve composition
(``paddlebox_tpu.online.OnlineLearner`` / ``scripts/onlinelearn.py``)
holds up over a horizon ≥3× any existing stream test (12 windows vs
stream_check's 3), with feature lifecycle aging on:

1. **soak** — one in-process daemon (train + publish + serve + shrink
   cycles) over 12 windows, sampled per window: resident key count,
   cursor size, RSS, and serving staleness must PLATEAU (last-third max
   ≤ bound, not monotonically increasing) — an always-on run must not
   leak keys, cursor bytes, or memory. Every lookup served during the
   run bit-matches that version's replay oracle, and the whole leg runs
   twice with the same seed — deterministic outcome required.
2. **tiered lifecycle** — the same aging policy through the full
   PassScopedTable → HostStore → SsdTier stack (async epilogue ON,
   demotion + shrink + compaction): host keys, SSD live-rows, disk
   bytes all plateau and the SSD live fraction stays above floor.
3. **kill legs** — real-SIGTERM and real-SIGKILL subprocess round-trips
   of ``scripts/onlinelearn.py``: marker consumed, open window replayed
   at-least-once, the resumed daemon drains to a final boundary whose
   ``state_digest`` bit-matches an unkilled oracle run; /healthz serves
   the ``online`` block throughout.
4. **corrupt-delta chaos** — a flipped-byte delta in the publish feed:
   the daemon's reload loop refuses it loudly (degrade counter +
   staleness) and keeps serving the prior snapshot; the next shrink
   cycle's forced BASE publish is the recovery path the daemon itself
   produces, and serving adopts it.
5. **shrink chaos** — ``online.shrink`` fault seam: a transient failure
   retries on the seeded policy and the cycle completes; a hard failure
   SKIPS the cycle loudly (counter + flight-recorder bundle + telemetry
   event) without stalling training.

``--bench-out`` appends ``online.{shape}.*`` JSON-line rows
(``scripts/perf_gate.py --fold`` picks up ``ONLINE_r*.json``).

Usage::

    JAX_PLATFORMS=cpu python scripts/online_check.py [--seed 7]
        [--windows 12] [--bench-out ONLINE_r0.json] [--skip-subprocess]

Exit code 0 == every leg passed and the soak was deterministic.
"""

from __future__ import annotations

import argparse
import glob as _glob
import hashlib
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: soak geometry: WINDOW files per window, ROWS records per file — the
#: default 12-window horizon is 3x stream_check's 3 windows
WINDOW, ROWS, BS = 2, 32, 16
SOAK_WINDOWS = 12

#: CI-generous plateau bounds (env-overridable)
STALENESS_BOUND_SEC = float(
    os.environ.get("ONLINE_CHECK_STALENESS_SEC", "30"))
RSS_GROWTH_FRAC = float(os.environ.get("ONLINE_CHECK_RSS_FRAC", "0.35"))


def _digest(arr) -> str:
    import numpy as np
    return hashlib.sha256(
        np.ascontiguousarray(arr).tobytes()).hexdigest()[:24]


def _rss_mb() -> float:
    with open("/proc/self/status") as fh:
        for line in fh:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0


def _assert_plateau(name, series, rel=0.05, abs_bound=None) -> None:
    """The soak invariant: the last third of a per-window series stays
    under bound (default: within ``rel`` of the earlier max) and is not
    still strictly increasing — growth must have stopped, not merely
    slowed."""
    assert len(series) >= 3, (name, series)
    third = max(1, len(series) // 3)
    head, tail = series[:-third], series[-third:]
    bound = abs_bound if abs_bound is not None \
        else max(head) * (1.0 + rel)
    assert max(tail) <= bound + 1e-9, (
        f"{name} did not plateau: last-third max {max(tail)} > bound "
        f"{bound} (series {series})")
    if len(tail) >= 2:
        assert any(b <= a for a, b in zip(tail, tail[1:])), (
            f"{name} still strictly increasing across the last third: "
            f"{series}")


def _mk_trainer(desc, seed, capacity=1 << 12):
    import optax

    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.train import Trainer
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    table = EmbeddingTable(mf_dim=4, capacity=capacity, cfg=cfg,
                           unique_bucket_min=2048)
    return Trainer(CtrDnn(hidden=(8,)), table, desc,
                   tx=optax.adam(1e-2), seed=seed)


def _srv(desc, capacity=1 << 12):
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.serving import ServingModel
    return ServingModel(CtrDnn(hidden=(8,)), desc, mf_dim=4,
                        capacity=capacity)


def _lookup_oracles(store, desc, probe, aids, capacity=1 << 12):
    """Per-version replay oracles (serve_check idiom): a FRESH consumer
    adopts each version and digests the same probe lookup the live
    queries ran — the bit-consistency reference."""
    out = {}
    for aid in sorted(set(aids)):
        srv = _srv(desc, capacity)
        srv.adopt(store, aid)
        out[aid] = _digest(srv.snapshot().lookup(probe))
        srv.release()
    return out


class _QueryWorker(threading.Thread):
    """Sustained serving traffic against the daemon's own ServingModel:
    each query pins ONE snapshot and records (version, lookup digest) —
    adoption swaps must never tear a read."""

    def __init__(self, srv, probe) -> None:
        super().__init__(daemon=True, name="online-query")
        self.srv = srv
        self.probe = probe
        self.records = []
        self.max_staleness = 0.0
        self.exc = None
        self._halt = threading.Event()

    def run(self) -> None:
        try:
            while not self._halt.is_set():
                if self.srv.adopted_aid is None:
                    time.sleep(0.01)
                    continue
                snap = self.srv.snapshot()
                self.records.append((snap.aid,
                                     _digest(snap.lookup(self.probe))))
                st = self.srv.serving_status()
                self.max_staleness = max(
                    self.max_staleness,
                    float(st.get("staleness_sec") or 0.0))
                time.sleep(0.003)
        except BaseException as e:   # noqa: BLE001 — reported by leg
            self.exc = e

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=60)
        if self.exc is not None:
            raise AssertionError(
                f"query worker died (queries must survive reload "
                f"swaps): {self.exc!r}") from self.exc


# ---------------------------------------------------------------------------
# leg 1: long-horizon soak (train + publish + serve + shrink, in-process)
# ---------------------------------------------------------------------------

def _run_soak_leg(workdir: str, seed: int,
                  windows: int = SOAK_WINDOWS) -> dict:
    import numpy as np

    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    from paddlebox_tpu.obs.hub import get_hub, reset_hub
    from paddlebox_tpu.online import MODES, OnlineLearner
    from paddlebox_tpu.resilience import preemption
    from paddlebox_tpu.train.checkpoint import (CheckpointManager,
                                                state_digest)

    assert windows >= 9, "soak must cover >=3x stream_check's 3 windows"
    reset_hub()
    preemption.clear_stop()
    jsonl = os.path.join(workdir, "telemetry.jsonl")
    files = generate_criteo_files(os.path.join(workdir, "data"),
                                  num_files=windows * WINDOW,
                                  rows_per_file=ROWS,
                                  vocab_per_slot=40, seed=seed)
    with flags_scope(seed=seed, telemetry_jsonl=jsonl,
                     stream_window_files=WINDOW,
                     stream_ckpt_every_windows=1,
                     shrink_every_windows=3,
                     shrink_delete_threshold=0.05,
                     show_click_decay_rate=0.9,
                     artifact_root=os.path.join(workdir, "registry"),
                     read_thread_num=1):
        desc = DataFeedDesc.criteo(batch_size=BS)
        desc.key_bucket_min = 2048
        trainer = _mk_trainer(desc, seed)
        cm = CheckpointManager(os.path.join(workdir, "ckpt"))
        srv = _srv(desc)

        def mkds():
            ds = DatasetFactory().create_dataset("QueueDataset", desc)
            ds.set_filelist(files)
            return ds

        learner = OnlineLearner(trainer, mkds, cm, serving=srv,
                                store=cm.artifacts,
                                filelist_fn=lambda: list(files),
                                max_idle_polls=2,
                                reload_poll_sec=0.05)
        samples = []
        healthz_seen = []
        orig_hook = learner._on_window

        def hook(widx, dataset):
            orig_hook(widx, dataset)
            cur = None
            try:
                cur = cm.load_cursor()
            except Exception:
                pass
            samples.append(dict(
                window=int(widx),
                live_rows=int(learner._live_rows()),
                cursor_bytes=len(json.dumps(cur, sort_keys=True))
                if cur else 0,
                rss_mb=round(_rss_mb(), 1),
                staleness=round(float(
                    srv.serving_status().get("staleness_sec") or 0.0),
                    3)))
            if widx == 2:   # mid-run /healthz aggregation check
                h = get_hub().health()
                assert "online" in h, sorted(h)
                ob = h["online"]
                assert ob["mode"] in MODES and ob["serving"], ob
                healthz_seen.append(ob)

        learner._on_window = hook
        probe = np.arange(1, 201, dtype=np.uint64)
        worker = _QueryWorker(srv, probe)
        worker.start()
        t0 = time.perf_counter()
        totals = learner.run()
        elapsed = time.perf_counter() - t0
        worker.stop()

        # ---- composition held for the whole horizon
        assert totals["windows"] == windows, totals
        assert learner.shrink_cycles == windows // 3, (
            learner.shrink_cycles, windows)
        assert learner.shrink_skipped_total == 0
        assert learner.leg_failures == 0
        assert healthz_seen, "mid-run /healthz check never ran"
        final = learner.online_status()
        assert final["mode"] in ("full", "degraded"), final

        # ---- plateau proofs (the soak invariant)
        live = [s["live_rows"] for s in samples]
        _assert_plateau("live_rows", live, rel=0.05)
        _assert_plateau("cursor_bytes",
                        [s["cursor_bytes"] for s in samples], rel=0.20)
        _assert_plateau("rss_mb", [s["rss_mb"] for s in samples],
                        rel=RSS_GROWTH_FRAC)
        _assert_plateau("staleness",
                        [s["staleness"] for s in samples],
                        abs_bound=STALENESS_BOUND_SEC)
        assert worker.max_staleness <= STALENESS_BOUND_SEC, \
            worker.max_staleness

        # ---- every served lookup bit-matches its version's oracle
        assert worker.records, "no queries were served during the soak"
        seen_aids = {aid for aid, _ in worker.records}
        assert len(seen_aids) >= 2, (
            f"hot reload never advanced the served version: {seen_aids}")
        oracle = _lookup_oracles(cm.artifacts, desc, probe, seen_aids)
        torn = [(aid, d) for aid, d in worker.records
                if oracle.get(aid) != d]
        assert not torn, f"served lookups tore across swaps: {torn[:3]}"

        # ---- final state is restorable and digest-stable
        versions = cm.artifacts.versions()
        assert len(versions) == windows, (len(versions), windows)
        last = cm.latest_step()
        fresh = _mk_trainer(desc, seed)
        assert CheckpointManager(
            os.path.join(workdir, "ckpt")).restore(fresh) == last
        final_digest = state_digest(fresh)

    with open(jsonl) as fh:
        events = [json.loads(line) for line in fh]
    counts = {}
    for e in events:
        counts[e["event"]] = counts.get(e["event"], 0) + 1
    assert counts.get("stream_window", 0) == windows, counts
    assert counts.get("online_shrink", 0) == windows // 3, counts

    return dict(
        ok=True,
        # `sig` is the determinism contract: byte-identical across
        # identically-seeded runs (timing fields live outside it)
        sig=dict(
            windows=int(totals["windows"]),
            examples=int(totals["examples"]),
            shrink_cycles=int(learner.shrink_cycles),
            shrunk_rows_total=int(learner.shrunk_rows_total),
            live_rows=live,
            versions=list(versions),
            final_step=int(last),
            final_digest=final_digest,
            oracle=oracle,
            events=dict(stream_window=counts["stream_window"],
                        online_shrink=counts["online_shrink"]),
        ),
        samples=samples,
        ex_per_sec=round(totals["examples"] / max(elapsed, 1e-9), 1),
        queries=len(worker.records),
        max_staleness=round(worker.max_staleness, 3),
    )


# ---------------------------------------------------------------------------
# leg 2: tiered/SSD feature lifecycle soak (async epilogue ON)
# ---------------------------------------------------------------------------

def _run_tiered_lifecycle_leg(workdir: str, seed: int,
                              windows: int = SOAK_WINDOWS) -> dict:
    """The aging policy through the full tier stack: BoxPS-style pass
    windows over PassScopedTable → HostStore → SsdTier with the async
    end_pass epilogue on, watermark demotion every window and a fenced
    shrink every 3 — host keys, SSD live rows, disk bytes must all
    plateau and compaction must keep the live fraction above floor."""
    import numpy as np

    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.ps import HostStore, PassScopedTable, \
        SparseSGDConfig
    from paddlebox_tpu.ps.table import FIELD_COL

    with flags_scope(seed=seed, async_end_pass=True,
                     host_demote_watermark=0.25,
                     host_demote_target=0.1,
                     ssd_segment_rows=256,
                     ssd_compact_live_frac=0.6):
        hs = HostStore(mf_dim=4, capacity=1024,
                       ssd_dir=os.path.join(workdir, "tier"))
        t = PassScopedTable(hs, pass_capacity=512, cfg=SparseSGDConfig())
        hot = np.arange(1, 161, dtype=np.uint64)
        samples, shrunk_total = [], 0
        for w in range(windows):
            churn = np.arange(10_000 + w * 120, 10_120 + w * 120,
                              dtype=np.uint64)
            keys = np.concatenate([hot, churn])
            t.begin_pass(keys)
            rows = t.index.lookup(keys)
            d = np.asarray(t.state.data).copy()
            d[rows[:len(hot)], FIELD_COL["show"]] += 3.0  # stays warm
            d[rows[len(hot):], FIELD_COL["show"]] += 0.2  # goes cold
            t.state = type(t.state).from_logical(d, t.state.capacity)
            t._touched[rows] = True
            t.end_pass()
            # drain the async epilogue before demotion decisions — the
            # window's write-back must land so every run sees the same
            # tier state (the shrink-vs-draining-epilogue race itself
            # is covered by tests/test_shrink_fence.py)
            t.fence()
            hs.demote_to_watermark()
            if (w + 1) % 3 == 0:
                # fenced against the epilogue; SSD ages + compacts too
                shrunk_total += t.shrink(delete_threshold=0.1,
                                         decay=0.7)
                # production follows a shrink with a BASE save (which
                # seals the active segment via manifest()) and compacts
                # on the demote worker — run the same sequence so the
                # sample sees the steady state, not the transient
                # just-shrunk dead fraction
                hs.ssd.manifest()
                hs.ssd.maybe_compact()
            st = hs.ssd.stats()
            row_bytes = 8 + 1 + hs.ssd.width * 4
            samples.append(dict(
                window=w, host_rows=len(hs), ssd_rows=len(hs.ssd),
                live_rows=len(hs) + len(hs.ssd),
                ssd_bytes=int(st["bytes"]),
                live_frac=round(st["live_rows"] * row_bytes
                                / max(1, st["bytes"]), 4)))
        assert shrunk_total > 0, "shrink cycles never dropped a row"
        # hot keys must survive every cycle (their decayed score stays
        # above threshold); churn keys must not accumulate
        back = hs.fetch(hot)
        assert float(back["show"].min()) > 0.0, "a hot key was aged out"
        _assert_plateau("tiered.live_rows",
                        [s["live_rows"] for s in samples], rel=0.05)
        _assert_plateau("tiered.host_rows",
                        [s["host_rows"] for s in samples], rel=0.05)
        # disk footprint: the mid-cycle peak (vacated copies pending
        # compaction) is bounded loosely; the post-shrink/post-compact
        # footprint — the steady-state claim — is bounded tightly
        _assert_plateau("tiered.ssd_bytes",
                        [s["ssd_bytes"] for s in samples], rel=0.30)
        _assert_plateau("tiered.ssd_bytes_post_shrink",
                        [s["ssd_bytes"] for i, s in enumerate(samples)
                         if (i + 1) % 3 == 0], rel=0.05)
        third = max(1, len(samples) // 3)
        tail_frac = [s["live_frac"] for s in samples[-third:]]
        assert min(tail_frac) >= 0.25, (
            f"SSD live fraction collapsed — compaction is not keeping "
            f"up: {tail_frac}")
    return dict(ok=True, shrunk_total=int(shrunk_total),
                samples=samples)


# ---------------------------------------------------------------------------
# leg 3: subprocess kill round-trips of scripts/onlinelearn.py
# ---------------------------------------------------------------------------

def _daemon_cmd(workdir: str, data_dir: str, seed: int) -> list:
    return [sys.executable,
            os.path.join(REPO, "scripts", "onlinelearn.py"),
            "--workdir", workdir, "--data-dir", data_dir,
            "--seed", str(seed), "--window-files", str(WINDOW),
            "--ckpt-every", "1", "--shrink-every", "3",
            "--shrink-threshold", "0.05", "--decay", "0.9",
            "--max-idle-polls", "3", "--serve", "--healthz-port", "0",
            # deep boundary history: the kill legs digest-compare the
            # victim against the oracle at a pre-kill window boundary,
            # so retention must not sweep it during the drain
            "--ckpt-keep", "64"]


def _read_port(proc, deadline_sec: float = 120.0) -> int:
    deadline = time.time() + deadline_sec
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            time.sleep(0.05)
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "healthz_port" in obj:
            return int(obj["healthz_port"])
    raise AssertionError("daemon never printed its healthz port")


def _healthz(port: int) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5) as r:
        return json.loads(r.read())


def _final_digest(workdir: str, seed: int, step=None):
    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.data import DataFeedDesc
    from paddlebox_tpu.train.checkpoint import (CheckpointManager,
                                                state_digest)
    with flags_scope(seed=seed):
        desc = DataFeedDesc.criteo(batch_size=BS)
        desc.key_bucket_min = 2048
        cm = CheckpointManager(os.path.join(workdir, "ckpt"))
        if step is None:
            step = cm.latest_step()
        t = _mk_trainer(desc, seed)
        assert cm.restore(t, step=step) == step
        return int(step), state_digest(t)


def _count_events(jsonl: str, name: str) -> int:
    if not os.path.exists(jsonl):
        return 0
    n = 0
    with open(jsonl) as fh:
        for line in fh:
            try:
                if json.loads(line).get("event") == name:
                    n += 1
            except json.JSONDecodeError:
                pass   # a torn tail line mid-write
    return n


def _run_kill_leg(workdir: str, seed: int, signame: str,
                  windows: int = 6) -> dict:
    """One real-signal round-trip: launch the daemon as a subprocess,
    land ``signame`` mid-window (gated on the daemon's own telemetry
    event stream), relaunch with the same workdir, and require the
    drained daemon's final boundary digest to bit-match an unkilled
    oracle run's."""
    from paddlebox_tpu.data.criteo import generate_criteo_files
    from paddlebox_tpu.resilience.preemption import (EXIT_RESUME,
                                                     read_resume_marker)
    from paddlebox_tpu.train.checkpoint import CheckpointManager

    data_dir = os.path.join(workdir, "data")
    generate_criteo_files(data_dir, num_files=windows * WINDOW,
                          rows_per_file=256, vocab_per_slot=40,
                          seed=seed)

    # (a) unkilled oracle
    oracle_dir = os.path.join(workdir, "oracle")
    r = subprocess.run(_daemon_cmd(oracle_dir, data_dir, seed),
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    oracle_step, oracle_digest = _final_digest(oracle_dir, seed)

    # (b) victim: the signal is sent right after the 2nd stream_window
    # event lands in the victim's telemetry — several windows of work
    # remain, so a SIGTERM lands mid-window (boundary-exact landings
    # are rare; retried for determinism of the leg's claims)
    healthz_ok = False
    victim_dir = rc = cursor = None
    for attempt in range(3):
        victim_dir = os.path.join(workdir, f"victim{attempt}")
        jsonl = os.path.join(victim_dir, "telemetry.jsonl")
        proc = subprocess.Popen(_daemon_cmd(victim_dir, data_dir, seed),
                                stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True)
        try:
            port = _read_port(proc)
            deadline = time.time() + 300
            while time.time() < deadline:
                if _count_events(jsonl, "stream_window") >= 2:
                    break
                if not healthz_ok:
                    try:   # /healthz aggregation answers while training
                        ob = _healthz(port).get("online") or {}
                        healthz_ok = bool(ob.get("serving")) \
                            and "windows_completed" in ob \
                            and "mode" in ob
                    except Exception:
                        pass
                time.sleep(0.01)
            else:
                raise AssertionError("daemon never reached 2 windows")
            # the 2nd window's event just landed — the daemon is in its
            # boundary save; a short beat later the signal lands INSIDE
            # window 3's batches (windows are ~0.2 s with a warm XLA
            # cache, so the beat stays small; retried if it still hits
            # a boundary or outruns the stream)
            time.sleep(0.1 + 0.1 * attempt)
            os.kill(proc.pid, getattr(signal, f"SIG{signame}"))
            rc = proc.wait(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        cursor = CheckpointManager(
            os.path.join(victim_dir, "ckpt")).load_cursor() or {}
        stream = cursor.get("stream") or {}
        if signame == "TERM":
            if stream.get("window_files"):
                break   # mid-window emergency cursor captured
        elif _count_events(jsonl, "stream_window") < windows:
            break       # SIGKILL landed before the stream drained
    assert healthz_ok, "/healthz online block never answered"
    stream = cursor.get("stream") or {}
    open_window = [os.path.basename(p)
                   for p in stream.get("window_files", [])]

    marker = read_resume_marker(os.path.join(victim_dir, "ckpt"))
    if signame == "TERM":
        # graceful: emergency boundary checkpoint + RESUME.json + 75
        assert rc == EXIT_RESUME, rc
        assert marker is not None and marker["exit_code"] == EXIT_RESUME
        assert open_window, (
            "SIGTERM never landed mid-window — no open window to "
            "replay (3 attempts)")
    else:
        assert rc == -signal.SIGKILL, rc
        assert marker is None, "SIGKILL cannot write a graceful marker"
        # progress past the last boundary is legitimately lost — the
        # relaunch must still have windows left to train
        assert _count_events(jsonl, "stream_window") < windows, \
            "SIGKILL never landed before the stream drained (3 attempts)"
        assert int(stream.get("windows_completed", 0)) < windows, stream

    # (c) relaunch with the same workdir: resume + drain; /healthz
    # answers while it does
    jsonl = os.path.join(victim_dir, "telemetry.jsonl")
    resumes0 = _count_events(jsonl, "cursor_resume")
    proc = subprocess.Popen(_daemon_cmd(victim_dir, data_dir, seed),
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    saw_online = False
    try:
        port = _read_port(proc)
        deadline = time.time() + 300
        while proc.poll() is None and time.time() < deadline:
            try:
                ob = _healthz(port).get("online") or {}
                # early polls can race the probe wiring — require the
                # block to show up at least once during the drain
                saw_online = saw_online or bool(ob.get("mode"))
            except (urllib.error.URLError, OSError, ValueError):
                pass   # between server teardown and process exit
            time.sleep(0.05)
        out, err = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)
    assert proc.returncode == 0, err[-2000:]
    assert saw_online, \
        "/healthz online block never answered during the drain"
    assert read_resume_marker(os.path.join(victim_dir, "ckpt")) is None, \
        "resume marker not consumed"
    status = json.loads(out.strip().splitlines()[-1])
    assert status["windows_completed"] == windows, status

    # at-least-once: the resume adopted the cursor and replayed exactly
    # the open window (SIGTERM) / re-entered the lost window (SIGKILL)
    events = []
    with open(jsonl) as fh:
        for line in fh:
            events.append(json.loads(line))
    resumes = [e for e in events if e["event"] == "cursor_resume"]
    assert len(resumes) > resumes0, \
        sorted({e["event"] for e in events})
    replayed = int(resumes[-1].get("replay_files", 0) or 0)
    if signame == "TERM":
        assert replayed == len(open_window), (replayed, open_window)

    # ---- bit-determinism vs the unkilled oracle
    step, digest = _final_digest(victim_dir, seed)
    if signame == "KILL":
        # SIGKILL resumes from the last BOUNDARY checkpoint — no
        # mid-window state survives, so the drained daemon's final
        # state must bit-match the oracle's exactly
        assert (step, digest) == (oracle_step, oracle_digest), (
            f"post-resume state diverged from the unkilled oracle:\n"
            f"  oracle step {oracle_step} digest {oracle_digest}\n"
            f"  victim step {step} digest {digest}")
        common_step, common_digest = step, digest
    else:
        # SIGTERM resumed MID-window: the open window's pre-kill
        # batches legitimately train twice (at-least-once), inflating
        # global_step by < one window — the bit-match contract is at
        # the last COMMON window boundary (stream_check's), and the
        # inflation stays bounded to the replayed window
        assert oracle_step <= step < oracle_step + windows * 256 // BS, (
            step, oracle_step)
        from paddlebox_tpu.config import flags_scope
        with flags_scope(seed=seed):
            victim_steps = set(CheckpointManager(
                os.path.join(victim_dir, "ckpt")).steps())
            oracle_steps = set(CheckpointManager(
                os.path.join(oracle_dir, "ckpt")).steps())
        kill_step = int(cursor["global_step"])
        common = sorted(s for s in victim_steps & oracle_steps
                        if s <= kill_step)
        assert common, "no common pre-kill boundary checkpoint"
        common_step = common[-1]
        _, d_oracle = _final_digest(oracle_dir, seed, step=common_step)
        _, common_digest = _final_digest(victim_dir, seed,
                                         step=common_step)
        assert common_digest == d_oracle, (
            f"killed run diverged from the oracle at the last common "
            f"window boundary (step {common_step}):\n"
            f"  oracle {d_oracle}\n  victim {common_digest}")
    return dict(ok=True, signal=signame, rc=rc,
                open_window=open_window, replayed_files=replayed,
                final_step=step, common_boundary=int(common_step),
                boundary_digest=common_digest)


# ---------------------------------------------------------------------------
# leg 4: corrupt-delta chaos through the daemon's own reload loop
# ---------------------------------------------------------------------------

def _run_corrupt_delta_leg(workdir: str, seed: int) -> dict:
    import numpy as np

    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    from paddlebox_tpu.obs.hub import get_hub, reset_hub
    from paddlebox_tpu.online import OnlineLearner
    from paddlebox_tpu.resilience import preemption
    from paddlebox_tpu.train.checkpoint import CheckpointManager

    reset_hub()
    preemption.clear_stop()
    staged = generate_criteo_files(os.path.join(workdir, "staged"),
                                   num_files=3 * WINDOW,
                                   rows_per_file=ROWS,
                                   vocab_per_slot=40, seed=seed)
    data_dir = os.path.join(workdir, "data")
    os.makedirs(data_dir)
    for p in staged[:WINDOW]:               # window 0 only, for now
        shutil.copy(p, data_dir)

    with flags_scope(seed=seed,
                     telemetry_jsonl=os.path.join(workdir,
                                                  "telemetry.jsonl"),
                     stream_window_files=WINDOW,
                     stream_ckpt_every_windows=1,
                     shrink_every_windows=3,
                     shrink_delete_threshold=0.05,
                     show_click_decay_rate=0.9,
                     artifact_root=os.path.join(workdir, "registry"),
                     read_thread_num=1):
        desc = DataFeedDesc.criteo(batch_size=BS)
        desc.key_bucket_min = 2048
        trainer = _mk_trainer(desc, seed)
        cm = CheckpointManager(os.path.join(workdir, "ckpt"))
        srv = _srv(desc)

        def filelist():
            return sorted(_glob.glob(os.path.join(data_dir, "*.txt")))

        def mkds():
            ds = DatasetFactory().create_dataset("QueueDataset", desc)
            ds.set_filelist(filelist())
            return ds

        learner = OnlineLearner(trainer, mkds, cm, serving=srv,
                                store=cm.artifacts,
                                filelist_fn=filelist, max_windows=3,
                                reload_poll_sec=0.05)
        probe = np.arange(1, 201, dtype=np.uint64)
        worker = _QueryWorker(srv, probe)
        worker.start()
        th = threading.Thread(target=learner.run, daemon=True)
        th.start()
        store = cm.artifacts
        hub = get_hub()

        def wait_for(cond, what, sec=120):
            deadline = time.time() + sec
            while time.time() < deadline:
                if cond():
                    return
                time.sleep(0.02)
            raise AssertionError(f"timed out waiting for {what}")

        # window 0 publishes the base; the daemon's loop adopts it
        wait_for(lambda: len(store.versions()) >= 1, "the base publish")
        v1 = store.versions()[0]
        wait_for(lambda: srv.adopted_aid == v1, "base adoption")
        # pause the daemon's reload loop at a known point so the
        # corruption deterministically lands BEFORE the next adoption
        loop = learner._loop
        loop.stop()

        for p in staged[WINDOW:2 * WINDOW]:   # window 1 -> delta v2
            shutil.copy(p, data_dir)
        wait_for(lambda: len(store.versions()) >= 2, "the delta publish")
        v2 = store.versions()[1]
        payload = os.path.join(store.version_dir(v2),
                               "sparse_delta.npz")
        with open(payload, "rb") as fh:
            blob = fh.read()
        flip = 13 % len(blob)
        with open(payload, "wb") as fh:
            fh.write(blob[:flip] + bytes([blob[flip] ^ 0xFF])
                     + blob[flip + 1:])

        refused0 = hub.counter("pbox_artifact_refused_total").value(
            reason="corrupt")
        degraded0 = loop.degraded
        for _ in range(3):   # the daemon's own poll refuses, loudly
            assert loop.poll_once() is None
        assert srv.adopted_aid == v1, "corrupt delta must not swap in"
        assert loop.degraded > degraded0, "degrade was silent"
        assert hub.counter("pbox_artifact_refused_total").value(
            reason="corrupt") > refused0, "refusal was silent"
        assert srv.serving_status()["staleness_sec"] > 0.0
        ob = hub.health().get("online") or {}
        assert ob.get("mode") in ("full", "degraded"), ob

        # recovery path the daemon itself produces: window 2 completes
        # the shrink cadence (wc=3) -> forced BASE publish, adoptable
        # without replaying the corrupt delta
        for p in staged[2 * WINDOW:]:
            shutil.copy(p, data_dir)
        th.join(timeout=300)
        assert not th.is_alive(), "daemon never drained"
        versions = store.versions()
        assert len(versions) == 3, versions
        v3 = versions[2]
        man = store.read_manifest(v3, verify=False)
        assert man.get("kind") == "base", (
            f"the shrink boundary was meant to force a BASE: {man}")
        assert loop.poll_once() == v3
        assert srv.adopted_aid == v3
        assert srv.serving_status()["staleness_sec"] == 0.0
        worker.stop()
        assert learner.shrink_cycles == 1
        assert learner.totals["windows"] == 3

        seen = {aid for aid, _ in worker.records}
        assert v2 not in seen, "a corrupt version answered queries"
        oracle = _lookup_oracles(store, desc, probe, seen)
        torn = [(a, d) for a, d in worker.records if oracle.get(a) != d]
        assert not torn, f"queries tore during the degrade window: {torn[:3]}"
    return dict(ok=True, refused_version=v2, recovered_version=v3,
                versions=versions, queries=len(worker.records))


# ---------------------------------------------------------------------------
# leg 5: online.shrink fault seam — transient retry / hard skip
# ---------------------------------------------------------------------------

def _run_shrink_chaos_leg(workdir: str, seed: int) -> dict:
    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    from paddlebox_tpu.obs import flightrec
    from paddlebox_tpu.obs.hub import get_hub, reset_hub
    from paddlebox_tpu.online import OnlineLearner
    from paddlebox_tpu.resilience import preemption
    from paddlebox_tpu.resilience.faults import FaultPlan, installed
    from paddlebox_tpu.train.checkpoint import CheckpointManager

    out = {}
    for sub, spec in (("transient", "online.shrink:fail:nth=1,times=1"),
                      ("hard", "online.shrink:fail:nth=1,exc=crash")):
        reset_hub()
        preemption.clear_stop()
        wd = os.path.join(workdir, sub)
        jsonl = os.path.join(wd, "telemetry.jsonl")
        files = generate_criteo_files(os.path.join(wd, "data"),
                                      num_files=3 * WINDOW,
                                      rows_per_file=ROWS,
                                      vocab_per_slot=40, seed=seed)
        frec_dir = os.path.join(wd, "flightrec")
        with flags_scope(seed=seed, telemetry_jsonl=jsonl,
                         stream_window_files=WINDOW,
                         stream_ckpt_every_windows=1,
                         shrink_every_windows=1,
                         shrink_delete_threshold=0.05,
                         show_click_decay_rate=0.9,
                         flightrec_dir=frec_dir,
                         read_thread_num=1):
            flightrec.configure_from_flags()
            desc = DataFeedDesc.criteo(batch_size=BS)
            desc.key_bucket_min = 2048
            trainer = _mk_trainer(desc, seed)
            cm = CheckpointManager(os.path.join(wd, "ckpt"))

            def mkds(files=files):
                ds = DatasetFactory().create_dataset("QueueDataset",
                                                     desc)
                ds.set_filelist(files)
                return ds

            learner = OnlineLearner(trainer, mkds, cm,
                                    filelist_fn=lambda f=files: list(f),
                                    max_idle_polls=2)
            plan = FaultPlan.parse(spec, seed=seed)
            with installed(plan):
                totals = learner.run()
            flightrec.install_recorder(None)
        assert totals["windows"] == 3, totals
        assert plan.stats()["online.shrink:fail"]["fired"] >= 1, \
            plan.stats()
        hub = get_hub()
        with open(jsonl) as fh:
            names = [json.loads(line)["event"] for line in fh]
        if sub == "transient":
            # the seeded online.shrink policy retried past the injected
            # failure: every cycle completed, none skipped
            assert learner.shrink_cycles == 3, learner.online_status()
            assert learner.shrink_skipped_total == 0
            assert names.count("online_shrink") == 3
        else:
            # hard failure: the first cycle SKIPPED loudly, training
            # continued, the cadence resumed on later windows
            assert learner.shrink_skipped_total == 1, \
                learner.online_status()
            assert learner.shrink_cycles == 2
            assert hub.counter(
                "pbox_online_shrink_skipped_total").value() == 1
            assert "online_shrink_skipped" in names, sorted(set(names))
            bundles = os.listdir(frec_dir) if os.path.isdir(frec_dir) \
                else []
            assert bundles, "shrink_skipped never tripped the recorder"
        out[sub] = dict(ok=True, cycles=int(learner.shrink_cycles),
                        skipped=int(learner.shrink_skipped_total),
                        fault=plan.stats())
    return out


# ---------------------------------------------------------------------------

def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--windows", type=int, default=SOAK_WINDOWS,
                    help="soak horizon (>=9: 3x stream_check's "
                         "3 windows)")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--keep", action="store_true")
    ap.add_argument("--bench-out", default=None,
                    help="append online.* bench rows (JSON lines) here")
    ap.add_argument("--skip-subprocess", action="store_true",
                    help="skip the real-signal subprocess legs")
    args = ap.parse_args()

    base = args.workdir or tempfile.mkdtemp(prefix="pbox_online_")
    try:
        # ---- soak x2: identical seed, identical outcome required
        soaks = []
        for run in (1, 2):
            wd = os.path.join(base, f"soak{run}")
            os.makedirs(wd, exist_ok=True)
            print(f"--- soak run {run} ({args.windows} windows, "
                  f"seed={args.seed}) ---")
            soaks.append(_run_soak_leg(wd, args.seed, args.windows))
            print(json.dumps({k: v for k, v in soaks[-1].items()
                              if k != "samples"}, sort_keys=True))
        if soaks[0]["sig"] != soaks[1]["sig"]:
            print("FAIL: soak outcome differs across identically-"
                  "seeded runs:")
            print(json.dumps(soaks[0]["sig"], sort_keys=True))
            print(json.dumps(soaks[1]["sig"], sort_keys=True))
            return 1

        # ---- tiered lifecycle x2 (pure numpy, deterministic)
        tiered = []
        for run in (1, 2):
            wd = os.path.join(base, f"tiered{run}")
            os.makedirs(wd, exist_ok=True)
            print(f"--- tiered lifecycle run {run} ---")
            tiered.append(_run_tiered_lifecycle_leg(wd, args.seed,
                                                    args.windows))
        if tiered[0] != tiered[1]:
            print("FAIL: tiered lifecycle outcome not deterministic")
            return 1
        print(json.dumps(dict(shrunk=tiered[0]["shrunk_total"],
                              last=tiered[0]["samples"][-1]),
                         sort_keys=True))

        # ---- chaos legs
        print("--- corrupt-delta chaos ---")
        corrupt = _run_corrupt_delta_leg(
            os.path.join(base, "corrupt"), args.seed)
        print(json.dumps(corrupt, sort_keys=True))
        print("--- shrink chaos (transient retry / hard skip) ---")
        chaos = _run_shrink_chaos_leg(os.path.join(base, "chaos"),
                                      args.seed)
        print(json.dumps(chaos, sort_keys=True))

        kills = {}
        if not args.skip_subprocess:
            for signame in ("TERM", "KILL"):
                print(f"--- real-SIG{signame} subprocess round-trip ---")
                kills[signame] = _run_kill_leg(
                    os.path.join(base, f"kill_{signame.lower()}"),
                    args.seed, signame)
                print(json.dumps(kills[signame], sort_keys=True))

        if args.bench_out:
            live_tail = soaks[0]["sig"]["live_rows"][-1]
            tiered_tail = tiered[0]["samples"][-1]["live_rows"]
            rows = [
                dict(metric="online.stream.ex_per_sec",
                     value=soaks[0]["ex_per_sec"], unit="ex/s",
                     mode="online", shape="stream"),
                dict(metric="online.stream.live_rows_plateau",
                     value=live_tail, unit="rows",
                     mode="online", shape="stream"),
                dict(metric="online.tiered.live_rows_plateau",
                     value=tiered_tail, unit="rows",
                     mode="online", shape="tiered"),
            ]
            with open(args.bench_out, "a") as fh:
                for row in rows:
                    fh.write(json.dumps(row) + "\n")
            print(f"bench rows -> {args.bench_out}")

        print(f"PASS: {args.windows}-window soak plateaued "
              f"(live/cursor/RSS/staleness) deterministically x2, "
              f"tiered lifecycle plateaued with SSD compaction, "
              f"corrupt delta refused + recovered via the forced-base "
              f"publish, shrink chaos retried/skipped loudly"
              + ("" if args.skip_subprocess else
                 ", SIGTERM/SIGKILL round-trips bit-matched the "
                 "unkilled oracle"))
        return 0
    finally:
        if not args.keep and args.workdir is None:
            shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
