#!/usr/bin/env python
"""Deterministic publish/adopt gate for the artifact layer (ISSUE 14).

A seeded WRITER (an ``EmbeddingTable`` publishing base+delta versions
through ``BoxPSHelper.publish_base/publish_delta`` → ``ArtifactStore``)
and a READER (``serving.ServingModel.adopt``) are driven through the
three failure scenarios the layer exists for:

1. **crash mid-publish** — ``artifact.publish:fail:exc=crash`` kills the
   writer after staging but before the atomic rename: the carcass is
   swept on the next store open and a fresh reader adopts the previous
   COMPLETE version, bit-identical to the oracle;
2. **corrupt delta** — one flipped byte in a published delta payload:
   adoption refuses the tip loudly (``ArtifactCorruptError``,
   ``pbox_artifact_refused_total``) and degrades to the newest
   verifiable version, again bit-identical;
3. **retention sweep vs held lease** — a reader holding a lease on an
   old version keeps it (and its lineage) alive through a
   ``retain(keep=2)`` sweep that would otherwise delete it; after
   release the sweep reclaims it and the reader's stale handle FENCES
   (``ArtifactLeaseLostError``) instead of serving swept files.

A tiered preamble also publishes a THREE-TIER table (host RAM + SSD
segments) and checks the artifact's spill-manifest REFERENCE digest
matches the tier's own manifest digest.

Every scenario ends with the reader on a complete, checksum-verified
version, and ``main()`` runs the whole thing twice with the same seed
asserting a byte-identical outcome — publish robustness is provable,
not hoped-for.

Usage::

    JAX_PLATFORMS=cpu python scripts/publish_check.py [--seed 7]

Exit code 0 == all scenarios recovered + deterministic.
"""

from __future__ import annotations

import argparse
import glob
import hashlib
import json
import os
import shutil
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def table_digest(t) -> str:
    """sha256 over an EmbeddingTable's logical rows, sorted by feasign
    (row-assignment order cancels out) — the reader-side bit-identity
    oracle."""
    import numpy as np
    with t.host_lock:
        keys, rows = t.index.items()
    order = np.argsort(keys)
    blob = t._gather_host(rows[order])
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(keys[order]).tobytes())
    for f in sorted(blob):
        h.update(f.encode())
        h.update(np.ascontiguousarray(blob[f]).tobytes())
    return h.hexdigest()


def run_publish_check(workdir: str, seed: int = 7) -> dict:
    """One full writer/reader scenario; returns the outcome summary
    (aid strings, digests, counters — no absolute paths, so two seeded
    runs compare byte-identical)."""
    import jax
    import numpy as np

    from paddlebox_tpu.artifacts import (ArtifactCorruptError,
                                         ArtifactLeaseLostError,
                                         ArtifactStore)
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.obs.hub import get_hub, reset_hub
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.ps.box_helper import BoxPSHelper
    from paddlebox_tpu.ps.table import FIELD_COL, TableState
    from paddlebox_tpu.resilience.faults import (FaultPlan, InjectedCrash,
                                                 installed)
    from paddlebox_tpu.serving import ServingModel
    from paddlebox_tpu.data.schema import DataFeedDesc

    reset_hub()
    root = os.path.join(workdir, "registry")
    cfg = SparseSGDConfig(mf_create_thresholds=1e9)
    desc = DataFeedDesc.criteo(batch_size=16)
    writer = EmbeddingTable(mf_dim=4, capacity=1 << 11, cfg=cfg)
    helper = BoxPSHelper(writer)

    def write(lo: int, hi: int, scale: float) -> None:
        keys = np.arange(lo, hi, dtype=np.uint64)
        rows = writer.index.assign(keys)
        data = np.asarray(jax.device_get(writer.state.data)).copy()
        data[rows, FIELD_COL["embed_w"]] = keys.astype(np.float32) * scale
        data[rows, FIELD_COL["show"]] = 1.0
        writer.state = TableState.from_logical(data, writer.capacity)
        writer._touched[rows] = True

    def oracle_digest(aids, store) -> str:
        """Digest a fresh table would hold after replaying ``aids``'
        payloads in order — computed straight from the published files."""
        t = EmbeddingTable(mf_dim=4, capacity=1 << 11, cfg=cfg)
        for i, aid in enumerate(aids):
            m = store.read_manifest(aid)
            name = ("sparse.npz" if m["kind"] == "base"
                    else "sparse_delta.npz")
            t.load(os.path.join(store.version_dir(aid), name),
                   merge=i > 0)
        return table_digest(t)

    def reader() -> "ServingModel":
        return ServingModel(CtrDnn(hidden=(4,)), desc, mf_dim=4,
                            capacity=1 << 11)

    out: dict = {}
    store = ArtifactStore(root)

    # ---- tiered preamble: a three-tier publisher's spill-manifest ref
    from paddlebox_tpu.ps.table import FIELDS, TWO_D_FIELDS
    from paddlebox_tpu.ps.tiered import TieredShardedEmbeddingTable
    tiered = TieredShardedEmbeddingTable(
        1, mf_dim=4, capacity_per_shard=1024, cfg=cfg, host_capacity=256,
        req_bucket_min=128, serve_bucket_min=128,
        ssd_dir=os.path.join(workdir, "tier"))
    tkeys = np.arange(1, 401, dtype=np.uint64)
    for i in range(0, len(tkeys), 128):   # chunked past host capacity:
        ks = tkeys[i:i + 128]             # the emergency demoter spills
        vals = ks.astype(np.float32)      # the cold tail to segments
        tiered.hosts[0].update(ks, {
            f: (np.tile(vals[:, None], (1, 4)) * 0.01
                if f in TWO_D_FIELDS else vals * 0.001)
            for f in FIELDS})
    assert tiered.hosts[0].demote_cold(count=150) > 0
    tiered_store = ArtifactStore(os.path.join(workdir, "registry_tiered"))
    tiered_helper = BoxPSHelper(tiered)
    tier_digest0 = tiered.rows_digest()   # writer-side oracle
    taid = tiered_helper.publish_base(tiered_store)
    # staged publish must be content-inert on the writer (only the
    # delta bookkeeping clears, and only after the commit)
    assert tiered.rows_digest() == tier_digest0, (
        "publish mutated the writer's tier content")
    tman = tiered_store.read_manifest(taid)
    spill_ref = tman["refs"].get("spill_manifest") or {}
    tier_manifest = tiered.spill_manifest()
    assert spill_ref.get("digest") == tier_manifest["digest"], (
        "artifact spill-manifest reference does not name the tier state")
    tsrv = reader()
    assert tsrv.adopt(tiered_store) == taid
    tvals = tsrv.embed_lookup(np.array([1, 200, 400], np.uint64))
    assert np.allclose(tvals[:, 2],
                       np.array([1, 200, 400], np.float32) * 0.001), (
        "tiered publish lost spilled rows")  # demoted rows merged back
    tsrv.release()
    out["tiered"] = {"aid": taid, "spill_digest": spill_ref["digest"],
                     "rows": int(len(tkeys))}

    # ---- publish a clean base + delta chain
    write(1, 201, 2.0)
    v1 = helper.publish_base(store)
    write(150, 261, 3.0)
    v2 = helper.publish_delta(store)
    d2 = oracle_digest([v1, v2], store)
    srv = reader()
    assert srv.adopt(store) == v2
    assert table_digest(srv.table) == d2, "clean adoption not bit-exact"
    srv.release()

    # ---- scenario 1: crash mid-publish (after staging, pre-rename)
    write(240, 301, 5.0)
    crashed = False
    with installed(FaultPlan.parse(
            "artifact.publish:fail:nth=1,exc=crash", seed=seed)) as p1:
        try:
            helper.publish_delta(store)
        except InjectedCrash:
            crashed = True
    assert crashed, "crash-mid-publish fault never fired"
    assert store.versions() == [v1, v2], "half-publish leaked a version"
    carcasses = glob.glob(os.path.join(root, ".stage-*"))
    assert carcasses, "crash left no stage carcass to sweep"
    # while the writer pid is (apparently) alive, even a zero-TTL open
    # must NOT touch the stage — a slow live publisher is not a carcass
    ArtifactStore(root, lease_ttl_sec=0.0)
    assert glob.glob(os.path.join(root, ".stage-*")), (
        "sweep took a live writer's stage")
    # now make the writer PROVABLY dead (marker naming a dead same-host
    # pid — the in-process stand-in for the SIGKILL subprocess variant
    # in tests/test_artifacts.py): the next open sweeps it
    import socket
    import subprocess
    proc = subprocess.Popen(["true"])
    proc.wait()
    dead_pid = proc.pid
    for c in carcasses:
        with open(os.path.join(c, "stage.json"), "w") as fh:
            json.dump({"pid": dead_pid, "host": socket.gethostname()},
                      fh)
    store = ArtifactStore(root)
    assert not glob.glob(os.path.join(root, ".stage-*")), (
        "carcass survived the sweep")
    srv = reader()
    crash_aid = srv.adopt(store)
    crash_ok = crash_aid == v2 and table_digest(srv.table) == d2
    assert crash_ok, "reader not on the previous complete version"
    srv.release()

    # ---- scenario 2: flipped byte in a published delta
    v3 = helper.publish_delta(store)   # the same rows, for real now
    d3 = oracle_digest([v1, v2, v3], store)
    p = os.path.join(store.version_dir(v3), "sparse_delta.npz")
    with open(p, "rb") as fh:
        blob = fh.read()
    flip = 11 % len(blob)
    with open(p, "wb") as fh:
        fh.write(blob[:flip] + bytes([blob[flip] ^ 0xFF])
                 + blob[flip + 1:])
    loud = False
    try:
        reader().adopt(store, v3)      # explicit version: refuse, never
    except ArtifactCorruptError:       # silently degrade
        loud = True
    assert loud, "corrupt delta adopted silently"
    srv = reader()
    corrupt_fallback = srv.adopt(store)   # unpinned: degrade gracefully
    assert corrupt_fallback == v2
    assert table_digest(srv.table) == d2, (
        "degraded adoption not bit-exact")
    srv.release()
    with open(p, "wb") as fh:          # repair: the chain verifies again
        fh.write(blob)
    srv = reader()
    assert srv.adopt(store) == v3
    repaired_ok = table_digest(srv.table) == d3
    assert repaired_ok
    srv.release()
    # writer-side completeness: the chain replay reproduces the
    # writer's OWN table bit-for-bit — in particular, the CRASHED v3
    # publish attempt (which staged with clear_touched=False) lost no
    # delta rows: the successful v3 still carried every one of them
    assert table_digest(writer) == d3, (
        "published chain diverges from the writer table — a failed "
        "publish dropped delta rows")

    # ---- scenario 3: retention sweep concurrent with a held lease
    holder = reader()
    assert holder.adopt(store, v3) == v3      # lease held on v3
    write(300, 361, 7.0)
    v4 = helper.publish_base(store)
    write(350, 401, 9.0)
    v5 = helper.publish_delta(store)
    removed_while_leased = store.retain(keep=2)
    assert removed_while_leased == [], (
        "retention swept a leased/lineage version")
    for aid in (v1, v2, v3):
        assert os.path.isfile(os.path.join(store.version_dir(aid),
                                           "MANIFEST.json")), aid
    # the leased reader still reads bit-verified payloads mid-sweep
    stale_handle = holder._handle
    stale_handle.read("sparse_delta.npz")
    holder.release()
    removed_after_release = store.retain(keep=2)
    assert removed_after_release == [v1, v2, v3], removed_after_release
    fenced = False
    try:
        stale_handle.path("sparse_delta.npz")    # stale handle FENCES
    except ArtifactLeaseLostError:
        fenced = True
    assert fenced, "stale handle served from swept files"
    srv = reader()
    final_aid = srv.adopt(store)
    assert final_aid == v5
    final_digest = table_digest(srv.table)
    assert final_digest == oracle_digest([v4, v5], store)
    srv.release()

    hub = get_hub()
    out.update({
        "ok": True,
        "chain": [v1, v2, v3, v4, v5],
        "digest_v2": d2, "digest_v3": d3, "digest_final": final_digest,
        "crash_fault": p1.stats(),
        "crash_reader_aid": crash_aid,
        "corrupt_fallback_aid": corrupt_fallback,
        "removed_while_leased": removed_while_leased,
        "removed_after_release": removed_after_release,
        "final_aid": final_aid,
        "counters": {
            "published": hub.counter(
                "pbox_artifact_published_total").value(kind="base")
            + hub.counter(
                "pbox_artifact_published_total").value(kind="delta"),
            "refused_corrupt": hub.counter(
                "pbox_artifact_refused_total").value(reason="corrupt"),
        },
    })
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh temp dir)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir for inspection")
    args = ap.parse_args()

    base = args.workdir or tempfile.mkdtemp(prefix="pbox_publish_")
    outcomes = []
    try:
        for run in (1, 2):  # same seed twice: outcome must be identical
            wd = os.path.join(base, f"run{run}")
            os.makedirs(wd, exist_ok=True)
            print(f"--- publish run {run} (seed={args.seed}) ---")
            outcomes.append(run_publish_check(wd, args.seed))
            print(json.dumps(outcomes[-1], indent=2, sort_keys=True))
        if outcomes[0] != outcomes[1]:
            print("FAIL: publish outcome differs across identically-"
                  "seeded runs")
            return 1
        print(f"PASS: crash-mid-publish, corrupt delta and "
              f"retention-vs-lease all left the reader on a complete "
              f"bit-verified version; deterministic across 2 runs "
              f"(seed={args.seed})")
        return 0
    finally:
        if not args.keep and args.workdir is None:
            shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
