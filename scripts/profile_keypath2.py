#!/usr/bin/env python
"""Round-5 probe set 2: grad-merge ordering, gather extract form, push
variants — the levers left after the slot-wire decode fix.

Prints one JSON line per probe. Run on the real chip.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.ps.table import gather_full_rows, init_table_state
from paddlebox_tpu.ps.sgd import SparseSGDConfig, opt_ext_width
from paddlebox_tpu.ps.table import next_bucket_fine

N_ITER = int(os.environ.get("PROF_ITERS", 16))
B, S, AVG, VOCAB = 4096, 26, 5.0, 100_000
MF = 8
CAP = 1 << 23
cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=1e-3)
EXT = opt_ext_width(cfg, MF)

rng = np.random.default_rng(0)
counts = 1 + rng.poisson(AVG - 1.0, size=(B, S))
K = int(counts.sum())
K_pad = next_bucket_fine(4096, K)

slot_of_key = np.repeat(np.tile(np.arange(S), B), counts.reshape(-1))
rows_np = np.empty((N_ITER, K_pad), np.int32)
for i in range(N_ITER):
    k_ids = rng.integers(0, VOCAB, size=K)
    rows_np[i, :K] = (slot_of_key * VOCAB + k_ids).astype(np.int32) % CAP
    rows_np[i, K:] = CAP

# host-computed dedup per iteration (uniq sorted / gidx / perm / uid_sorted)
uniqs = [np.unique(rows_np[i][:K], return_inverse=True)
         for i in range(N_ITER)]
u_max = max(len(u) for u, _ in uniqs)
U_pad = next_bucket_fine(4096, u_max + 1)
gidx_np = np.zeros((N_ITER, K_pad), np.int32)
for i, (u, inv) in enumerate(uniqs):
    gidx_np[i, :K] = inv
    gidx_np[i, K:] = len(u)  # pad position
gidx_stack = jnp.asarray(gidx_np)
# sorted-by-row order: perm sorts keys by row id; uid_sorted nondecreasing
perm_np = np.empty((N_ITER, K_pad), np.int32)
uid_sorted_np = np.empty((N_ITER, K_pad), np.int32)
for i in range(N_ITER):
    p = np.argsort(rows_np[i], kind="stable")
    perm_np[i] = p
    uid_sorted_np[i] = gidx_np[i][p]
perm_stack = jnp.asarray(perm_np)
uid_sorted_stack = jnp.asarray(uid_sorted_np)

g_k = jnp.asarray(rng.normal(size=(K_pad, 3 + MF)).astype(np.float32))
state = init_table_state(CAP, MF, ext=EXT)
uniq_pad_np = np.empty((N_ITER, U_pad), np.int32)
for i, (u, _) in enumerate(uniqs):
    uniq_pad_np[i, :len(u)] = u
    uniq_pad_np[i, len(u):] = CAP + 1 + np.arange(U_pad - len(u))
uniq_stack = jnp.asarray(uniq_pad_np)

print(json.dumps({"probe": "shape", "K": K, "K_pad": K_pad,
                  "U_pad": U_pad}), flush=True)


def timeit(name, fn, *args, **extra):
    r = fn(*args)
    v = np.asarray(jax.device_get(r)).ravel()
    t0 = time.perf_counter()
    r = fn(*args)
    v = np.asarray(jax.device_get(r)).ravel()
    dt = (time.perf_counter() - t0) / N_ITER * 1000
    print(json.dumps({"probe": name, "ms_per_iter": round(dt, 3),
                      "val": float(v[0]), **extra}), flush=True)
    return dt


# ---- merge variants: segment_sum K→U ----
@jax.jit
def p_merge_unsorted(g_k, gidx_stack):
    def body(i, acc):
        g = jax.ops.segment_sum(g_k + acc * 1e-9, gidx_stack[i],
                                num_segments=U_pad)
        return acc + g.sum()
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("merge_unsorted", p_merge_unsorted, g_k, gidx_stack)


@jax.jit
def p_merge_sorted_hint(g_k, perm_stack, uid_sorted_stack):
    """Permute grads into row-sorted order (one K-gather), then
    segment_sum with nondecreasing ids + sorted hint."""
    def body(i, acc):
        gs = g_k[perm_stack[i]] + acc * 1e-9
        g = jax.ops.segment_sum(gs, uid_sorted_stack[i],
                                num_segments=U_pad,
                                indices_are_sorted=True)
        return acc + g.sum()
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("merge_perm_plus_sorted_hint", p_merge_sorted_hint, g_k,
       perm_stack, uid_sorted_stack)


@jax.jit
def p_merge_sorted_nohint(g_k, perm_stack, uid_sorted_stack):
    def body(i, acc):
        gs = g_k[perm_stack[i]] + acc * 1e-9
        g = jax.ops.segment_sum(gs, uid_sorted_stack[i],
                                num_segments=U_pad)
        return acc + g.sum()
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("merge_perm_plus_sorted_nohint", p_merge_sorted_nohint, g_k,
       perm_stack, uid_sorted_stack)


# isolate: sorted ids WITHOUT the perm gather (upper bound of the win)
@jax.jit
def p_merge_sorted_only(g_k, uid_sorted_stack):
    def body(i, acc):
        g = jax.ops.segment_sum(g_k + acc * 1e-9, uid_sorted_stack[i],
                                num_segments=U_pad,
                                indices_are_sorted=True)
        return acc + g.sum()
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("merge_sorted_ids_only_hint", p_merge_sorted_only, g_k,
       uid_sorted_stack)

# sortedness vs num_segments: random ids into B*S segments
rand_small_np = rng.integers(0, B * S, size=(N_ITER, K_pad)) \
    .astype(np.int32)
rand_small = jnp.asarray(rand_small_np)

@jax.jit
def p_segsum_small_random(g_k, rand_small):
    def body(i, acc):
        g = jax.ops.segment_sum(g_k + acc * 1e-9, rand_small[i],
                                num_segments=B * S + 1)
        return acc + g.sum()
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("segsum_small_random_ids", p_segsum_small_random, g_k, rand_small)


# ---- gather extract forms ----
@jax.jit
def p_gather_take(state, uniq_stack):
    def body(i, acc):
        rows = gather_full_rows(state, uniq_stack[i])
        return acc + rows.sum()
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("gather_take_along_axis", p_gather_take, state, uniq_stack)


@jax.jit
def p_gather_maskex(state, uniq_stack):
    """Line fetch + ONE-HOT mask extract (VPU mask+sum over rpl) instead
    of take_along_axis (a second per-index gather)."""
    rpl, fp, _ = state.geometry
    def body(i, acc):
        rows = jnp.minimum(uniq_stack[i], CAP)
        lines = state.packed[rows // rpl]              # [U, 128]
        sub = (rows % rpl).astype(jnp.int32)
        grouped = lines.reshape(-1, rpl, fp)
        oh = (jnp.arange(rpl, dtype=jnp.int32)[None, :]
              == sub[:, None]).astype(lines.dtype)     # [U, rpl]
        vals = jnp.einsum("urf,ur->uf", grouped, oh)
        return acc + vals.sum()
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("gather_maskextract", p_gather_maskex, state, uniq_stack)


# line fetch only (floor for any extract scheme)
@jax.jit
def p_gather_lines_only(state, uniq_stack):
    rpl, fp, _ = state.geometry
    def body(i, acc):
        rows = jnp.minimum(uniq_stack[i], CAP)
        lines = state.packed[rows // rpl]
        return acc + lines.sum()
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("gather_lines_only", p_gather_lines_only, state, uniq_stack)


# ---- push variants ----
d_lines = jnp.asarray(rng.normal(size=(U_pad, 128)).astype(np.float32))

@jax.jit
def p_scatter_lines(state, uniq_stack, d_lines):
    rpl, fp, _ = state.geometry
    def body(i, packed):
        return packed.at[uniq_stack[i] // rpl].add(d_lines, mode="drop")
    return jax.lax.fori_loop(0, N_ITER, body, state.packed)[0, 0]

timeit("scatter_add_lines_U", p_scatter_lines, state, uniq_stack,
       d_lines)


# line-dedup'd scatter: merge co-resident rows' deltas first (uniq is
# sorted, so line ids are nondecreasing → sorted segment_sum), then
# scatter unique lines. Uses a host-precomputed line-uid (in real step
# it derives from uniq with one cumsum).
line_uid_np = np.empty((N_ITER, U_pad), np.int32)
n_ulines = 0
for i in range(N_ITER):
    lines_i = uniq_pad_np[i] // 8
    uid = np.zeros(U_pad, np.int32)
    uid[1:] = np.cumsum(lines_i[1:] != lines_i[:-1])
    line_uid_np[i] = uid
    n_ulines = max(n_ulines, uid[-1] + 1)
UL_pad = next_bucket_fine(4096, int(n_ulines) + 1)
line_uid_stack = jnp.asarray(line_uid_np)

@jax.jit
def p_scatter_linededup(state, uniq_stack, line_uid_stack, d_lines):
    rpl, fp, _ = state.geometry
    def body(i, packed):
        uid = line_uid_stack[i]
        merged = jax.ops.segment_sum(d_lines, uid, num_segments=UL_pad,
                                     indices_are_sorted=True)
        first_pos = jnp.full(UL_pad, U_pad - 1, jnp.int32).at[uid].min(
            jnp.arange(U_pad, dtype=jnp.int32), mode="drop")
        tgt_lines = (uniq_stack[i] // rpl)[first_pos]
        return packed.at[tgt_lines].add(merged, mode="drop")
    return jax.lax.fori_loop(0, N_ITER, body, state.packed)[0, 0]

timeit("scatter_add_linededup", p_scatter_linededup, state, uniq_stack,
       line_uid_stack, d_lines, UL_pad=UL_pad)

print(json.dumps({"probe": "done"}), flush=True)
