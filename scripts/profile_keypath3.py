#!/usr/bin/env python
"""Round-5 probe set 3: merge form/dtype, packed-line expand, dedup sort
form — the levers left after the decode + gather-extract fixes.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.ps.table import next_bucket_fine

N_ITER = int(os.environ.get("PROF_ITERS", 16))
B, S, AVG, VOCAB = 4096, 26, 5.0, 100_000
CAP = 1 << 23

rng = np.random.default_rng(0)
counts = 1 + rng.poisson(AVG - 1.0, size=(B, S))
K = int(counts.sum())
K_pad = next_bucket_fine(4096, K)
U_pad = 491520
U_real = 481763

gidx_stack = jnp.asarray(
    rng.integers(0, U_real, size=(N_ITER, K_pad)).astype(np.int32))
g_k = jnp.asarray(rng.normal(size=(K_pad, 11)).astype(np.float32))
rows_np = np.empty((N_ITER, K_pad), np.int32)
slot_of_key = np.repeat(np.tile(np.arange(S), B), counts.reshape(-1))
for i in range(N_ITER):
    k_ids = rng.integers(0, VOCAB, size=K)
    rows_np[i, :K] = (slot_of_key * VOCAB + k_ids).astype(np.int32) % CAP
    rows_np[i, K:] = CAP
rows_stack = jnp.asarray(rows_np)

print(json.dumps({"probe": "shape", "K_pad": K_pad, "U_pad": U_pad}),
      flush=True)


def timeit(name, fn, *args, **extra):
    r = fn(*args)
    v = np.asarray(jax.device_get(r)).ravel()
    t0 = time.perf_counter()
    r = fn(*args)
    v = np.asarray(jax.device_get(r)).ravel()
    dt = (time.perf_counter() - t0) / N_ITER * 1000
    print(json.dumps({"probe": name, "ms_per_iter": round(dt, 3),
                      "val": float(v[0]), **extra}), flush=True)
    return dt


@jax.jit
def p_merge_f32(g_k, gidx_stack):
    def body(i, acc):
        g = jax.ops.segment_sum(g_k + acc * 1e-9, gidx_stack[i],
                                num_segments=U_pad)
        return acc + g.sum()
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("merge_f32", p_merge_f32, g_k, gidx_stack)


@jax.jit
def p_merge_bf16(g_k, gidx_stack):
    def body(i, acc):
        g = jax.ops.segment_sum(
            (g_k + acc * 1e-9).astype(jnp.bfloat16), gidx_stack[i],
            num_segments=U_pad)
        return acc + g.astype(jnp.float32).sum()
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("merge_bf16", p_merge_bf16, g_k, gidx_stack)


@jax.jit
def p_merge_at_add(g_k, gidx_stack):
    def body(i, acc):
        g = jnp.zeros((U_pad, 11), jnp.float32).at[gidx_stack[i]].add(
            g_k + acc * 1e-9)
        return acc + g.sum()
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("merge_at_add", p_merge_at_add, g_k, gidx_stack)


# merge with 16-wide (lane-fraction-aligned) data
g_k16 = jnp.asarray(rng.normal(size=(K_pad, 16)).astype(np.float32))

@jax.jit
def p_merge_w16(g_k16, gidx_stack):
    def body(i, acc):
        g = jax.ops.segment_sum(g_k16 + acc * 1e-9, gidx_stack[i],
                                num_segments=U_pad)
        return acc + g.sum()
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("merge_w16", p_merge_w16, g_k16, gidx_stack)


# expand from PACKED 16-lane lines with mask extract (vs [U, 11] rows)
vals_u = jnp.asarray(rng.normal(size=(U_pad, 11)).astype(np.float32))

@jax.jit
def p_expand_plain(vals_u, gidx_stack):
    def body(i, acc):
        v = vals_u[gidx_stack[i]] + acc * 1e-9
        return acc + v.sum()
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("expand_plain", p_expand_plain, vals_u, gidx_stack)

vals_packed = jnp.asarray(
    rng.normal(size=(U_pad // 8, 128)).astype(np.float32))

@jax.jit
def p_expand_packedlines(vals_packed, gidx_stack):
    def body(i, acc):
        g = gidx_stack[i]
        lines = vals_packed[g // 8]                    # [K, 128]
        sub = (g % 8).astype(jnp.int32)
        grouped = lines.reshape(-1, 8, 16)
        oh = (jnp.arange(8, dtype=jnp.int32)[None, :]
              == sub[:, None]).astype(lines.dtype)
        v = jnp.einsum("krf,kr->kf", grouped, oh) + acc * 1e-9
        return acc + v.sum()
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("expand_packedlines_maskex", p_expand_packedlines, vals_packed,
       gidx_stack)


# dedup: current 2-array sort vs packed single-i64 sort
from paddlebox_tpu.ops.device_unique import dedup_rows

@jax.jit
def p_dedup_current(rows_stack):
    def body(i, acc):
        u, g = dedup_rows(rows_stack[i], CAP)
        return acc + (u.sum() + g.sum())
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros((), jnp.int32))

timeit("dedup_current", p_dedup_current, rows_stack)


@jax.jit
def p_dedup_i64pack(rows_stack):
    def body(i, acc):
        rows = rows_stack[i]
        k = rows.shape[0]
        pos = jnp.arange(k, dtype=jnp.int64)
        packed = (rows.astype(jnp.int64) << 20) | pos
        sp = jax.lax.sort(packed)
        sr = (sp >> 20).astype(jnp.int32)
        perm = (sp & ((1 << 20) - 1)).astype(jnp.int32)
        is_first = jnp.concatenate([jnp.ones(1, bool), sr[1:] != sr[:-1]])
        uid_sorted = jnp.cumsum(is_first.astype(jnp.int32)) - 1
        gidx = jnp.zeros(k, jnp.int32).at[perm].set(uid_sorted,
                                                    unique_indices=True)
        oob = CAP + 1 + jnp.arange(k, dtype=jnp.int32)
        uniq = oob.at[uid_sorted].set(sr)
        return acc + (uniq.sum() + gidx.sum())
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros((), jnp.int32))

timeit("dedup_i64pack", p_dedup_i64pack, rows_stack)

print(json.dumps({"probe": "done"}), flush=True)


# line-layout merge: scatter-add one-hot-masked [K, 128] line deltas
# into [U/8, 128] (what autodiff of the packed-line expand produces)
@jax.jit
def p_merge_lines(g_k16, gidx_stack):
    def body(i, acc):
        g = gidx_stack[i]
        sub = (g % 8).astype(jnp.int32)
        oh = (jnp.arange(8, dtype=jnp.int32)[None, :]
              == sub[:, None]).astype(jnp.float32)       # [K, 8]
        d = (oh[:, :, None] * (g_k16 + acc * 1e-9)[:, None, :]
             ).reshape(-1, 128)                          # [K, 128]
        out = jnp.zeros((U_pad // 8, 128), jnp.float32).at[g // 8].add(d)
        return acc + out.sum()
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("merge_lines_f32", p_merge_lines, g_k16, gidx_stack)


@jax.jit
def p_merge_f32_sorted_small(g_k, gidx_stack):
    """Two-level: scatter into [U/64 buckets of 64*11]..."""
    def body(i, acc):
        g = gidx_stack[i]
        col = (g % 64).astype(jnp.int32)
        oh_cols = col[:, None] * 11 + jnp.arange(11, dtype=jnp.int32)[None, :]
        out = jnp.zeros((U_pad // 64, 64 * 11), jnp.float32).at[
            (g // 64)[:, None], oh_cols].add(g_k + acc * 1e-9)
        return acc + out.sum()
    return jax.lax.fori_loop(0, N_ITER, body, jnp.zeros(()))

timeit("merge_bucketed64", p_merge_f32_sorted_small, g_k, gidx_stack)
