#!/usr/bin/env python
"""Seeded end-to-end chaos check (ISSUE 2 acceptance criteria).

Runs a tiny training scenario under a deterministic ``FaultPlan`` that
injects, in ONE run:

1. a transient CommandBackend failure (first remote ``exists`` call),
2. a corrupt record file (every line of one input file is mangled at
   the ``parser.record`` seam), and
3. a mid-save checkpoint crash (the second ``save`` dies just before
   its atomic publish),
4. a transient ``stream.window`` dispatch failure on a WINDOWED
   streaming job (docs/RESILIENCE.md §Streaming),
5. ``ssd.io`` faults on a THREE-TIER (HBM+mem+SSD) table
   (docs/STORAGE.md): a transient segment write during demotion and a
   transient segment read during promotion (both retried on the seeded
   RetryPolicy), a hard CRASH mid-demotion, and a flipped byte in a
   manifested segment file, and
6. ``artifact.publish`` / ``artifact.read`` faults on the versioned
   publishing layer (artifacts.py; docs/RESILIENCE.md §Publishing): a
   transient publish failure retried on the seeded RetryPolicy, and a
   hard-corrupt read of the newest version refused loudly with a
   graceful fallback to its verifiable parent, and
7. a transient ``artifact.read`` failure during the SERVING hot-reload
   poll (serving.ReloadLoop; docs/SERVING.md): the store's seeded
   RetryPolicy retries it INSIDE the poll — the new version still
   adopts on that same poll, no refusal is booked, and the query path
   never sees a gap (the prior snapshot answers throughout),
8. ``elastic.kv`` / ``elastic.rendezvous`` faults on the ELASTIC
   membership plane (distributed/elastic.py; docs/RESILIENCE.md
   §Elastic membership): a transient KV fault during the membership
   list retried on the seeded RetryPolicy, a delayed-but-alive
   heartbeat absorbed by the ``dead_checks`` hysteresis (no membership
   flap, no spurious re-shard), and a rendezvous that times out on a
   genuinely missing host DIAGNOSABLY — the error names the host,

then asserts full recovery:

- the pass completes and the quarantine list names EXACTLY the corrupt
  file,
- ``restore()`` into a fresh trainer returns the last consistent step,
- the windowed stream retries the broken window from its boundary
  checkpoint and still consumes every file,
- the tiered trainer restores THROUGH spill-manifest verification after
  the mid-demotion crash with no lost rows (bit-identical full-model
  digest), while the corrupt segment makes the same restore refuse
  LOUDLY (``CheckpointCorruptError``) — never silent zeros,
- the telemetry JSONL records nonzero ``retry_attempts`` /
  ``files_quarantined`` counters,

and finally runs the WHOLE scenario a second time with the same seed
and asserts the resilience outcome (quarantine list, fault-plan stats,
restored step, counters) is byte-identical — chaos is reproducible.

Usage::

    JAX_PLATFORMS=cpu python scripts/chaos_check.py [--seed 7]

Exit code 0 == recovered + deterministic.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _run_ssd_chaos(workdir: str, seed: int) -> dict:
    """Fault (5): the SSD third tier under chaos (ps/ssd.py). Drives a
    1-mesh tiered trainer whose host stores hold more rows than the
    demote watermark allows, so segments exist and the checkpoint
    records a spill manifest — then injects the ``ssd.io`` seam."""
    import jax
    import numpy as np
    import optax

    from paddlebox_tpu.data import DataFeedDesc
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.ps import SparseSGDConfig
    from paddlebox_tpu.ps.table import FIELDS, TWO_D_FIELDS
    from paddlebox_tpu.ps.tiered import TieredShardedEmbeddingTable
    from paddlebox_tpu.resilience.faults import (FaultPlan, InjectedCrash,
                                                 installed)
    from paddlebox_tpu.train.checkpoint import (CheckpointCorruptError,
                                                CheckpointManager)
    from paddlebox_tpu.train.sharded import ShardedTrainer

    chips = len(jax.devices())
    mesh = make_mesh(chips)
    desc = DataFeedDesc.criteo(batch_size=32)
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    ckpt_root = os.path.join(workdir, "ckpt_ssd")

    def mk(tier: str):
        table = TieredShardedEmbeddingTable(
            chips, mf_dim=4, capacity_per_shard=2048, cfg=cfg,
            host_capacity=512, req_bucket_min=256, serve_bucket_min=256,
            ssd_dir=os.path.join(workdir, tier))
        tr = ShardedTrainer(DeepFM(hidden=(8,)), table, desc, mesh,
                            tx=optax.adam(1e-2))
        return table, tr

    def mk_fields(ks: np.ndarray):
        base = ks.astype(np.float32)
        return {f: (np.tile(base[:, None], (1, 4)) * 0.01
                    if f in TWO_D_FIELDS else base * 0.001)
                for f in FIELDS}

    def digest(table) -> str:
        # the layer's own read-only fingerprint (ps/host_store
        # rows_digest folded per shard) — unlike an export_rows walk
        # it clears no touched flags, so digesting twice is inert
        return table.rows_digest()

    table, tr = mk("tier1")
    keys = np.arange(1, 801, dtype=np.uint64)
    for s, ks in enumerate(table._split_by_owner(keys)):
        # chunked: the store holds 512 rows, the model 800 — inserting
        # past capacity drives the emergency headroom demoter
        for i in range(0, len(ks), 256):
            chunk = ks[i:i + 256]
            table.hosts[s].update(chunk, mk_fields(chunk))

    # (5a) transient segment WRITE during demotion — retried to success
    with installed(FaultPlan.parse("ssd.io:fail:nth=1", seed=seed)) as pw:
        demoted = sum(h.demote_cold(count=200) for h in table.hosts)
    assert demoted > 0, "ssd chaos: nothing demoted"
    assert pw.stats()["ssd.io:fail"]["fired"] == 1, pw.stats()

    # (5b) transient segment READ during promotion (the LoadSSD2Mem
    # path inside fetch) — retried the same way, values intact
    probe = np.sort(table.hosts[0].ssd.keys())[:5]
    with installed(FaultPlan.parse("ssd.io:fail:nth=1", seed=seed)) as pr:
        got = table.hosts[0].fetch(probe)
    assert pr.stats()["ssd.io:fail"]["fired"] == 1, pr.stats()
    assert np.allclose(got["embed_w"], probe.astype(np.float32) * 0.001)

    digest0 = digest(table)
    total0 = sum(h.total_rows() for h in table.hosts)
    cm = CheckpointManager(ckpt_root)
    cm.save(tr)
    step = int(tr.global_step)
    mpath = os.path.join(ckpt_root, f"ckpt-{step:012d}",
                         "spill_manifest.json")
    assert os.path.isfile(mpath), \
        "tiered checkpoint recorded no spill manifest"

    # (5c) hard crash MID-DEMOTION (process dies inside the segment
    # append) — the checkpoint published above must stay restorable
    crashed = False
    with installed(FaultPlan.parse("ssd.io:fail:nth=1,exc=crash",
                                   seed=seed)):
        try:
            for h in table.hosts:
                h.demote_cold(count=100)
        except InjectedCrash:
            crashed = True
    assert crashed, "mid-demotion crash fault never fired"

    # (5d) corrupt ONE manifested segment: the restart must refuse
    # LOUDLY before any promote could read garbage
    with open(mpath) as fh:
        seg0 = json.load(fh)["shards"]["0"]["segments"][0]["path"]
    with open(seg0, "rb") as fh:
        blob = fh.read()
    with open(seg0, "wb") as fh:
        fh.write(blob[:8] + bytes([blob[8] ^ 0xFF]) + blob[9:])
    _, tr_c = mk("tier_corrupt")
    loud = False
    try:
        CheckpointManager(ckpt_root).restore(tr_c)
    except CheckpointCorruptError:
        loud = True
    assert loud, "corrupt segment restored silently"

    # repair the segment: the SAME restart now recovers every row
    # through manifest verification — nothing lost to the crash
    with open(seg0, "wb") as fh:
        fh.write(blob)
    table_r, tr_r = mk("tier_restore")
    restored = CheckpointManager(ckpt_root).restore(tr_r)
    assert restored == step, (restored, step)
    assert sum(h.total_rows() for h in table_r.hosts) == total0
    assert digest(table_r) == digest0, (
        "restore after mid-demotion crash lost or mutated rows")
    return {
        "ssd_demoted": int(demoted),
        "ssd_write_fault_fired": pw.stats()["ssd.io:fail"]["fired"],
        "ssd_read_fault_fired": pr.stats()["ssd.io:fail"]["fired"],
        "ssd_crash_mid_demotion": crashed,
        "ssd_corrupt_segment_loud": loud,
        "ssd_restored_step": int(restored),
        "ssd_rows": int(total0),
        "ssd_digest": digest0,
    }


def _run_artifact_chaos(workdir: str, seed: int) -> dict:
    """Fault (6): the versioned artifact/publishing layer under chaos
    (artifacts.py). A writer publishes a base+delta chain through
    ``BoxPSHelper``; the transient ``artifact.publish`` failure must be
    retried to success on the seeded RetryPolicy, and a hard-corrupt
    ``artifact.read`` of the tip must refuse LOUDLY while unpinned
    adoption gracefully falls back to the verifiable parent."""
    import jax
    import numpy as np

    from paddlebox_tpu.artifacts import (ArtifactCorruptError,
                                         ArtifactStore)
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.ps.box_helper import BoxPSHelper
    from paddlebox_tpu.ps.table import FIELD_COL, TableState
    from paddlebox_tpu.resilience.faults import FaultPlan, installed

    cfg = SparseSGDConfig(mf_create_thresholds=1e9)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 10, cfg=cfg)
    helper = BoxPSHelper(table)
    store = ArtifactStore(os.path.join(workdir, "artifacts"))

    def write(lo: int, hi: int, scale: float) -> None:
        keys = np.arange(lo, hi, dtype=np.uint64)
        rows = table.index.assign(keys)
        data = np.asarray(jax.device_get(table.state.data)).copy()
        data[rows, FIELD_COL["embed_w"]] = keys.astype(np.float32) * scale
        table.state = TableState.from_logical(data, table.capacity)
        table._touched[rows] = True

    # (6a) transient publish failure — retried to a successful commit
    write(1, 101, 2.0)
    with installed(FaultPlan.parse("artifact.publish:fail:nth=1",
                                   seed=seed)) as pp:
        base_aid = helper.publish_base(store)
    assert pp.stats()["artifact.publish:fail"]["fired"] == 1, pp.stats()
    write(80, 151, 3.0)
    delta_aid = helper.publish_delta(store)

    # (6b) hard-corrupt read of the tip: every registry read of the
    # delta version mangles — explicit adoption refuses LOUDLY, and
    # unpinned adoption degrades to the verifiable base
    loud = False
    with installed(FaultPlan.parse(
            f"artifact.read:corrupt:times=0,match=*{delta_aid}*",
            seed=seed)) as pr:
        try:
            store.open(delta_aid)
        except ArtifactCorruptError:
            loud = True
        with store.open() as h:
            fallback_aid = h.aid
            reader = EmbeddingTable(mf_dim=4, capacity=1 << 10, cfg=cfg)
            reader.load(h.path("sparse.npz"), merge=False)
    assert loud, "corrupt artifact read adopted silently"
    assert fallback_aid == base_aid, (fallback_aid, base_aid)
    probe = reader.host_pull(np.array([1], np.uint64))
    assert np.allclose(probe[0, 2], 2.0), "fallback lost base rows"
    # the repaired (fault-free) tip adopts normally again
    with store.open() as h:
        healthy_aid = h.aid
    assert healthy_aid == delta_aid
    return {
        "artifact_base": base_aid,
        "artifact_delta": delta_aid,
        "artifact_publish_fault_fired":
            pp.stats()["artifact.publish:fail"]["fired"],
        "artifact_read_fault_stats": pr.stats(),
        "artifact_corrupt_loud": loud,
        "artifact_fallback": fallback_aid,
        "artifact_healthy_tip": healthy_aid,
    }


def _run_serving_chaos(workdir: str, seed: int) -> dict:
    """Fault (7): transient ``artifact.read`` during the background
    hot-reload poll (serving.ReloadLoop.poll_once). The read retries on
    the seeded RetryPolicy inside ``store.open`` — the poll itself
    succeeds (no refusal, no degrade) and serving never gaps: queries
    issued before, during and after the faulted poll all answer a
    published version bit-exactly."""
    import jax
    import numpy as np

    from paddlebox_tpu.artifacts import ArtifactStore
    from paddlebox_tpu.data.schema import DataFeedDesc
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.ps.box_helper import BoxPSHelper
    from paddlebox_tpu.ps.table import FIELD_COL, TableState
    from paddlebox_tpu.resilience.faults import FaultPlan, installed
    from paddlebox_tpu.serving import ReloadLoop, ServingModel

    cfg = SparseSGDConfig(mf_create_thresholds=1e9)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 10, cfg=cfg)
    helper = BoxPSHelper(table)
    store = ArtifactStore(os.path.join(workdir, "artifacts_serving"))

    def write(lo: int, hi: int, scale: float) -> None:
        keys = np.arange(lo, hi, dtype=np.uint64)
        rows = table.index.assign(keys)
        data = np.asarray(jax.device_get(table.state.data)).copy()
        data[rows, FIELD_COL["embed_w"]] = keys.astype(np.float32) * scale
        table.state = TableState.from_logical(data, table.capacity)
        table._touched[rows] = True

    write(1, 101, 2.0)
    base_aid = helper.publish_base(store)
    desc = DataFeedDesc.criteo(batch_size=16)
    srv = ServingModel(CtrDnn(hidden=(4,)), desc, mf_dim=4,
                       capacity=1 << 10)
    assert srv.adopt(store) == base_aid
    loop = ReloadLoop(srv, store, poll_sec=0.01)
    probe = np.arange(1, 121, dtype=np.uint64)

    def lookup_scale() -> np.ndarray:
        return srv.embed_lookup(probe)[:, 2]

    before = lookup_scale()
    assert np.allclose(before[:100], probe[:100].astype(np.float32) * 2)
    write(80, 121, 3.0)
    delta_aid = helper.publish_delta(store)
    refused0 = loop.refused
    with installed(FaultPlan.parse("artifact.read:fail:nth=1",
                                   seed=seed)) as plan:
        during = lookup_scale()     # query while the poll will retry
        adopted = loop.poll_once()
    assert plan.stats()["artifact.read:fail"]["fired"] == 1, plan.stats()
    assert adopted == delta_aid, (
        "transient read during the reload poll was not retried to a "
        f"successful adoption (got {adopted})")
    assert loop.refused == refused0, (
        "a retried transient read must not book a reload refusal")
    assert np.array_equal(during, before), (
        "a query during the faulted poll saw a torn state")
    after = lookup_scale()
    assert np.allclose(after[79:120],
                       probe[79:120].astype(np.float32) * 3), (
        "adopted delta rows not served after the retried poll")
    srv.release()
    return {
        "serving_base": base_aid,
        "serving_delta": delta_aid,
        "serving_reload_fault_fired":
            plan.stats()["artifact.read:fail"]["fired"],
        "serving_reload_adopted": adopted,
        "serving_reload_refusals": loop.refused - refused0,
        "serving_no_gap": True,
    }


def _run_elastic_chaos(workdir: str, seed: int) -> dict:
    """Fault (8): the elastic membership plane under chaos. A transient
    ``elastic.kv`` fault during the membership list is retried on the
    seeded RetryPolicy; a delayed-but-alive heartbeat (aged lease that
    recovers) is absorbed by the ``dead_checks`` hysteresis with ZERO
    membership flaps — the false-dead host never leaves, so no spurious
    re-shard can fire; and a rendezvous on a genuinely missing host
    times out naming the host (the on-call diagnosis, not a bare
    timeout)."""
    import time

    from paddlebox_tpu.distributed.elastic import (ElasticManager,
                                                   FileKVStore)
    from paddlebox_tpu.resilience.faults import FaultPlan, installed

    store = FileKVStore(os.path.join(workdir, "elastic_chaos"))
    for h in ("e0", "e1"):
        store.put(f"paddlebox/chaos/nodes/{h}",
                  json.dumps({"host": h}).encode())
    # huge TTL: "death" below is an explicit mtime age-out, never a race
    mgr = ElasticManager(store, "chaos", "e0", 2, ttl=3600.0,
                         heartbeat_period=0.05, dead_checks=2)

    # (8a) transient KV fault while listing members: retried to success
    with installed(FaultPlan.parse("elastic.kv:fail:nth=1",
                                   seed=seed)) as plan:
        alive = mgr.alive_hosts()
    assert plan.stats()["elastic.kv:fail"]["fired"] == 1, plan.stats()
    assert alive == ["e0", "e1"], (
        f"retried membership list lost hosts: {alive}")

    # (8b) delayed-but-alive heartbeat: one aged poll then a recovery —
    # hysteresis must absorb it with no scale event in between
    assert mgr.scale_event() is None            # baseline {e0, e1}
    key1 = "paddlebox/chaos/nodes/e1"
    old = time.time() - 7200.0
    os.utime(store._path(key1), (old, old))
    flap1 = mgr.scale_event()                   # miss 1: inside grace
    store.touch(key1)                           # heartbeat catches up
    flap2 = mgr.scale_event()                   # recovered: count reset
    assert flap1 is None and flap2 is None, (
        f"false-dead heartbeat flapped membership: {flap1} / {flap2}")

    # (8c) e1 really gone: the rendezvous barrier times out NAMING it
    store.delete(key1)
    try:
        mgr.wait_for_np(timeout=0.3)
        raise AssertionError("wait_for_np must time out with e1 gone")
    except TimeoutError as exc:
        assert "e1" in str(exc), (
            f"rendezvous timeout does not name the missing host: {exc}")
    return {
        "elastic_kv_fault_fired": plan.stats()["elastic.kv:fail"]["fired"],
        "elastic_alive_after_fault": alive,
        "elastic_false_dead_flapped": False,
        "elastic_timeout_named": ["e1"],
    }


def run_scenario(workdir: str, seed: int) -> dict:
    """One full chaos run; returns the resilience outcome summary."""
    import optax

    from paddlebox_tpu.config import FLAGS, flags_scope
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.obs.hub import reset_hub
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.resilience.faults import (FaultPlan, InjectedCrash,
                                                 installed)
    from paddlebox_tpu.train import Trainer
    from paddlebox_tpu.train.checkpoint import CheckpointManager
    from paddlebox_tpu.utils.file_mgr import FileMgr

    reset_hub()
    jsonl = os.path.join(workdir, "telemetry.jsonl")
    files = generate_criteo_files(os.path.join(workdir, "data"),
                                  num_files=3, rows_per_file=120,
                                  vocab_per_slot=40, seed=seed)
    corrupt_file = files[1]
    plan = FaultPlan.parse(
        "file_mgr.command:fail:nth=1; "
        f"parser.record:corrupt:match=*{os.path.basename(corrupt_file)}*,"
        "times=0; "
        "checkpoint.save_commit:fail:nth=2,exc=crash; "
        "stream.window:fail:nth=2", seed=seed)
    outcome: dict = {}
    with flags_scope(seed=seed, native_parse=False,
                     poison_budget_files=1, poison_budget_records=0,
                     retry_base_delay_sec=0.01, retry_max_delay_sec=0.05,
                     telemetry_jsonl=jsonl, read_thread_num=4), \
            installed(plan):
        desc = DataFeedDesc.criteo(batch_size=32)
        desc.key_bucket_min = 2048
        cfg = SparseSGDConfig(mf_create_thresholds=0.0,
                              mf_initial_range=0.0)

        def mk() -> Trainer:
            table = EmbeddingTable(mf_dim=4, capacity=1 << 12, cfg=cfg,
                                   unique_bucket_min=2048)
            return Trainer(CtrDnn(hidden=(8,)), table, desc,
                           tx=optax.adam(1e-2), seed=seed)

        trainer = mk()  # attaches the JSONL sink via FLAGS

        # (1) transient CommandBackend failure, retried to success
        mgr = FileMgr()
        mgr.init(scheme="chaos", command=["true"])
        assert mgr.exists("chaos://cluster/health"), \
            "retried exists must succeed"

        # (2) corrupt record file → quarantined, survivors drain
        ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
        ds.set_filelist(files)
        ds.load_into_memory()
        quarantined = [p for p, _ in ds.quarantined_files]
        assert quarantined == [corrupt_file], (
            f"quarantine list {quarantined} != [{corrupt_file}]")
        assert len(ds) == 240, f"expected 240 surviving records, {len(ds)}"

        # (3) checkpointed training with a mid-save crash
        ckpt_root = os.path.join(workdir, "ckpt")
        cm = CheckpointManager(ckpt_root)
        trainer.run_pass(ds, checkpoint=cm)
        cm.save(trainer)                       # save #1 commits
        consistent_step = trainer.global_step
        trainer.run_pass(ds, checkpoint=cm)
        crashed = False
        try:
            cm.save(trainer)                   # save #2 dies pre-publish
        except InjectedCrash:
            crashed = True
        assert crashed, "mid-save crash fault never fired"

        # restarted process: fresh manager + trainer restore cleanly
        fresh = mk()
        restored = CheckpointManager(ckpt_root).restore(fresh)
        assert restored == consistent_step, (
            f"restore() returned {restored}, want {consistent_step}")

        # (4) stream.window seam: window 2's dispatch dies once; the
        # stream retries it from the window-1 boundary checkpoint and
        # still drains every (healthy) file
        healthy = [files[0], files[2]]
        with flags_scope(stream_window_files=1, read_thread_num=1,
                         stream_ckpt_every_windows=1,
                         pass_retry_limit=1):
            sds = DatasetFactory().create_dataset("QueueDataset", desc)
            sds.set_filelist(healthy)
            streamer = mk()
            sout = streamer.train_stream(
                sds, CheckpointManager(os.path.join(workdir,
                                                    "ckpt_stream")))
        assert sout["windows"] == 2, sout
        assert sds.files_completed == healthy
        assert plan.stats()["stream.window:fail"]["fired"] == 1

        # (5) ssd.io seam on a three-tier table (sub-plans installed
        # around each injection so the op counting stays trivial)
        ssd_outcome = _run_ssd_chaos(workdir, seed)

        # (6) artifact.publish / artifact.read seams on the versioned
        # publishing layer (same sub-plan discipline)
        artifact_outcome = _run_artifact_chaos(workdir, seed)

        # (7) transient artifact.read during the serving hot-reload
        # poll: retried inside the poll, no serving gap
        serving_outcome = _run_serving_chaos(workdir, seed)

        # (8) elastic.kv / elastic.rendezvous seams on the membership
        # plane: transient list retried, false-dead heartbeat absorbed
        # by hysteresis, missing-host rendezvous diagnosed by name
        elastic_outcome = _run_elastic_chaos(workdir, seed)

    # telemetry JSONL: final pass event carries nonzero counters
    with open(jsonl) as fh:
        events = [json.loads(line) for line in fh]
    passes = [e for e in events if e["event"] == "pass"]
    assert passes, "no pass events in telemetry JSONL"
    res = passes[-1]["resilience"]
    assert res["retry_attempts"] > 0, f"retry_attempts == 0: {res}"
    assert res["files_quarantined"] > 0, f"files_quarantined == 0: {res}"
    assert any(e["event"] == "file_quarantined" for e in events)
    assert any(e["event"] == "fault_injected" for e in events)

    outcome.update(
        quarantined=[os.path.basename(p) for p in quarantined],
        restored_step=restored,
        fault_stats=plan.stats(),
        resilience={k: res[k] for k in ("retry_attempts",
                                        "files_quarantined",
                                        "records_poisoned",
                                        "faults_injected")},
        surviving_records=len(ds),
        stream_windows=int(sout["windows"]),
        **ssd_outcome,
        **artifact_outcome,
        **serving_outcome,
        **elastic_outcome,
    )
    return outcome


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh temp dir)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir for inspection")
    args = ap.parse_args()

    base = args.workdir or tempfile.mkdtemp(prefix="pbox_chaos_")
    outcomes = []
    try:
        for run in (1, 2):  # same seed twice: outcome must be identical
            wd = os.path.join(base, f"run{run}")
            os.makedirs(wd, exist_ok=True)
            print(f"--- chaos run {run} (seed={args.seed}) ---")
            outcomes.append(run_scenario(wd, args.seed))
            print(json.dumps(outcomes[-1], indent=2, sort_keys=True))
        if outcomes[0] != outcomes[1]:
            print("FAIL: chaos outcome differs across identically-seeded "
                  "runs:")
            print(json.dumps(outcomes[0], sort_keys=True))
            print(json.dumps(outcomes[1], sort_keys=True))
            return 1
        print(f"PASS: recovered from all injected faults; outcome "
              f"deterministic across 2 runs (seed={args.seed})")
        return 0
    finally:
        if not args.keep and args.workdir is None:
            shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
