#!/usr/bin/env python
"""Seeded end-to-end chaos check (ISSUE 2 acceptance criteria).

Runs a tiny training scenario under a deterministic ``FaultPlan`` that
injects, in ONE run:

1. a transient CommandBackend failure (first remote ``exists`` call),
2. a corrupt record file (every line of one input file is mangled at
   the ``parser.record`` seam), and
3. a mid-save checkpoint crash (the second ``save`` dies just before
   its atomic publish), and
4. a transient ``stream.window`` dispatch failure on a WINDOWED
   streaming job (docs/RESILIENCE.md §Streaming),

then asserts full recovery:

- the pass completes and the quarantine list names EXACTLY the corrupt
  file,
- ``restore()`` into a fresh trainer returns the last consistent step,
- the windowed stream retries the broken window from its boundary
  checkpoint and still consumes every file,
- the telemetry JSONL records nonzero ``retry_attempts`` /
  ``files_quarantined`` counters,

and finally runs the WHOLE scenario a second time with the same seed
and asserts the resilience outcome (quarantine list, fault-plan stats,
restored step, counters) is byte-identical — chaos is reproducible.

Usage::

    JAX_PLATFORMS=cpu python scripts/chaos_check.py [--seed 7]

Exit code 0 == recovered + deterministic.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_scenario(workdir: str, seed: int) -> dict:
    """One full chaos run; returns the resilience outcome summary."""
    import optax

    from paddlebox_tpu.config import FLAGS, flags_scope
    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.obs.hub import reset_hub
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.resilience.faults import (FaultPlan, InjectedCrash,
                                                 installed)
    from paddlebox_tpu.train import Trainer
    from paddlebox_tpu.train.checkpoint import CheckpointManager
    from paddlebox_tpu.utils.file_mgr import FileMgr

    reset_hub()
    jsonl = os.path.join(workdir, "telemetry.jsonl")
    files = generate_criteo_files(os.path.join(workdir, "data"),
                                  num_files=3, rows_per_file=120,
                                  vocab_per_slot=40, seed=seed)
    corrupt_file = files[1]
    plan = FaultPlan.parse(
        "file_mgr.command:fail:nth=1; "
        f"parser.record:corrupt:match=*{os.path.basename(corrupt_file)}*,"
        "times=0; "
        "checkpoint.save_commit:fail:nth=2,exc=crash; "
        "stream.window:fail:nth=2", seed=seed)
    outcome: dict = {}
    with flags_scope(seed=seed, native_parse=False,
                     poison_budget_files=1, poison_budget_records=0,
                     retry_base_delay_sec=0.01, retry_max_delay_sec=0.05,
                     telemetry_jsonl=jsonl, read_thread_num=4), \
            installed(plan):
        desc = DataFeedDesc.criteo(batch_size=32)
        desc.key_bucket_min = 2048
        cfg = SparseSGDConfig(mf_create_thresholds=0.0,
                              mf_initial_range=0.0)

        def mk() -> Trainer:
            table = EmbeddingTable(mf_dim=4, capacity=1 << 12, cfg=cfg,
                                   unique_bucket_min=2048)
            return Trainer(CtrDnn(hidden=(8,)), table, desc,
                           tx=optax.adam(1e-2), seed=seed)

        trainer = mk()  # attaches the JSONL sink via FLAGS

        # (1) transient CommandBackend failure, retried to success
        mgr = FileMgr()
        mgr.init(scheme="chaos", command=["true"])
        assert mgr.exists("chaos://cluster/health"), \
            "retried exists must succeed"

        # (2) corrupt record file → quarantined, survivors drain
        ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
        ds.set_filelist(files)
        ds.load_into_memory()
        quarantined = [p for p, _ in ds.quarantined_files]
        assert quarantined == [corrupt_file], (
            f"quarantine list {quarantined} != [{corrupt_file}]")
        assert len(ds) == 240, f"expected 240 surviving records, {len(ds)}"

        # (3) checkpointed training with a mid-save crash
        ckpt_root = os.path.join(workdir, "ckpt")
        cm = CheckpointManager(ckpt_root)
        trainer.run_pass(ds, checkpoint=cm)
        cm.save(trainer)                       # save #1 commits
        consistent_step = trainer.global_step
        trainer.run_pass(ds, checkpoint=cm)
        crashed = False
        try:
            cm.save(trainer)                   # save #2 dies pre-publish
        except InjectedCrash:
            crashed = True
        assert crashed, "mid-save crash fault never fired"

        # restarted process: fresh manager + trainer restore cleanly
        fresh = mk()
        restored = CheckpointManager(ckpt_root).restore(fresh)
        assert restored == consistent_step, (
            f"restore() returned {restored}, want {consistent_step}")

        # (4) stream.window seam: window 2's dispatch dies once; the
        # stream retries it from the window-1 boundary checkpoint and
        # still drains every (healthy) file
        healthy = [files[0], files[2]]
        with flags_scope(stream_window_files=1, read_thread_num=1,
                         stream_ckpt_every_windows=1,
                         pass_retry_limit=1):
            sds = DatasetFactory().create_dataset("QueueDataset", desc)
            sds.set_filelist(healthy)
            streamer = mk()
            sout = streamer.train_stream(
                sds, CheckpointManager(os.path.join(workdir,
                                                    "ckpt_stream")))
        assert sout["windows"] == 2, sout
        assert sds.files_completed == healthy
        assert plan.stats()["stream.window:fail"]["fired"] == 1

    # telemetry JSONL: final pass event carries nonzero counters
    with open(jsonl) as fh:
        events = [json.loads(line) for line in fh]
    passes = [e for e in events if e["event"] == "pass"]
    assert passes, "no pass events in telemetry JSONL"
    res = passes[-1]["resilience"]
    assert res["retry_attempts"] > 0, f"retry_attempts == 0: {res}"
    assert res["files_quarantined"] > 0, f"files_quarantined == 0: {res}"
    assert any(e["event"] == "file_quarantined" for e in events)
    assert any(e["event"] == "fault_injected" for e in events)

    outcome.update(
        quarantined=[os.path.basename(p) for p in quarantined],
        restored_step=restored,
        fault_stats=plan.stats(),
        resilience={k: res[k] for k in ("retry_attempts",
                                        "files_quarantined",
                                        "records_poisoned",
                                        "faults_injected")},
        surviving_records=len(ds),
        stream_windows=int(sout["windows"]),
    )
    return outcome


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh temp dir)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir for inspection")
    args = ap.parse_args()

    base = args.workdir or tempfile.mkdtemp(prefix="pbox_chaos_")
    outcomes = []
    try:
        for run in (1, 2):  # same seed twice: outcome must be identical
            wd = os.path.join(base, f"run{run}")
            os.makedirs(wd, exist_ok=True)
            print(f"--- chaos run {run} (seed={args.seed}) ---")
            outcomes.append(run_scenario(wd, args.seed))
            print(json.dumps(outcomes[-1], indent=2, sort_keys=True))
        if outcomes[0] != outcomes[1]:
            print("FAIL: chaos outcome differs across identically-seeded "
                  "runs:")
            print(json.dumps(outcomes[0], sort_keys=True))
            print(json.dumps(outcomes[1], sort_keys=True))
            return 1
        print(f"PASS: recovered from all injected faults; outcome "
              f"deterministic across 2 runs (seed={args.seed})")
        return 0
    finally:
        if not args.keep and args.workdir is None:
            shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
