#!/usr/bin/env python
"""Black-box observability gate (ISSUE 16): every anomaly trigger must
yield exactly ONE well-formed postmortem bundle, every default alert
rule must fire AND clear deterministically, and the flags-off overhead
of the whole black-box layer must stay bounded.

One seeded scenario (``run_obs_check``), eight legs against ONE hub +
ONE flight recorder so bundle sequence numbers are provable:

1. **quality + NaN rollback** — a real ``Trainer.run_pass`` loop over
   seeded criteo files with ``quality_window_passes`` on emits
   ``quality_window`` events + ``pbox_quality_*`` instruments; then a
   poisoned pass (``NanInfError`` with a boundary checkpoint) rolls
   back, books ``pbox_nan_rollbacks_total`` and dumps exactly one
   ``nan_rollback`` bundle.
2. **corrupt reload tip** — ``BoxPSHelper`` publishes base+delta, the
   delta gets a flipped byte, three ``ReloadLoop.poll_once`` refusals
   fire the ``reload_degrade`` trigger thrice — debounce collapses
   them into ONE bundle; serving stays on the prior version.
3. **pipeline hang** — a ``PassEpilogue`` job sleeps past
   ``pipeline_wait_timeout_sec``; the fence raises
   ``PipelineHangError`` and ``note_hang`` dumps one
   ``pipeline_hang`` bundle with live thread stacks.
4. **alerts fire/clear** — every default rule is driven over its
   threshold and back via ``evaluate_once``; each transition books
   ``pbox_alerts_active``/``pbox_alerts_fired_total`` + events, and
   the first fire dumps ONE ``slo_breach`` bundle (debounce eats the
   storm); the two membership rules route to ONE separate
   ``membership_change`` bundle.
5. **manual dump** — ``hub.dump_blackbox(reason)`` → one ``manual``
   bundle.
6. **rotation + torn tail** — a size-capped ``JsonlSink`` rotates into
   a keep-K set; ``telemetry_report.load_events`` reads the rotated
   set oldest-first and skips a torn final line with a warning.
7. **/alertz + /healthz** — the debug routes serve the alert status
   and the healthz alerts block.
8. **flags-off overhead** — with defaults off the hub is inert and
   100k emit + 100k trigger no-ops stay under a generous wall bound.

Every bundle is schema-checked (``BUNDLE_SCHEMA`` keys). ``main()``
runs the scenario twice with the same seed and asserts a
byte-identical outcome — the black box is provable, not hoped-for.

Usage::

    JAX_PLATFORMS=cpu python scripts/obs_check.py [--seed 7]

Exit code 0 == every trigger/rule behaved + deterministic.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# the 12 keys every postmortem bundle must carry (flightrec.BUNDLE_SCHEMA)
BUNDLE_KEYS = frozenset((
    "schema", "trigger", "reason", "ctx", "ts", "run", "health", "ring",
    "instruments", "critical_path", "flags", "threads"))

# generous CI bound for 200k flags-off no-ops (the real number is ~ns/op;
# the bound only guards against an accidental O(sinks) or lock on the
# inert path)
OVERHEAD_WALL_SEC = 5.0


def _bundle_names(rec) -> list:
    return [os.path.basename(p) for p in rec.bundles()]


def _check_bundle(path: str) -> dict:
    with open(path) as fh:
        b = json.load(fh)
    missing = BUNDLE_KEYS - set(b)
    assert not missing, f"bundle {path} missing keys: {sorted(missing)}"
    assert b["schema"] == 1
    assert isinstance(b["ring"], list)
    assert isinstance(b["threads"], dict) and b["threads"], \
        "bundle carries no thread stacks"
    assert isinstance(b["instruments"], dict)
    assert isinstance(b["flags"], dict) and "flightrec_dir" in b["flags"]
    return b


# ---- leg 1: quality window + NaN rollback ------------------------------
def _run_quality_nan_leg(workdir: str, seed: int, out: dict) -> None:
    import numpy as np
    import optax

    from paddlebox_tpu.data import DataFeedDesc, DatasetFactory
    from paddlebox_tpu.data.criteo import generate_criteo_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.obs.hub import get_hub
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.train import Trainer
    from paddlebox_tpu.train.checkpoint import CheckpointManager
    from paddlebox_tpu.train.trainer import NanInfError

    hub = get_hub()
    files = generate_criteo_files(os.path.join(workdir, "data"),
                                  num_files=2, rows_per_file=160,
                                  vocab_per_slot=30, seed=seed)
    desc = DataFeedDesc.criteo(batch_size=32)
    desc.key_bucket_min = 2048
    cfg = SparseSGDConfig(mf_create_thresholds=0.0, mf_initial_range=0.0)
    table = EmbeddingTable(mf_dim=4, capacity=1 << 12, cfg=cfg,
                           unique_bucket_min=2048)
    tr = Trainer(CtrDnn(hidden=(8,)), table, desc, tx=optax.adam(1e-2),
                 seed=0)
    ds = DatasetFactory().create_dataset("InMemoryDataset", desc)
    ds.set_filelist(files)
    ds.load_into_memory()

    from paddlebox_tpu.obs.sinks import MemorySink
    mem = MemorySink()
    hub.add_sink(mem)
    cm = CheckpointManager(os.path.join(workdir, "ckpt"))
    for _ in range(3):          # fill the quality window
        tr.run_pass(ds, checkpoint=cm)
        cm.save(tr)

    qevs = [e for e in mem.events if e["event"] == "quality_window"]
    out["quality_windows"] = len(qevs)
    out["quality_degraded_flag_seen"] = all(
        "degraded" in e for e in qevs)
    snap = hub.snapshot()
    out["quality_instruments"] = sorted(
        n for n in snap if n.startswith("pbox_quality_"))

    # poison ONE pass: NanInfError with a boundary target rolls back,
    # books the counter and dumps exactly one nan_rollback bundle
    real = tr.train_pass
    calls = []

    def poisoned_once(*a, **kw):
        calls.append(1)
        if len(calls) == 1:
            raise NanInfError("nan/inf loss at step 3 (injected)")
        return real(*a, **kw)

    tr.train_pass = poisoned_once
    res = tr.run_pass(ds, checkpoint=cm, max_retries=1)
    out["nan_retried_and_recovered"] = (
        len(calls) == 2 and bool(np.isfinite(res["last_loss"])))
    out["nan_rollbacks_total"] = hub.counter(
        "pbox_nan_rollbacks_total", "").value()
    hub.remove_sink(mem)


# ---- leg 2: corrupt reload tip -----------------------------------------
def _run_corrupt_tip_leg(workdir: str, seed: int, out: dict) -> None:
    import jax
    import numpy as np

    from paddlebox_tpu.artifacts import ArtifactStore
    from paddlebox_tpu.data.schema import DataFeedDesc
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.ps import EmbeddingTable, SparseSGDConfig
    from paddlebox_tpu.ps.box_helper import BoxPSHelper
    from paddlebox_tpu.ps.table import FIELD_COL, TableState
    from paddlebox_tpu.serving import ReloadLoop, ServingModel

    desc = DataFeedDesc.criteo(batch_size=16)
    cfg = SparseSGDConfig(mf_create_thresholds=1e9)
    t = EmbeddingTable(mf_dim=4, capacity=1 << 10, cfg=cfg)
    helper = BoxPSHelper(t)
    store = ArtifactStore(os.path.join(workdir, "registry_chaos"))

    def write(lo, hi, scale):
        keys = np.arange(lo, hi, dtype=np.uint64)
        rows = t.index.assign(keys)
        data = np.asarray(jax.device_get(t.state.data)).copy()
        data[rows, FIELD_COL["embed_w"]] = keys.astype(np.float32) * scale
        t.state = TableState.from_logical(data, t.capacity)
        t._touched[rows] = True

    write(1, 101, 2.0)
    v1 = helper.publish_base(store)
    srv = ServingModel(CtrDnn(hidden=(8,)), desc, mf_dim=4,
                       capacity=1 << 10)
    assert srv.adopt(store) == v1
    loop = ReloadLoop(srv, store, poll_sec=0.02)

    write(50, 151, 5.0)
    v2 = helper.publish_delta(store)
    p = os.path.join(store.version_dir(v2), "sparse_delta.npz")
    with open(p, "rb") as fh:
        blob = fh.read()
    flip = 13 % len(blob)
    with open(p, "wb") as fh:
        fh.write(blob[:flip] + bytes([blob[flip] ^ 0xFF])
                 + blob[flip + 1:])
    for _ in range(3):       # corrupt tip: no poll may swap it in
        assert loop.poll_once() is None
    out["corrupt_tip_not_adopted"] = (srv.adopted_aid == v1)
    # the store refuses the corrupt tip before hot_reload ever sees it;
    # the degrade path (serving BEHIND the tip) is what fires the
    # reload_degrade trigger — three polls, debounced into one bundle
    out["corrupt_refused_loud"] = (loop.degraded >= 3)


# ---- leg 3: pipeline hang ----------------------------------------------
def _run_hang_leg(out: dict) -> None:
    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.ps.epilogue import PassEpilogue, PipelineHangError

    ep = PassEpilogue("obs_check")
    ep.submit(lambda: time.sleep(0.6), label="wedge")
    hung = False
    with flags_scope(pipeline_wait_timeout_sec=0.15):
        try:
            ep.fence()
        except PipelineHangError:
            hung = True
    out["hang_raised"] = hung
    ep.fence()               # job finishes; drain cleanly


# ---- leg 4: alerts fire/clear ------------------------------------------
def _run_alerts_leg(out: dict) -> None:
    from paddlebox_tpu.obs.alerts import AlertEngine, default_rules
    from paddlebox_tpu.obs.hub import get_hub
    from paddlebox_tpu.obs.instruments import SERVING_LATENCY_BUCKETS

    hub = get_hub()
    engine = AlertEngine(hub, rules=default_rules())
    hub.set_alerts_probe(engine.status)
    out["alert_rules"] = sorted(r.name for r in engine.rules)

    # pin every watched metric to a quiet baseline so the first eval is
    # transition-free, then drive each rule over its threshold and back
    hub.gauge("pbox_serving_staleness_sec", "").set(0.0)
    hub.gauge("pbox_stream_lag_files", "").set(0.0)
    hub.gauge("pbox_quality_degraded", "").set(0.0)
    hub.gauge("pbox_online_windows_since_shrink", "").set(0.0)
    hub.gauge("pbox_membership_degraded", "").set(0.0)
    hub.counter("pbox_membership_scale_events_total", "").inc(
        n=0, direction="lost")
    hist = hub.histogram("pbox_serving_latency_seconds", "",
                         buckets=SERVING_LATENCY_BUCKETS)
    for _ in range(50):
        hist.observe(0.0002, op="predict")
    # trend baselines: the hang + NaN legs already booked these counters
    hub.counter("pbox_pipeline_hangs_total", "").inc(n=0)
    hub.counter("pbox_nan_rollbacks_total", "").inc(n=0)

    transitions = []

    def ev():
        for tr in engine.evaluate_once():
            transitions.append((tr["rule"], tr["to"]))

    ev()
    baseline_clean = not transitions
    # threshold rules: breach, eval, restore, eval
    hub.gauge("pbox_serving_staleness_sec", "").set(1e4)
    ev()
    hub.gauge("pbox_serving_staleness_sec", "").set(0.0)
    ev()
    hub.gauge("pbox_stream_lag_files", "").set(1e4)
    ev()
    hub.gauge("pbox_stream_lag_files", "").set(0.0)
    ev()
    hub.gauge("pbox_quality_degraded", "").set(1.0)
    ev()
    hub.gauge("pbox_quality_degraded", "").set(0.0)
    ev()
    for _ in range(10):            # p99 over the bound...
        hist.observe(0.9, op="predict")
    ev()
    for _ in range(5000):          # ...diluted back under it
        hist.observe(0.0002, op="predict")
    ev()
    # trend rules: one increment fires, the flat next window clears
    hub.counter("pbox_pipeline_hangs_total", "").inc(stage="endpass")
    ev()
    ev()
    hub.counter("pbox_nan_rollbacks_total", "").inc()
    ev()
    ev()
    # online lifecycle rules (docs/ONLINE.md): shrink_overdue is a
    # plain threshold on windows since the last shrink cycle...
    hub.gauge("pbox_online_windows_since_shrink", "").set(1e4)
    ev()
    hub.gauge("pbox_online_windows_since_shrink", "").set(0.0)
    ev()
    # ...backlog_growth needs the lag RISING across three consecutive
    # evaluations (values stay far under the stream_lag threshold so
    # the sibling rule on the same metric sleeps through this)
    for lag in (1.0, 2.0, 3.0, 4.0):
        hub.gauge("pbox_stream_lag_files", "").set(lag)
        ev()
    hub.gauge("pbox_stream_lag_files", "").set(0.0)
    ev()
    # elastic membership rules (docs/RESILIENCE.md §Elastic
    # membership): rank_dead trends the `lost` series of the scale
    # counter — one lost rank fires, a flat window clears...
    hub.counter("pbox_membership_scale_events_total", "").inc(
        direction="lost")
    ev()
    ev()
    # ...world_degraded is a plain threshold on the degraded gauge
    # (1 while running below target np)
    hub.gauge("pbox_membership_degraded", "").set(1.0)
    ev()
    hub.gauge("pbox_membership_degraded", "").set(0.0)
    ev()

    out["alerts_baseline_clean"] = baseline_clean
    out["alert_transitions"] = transitions
    fired = [r for r, to in transitions if to == "fired"]
    cleared = [r for r, to in transitions if to == "cleared"]
    out["alerts_all_fired_and_cleared"] = (
        sorted(set(fired)) == out["alert_rules"]
        and sorted(set(cleared)) == out["alert_rules"])
    out["alerts_none_left_firing"] = not engine.active()
    out["alerts_fired_total"] = {
        r: hub.counter("pbox_alerts_fired_total", "").value(rule=r)
        for r in out["alert_rules"]}


# ---- leg 6: rotation + torn tail ---------------------------------------
def _run_rotation_leg(workdir: str, out: dict) -> None:
    import glob

    from paddlebox_tpu.obs.sinks import JsonlSink
    from scripts.telemetry_report import load_events

    path = os.path.join(workdir, "rot", "events.jsonl")
    os.makedirs(os.path.dirname(path))
    sink = JsonlSink(path, max_bytes=1500, keep=2)
    for i in range(120):
        sink.emit({"event": "tick", "i": i, "pad": "x" * 40})
    sink.close()
    out["rotated_set"] = sorted(
        os.path.basename(f) for f in glob.glob(path + "*"))
    whole = load_events(path)
    out["rotation_oldest_first"] = (
        [e["i"] for e in whole] == sorted(e["i"] for e in whole))
    # a torn tail (writer killed mid-write) must be skipped, not fatal
    with open(path, "ab") as fh:
        fh.write(b'{"event": "torn')
    torn = load_events(path)
    out["torn_tail_skipped"] = (len(torn) == len(whole))


# ---- leg 7: debug routes -----------------------------------------------
def _run_http_leg(out: dict) -> None:
    from paddlebox_tpu.obs.hub import get_hub

    hub = get_hub()
    srv = hub.start_prom_http(0)
    port = srv.server_address[1]
    try:
        az = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/alertz", timeout=5).read())
        out["alertz_ok"] = (len(az["rules"]) == len(out["alert_rules"])
                            and az["firing"] == 0)
        hz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5).read())
        out["healthz_alerts_block"] = (
            hz.get("alerts", {}).get("rules") == len(out["alert_rules"])
            and hz.get("alerts", {}).get("firing") == 0)
        metrics = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        out["metrics_expose_alerts"] = "pbox_alerts_active" in metrics
        out["metrics_expose_bundles"] = \
            "pbox_flightrec_bundles_total" in metrics
    finally:
        srv.shutdown()


# ---- leg 8: flags-off overhead -----------------------------------------
def _run_overhead_leg(out: dict) -> None:
    from paddlebox_tpu.obs import flightrec
    from paddlebox_tpu.obs.hub import reset_hub

    hub = reset_hub()          # defaults-off: no sinks, no recorder
    out["inert_hub_inactive"] = not hub.active
    out["inert_no_recorder"] = flightrec.get_recorder() is None
    t0 = time.perf_counter()
    for i in range(100_000):
        hub.emit("tick", i=i)
    for i in range(100_000):
        flightrec.trigger("manual", reason="noop")
    wall = time.perf_counter() - t0
    out["overhead_ok"] = wall < OVERHEAD_WALL_SEC
    out["still_inactive_after"] = not hub.active


# ---- scenario ----------------------------------------------------------
def run_obs_check(workdir: str, seed: int = 7) -> dict:
    """The full black-box scenario. Deterministic for a fixed seed:
    the outcome dict holds only structural facts (counts, bools,
    bundle filenames, transition sequences)."""
    from paddlebox_tpu.config import flags_scope
    from paddlebox_tpu.obs import flightrec
    from paddlebox_tpu.obs.hub import get_hub, reset_hub

    out = {}
    reset_hub()
    bb_dir = os.path.join(workdir, "blackbox")
    with flags_scope(flightrec_dir=bb_dir, flightrec_ring_events=256,
                     flightrec_debounce_sec=600.0, flightrec_keep=16,
                     quality_window_passes=2, quality_auc_drop=0.01,
                     quality_calibration_buckets=5):
        flightrec.configure_from_flags()
        rec = flightrec.get_recorder()
        assert rec is not None, "flightrec_dir did not install a recorder"
        hub = get_hub()
        assert hub.active, "recorder sink must activate the hub"

        _run_quality_nan_leg(workdir, seed, out)
        _run_corrupt_tip_leg(workdir, seed, out)
        _run_hang_leg(out)
        _run_alerts_leg(out)
        hub.dump_blackbox("obs_check operator dump")

        # ---- bundle audit: exactly one per trigger, schema-complete,
        # seq-ordered names (the debounce ate the reload + SLO storms)
        names = _bundle_names(rec)
        out["bundles"] = names
        triggers = [n.split("-", 2)[2].rsplit(".", 1)[0] for n in names]
        out["bundle_triggers"] = triggers
        out["one_bundle_per_trigger"] = (
            len(triggers) == len(set(triggers)))
        schema_ok = True
        for pth in rec.bundles():
            _check_bundle(pth)
        out["bundles_schema_ok"] = schema_ok
        # the alerts leg fired every default rule; debounce collapsed
        # the SLO storm into the single slo_breach bundle audited above
        # (the two membership rules route to their own
        # membership_change bundle — a topology fact, not an SLO
        # breach — likewise collapsed to one by the debounce)
        out["slo_breach_suppressed"] = hub.counter(
            "pbox_flightrec_suppressed_total",
            "").value(trigger="slo_breach")

        _run_rotation_leg(workdir, out)
        _run_http_leg(out)

    _run_overhead_leg(out)
    reset_hub()
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch workdirs")
    args = ap.parse_args()

    outcomes = []
    for run in (1, 2):
        wd = tempfile.mkdtemp(prefix=f"obs_check_r{run}_")
        try:
            outcomes.append(run_obs_check(wd, seed=args.seed))
        finally:
            if not args.keep:
                import shutil
                shutil.rmtree(wd, ignore_errors=True)
    print(json.dumps(outcomes[-1], indent=2, sort_keys=True))
    checks = {
        "nan leg": outcomes[-1]["nan_retried_and_recovered"],
        "corrupt tip": (outcomes[-1]["corrupt_tip_not_adopted"]
                        and outcomes[-1]["corrupt_refused_loud"]),
        "hang": outcomes[-1]["hang_raised"],
        "alerts": outcomes[-1]["alerts_all_fired_and_cleared"],
        "bundles": (outcomes[-1]["one_bundle_per_trigger"]
                    and outcomes[-1]["bundles_schema_ok"]),
        "rotation": outcomes[-1]["rotation_oldest_first"]
                    and outcomes[-1]["torn_tail_skipped"],
        "routes": outcomes[-1]["alertz_ok"],
        "overhead": outcomes[-1]["overhead_ok"],
        "deterministic": outcomes[0] == outcomes[1],
    }
    for name, ok in checks.items():
        print(f"{'PASS' if ok else 'FAIL'}  {name}")
    return 0 if all(checks.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
